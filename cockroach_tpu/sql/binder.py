"""SQL binder/lowering — the optbuilder analog (pkg/sql/opt/optbuilder).

Turns a parsed ``Select`` AST into a ``Rel`` plan against a catalog:

- FROM sources bind to scans (or nested Selects); implicit-join queries are
  planned by extracting equi-join conjuncts from WHERE and greedily joining
  connected sources largest-probe-first (a cut-down version of the join
  ordering the reference's cost-based xform rules perform);
- single-source conjuncts push down below the join (the norm rules'
  filter-pushdown equivalent);
- EXISTS / IN (SELECT ...) decorrelate into semi/anti joins on the
  correlated equality columns (optbuilder's subquery hoisting);
- aggregation splits into pre-projection -> groupby -> HAVING filter ->
  post-projection, with aggregates collected across SELECT/HAVING/ORDER BY;
- string predicates (LIKE, =, IN, range) lower to host-prepared dictionary
  lookups (CodeLookup), date/interval literal arithmetic constant-folds to
  day literals on the host.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np

from ..catalog import Catalog
from ..coldata.types import BOOL, FLOAT64, INT64, Family, SQLType
from ..ops import expr as ex
from . import parser as P
from .rel import Rel

AGG_FUNCS = {"sum", "avg", "min", "max", "count", "stddev", "stddev_samp",
             "stddev_pop", "variance", "var_samp", "var_pop",
             "bool_and", "bool_or", "every", "string_agg"}

# SQL spellings -> kernel aggregate names (sample variants are the defaults,
# matching CockroachDB/Postgres; EVERY is the standard spelling of bool_and)
_AGG_CANON = {"variance": "var", "var_samp": "var", "stddev_samp": "stddev",
              "every": "bool_and"}


class BindError(Exception):
    pass


# ---------------------------------------------------------------------------
# helpers


def _positional(seq, numlit) -> str:
    """ORDER BY <position>: 1-based, bounds-checked (0 would silently hit
    Python's negative indexing). seq: output names or (name, expr) items."""
    pos = int(numlit.value)
    if pos < 1 or pos > len(seq):
        raise BindError(
            f"ORDER BY position {pos} is out of range (1..{len(seq)})"
        )
    item = seq[pos - 1]
    return item if isinstance(item, str) else item[0]


def _conjuncts(e: P.Node | None) -> list[P.Node]:
    if e is None:
        return []
    if isinstance(e, P.Bin) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _days(date_str: str) -> int:
    return int(
        (np.datetime64(date_str) - np.datetime64("1970-01-01")).astype(int)
    )


def _date_add(days: int, n: int, unit: str) -> int:
    """Calendar-correct date + interval on the host (constant folding)."""
    d = np.datetime64("1970-01-01") + np.timedelta64(days, "D")
    if unit == "day":
        d = d + np.timedelta64(n, "D")
    elif unit == "month":
        m = d.astype("datetime64[M]") + np.timedelta64(n, "M")
        dom = (d - d.astype("datetime64[M]")).astype(int)
        d = m.astype("datetime64[D]") + np.timedelta64(dom, "D")
    elif unit == "year":
        return _date_add(days, 12 * n, "month")
    else:
        raise BindError(f"unsupported interval unit {unit}")
    return int((d - np.datetime64("1970-01-01")).astype(int))


def _fold(e: P.Node) -> P.Node:
    """Fold date/interval/numeric literal arithmetic into literals."""
    if isinstance(e, P.Bin) and e.op in ("+", "-"):
        l, r = _fold(e.left), _fold(e.right)
        if isinstance(l, P.NumLit) and isinstance(r, P.IntervalLit):
            # folded DateLits are day numbers; intervals add calendar-exactly
            n = r.n if e.op == "+" else -r.n
            return P.NumLit(_date_add(int(l.value), n, r.unit))
        if isinstance(l, P.NumLit) and isinstance(r, P.NumLit):
            v = l.value + r.value if e.op == "+" else l.value - r.value
            return P.NumLit(v)
        return P.Bin(e.op, l, r)
    if isinstance(e, P.DateLit):
        return P.NumLit(_days(e.value))
    return e


# SQL: now()/current_date are constant WITHIN a statement. The session
# resets this at each execute(); every occurrence in one statement then
# folds to the same instant (conn_executor's statement timestamp role).
_STMT_NOW_US: list[int | None] = [None]


def begin_statement() -> None:
    _STMT_NOW_US[0] = None
    # fresh snapshots for crdb_internal virtual tables: bind-time and
    # build-time materializations within THIS statement stay identical
    from . import crdb_internal

    crdb_internal.bump_generation()


def _statement_now_us() -> int:
    if _STMT_NOW_US[0] is None:
        import time as _time

        _STMT_NOW_US[0] = int(_time.time() * 1e6)
    return _STMT_NOW_US[0]


def _intersect_except(left: Rel, right: Rel, op: str) -> Rel:
    """INTERSECT / EXCEPT with SQL set (DISTINCT) semantics via the
    tagged-union reduction: dedupe both arms, tag rows 0/1, UNION ALL,
    group by every output column, keep groups by their tag profile.
    Grouping — unlike a join — already treats NULLs as equal, which is
    exactly the set-operation rule, and union_all reconciles string
    dictionaries across arms. (INTERSECT/EXCEPT ALL bag semantics are
    rejected at parse time.)"""
    if len(left.schema) != len(right.schema):
        raise BindError(f"{op.upper()} inputs must have equal arity")
    names = list(left.schema.names)
    tag = "__setop_tag"
    while tag in names:
        tag += "_"

    def tagged(r: Rel, t: int) -> Rel:
        r = r.distinct()
        items = [(n, r.c(r.schema.names[i]))
                 for i, n in enumerate(names)]
        return r.project(items + [(tag, ex.lit(t))])

    u = tagged(left, 0).union_all(tagged(right, 1))
    g = u.groupby(names, [("__mn", "min", tag), ("__mx", "max", tag)])
    if op == "intersect":
        keep = ex.and_(ex.Cmp("eq", g.c("__mn"), ex.lit(0)),
                       ex.Cmp("eq", g.c("__mx"), ex.lit(1)))
    else:  # except: present in left only
        keep = ex.Cmp("eq", g.c("__mx"), ex.lit(0))
    g = g.filter(keep)
    return g.project([(n, g.c(n)) for n in names])


def _replace_node(tree: P.Node, target: P.Node, repl: P.Node) -> P.Node:
    """Rebuild `tree` with the (identity-matched) `target` node replaced.
    Frozen dataclass AST: rebuild only along the path to the target."""
    if tree is target:
        return repl
    import dataclasses as _dc

    if not _dc.is_dataclass(tree):
        return tree
    changes = {}
    for f in _dc.fields(tree):
        v = getattr(tree, f.name)
        if isinstance(v, P.Node):
            nv = _replace_node(v, target, repl)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple):
            nvs = tuple(
                _replace_node(x, target, repl) if isinstance(x, P.Node)
                else x
                for x in v
            )
            if any(a is not b for a, b in zip(nvs, v)):
                changes[f.name] = nvs
    return _dc.replace(tree, **changes) if changes else tree


def _like_regex(pattern: str) -> re.Pattern:
    parts = []
    for ch in pattern:
        if ch == "%":
            parts.append(".*")
        elif ch == "_":
            parts.append(".")
        else:
            parts.append(re.escape(ch))
    return re.compile("^" + "".join(parts) + "$", re.DOTALL)


def _walk(e: P.Node):
    yield e
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, P.Node) and not isinstance(v, P.Select):
            yield from _walk(v)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, P.Node) and not isinstance(x, P.Select):
                    yield from _walk(x)
                elif (isinstance(x, tuple) and len(x) == 2
                      and isinstance(x[0], P.Node)):
                    yield from _walk(x[0])
                    yield from _walk(x[1])


def _has_agg(e: P.Node) -> bool:
    # a sum() INSIDE an OVER clause is a window aggregate, not grouping:
    # WindowCall subtrees are pruned from the walk entirely
    if isinstance(e, P.WindowCall):
        return False
    if isinstance(e, P.FuncCall) and e.name in AGG_FUNCS:
        return True
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, P.Node) and not isinstance(v, P.Select):
            if _has_agg(v):
                return True
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, P.Node) and not isinstance(x, P.Select):
                    if _has_agg(x):
                        return True
                elif isinstance(x, tuple):
                    # nested pair tuples (CASE whens: (cond, result))
                    for y in x:
                        if (isinstance(y, P.Node)
                                and not isinstance(y, P.Select)
                                and _has_agg(y)):
                            return True
    return False


# ---------------------------------------------------------------------------
# bound sources


@dataclass
class Source:
    """One FROM item bound to a Rel, with name scoping."""

    alias: str
    rel: Rel
    cols: tuple[str, ...]  # output names as exposed to the query
    # base-table cardinality, captured before filter pushdown (join ordering
    # still sees the true relative sizes); subqueries get a large default
    base_rows: int = 1 << 30
    # post-pushdown cardinality estimate from ANALYZE histograms
    # (statistics_builder.go selectivity role); None = no estimate, join
    # ordering falls back to base_rows
    est_rows: int | None = None
    # base-table provenance (None for subquery sources); lets bind-time
    # checks prove column non-nullability from the catalog's valid bitmaps
    table: str | None = None
    # combined sources (a bound LEFT JOIN) expose their constituent aliases
    # so table-qualified references through either side still resolve
    sub_aliases: tuple[tuple[str, tuple[str, ...]], ...] = ()


class Scope:
    """Resolves Ident -> (source index, source-local column POSITION).

    Positions (not names) are the only sound currency once a combined
    source (a bound LEFT JOIN) or a self-join carries duplicate names."""

    def __init__(self, sources: list[Source]):
        self.sources = sources

    def resolve(self, ident: P.Ident) -> tuple[int, int]:
        if ident.table is not None:
            for i, s in enumerate(self.sources):
                if s.alias == ident.table:
                    if ident.name not in s.cols:
                        raise BindError(
                            f"column {ident.name} not in {ident.table}"
                        )
                    return i, s.cols.index(ident.name)
                off = 0
                for sub_alias, sub_cols in s.sub_aliases:
                    if sub_alias == ident.table:
                        if ident.name not in sub_cols:
                            raise BindError(
                                f"column {ident.name} not in {ident.table}"
                            )
                        return i, off + sub_cols.index(ident.name)
                    off += len(sub_cols)
            raise BindError(f"unknown table alias {ident.table}")
        hits = [
            (i, p)
            for i, s in enumerate(self.sources)
            for p, c in enumerate(s.cols)
            if c == ident.name
        ]
        if not hits:
            raise BindError(f"unknown column {ident.name}")
        if len(hits) > 1:
            raise BindError(f"ambiguous column {ident.name}: qualify it")
        return hits[0]

    def name_of(self, i: int, pos: int) -> str:
        return self.sources[i].cols[pos]

    def sources_of(self, e: P.Node) -> set[int]:
        out = set()
        for x in _walk(e):
            if isinstance(x, P.Ident):
                out.add(self.resolve(x)[0])
        return out


# ---------------------------------------------------------------------------
# expression lowering against a single Rel


class ExprLowerer:
    """Lower AST expressions against one Rel's schema (after joins).

    resolver, when given, maps an Ident to a column POSITION via the query's
    scope + join column map — the only correct resolution once a self-join
    has produced duplicate column names in the joined schema."""

    def __init__(self, rel: Rel, names: dict[str, int] | None = None,
                 resolver=None):
        self.rel = rel
        self.resolver = resolver
        # name -> column index (defaults to the rel's schema)
        self.names = names or {
            n: i for i, n in enumerate(rel.schema.names)
        }

    def idx(self, ident: P.Ident) -> int:
        if self.resolver is not None:
            return self.resolver(ident)
        if ident.name in self.names:
            return self.names[ident.name]
        raise BindError(f"unknown column {ident.name}")

    def _is_string_col(self, e: P.Node) -> int | None:
        if isinstance(e, P.Ident):
            i = self.idx(e)
            if self.rel.schema.types[i].family is Family.STRING:
                return i
        return None

    def _colname(self, i: int) -> str:
        return self.rel.schema.names[i]

    # positional string-predicate helpers: Rel's name-based str_* entry
    # points mis-resolve duplicate names after self-joins, so the lowerer
    # builds the dictionary-code lookups itself from a column POSITION
    def _str_pred_at(self, i: int, fn) -> ex.Expr:
        d = self.rel.dicts[i]
        table = np.array([bool(fn(str(v))) for v in d.values])
        if len(table) == 0:
            table = np.zeros(1, dtype=bool)
        return ex.CodeLookup(col=i, table=table)

    def _str_eq_at(self, i: int, value: str) -> ex.Expr:
        from ..coldata.types import INT32

        code = self.rel.dicts[i].code_of(value)
        return ex.Cmp("eq", ex.ColRef(i), ex.Const(code, INT32))

    def lower(self, e: P.Node) -> ex.Expr:
        e = _fold(e)
        if isinstance(e, P.Ident):
            try:
                return ex.ColRef(self.idx(e))
            except BindError:
                if e.table is None and e.name in ("current_date",
                                                  "current_timestamp"):
                    from ..coldata.types import DATE as _DATE
                    from ..coldata.types import TIMESTAMP as _TS

                    us = _statement_now_us()
                    if e.name == "current_date":
                        return ex.Const(us // 86_400_000_000, _DATE)
                    return ex.Const(us, _TS)
                raise
        if isinstance(e, P.NumLit):
            if isinstance(e.value, int):
                return ex.lit(int(e.value))
            return ex.Const(float(e.value), FLOAT64)
        if isinstance(e, P.NullLit):
            return ex.Const(None, INT64)
        if (isinstance(e, P.Bin) and e.op in ("+", "-")
                and isinstance(e.right, P.IntervalLit)):
            # column ± day/week interval: a constant day add (exact).
            # month/year intervals on COLUMNS need per-row calendar
            # arithmetic (literal dates fold calendar-exactly in _fold)
            iv = e.right
            if iv.unit in ("day", "week"):
                days = iv.n * (7 if iv.unit == "week" else 1)
                return ex.BinOp(e.op, self.lower(e.left),
                                ex.Const(days, INT64))
            raise BindError(
                f"column {e.op} INTERVAL {iv.unit} is not supported "
                "(day/week intervals only; month/year need per-row "
                "calendar arithmetic)"
            )
        if isinstance(e, P.Bin) and e.op in ("and", "or"):
            return ex.BoolOp(e.op, (self.lower(e.left), self.lower(e.right)))
        if isinstance(e, P.Bin):
            if e.op == "%":
                raise BindError("modulo not supported on device")
            return ex.BinOp(e.op, self.lower(e.left), self.lower(e.right))
        if isinstance(e, P.Not):
            return ex.Not(self.lower(e.arg))
        if isinstance(e, P.IsNull):
            return ex.IsNull(self.lower(e.arg), negate=e.negated)
        if isinstance(e, P.Cmp):
            return self.lower_cmp(e)
        if isinstance(e, P.Between):
            b = ex.and_(
                self.lower(P.Cmp("ge", e.arg, e.lo)),
                self.lower(P.Cmp("le", e.arg, e.hi)),
            )
            return ex.Not(b) if e.negated else b
        if isinstance(e, P.Like):
            i = self._is_string_col(e.arg)
            if i is None:
                raise BindError("LIKE requires a string column")
            rx = _like_regex(e.pattern.lower() if e.ci else e.pattern)
            if e.ci:  # ILIKE: case-insensitive on both sides
                pred = self._str_pred_at(
                    i, lambda s: rx.match(s.lower()) is not None
                )
            else:
                pred = self._str_pred_at(
                    i, lambda s: rx.match(s) is not None
                )
            return ex.Not(pred) if e.negated else pred
        if isinstance(e, P.IsDistinct):
            a = self.lower(e.left)
            b = self.lower(e.right)
            ta = ex.expr_type(a, self.rel.schema)
            if ta.family is Family.STRING:
                raise BindError(
                    "IS DISTINCT FROM over strings is not supported"
                )
            # NOT DISTINCT == (both NULL) OR (a = b known-true); Kleene
            # algebra keeps the result two-valued
            not_distinct = ex.or_(
                ex.and_(ex.IsNull(a), ex.IsNull(b)),
                ex.and_(ex.Cmp("eq", a, b),
                        ex.IsNull(a, negate=True),
                        ex.IsNull(b, negate=True)),
            )
            return not_distinct if e.negated else ex.Not(not_distinct)
        if isinstance(e, P.InList):
            i = self._is_string_col(e.arg)
            if i is not None:
                vals = [
                    x.value for x in e.items if isinstance(x, P.StrLit)
                ]
                if len(vals) != len(e.items):
                    raise BindError("string IN list must be all literals")
                vset = set(vals)
                pred = self._str_pred_at(i, lambda s: s in vset)
                return ex.Not(pred) if e.negated else pred
            if (isinstance(e.arg, P.FuncCall)
                    and e.arg.name == "substring"):
                return self.lower_substring_in(e)
            arg = self.lower(e.arg)
            cmps = [
                ex.Cmp("eq", arg, self.lower(x)) for x in e.items
            ]
            pred = ex.or_(*cmps) if len(cmps) > 1 else cmps[0]
            return ex.Not(pred) if e.negated else pred
        if isinstance(e, P.Case):
            whens = tuple(
                (self.lower(c), self.lower(v)) for c, v in e.whens
            )
            if e.otherwise is None:
                otherwise = ex.Const(None, ex.expr_type(
                    whens[0][1], self.rel.schema))
            else:
                otherwise = self.lower(e.otherwise)
            return ex.Case(whens, otherwise)
        if isinstance(e, P.Cast):
            from ..coldata.types import BOOL as _BOOL
            from ..coldata.types import DATE as _DATE
            from ..coldata.types import TIMESTAMP as _TS

            dec = SQLType(
                Family.DECIMAL,
                precision=e.precision if e.precision is not None else 38,
                scale=e.scale if e.scale is not None else 2,
            )
            to = {
                "int": INT64, "integer": INT64, "bigint": INT64,
                "smallint": SQLType(Family.INT, width=16),
                "float": FLOAT64, "double": FLOAT64, "real": FLOAT64,
                "decimal": dec, "numeric": dec,
                "bool": _BOOL, "boolean": _BOOL,
                "date": _DATE, "timestamp": _TS,
            }.get(e.to)
            if to is None:
                raise BindError(f"unsupported cast target {e.to}")
            if isinstance(e.arg, P.StrLit):
                # string-literal casts resolve at bind time ('5'::int)
                v = e.arg.value
                try:
                    if to.family is Family.INT:
                        return ex.Const(int(v), to)
                    if to.family is Family.FLOAT:
                        return ex.Const(float(v), to)
                    if to.family is Family.DECIMAL:
                        # Const holds the UNSCALED value for DECIMAL —
                        # eval_expr applies the 10^scale encoding
                        return ex.Const(float(v), to)
                    if to.family is Family.BOOL:
                        lv = v.strip().lower()
                        if lv in ("t", "true", "yes", "on", "1"):
                            return ex.Const(True, to)
                        if lv in ("f", "false", "no", "off", "0"):
                            return ex.Const(False, to)
                        raise BindError(
                            f"invalid bool literal {v!r}"
                        )
                    if to.family is Family.DATE:
                        days = int((np.datetime64(v, "D") -
                                    np.datetime64("1970-01-01", "D")
                                    ).astype(int))
                        return ex.Const(days, to)
                    if to.family is Family.TIMESTAMP:
                        # microsecond unit keeps the time-of-day (a "D"
                        # parse would silently floor to midnight)
                        us = int((np.datetime64(v.strip().replace(" ", "T"),
                                                "us")
                                  - np.datetime64("1970-01-01", "us")
                                  ).astype(np.int64))
                        return ex.Const(us, to)
                except ValueError as err:
                    raise BindError(
                        f"invalid {e.to} literal {v!r}: {err}"
                    ) from None
            return ex.Cast(self.lower(e.arg), to)
        if isinstance(e, P.Extract):
            if e.part == "year":
                return ex.ExtractYear(self.lower(e.arg))
            if e.part in ex.EXTRACT_PARTS:
                return ex.ExtractPart(e.part, self.lower(e.arg))
            raise BindError(f"EXTRACT({e.part}) not supported")
        if isinstance(e, P.FuncCall) and e.name in AGG_FUNCS:
            raise BindError(
                f"aggregate {e.name} not allowed in this context"
            )
        if (isinstance(e, P.FuncCall) and len(e.args) == 1
                and e.name in ("abs", "ceil", "ceiling", "floor", "round",
                               "sign", "trunc", "log")
                + tuple(ex._FUNC1_FLOAT)):
            # CockroachDB's log(x) is base 10 (builtins.go); ln is natural
            name = {"ceiling": "ceil", "log": "log10"}.get(e.name, e.name)
            return ex.Func1(name, self.lower(e.args[0]))
        if (isinstance(e, P.FuncCall) and len(e.args) == 2
                and e.name in ("pow", "power", "mod", "div", "atan2")):
            name = "pow" if e.name == "power" else e.name
            return ex.Func2(name, self.lower(e.args[0]),
                            self.lower(e.args[1]))
        if (isinstance(e, P.FuncCall) and len(e.args) == 2
                and e.name == "round"):
            n = self.lower(e.args[1])
            if not isinstance(n, ex.Const) or n.value is None:
                raise BindError("round(x, n) requires a literal n")
            return ex.Func2("round2", self.lower(e.args[0]),
                            ex.Const(int(n.value), INT64))
        if (isinstance(e, P.FuncCall) and e.name in ("greatest", "least")
                and e.args):
            lowered = tuple(self.lower(a) for a in e.args)
            for le in lowered:
                if ex.expr_type(le, self.rel.schema).family in (
                        Family.STRING, Family.BYTES, Family.JSON):
                    # dict codes don't order by value; needs a rank-table
                    # rewrite like string range predicates
                    raise BindError(
                        f"{e.name} over strings is not supported"
                    )
            out = ex.Greatest(lowered, is_least=e.name == "least")
            try:  # surface family-unification failures at bind time
                ex.expr_type(out, self.rel.schema)
            except TypeError as err:
                raise BindError(str(err)) from None
            return out
        if isinstance(e, P.FuncCall) and e.name == "nullif" \
                and len(e.args) == 2:
            a = self.lower(e.args[0])
            b = self.lower(e.args[1])
            t = ex.expr_type(a, self.rel.schema)
            if t.family is Family.STRING:
                # dict codes from different columns don't compare; the
                # string path would need a shared-dictionary rewrite
                raise BindError("NULLIF over strings is not supported")
            return ex.Case(whens=((ex.Cmp("eq", a, b), ex.Const(None, t)),),
                           otherwise=a)
        if isinstance(e, P.FuncCall) and e.name == "coalesce" and e.args:
            return ex.Coalesce(tuple(self.lower(a) for a in e.args))
        if (isinstance(e, P.FuncCall) and not e.args
                and e.name in ("now", "current_timestamp",
                               "transaction_timestamp",
                               "statement_timestamp")):
            from ..coldata.types import TIMESTAMP as _TS

            return ex.Const(_statement_now_us(), _TS)
        if (isinstance(e, P.FuncCall)
                and e.name in ("starts_with", "strpos")
                and len(e.args) == 2):
            i = self._is_string_col(e.args[0])
            lit = e.args[1]
            if i is None or not isinstance(lit, P.StrLit):
                raise BindError(f"{e.name} requires (string column, "
                                "string literal)")
            d = self.rel.dicts[i]
            if e.name == "starts_with":
                table = np.array(
                    [str(v).startswith(lit.value) for v in d.values],
                    dtype=bool,
                )
                out_t = BOOL
            else:  # strpos: 1-based position, 0 when absent
                table = np.array(
                    [str(v).find(lit.value) + 1 for v in d.values],
                    dtype=np.int64,
                )
                out_t = INT64
            if len(table) == 0:
                table = np.zeros(1, table.dtype)
            return ex.CodeLookup(col=i, table=table, out_type=out_t)
        if (isinstance(e, P.FuncCall) and e.name == "ascii"
                and len(e.args) == 1):
            i = self._is_string_col(e.args[0])
            if i is None:
                raise BindError("ascii requires a string column")
            d = self.rel.dicts[i]
            table = np.array(
                [ord(str(v)[0]) if len(str(v)) else 0 for v in d.values],
                dtype=np.int64,
            )
            if len(table) == 0:
                table = np.zeros(1, np.int64)
            return ex.CodeLookup(col=i, table=table, out_type=INT64)
        if (isinstance(e, P.FuncCall)
                and e.name in ("length", "char_length")
                and len(e.args) == 1):
            i = self._is_string_col(e.args[0])
            if i is None:
                raise BindError(f"{e.name} requires a string column")
            d = self.rel.dicts[i]
            table = np.array([len(str(v)) for v in d.values],
                             dtype=np.int64)
            if len(table) == 0:
                table = np.zeros(1, np.int64)
            return ex.CodeLookup(col=i, table=table, out_type=INT64)
        raise BindError(f"cannot lower expression {e}")

    def lower_cmp(self, e: P.Cmp) -> ex.Expr:
        # string column vs string literal
        for a, b, flip in ((e.left, e.right, False), (e.right, e.left, True)):
            i = self._is_string_col(a)
            if i is not None and isinstance(b, P.StrLit):
                op = e.op
                if flip:
                    op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                          "eq": "eq", "ne": "ne"}[op]
                if op == "eq":
                    return self._str_eq_at(i, b.value)
                if op == "ne":
                    return ex.Not(self._str_eq_at(i, b.value))
                import operator as _op

                fns = {"lt": _op.lt, "le": _op.le, "gt": _op.gt,
                       "ge": _op.ge}
                return self._str_pred_at(
                    i, lambda s: fns[op](s, b.value)
                )
        # substring(col from a for n) = 'lit'  (Q22 country-code pattern)
        if (isinstance(e.left, P.FuncCall) and e.left.name == "substring"
                and isinstance(e.right, P.StrLit)):
            return self.lower_substring_in(
                P.InList(e.left, (e.right,), negated=(e.op == "ne"))
            )
        l = self.lower(e.left)
        r = self.lower(e.right)
        # exact decimal compare: float literal vs DECIMAL column folds to a
        # scaled-int literal when representable (avoids fp rounding surprises)
        lt = ex.expr_type(l, self.rel.schema)
        rt = ex.expr_type(r, self.rel.schema)
        if (lt.family is Family.DECIMAL and isinstance(r, ex.Const)
                and rt.family is Family.FLOAT):
            scaled = r.value * (10 ** lt.scale)
            if abs(scaled - round(scaled)) < 1e-9:
                r = ex.Const(r.value, lt)
        if (rt.family is Family.DECIMAL and isinstance(l, ex.Const)
                and lt.family is Family.FLOAT):
            scaled = l.value * (10 ** rt.scale)
            if abs(scaled - round(scaled)) < 1e-9:
                l = ex.Const(l.value, rt)
        return ex.Cmp(e.op, l, r)

    def lower_substring_in(self, e: P.InList) -> ex.Expr:
        fc = e.arg
        col = fc.args[0]
        i = self._is_string_col(col)
        if i is None:
            raise BindError("substring requires a string column")
        start = int(fc.args[1].value) - 1
        n = int(fc.args[2].value)
        vals = {x.value for x in e.items}
        pred = self._str_pred_at(i, lambda s: s[start:start + n] in vals)
        return ex.Not(pred) if e.negated else pred


# ---------------------------------------------------------------------------
# the binder


class Binder:
    def __init__(self, catalog: Catalog):
        self.catalog = catalog
        self.ctes: dict[str, Rel] = {}

    def bind(self, sel: P.Select) -> Rel:
        if sel.set_ops:
            return self._bind_set_ops(sel)
        for name, csel in sel.ctes:
            # CTEs bind once; every reference shares the one plan subtree
            # (the distributed lowering memoizes shared subtrees, so a CTE
            # used twice computes once inside the SPMD program)
            self.ctes[name] = self.bind(csel)
        if not sel.from_:
            # FROM-less SELECT: one synthetic row (Postgres' implicit
            # dual); constants/builtins project over it
            sel = P.dataclasses.replace(
                sel, from_=(P.TableRef("__dual", None),)
            )
            if "__dual" not in self.catalog.tables:
                import numpy as _np

                from ..catalog import Table as _Table
                from ..coldata.types import INT64 as _I64
                from ..coldata.types import Schema as _Schema

                self.catalog.add(_Table.from_strings(
                    "__dual", _Schema.of(__dual=_I64),
                    {"__dual": _np.zeros(1, _np.int64)},
                ))
        sources, join_filters = self._bind_from(sel.from_)
        scope = Scope(sources)

        conjuncts = [(_fold(c)) for c in _conjuncts(sel.where)]
        conjuncts = join_filters + conjuncts

        # classify conjuncts
        equi_edges: list[tuple[int, str, int, str]] = []
        per_source: dict[int, list[P.Node]] = {}
        residual: list[P.Node] = []
        sub_joins: list[tuple[P.Node, set[int]]] = []
        corr_scalars: list[P.Node] = []
        for c in conjuncts:
            if isinstance(c, (P.Exists, P.InSelect)) or (
                isinstance(c, P.Not)
                and isinstance(c.arg, (P.Exists, P.InSelect))
            ):
                node = c.arg if isinstance(c, P.Not) else c
                negate = isinstance(c, P.Not)
                sub_joins.append((node, negate))
                continue
            sub = next((x for x in _walk(c)
                        if isinstance(x, P.ScalarSubquery)), None)
            if sub is not None and self._scalar_sub_is_correlated(sub):
                corr_scalars.append(c)
                continue
            if isinstance(c, P.Cmp) and c.op == "eq" and \
                    isinstance(c.left, P.Ident) and isinstance(c.right, P.Ident):
                li, lp = scope.resolve(c.left)
                ri, rp = scope.resolve(c.right)
                if li != ri:
                    equi_edges.append((li, lp, ri, rp))
                    continue
            srcs = scope.sources_of(c)
            if len(srcs) == 1:
                per_source.setdefault(next(iter(srcs)), []).append(c)
            else:
                # an OR whose every branch repeats the same equi-join edge
                # (TPC-H q19's shape) contributes that edge to the join
                # graph; the full OR stays as a post-join filter
                equi_edges.extend(self._or_common_equis(c, scope))
                residual.append(c)

        # scalar subqueries inside residual/per-source conjuncts: execute
        # uncorrelated ones now (constant folding through the engine)
        # (correlated scalar subqueries are future work)

        # push single-source filters down
        for i, preds in per_source.items():
            s = sources[i]
            lower = ExprLowerer(s.rel)
            for p in preds:
                s.rel = s.rel.filter(self._lower_with_subqueries(lower, p))
                lower = ExprLowerer(s.rel)
            s.est_rows = self._estimate_source_rows(s, preds)

        # greedy join order: start at the largest source
        joined = self._join_sources(sources, equi_edges, scope)

        # decorrelated EXISTS / IN-select as semi/anti joins
        for node, negate in sub_joins:
            joined = self._apply_sub_join(joined, node, negate, scope, sources)

        resolver = self._make_resolver(scope, joined)

        # correlated scalar subqueries: decorrelate into a grouped join
        for c in corr_scalars:
            joined = self._apply_corr_scalar(joined, c, scope)
            resolver = self._make_resolver(scope, joined)

        # residual multi-source predicates
        if residual:
            for c in residual:
                lower = ExprLowerer(joined.rel, resolver=resolver)
                joined.rel = joined.rel.filter(
                    self._lower_with_subqueries(lower, c))

        # correlated scalar subqueries in the SELECT list: LEFT-join the
        # grouped inner (a key with no inner rows keeps the row, scalar
        # NULL — SQL's select-position semantics, unlike the WHERE
        # position's row-dropping inner join) and rewrite each item to
        # reference the joined column through a marker ident
        sub_markers: dict[str, int] = {}
        if any(isinstance(x, P.ScalarSubquery)
               and self._scalar_sub_is_correlated(x)
               for it in sel.items for x in _walk(it.expr)):
            new_items = []
            for it in sel.items:
                expr = it.expr
                for x in _walk(expr):
                    if (isinstance(x, P.ScalarSubquery)
                            and self._scalar_sub_is_correlated(x)):
                        rel2, sub_pos, _, _ = self._join_corr_scalar(
                            joined, scope, x, how="left"
                        )
                        joined = BoundQuery(rel2, joined.sources,
                                            joined.colmap)
                        mname = f"_s{len(sub_markers)}"
                        sub_markers[mname] = sub_pos
                        marker: P.Node = P.Ident("__selsub", mname)
                        inner_item = x.select.items[0].expr
                        if (isinstance(inner_item, P.FuncCall)
                                and inner_item.name == "count"):
                            # count over an empty correlated group is 0,
                            # not NULL (the classic decorrelation count
                            # bug; the left join yields NULL there)
                            marker = P.FuncCall(
                                "coalesce", (marker, P.NumLit(0))
                            )
                        expr = _replace_node(expr, x, marker)
                new_items.append(P.SelectItem(expr, it.alias))
            sel = P.dataclasses.replace(sel, items=tuple(new_items))
            base_resolver = resolver

            def resolver(ident: P.Ident, _base=base_resolver):  # noqa: F811
                if ident.table == "__selsub":
                    return sub_markers[ident.name]
                if _base is not None:
                    return _base(ident)
                return joined.rel.idx(ident.name)

        return self._finish(sel, joined.rel, resolver)

    def _bind_set_ops(self, sel: P.Select) -> Rel:
        """UNION [ALL] chain (left-associative; non-ALL steps deduplicate,
        SQL set semantics). ORDER BY / LIMIT on `sel` apply to the WHOLE
        union (the parser hoists a trailing arm's order/limit up).
        Reference surface: sql.y set operations -> UnionClause."""
        import dataclasses as _dc

        # CTEs scope over EVERY arm: register them on this binder first,
        # then bind each arm with the shared registry
        for name, csel in sel.ctes:
            self.ctes[name] = self.bind(csel)
        base = _dc.replace(sel, set_ops=(), order_by=(), limit=None,
                           offset=0, ctes=())
        rel = self.bind(base)
        for op, is_all, arm in sel.set_ops:
            arm_rel = self.bind(arm)
            if op == "union":
                rel = rel.union_all(arm_rel)
                if not is_all:
                    rel = rel.distinct()
            else:
                rel = _intersect_except(rel, arm_rel, op)
        keys = []
        for o in sel.order_by:
            if isinstance(o.expr, P.Ident) and o.expr.name in rel.schema.names:
                keys.append((o.expr.name, o.desc))
            elif isinstance(o.expr, P.NumLit):
                keys.append(
                    (_positional(rel.schema.names, o.expr), o.desc))
            else:
                raise BindError(
                    "UNION ORDER BY must name an output column or position"
                )
        if keys:
            rel = rel.sort(keys)
        if sel.limit is not None or sel.offset:
            rel = rel.limit(sel.limit if sel.limit is not None else (1 << 62),
                            sel.offset)
        return rel

    @staticmethod
    def _make_resolver(scope: Scope, joined: "BoundQuery"):
        """Ident -> joined-schema POSITION via scope + join column map;
        required once self-joins duplicate names in the joined schema."""
        if joined.colmap is None:
            return None

        def resolve(ident: P.Ident) -> int:
            i, p = scope.resolve(ident)
            pos = joined.colmap.get((i, p))
            if pos is None:
                raise BindError(
                    f"column {ident.name} not available after join"
                )
            return pos

        return resolve

    # -- FROM ---------------------------------------------------------------

    def _bind_from(self, items) -> tuple[list[Source], list[P.Node]]:
        sources: list[Source] = []
        join_filters: list[P.Node] = []

        def bind_item(it):
            if isinstance(it, P.TableRef) and it.name in self.ctes:
                rel = self.ctes[it.name]
                sources.append(
                    Source(it.alias or it.name, rel, rel.schema.names)
                )
            elif isinstance(it, P.TableRef):
                rel = Rel.scan(self.catalog, it.name)
                sources.append(
                    Source(it.alias or it.name, rel, rel.schema.names,
                           base_rows=self.catalog.get(it.name).estimated_rows(),
                           table=it.name)
                )
            elif isinstance(it, P.SubqueryRef):
                rel = self.bind(it.select)
                sources.append(Source(it.alias, rel, rel.schema.names))
            elif isinstance(it, P.Join) and it.kind == "inner":
                bind_item(it.left)
                bind_item(it.right)
                # ON conjuncts go into the shared predicate pool; the join
                # planner extracts the equi keys
                join_filters.extend(_conjuncts(it.on))
            elif isinstance(it, P.Join) and it.kind == "left":
                sources.append(self._bind_left_join(it))
            else:
                raise BindError(f"unsupported FROM item {it}")

        for it in items:
            bind_item(it)
        return sources, join_filters

    def _bind_left_join(self, it: P.Join) -> Source:
        """LEFT OUTER JOIN of two primaries -> one combined source.

        ON conjuncts split into equi keys and single-side predicates; a
        right-only predicate filters the build side BEFORE the outer join
        (ON-clause semantics: a failed predicate null-extends rather than
        dropping the left row). Left-only ON predicates would need a
        post-join mask and are refused."""
        sub_sources, _ = self._bind_from([it.left, it.right])
        if len(sub_sources) != 2:
            raise BindError("nested outer joins not supported")
        left, right = sub_sources
        sub_scope = Scope([left, right])
        keys: list[tuple[int, int]] = []
        for c in _conjuncts(it.on):
            c = _fold(c)
            if (isinstance(c, P.Cmp) and c.op == "eq"
                    and isinstance(c.left, P.Ident)
                    and isinstance(c.right, P.Ident)):
                li, lp = sub_scope.resolve(c.left)
                ri, rp = sub_scope.resolve(c.right)
                if {li, ri} == {0, 1}:
                    keys.append((lp, rp) if li == 0 else (rp, lp))
                    continue
            srcs = sub_scope.sources_of(c)
            if srcs == {1}:
                def _right_resolver(ident: P.Ident) -> int:
                    i, p = sub_scope.resolve(ident)
                    if i != 1:
                        raise BindError("predicate crossed join sides")
                    return p
                lower = ExprLowerer(right.rel, resolver=_right_resolver)
                right = Source(right.alias, right.rel.filter(lower.lower(c)),
                               right.cols, right.base_rows, right.table)
            else:
                raise BindError(
                    "LEFT JOIN ON supports equi keys and right-side "
                    "predicates only"
                )
        if not keys:
            raise BindError("LEFT JOIN requires at least one equi key")
        rel = left.rel.join(right.rel, on=keys, how="left",
                            build_unique=False)
        return Source(
            alias=f"{left.alias}*{right.alias}", rel=rel,
            cols=rel.schema.names, base_rows=left.base_rows,
            sub_aliases=((left.alias, left.cols), (right.alias, right.cols)),
        )

    # -- join planning ------------------------------------------------------

    # -- cardinality estimation (statistics_builder.go reduction) -----------

    def _source_stats(self, s: "Source"):
        if s.table is None:
            return None
        return getattr(self.catalog.get(s.table), "table_stats", None)

    def _estimate_source_rows(self, s: "Source", preds) -> int | None:
        """base_rows x the product of per-conjunct selectivities estimated
        from ANALYZE histograms (independence assumption, like the
        reference). None when the base table has no statistics."""
        st = self._source_stats(s)
        if st is None:
            return None
        frac = 1.0
        for p in preds:
            frac *= self._pred_fraction(st, p, s)
        return max(1, int(round(st.row_count * frac)))

    _DEFAULT_PRED_FRAC = 1.0 / 3.0  # unestimatable conjunct (reference's
    # unknown-selectivity constant is also 1/3, memo/statistics_builder.go)

    def _pred_fraction(self, st, p: P.Node, s: "Source") -> float:
        if isinstance(p, P.Cmp) and p.op in ("lt", "le", "gt", "ge", "eq"):
            col, lit, op = None, None, p.op
            if isinstance(p.left, P.Ident):
                col, lit = p.left, p.right
            elif isinstance(p.right, P.Ident):
                col, lit = p.right, p.left
                flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                        "eq": "eq"}
                op = flip[op]
            if col is not None and col.name in st.cols:
                v = self._literal_for_stats(lit, col.name, s)
                if v is not None:
                    return st.cols[col.name].cmp_fraction(op, v)
        if isinstance(p, P.Between) and isinstance(p.arg, P.Ident) \
                and p.arg.name in st.cols:
            lo = self._literal_for_stats(p.lo, p.arg.name, s)
            hi = self._literal_for_stats(p.hi, p.arg.name, s)
            if lo is not None and hi is not None:
                cs = st.cols[p.arg.name]
                f = max(0.0, cs.frac_le(hi) - cs.frac_le(lo - 1))
                return 1.0 - f if p.negated else f
        return self._DEFAULT_PRED_FRAC

    def _literal_for_stats(self, e: P.Node, col: str, s: "Source"):
        """Literal -> the RAW statistics domain (scaled DECIMALs, day
        counts) for column `col`, or None if not a literal."""
        from .session import NotALiteral, Session

        try:
            t = s.rel.type_of(col)
        except (KeyError, ValueError):
            return None
        try:
            v = Session._literal(_fold(e), t)
        except (NotALiteral, BindError):
            return None
        if v is None or isinstance(v, str):
            return None
        return int(v) if not isinstance(v, float) else int(round(v))

    def _col_ndv(self, s: "Source", pos: int, est: float) -> float:
        st = self._source_stats(s)
        if st is not None and pos < len(s.rel.schema.names):
            cs = st.cols.get(s.rel.schema.names[pos])
            if cs is not None and cs.ndv > 0:
                # a filtered source cannot have more distinct keys than rows
                return float(min(cs.ndv, max(1.0, est)))
        return max(1.0, est)  # unknown: assume keys ~unique (FK shape)

    def _join_sources(self, sources, equi_edges, scope) -> "BoundQuery":
        n = len(sources)
        if n == 1:
            colmap = {(0, p): p
                      for p in range(len(sources[0].rel.schema))}
            return BoundQuery(sources[0].rel, {0: sources[0]}, colmap)
        sizes = [
            s.est_rows if s.est_rows is not None else s.base_rows
            for s in sources
        ]
        from ..utils import settings as _settings

        if (_settings.get("sql.opt.join_order") == "cost"
                and 2 <= n <= 6):
            tree = self._dp_join_order(sources, equi_edges, sizes)
            if tree is not None:
                return self._build_join_tree(tree, sources, equi_edges)
        start = max(range(n), key=lambda i: sizes[i])
        placed = {start}
        rel = sources[start].rel
        colmap = {(start, p): p for p in range(len(rel.schema))}
        while len(placed) < n:
            # find edges from placed to unplaced, fully positional: probe
            # side through colmap, build side source-local
            cand: dict[int, list[tuple[int, int]]] = {}
            for li, lp, ri, rp in equi_edges:
                if li in placed and ri not in placed:
                    cand.setdefault(ri, []).append((colmap[(li, lp)], rp))
                elif ri in placed and li not in placed:
                    cand.setdefault(li, []).append((colmap[(ri, rp)], lp))
            if not cand:
                # no equi edge reaches the remaining sources: cartesian
                # product with the smallest one (crossJoiner role)
                nxt = min((i for i in range(n) if i not in placed),
                          key=lambda i: sizes[i])
                off = len(rel.schema)
                nb = len(sources[nxt].rel.schema)
                rel = rel.cross_join(sources[nxt].rel)
                for p in range(nb):
                    colmap[(nxt, p)] = off + p
                placed.add(nxt)
                continue
            # smallest build side first
            nxt = min(cand, key=lambda i: sizes[i])
            on = cand[nxt]  # (probe joined POSITION, build local POSITION)
            off = len(rel.schema)
            nb = len(sources[nxt].rel.schema)
            rel = rel.join(
                sources[nxt].rel, on=on, how="inner", build_unique=False
            )
            for p in range(nb):
                colmap[(nxt, p)] = off + p
            placed.add(nxt)
        return BoundQuery(rel, {i: sources[i] for i in placed}, colmap)

    def _dp_join_order(self, sources, equi_edges, sizes):
        """Selinger-style left-deep DP over the equi-join graph
        (opt/xform's JoinOrderBuilder reduced to reorder_joins_limit=6
        left-deep trees). State = subset of placed sources; value =
        (cost, est rows, order). Joining a connected source keeps
        max(rows, size) rows (the FK-join assumption the distributor's
        estimated_rows also makes); an unconnected source multiplies
        (cartesian). Cost = sum of intermediate result sizes. Returns the
        best order as an index tuple, or None to decline (missing
        estimates) so the caller falls back to the greedy heuristic."""
        n = len(sources)
        if any(sz is None for sz in sizes):
            return None
        adj = [set() for _ in range(n)]
        for li, _lp, ri, _rp in equi_edges:
            adj[li].add(ri)
            adj[ri].add(li)
        # best[mask] = (cost, rows, order)
        best: dict[int, tuple[float, float, tuple[int, ...]]] = {
            1 << i: (0.0, float(max(1, sizes[i])), (i,)) for i in range(n)
        }
        for mask in range(1, 1 << n):
            cur = best.get(mask)
            if cur is None or mask == (1 << n) - 1:
                continue
            cost, rows, order = cur
            connected = set()
            for i in order:
                connected |= adj[i]
            for j in range(n):
                if mask & (1 << j):
                    continue
                sj = float(max(1, sizes[j]))
                out = (max(rows, sj) if j in connected else rows * sj)
                cand = (cost + out, out, order + (j,))
                prev = best.get(mask | (1 << j))
                if prev is None or cand[0] < prev[0]:
                    best[mask | (1 << j)] = cand
        full = best.get((1 << n) - 1)
        return None if full is None else full[2]

    def _build_join_tree(self, order, sources, equi_edges) -> "BoundQuery":
        """Materialize a left-deep join in the DP's order: each step joins
        the next source on every equi edge reaching the placed prefix
        (positions resolved through colmap), or cross-joins when no edge
        reaches (the DP already priced that cartesian)."""
        n = len(sources)
        start = order[0]
        placed = {start}
        rel = sources[start].rel
        colmap = {(start, p): p for p in range(len(rel.schema))}
        for nxt in order[1:]:
            on = []  # (probe joined POSITION, build local POSITION)
            for li, lp, ri, rp in equi_edges:
                if li in placed and ri == nxt:
                    on.append((colmap[(li, lp)], rp))
                elif ri in placed and li == nxt:
                    on.append((colmap[(ri, rp)], lp))
            off = len(rel.schema)
            nb = len(sources[nxt].rel.schema)
            if on:
                rel = rel.join(sources[nxt].rel, on=on, how="inner",
                               build_unique=False)
            else:
                rel = rel.cross_join(sources[nxt].rel)
            for p in range(nb):
                colmap[(nxt, p)] = off + p
            placed.add(nxt)
        return BoundQuery(rel, {i: sources[i] for i in range(n)}, colmap)

    def _apply_sub_join(self, joined: "BoundQuery", node, negate, scope,
                        sources) -> "BoundQuery":
        if isinstance(node, P.InSelect):
            how = "anti" if (negate != node.negated) else "semi"
            sub = self.bind_subquery_for_in(node.select)
            arg = node.arg
            if not isinstance(arg, P.Ident):
                raise BindError("IN (SELECT) argument must be a column")
            resolver = self._make_resolver(scope, joined)
            outer_pos = (resolver(arg) if resolver is not None
                         else joined.rel.idx(arg.name))
            inner_col = sub.schema.names[0]
            if how == "anti":
                # NOT IN under three-valued logic: a NULL in the subquery
                # empties the output; a NULL probe key is not-true (dropped)
                # — EXCEPT against an empty subquery, where x NOT IN () is
                # TRUE for every x including NULL. A plain anti join gets
                # only the last case right. When bind-time analysis proves
                # both sides non-nullable, the anti join is exact; otherwise
                # evaluate the (uncorrelated) subquery once and pick the
                # branch, the way the reference's optbuilder wraps NOT IN in
                # null-rejecting projections (pkg/sql/opt/optbuilder).
                nullable = True
                try:
                    self._require_non_nullable(arg, scope, "NOT IN argument")
                    self._require_inner_non_nullable(node.select)
                    nullable = False
                except BindError:
                    pass
                if nullable:
                    # bind-time evaluation of the (uncorrelated) subquery —
                    # the same eager-execution precedent as scalar
                    # subqueries; the anti join below re-runs the sub plan,
                    # an accepted double execution for this rare shape
                    vals = sub.run()[inner_col]
                    n_sub = len(vals)
                    has_null = (vals.dtype == object
                                and any(v is None for v in vals))
                    if has_null:
                        # never-true — but keep the anti join in the plan
                        # (below) so the subquery's table scans stay
                        # visible to in-txn read-span tracking
                        joined.rel = joined.rel.filter(ex.lit(False))
                    elif n_sub > 0:
                        # drop NULL probe keys, then anti join
                        joined.rel = joined.rel.filter(
                            ex.Not(ex.IsNull(ex.ColRef(outer_pos)))
                        )
                    # empty subquery: plain anti join keeps every row
                    # (including NULL keys) — exactly NOT IN () = TRUE
            joined.rel = joined.rel.join(
                sub, on=[(outer_pos, inner_col)], how=how, build_unique=False
            )
            return joined
        how = "anti" if negate else "semi"
        if isinstance(node, P.Exists):
            # correlated equality conjuncts reference outer columns
            sub_sel = node.select
            inner_rel, corr, ne_pairs = self._bind_correlated(
                sub_sel, joined)
            resolver = self._make_resolver(scope, joined)

            def opos(ident: P.Ident) -> int:
                return (resolver(ident) if resolver is not None
                        else joined.rel.idx(ident.name))

            on_pos = [(opos(oid), iname) for oid, iname in corr]
            if not ne_pairs:
                joined.rel = joined.rel.join(
                    inner_rel, on=on_pos, how=how, build_unique=False
                )
                return joined
            # EXISTS with an extra `inner.s <> outer.s` correlation (TPC-H
            # q21): aggregate the inner per correlation key to (min s,
            # max s); some inner s differs from outer s iff min != s or
            # max != s. NOT EXISTS additionally keeps keys with no inner
            # rows (left join, NULL min). The reference reaches the same
            # plans through optbuilder's apply-decorrelation rules.
            if len(ne_pairs) != 1:
                raise BindError("at most one <> correlation supported")
            o_ident, i_name = ne_pairs[0]
            grouped = inner_rel.groupby(
                [ik for _, ik in corr],
                [("_mn", "min", i_name), ("_mx", "max", i_name)],
            )
            n0 = len(joined.rel.schema)
            names0 = joined.rel.schema.names
            s_pos = opos(o_ident)
            mn_pos = n0 + len(corr)
            mx_pos = mn_pos + 1
            if how == "semi":
                rel = joined.rel.join(grouped, on=on_pos, how="inner",
                                      build_unique=True)
                pred = ex.or_(
                    ex.Cmp("ne", ex.ColRef(mn_pos), ex.ColRef(s_pos)),
                    ex.Cmp("ne", ex.ColRef(mx_pos), ex.ColRef(s_pos)),
                )
            else:
                rel = joined.rel.join(grouped, on=on_pos, how="left",
                                      build_unique=True)
                pred = ex.or_(
                    ex.IsNull(ex.ColRef(mn_pos)),
                    ex.and_(
                        ex.Cmp("eq", ex.ColRef(mn_pos), ex.ColRef(s_pos)),
                        ex.Cmp("eq", ex.ColRef(mx_pos), ex.ColRef(s_pos)),
                    ),
                )
            rel = rel.filter(pred)
            joined.rel = rel.project(
                [(names0[i], ex.ColRef(i)) for i in range(n0)]
            )
            return joined
        raise BindError(f"unsupported subquery predicate {node}")

    @staticmethod
    def _or_common_equis(c: P.Node, scope: Scope):
        """Equi edges present in EVERY branch of an OR (hoistable to the
        join graph; the OR itself remains a residual filter)."""
        if not (isinstance(c, P.Bin) and c.op == "or"):
            return []

        def disjuncts(e):
            if isinstance(e, P.Bin) and e.op == "or":
                return disjuncts(e.left) + disjuncts(e.right)
            return [e]

        per_branch = []
        for b in disjuncts(c):
            eqs = set()
            for cj in _conjuncts(b):
                if (isinstance(cj, P.Cmp) and cj.op == "eq"
                        and isinstance(cj.left, P.Ident)
                        and isinstance(cj.right, P.Ident)):
                    try:
                        li, lp = scope.resolve(cj.left)
                        ri, rp = scope.resolve(cj.right)
                    except BindError:
                        continue
                    if li != ri:
                        key = ((li, lp), (ri, rp))
                        if key[0] > key[1]:
                            key = (key[1], key[0])
                        eqs.add(key)
            per_branch.append(eqs)
        common = set.intersection(*per_branch) if per_branch else set()
        return [(li, lp, ri, rp) for (li, lp), (ri, rp) in common]

    def _scalar_sub_is_correlated(self, sub: P.ScalarSubquery) -> bool:
        """True when the subquery references columns outside its own FROM."""
        try:
            inner_sources, _ = self._bind_from(sub.select.from_)
        except BindError:
            return False
        inner_scope = Scope(inner_sources)
        nodes = list(sub.select.items) + (
            [sub.select.where] if sub.select.where is not None else []
        )
        for n in nodes:
            for x in _walk(n):
                if isinstance(x, P.Ident):
                    try:
                        inner_scope.resolve(x)
                    except BindError:
                        return True
        return False

    def _join_corr_scalar(self, joined: "BoundQuery", scope: Scope,
                          sub: P.ScalarSubquery, how: str):
        """Shared decorrelation core: bind the subquery GROUPED BY its
        equality-correlation keys and join the group result onto the
        outer rel (`how`: inner for WHERE position — a missing key drops
        the row; left for SELECT position — a missing key yields a NULL
        scalar, row kept). Returns (rel, sub_pos, n_outer, outer_names).
        A bare (non-aggregate) inner column wraps in max(): exact when
        the correlation key is unique, a documented divergence from the
        reference's more-than-one-row runtime error otherwise."""
        sel2 = sub.select
        if len(sel2.items) != 1:
            raise BindError("scalar subquery must produce one column")
        inner_sources, jf2 = self._bind_from(sel2.from_)
        inner_scope = Scope(inner_sources)

        def is_inner(ident: P.Ident) -> bool:
            try:
                inner_scope.resolve(ident)
                return True
            except BindError:
                return False

        corr: list[tuple[P.Ident, P.Ident]] = []  # (outer, inner)
        inner_where: list[P.Node] = []
        for c in jf2 + [_fold(x) for x in _conjuncts(sel2.where)]:
            if (isinstance(c, P.Cmp) and c.op == "eq"
                    and isinstance(c.left, P.Ident)
                    and isinstance(c.right, P.Ident)):
                li, ri = is_inner(c.left), is_inner(c.right)
                if li and not ri:
                    corr.append((c.right, c.left))
                    continue
                if ri and not li:
                    corr.append((c.left, c.right))
                    continue
            for x in _walk(c):
                if isinstance(x, P.Ident) and not is_inner(x):
                    raise BindError(
                        "correlated scalar subquery supports only equality "
                        f"correlation (found outer ref {x.name})"
                    )
            inner_where.append(c)

        if not corr:
            raise BindError("scalar subquery correlation not found")

        item = sel2.items[0].expr
        if not any(isinstance(x, P.FuncCall) and x.name in AGG_FUNCS
                   for x in _walk(item)):
            # a bare (aggregate-free) item gets max() single-row
            # semantics; exact when the correlation key is unique (see
            # docstring divergence note)
            item = P.FuncCall("max", (item,))

        # rewritten inner AST: group by the correlation keys
        key_items = tuple(
            P.SelectItem(inner_id, alias=f"_ck{i}")
            for i, (_, inner_id) in enumerate(corr)
        )
        where2 = None
        for c in inner_where:
            where2 = c if where2 is None else P.Bin("and", where2, c)
        sel3 = P.Select(
            items=key_items + (P.SelectItem(item, alias="_sub"),),
            from_=sel2.from_,
            where=where2,
            group_by=tuple(inner_id for _, inner_id in corr),
            having=None, order_by=(), limit=None, offset=0,
            distinct=False,
        )
        grouped = self.bind(sel3)

        resolver = self._make_resolver(scope, joined)
        n_outer = len(joined.rel.schema)
        outer_names = joined.rel.schema.names
        on = [
            (resolver(outer_id) if resolver else
             joined.rel.idx(outer_id.name), f"_ck{i}")
            for i, (outer_id, _) in enumerate(corr)
        ]
        rel = joined.rel.join(grouped, on=on, how=how, build_unique=True)
        sub_pos = n_outer + len(corr)  # "_sub" column position
        return rel, sub_pos, n_outer, outer_names

    def _apply_corr_scalar(self, joined: "BoundQuery", conjunct: P.Node,
                           scope: Scope) -> "BoundQuery":
        """Decorrelate `expr CMP (select agg(...) from ... where inner.k =
        outer.k and ...)` — the reference's optbuilder/norm rules turn these
        into grouped joins (plan_opt.go); here the rewrite happens on the
        AST: bind the subquery GROUPED BY its correlation keys, inner-join
        the group result on the keys (group output is unique per key), then
        filter and project the helper columns away.

        Inner-join semantics are exactly SQL's: a key with no inner rows
        yields a NULL scalar, the comparison is not-true, the row drops."""
        # fold any UNCORRELATED subqueries in the conjunct to literals first
        # so the marker substitution below can only ever target the one
        # correlated subquery
        conjunct = self._replace_scalar_subqueries(conjunct)
        subs = [x for x in _walk(conjunct)
                if isinstance(x, P.ScalarSubquery)]
        if len(subs) != 1:
            raise BindError(
                "at most one correlated scalar subquery per predicate"
            )
        sub = subs[0]
        rel, sub_pos, n_outer, outer_names = self._join_corr_scalar(
            joined, scope, sub, how="inner"
        )
        resolver = self._make_resolver(scope, joined)

        # lower the conjunct with the subquery replaced by the joined column
        marker = P.Ident("__corr__", "_sub")

        def replace(e: P.Node) -> P.Node:
            if isinstance(e, P.ScalarSubquery):
                return marker
            if isinstance(e, P.Cmp):
                return P.Cmp(e.op, replace(e.left), replace(e.right))
            if isinstance(e, P.Bin):
                return P.Bin(e.op, replace(e.left), replace(e.right))
            if isinstance(e, P.Not):
                return P.Not(replace(e.arg))
            return e

        def resolve2(ident: P.Ident) -> int:
            if ident is marker or (ident.table == "__corr__"):
                return sub_pos
            if resolver is not None:
                return resolver(ident)
            return joined.rel.idx(ident.name)

        lower = ExprLowerer(rel, resolver=resolve2)
        rel = rel.filter(lower.lower(replace(conjunct)))
        # project the helper columns away, restoring original positions
        rel = rel.project(
            [(outer_names[i], ex.ColRef(i)) for i in range(n_outer)]
        )
        return BoundQuery(rel, joined.sources, joined.colmap)

    def bind_subquery_for_in(self, sel: P.Select) -> Rel:
        rel = self.bind(sel)
        if len(rel.schema) != 1:
            raise BindError("IN subquery must produce one column")
        return rel

    def _base_col_non_nullable(self, table: str, col: str) -> bool:
        """Whether a base-table column provably holds no NULLs. Host tables
        are static preloaded data, so inspecting the valid bitmap is sound;
        KV-backed tables expose no host bitmap (nullability is decoded on
        device) and conservatively report nullable."""
        valids = getattr(self.catalog.get(table), "valids", None)
        if valids is None:
            return False
        v = valids.get(col)
        return v is None or bool(np.asarray(v).all())

    def _require_non_nullable(self, ident: P.Ident, scope, what: str) -> None:
        i, pos = scope.resolve(ident)
        name = scope.name_of(i, pos)
        src = scope.sources[i]
        if src.table is None or not self._base_col_non_nullable(
            src.table, name
        ):
            raise BindError(
                f"{what} {ident.name} may be NULL; NOT IN over nullable "
                "columns is not supported (three-valued NOT IN semantics)"
            )

    def _require_inner_non_nullable(self, sel: P.Select) -> None:
        """Prove the single output column of a NOT IN subquery non-nullable:
        a plain column of a single base table with an all-valid bitmap."""
        items = sel.from_
        ok = (
            len(items) == 1 and isinstance(items[0], P.TableRef)
            and len(sel.items) == 1
            and isinstance(sel.items[0].expr, P.Ident)
            and self._base_col_non_nullable(
                items[0].name, sel.items[0].expr.name
            )
        )
        if not ok:
            raise BindError(
                "NOT IN subquery column may be NULL; NOT IN over nullable "
                "columns is not supported (three-valued NOT IN semantics)"
            )

    def _bind_correlated(self, sel: P.Select, joined: "BoundQuery"):
        """Bind an EXISTS subquery: conjuncts of its WHERE that are
        equality with an outer column become the semi-join keys."""
        inner_sources, jf = self._bind_from(sel.from_)
        if len(inner_sources) != 1:
            raise BindError("correlated EXISTS supports one inner table")
        inner = inner_sources[0]
        outer_names = set(joined.rel.schema.names)

        def side(ident: P.Ident) -> str:
            """'inner' | 'outer' for one identifier, honoring qualifiers.
            An unqualified name present on both sides is ambiguous."""
            if ident.table is not None:
                if ident.table == inner.alias:
                    return "inner"
                return "outer"
            inn = ident.name in inner.cols
            out = ident.name in outer_names
            if inn and out:
                raise BindError(
                    f"ambiguous correlated column {ident.name}: qualify it"
                )
            if inn:
                return "inner"
            if out:
                return "outer"
            raise BindError(f"unknown column {ident.name}")

        # pairs carry the outer IDENT (not its bare name): resolution to a
        # joined-schema position must honor qualifiers, or a self-joined
        # outer table would silently bind the wrong duplicate column
        corr: list[tuple[P.Ident, str]] = []
        ne_pairs: list[tuple[P.Ident, str]] = []
        inner_preds: list[P.Node] = []
        for c in jf + [(_fold(x)) for x in _conjuncts(sel.where)]:
            if (isinstance(c, P.Cmp) and c.op in ("eq", "ne")
                    and isinstance(c.left, P.Ident)
                    and isinstance(c.right, P.Ident)):
                ls, rs = side(c.left), side(c.right)
                pair = None
                if ls == "inner" and rs == "outer":
                    pair = (c.right, c.left.name)
                elif rs == "inner" and ls == "outer":
                    pair = (c.left, c.right.name)
                if pair is not None:
                    (corr if c.op == "eq" else ne_pairs).append(pair)
                    continue
            # any other predicate must be purely inner; an outer reference
            # here is a correlation shape the semi-join rewrite can't express
            for x in _walk(c):
                if isinstance(x, P.Ident) and side(x) == "outer":
                    raise BindError(
                        "correlated non-equality predicate "
                        f"({x.table or ''}.{x.name}) not supported"
                    )
            inner_preds.append(c)
        rel = inner.rel
        for p in inner_preds:
            rel = rel.filter(ExprLowerer(rel).lower(p))
        if not corr:
            raise BindError("uncorrelated EXISTS not supported")
        return rel, corr, ne_pairs

    def _lower_with_subqueries(self, lower: ExprLowerer, c: P.Node) -> ex.Expr:
        """Lower a predicate, executing uncorrelated scalar subqueries into
        literals first (the one-row result is a plan-time constant)."""
        c = self._replace_scalar_subqueries(c)
        return lower.lower(c)

    def _replace_scalar_subqueries(self, c: P.Node) -> P.Node:
        if isinstance(c, P.ScalarSubquery):
            if self._scalar_sub_is_correlated(c):
                return c  # handled by _apply_corr_scalar
            rel = self.bind(c.select)
            res = rel.run()
            if len(rel.schema) != 1:
                raise BindError("scalar subquery must produce one column")
            col = res[rel.schema.names[0]]
            if len(col) == 0:
                return P.NullLit()  # empty scalar subquery IS NULL
            if len(col) != 1:
                raise BindError("scalar subquery returned more than one row")
            v = col[0]
            if isinstance(v, (str, bytes)):
                return P.StrLit(v if isinstance(v, str) else v.decode())
            if np.asarray(v).dtype.kind in "iu":
                return P.NumLit(int(v))
            return P.NumLit(float(v))
        if isinstance(c, P.Cmp):
            return P.Cmp(c.op, self._replace_scalar_subqueries(c.left),
                         self._replace_scalar_subqueries(c.right))
        if isinstance(c, P.Bin):
            return P.Bin(c.op, self._replace_scalar_subqueries(c.left),
                         self._replace_scalar_subqueries(c.right))
        if isinstance(c, P.Not):
            return P.Not(self._replace_scalar_subqueries(c.arg))
        return c

    # -- SELECT list / aggregation / ordering -------------------------------

    def _finish(self, sel: P.Select, rel: Rel, resolver=None) -> Rel:
        has_agg = (
            bool(sel.group_by)
            or any(_has_agg(it.expr) for it in sel.items)
            or (sel.having is not None and _has_agg(sel.having))
        )
        window_names = None
        if any(isinstance(it.expr, P.WindowCall) for it in sel.items):
            if has_agg:
                raise BindError(
                    "window functions over aggregated results are not "
                    "supported in this build"
                )
            rel, window_names = self._apply_windows(sel, rel, resolver)
        if has_agg:
            rel = self._aggregate(sel, rel, resolver)
        else:
            rel = self._project(sel, rel, resolver,
                                window_names=window_names)
        if sel.distinct:
            rel = rel.distinct()
        rel = self._order_limit(sel, rel)
        return rel

    _WINDOW_ONLY = {"row_number", "rank", "dense_rank", "ntile",
                    "percent_rank", "cume_dist", "lag", "lead",
                    "first_value", "last_value"}
    _WINDOW_AGGS = {"sum", "count", "min", "max", "avg"}

    def _apply_windows(self, sel: P.Select, rel: Rel, resolver):
        """Append one column per top-level OVER item (colexecwindow via
        Rel.window); returns (rel, {id(WindowCall) -> appended name}).

        Scope (documented reductions): window calls are top-level SELECT
        items; PARTITION BY / ORDER BY / function arguments are plain
        columns; the default frame with ORDER BY is ROWS UNBOUNDED
        PRECEDING..CURRENT ROW (the reference's RANGE default differs on
        ties)."""
        lower = ExprLowerer(rel, resolver=resolver)

        def colname(e: P.Node, what: str) -> str:
            le = lower.lower(e)
            if not isinstance(le, ex.ColRef):
                raise BindError(
                    f"window {what} must be a plain column in this build"
                )
            return rel.schema.names[le.idx]

        # group calls by their window (partition, order, frame) so each
        # distinct window sorts once
        groups: dict[tuple, list] = {}
        names: dict[int, str] = {}
        used = set(rel.schema.names)
        for it in sel.items:
            wc = it.expr
            if not isinstance(wc, P.WindowCall):
                continue
            func = wc.func.name.lower()
            if func not in self._WINDOW_ONLY | self._WINDOW_AGGS:
                raise BindError(f"unknown window function {func}()")
            if wc.func.distinct:
                raise BindError(
                    f"{func}(DISTINCT ...) OVER is not supported"
                )
            parts = tuple(colname(e, "PARTITION BY") for e in wc.partition_by)
            order = tuple(
                (colname(e, "ORDER BY"), desc) for e, desc in wc.order_by
            )
            frame = wc.frame
            default_kind = "rows"
            if not wc.has_frame_clause and func in (
                self._WINDOW_AGGS | {"first_value", "last_value"}
            ):
                # SQL default: cumulative with ORDER BY, whole partition
                # without. The true default is RANGE UNBOUNDED PRECEDING
                # .. CURRENT ROW (peer-INCLUSIVE); the range kernel needs
                # a single numeric order key, so that shape gets the exact
                # semantics and everything else keeps the ROWS reduction
                # (divergence only for ties on string/multi-key orders)
                frame = (None, 0) if order else None
                if order and len(order) == 1:
                    i = rel.idx(order[0][0])
                    from ..coldata.types import Family as _F

                    if rel.schema.types[i].family in (
                            _F.INT, _F.FLOAT, _F.DECIMAL, _F.DATE):
                        default_kind = "range"
            arg = None
            offset = 1
            if func in ("lag", "lead"):
                if not wc.func.args:
                    raise BindError(f"{func}() needs a column argument")
                arg = colname(wc.func.args[0], "argument")
                if len(wc.func.args) > 2:
                    raise BindError(
                        f"{func}() default-value argument is not "
                        "supported (NULL is returned past the edge)"
                    )
                if len(wc.func.args) > 1:
                    a = wc.func.args[1]
                    if not isinstance(a, P.NumLit):
                        raise BindError(
                            f"{func}() offset must be a literal")
                    offset = int(a.value)
            elif func == "ntile":
                if not (wc.func.args
                        and isinstance(wc.func.args[0], P.NumLit)):
                    raise BindError("ntile() needs a literal bucket count")
                offset = int(wc.func.args[0].value)
            elif func in self._WINDOW_AGGS or func in ("first_value",
                                                       "last_value"):
                if func == "count" and (
                    not wc.func.args
                    or isinstance(wc.func.args[0], P.Star)
                ):
                    arg = None
                else:
                    if not wc.func.args:
                        raise BindError(f"{func}() needs an argument")
                    arg = colname(wc.func.args[0], "argument")
            out = it.alias or func
            while out in used:
                out = f"_{out}w"
            used.add(out)
            names[id(wc)] = out
            fkind = wc.frame_kind if wc.has_frame_clause else default_kind
            if fkind == "groups" and wc.has_frame_clause and not order:
                raise BindError("GROUPS mode requires an ORDER BY clause")
            if fkind == "range" and wc.has_frame_clause:
                # Postgres rule: RANGE with offsets needs exactly one
                # NUMERIC ORDER BY key; peer-only frames (UNBOUNDED /
                # CURRENT ROW bounds) work for any order-key shape
                if any(b not in (None, 0) for b in (wc.frame or ())):
                    if len(order) != 1:
                        raise BindError(
                            "RANGE frame with offsets requires exactly "
                            "one ORDER BY key"
                        )
                    from ..coldata.types import Family as _F

                    fam = rel.schema.types[rel.idx(order[0][0])].family
                    if fam not in (_F.INT, _F.FLOAT, _F.DECIMAL, _F.DATE):
                        raise BindError(
                            "RANGE frame offsets require a numeric "
                            f"ORDER BY key, got {fam.name}"
                        )
            excl = wc.exclude if wc.has_frame_clause else "no_others"
            if excl == "ties" and func in ("first_value", "last_value"):
                raise BindError(
                    "EXCLUDE TIES with first_value/last_value is not "
                    "supported"
                )
            groups.setdefault((parts, order, frame, fkind, excl),
                              []).append((out, func, arg, offset))
        for (parts, order, frame, fkind, excl), funcs in groups.items():
            rel = rel.window(list(parts), list(order), funcs, frame=frame,
                             frame_kind=fkind, exclude=excl)
        return rel, names

    def _project(self, sel: P.Select, rel: Rel, resolver=None,
                 window_names=None) -> Rel:
        items: list[tuple[str, ex.Expr]] = []
        expr_names: dict[P.Node, str] = {}
        used: set[str] = set()
        lower = ExprLowerer(rel, resolver=resolver)
        dict_attach: list[tuple[str, object]] = []
        for it in sel.items:
            if isinstance(it.expr, P.Star):
                for n in rel.schema.names:
                    if window_names and n in set(window_names.values()):
                        continue  # window outputs are not part of *
                    items.append((self._uniq(n, used), ex.ColRef(rel.idx(n))))
                continue
            name = self._uniq(
                it.alias or self._default_name(it.expr, len(items)), used
            )
            if window_names is not None and id(it.expr) in window_names:
                # the window column was appended by _apply_windows
                items.append(
                    (name, ex.ColRef(rel.idx(window_names[id(it.expr)])))
                )
                expr_names[it.expr] = name
                continue
            st = self._string_transform(rel, it.expr, lower)
            if st is not None:
                expr, d = st
                items.append((name, expr))
                dict_attach.append((name, d))
            else:
                items.append((name, lower.lower(it.expr)))
            expr_names[it.expr] = name
        # resolve ORDER BY to output columns, adding hidden ones as needed
        hidden: list[tuple[str, ex.Expr]] = []
        order_keys: list[tuple[str, bool]] = []
        for o in sel.order_by:
            if o.expr in expr_names:
                order_keys.append((expr_names[o.expr], o.desc))
            elif isinstance(o.expr, P.NumLit):
                order_keys.append((_positional(items, o.expr), o.desc))
            elif (isinstance(o.expr, P.Ident)
                  and o.expr.name in {n for n, _ in items}):
                order_keys.append((o.expr.name, o.desc))
            elif (isinstance(o.expr, P.Ident)
                  and o.expr.name in rel.schema.names):
                hn = self._uniq(o.expr.name, used)
                hidden.append((hn, ex.ColRef(rel.idx(o.expr.name))))
                order_keys.append((hn, o.desc))
            else:
                raise BindError(f"cannot order by {o.expr}")
        proj = rel.project(items + hidden)
        for name, d in dict_attach:
            proj = proj.with_dict(name, d)
        proj._visible = len(items)  # order_limit projects hidden cols away
        proj._order_keys = order_keys
        return proj

    @staticmethod
    def _string_transform(rel: Rel, e: P.Node, lower: ExprLowerer):
        """String-valued functions of a STRING column (substring) — host-
        evaluated per dictionary entry, a code-remap gather on device.
        Returns (expr, Dictionary) or None."""
        if not (isinstance(e, P.FuncCall) and len(e.args) >= 1
                and isinstance(e.args[0], P.Ident)):
            return None
        def _lit(k):
            a = _fold(e.args[k])  # folds unary minus / literal arithmetic
            if isinstance(a, P.StrLit):
                return a.value
            if isinstance(a, P.NumLit):
                return a.value
            raise BindError(f"{e.name}: argument {k + 1} must be a literal")

        def _initcap(s: str) -> str:
            out, start = [], True
            for ch in s:
                out.append(ch.upper() if start else ch.lower())
                start = not ch.isalnum()
            return "".join(out)

        if e.name == "substring" and len(e.args) == 3:
            start = int(e.args[1].value) - 1
            n = int(e.args[2].value)
            fn = lambda s: s[start:start + n]  # noqa: E731
        elif e.name in ("upper", "lower") and len(e.args) == 1:
            fn = (str.upper if e.name == "upper" else str.lower)
        elif e.name in ("trim", "btrim") and len(e.args) <= 2:
            chars = str(_lit(1)) if len(e.args) == 2 else None
            fn = lambda s: s.strip(chars)  # noqa: E731
        elif e.name in ("ltrim", "rtrim") and len(e.args) <= 2:
            chars = str(_lit(1)) if len(e.args) == 2 else None
            strip = str.lstrip if e.name == "ltrim" else str.rstrip
            fn = lambda s: strip(s, chars)  # noqa: E731
        elif e.name == "replace" and len(e.args) == 3:
            old, new = str(_lit(1)), str(_lit(2))
            fn = lambda s: s.replace(old, new)  # noqa: E731
        elif e.name == "initcap" and len(e.args) == 1:
            fn = _initcap
        elif e.name == "reverse" and len(e.args) == 1:
            fn = lambda s: s[::-1]  # noqa: E731
        elif e.name in ("lpad", "rpad") and len(e.args) in (2, 3):
            width = int(_lit(1))
            fill = str(_lit(2)) if len(e.args) == 3 else " "
            left = e.name == "lpad"

            def fn(s, width=width, fill=fill, left=left):
                if width <= 0:
                    return ""  # postgres: non-positive width pads to empty
                if len(s) >= width:
                    return s[:width]
                pad = (fill * width)[: width - len(s)] if fill else ""
                return pad + s if left else s + pad
        elif e.name in ("left", "right") and len(e.args) == 2:
            n = int(_lit(1))
            # python slicing matches Postgres for negative n too:
            # left(s,-2) drops the last 2, right(s,-2) drops the first 2
            if e.name == "left":
                fn = lambda s: s[:n]  # noqa: E731
            else:
                fn = lambda s: s[-n:] if n else ""  # noqa: E731
        elif e.name == "repeat" and len(e.args) == 2:
            n = int(_lit(1))
            fn = lambda s: s * max(n, 0)  # noqa: E731
        elif e.name == "split_part" and len(e.args) == 3:
            delim, field_n = str(_lit(1)), int(_lit(2))

            def fn(s, delim=delim, field_n=field_n):
                parts = s.split(delim) if delim else [s]
                return parts[field_n - 1] if 1 <= field_n <= len(parts) \
                    else ""
        elif e.name == "translate" and len(e.args) == 3:
            src, dst = str(_lit(1)), str(_lit(2))
            tbl = {ord(c): (dst[i] if i < len(dst) else None)
                   for i, c in enumerate(src)}
            fn = lambda s: s.translate(tbl)  # noqa: E731
        elif e.name == "md5" and len(e.args) == 1:
            import hashlib

            fn = lambda s: hashlib.md5(s.encode()).hexdigest()  # noqa: E731
        elif e.name == "concat" and len(e.args) >= 1:
            suffix = "".join(str(_lit(k)) for k in range(1, len(e.args)))
            fn = lambda s: s + suffix  # noqa: E731
        else:
            return None
        i = lower.idx(e.args[0])
        if rel.schema.types[i].family is not Family.STRING:
            return None
        from ..coldata.batch import Dictionary
        from ..coldata.types import STRING

        d = rel.dicts[i]
        mapped = np.array([fn(str(v)) for v in d.values],
                          dtype=object)
        if len(mapped):
            uvals, codes = np.unique(mapped.astype(str), return_inverse=True)
            table = codes.astype(np.int32)
        else:
            uvals = np.array([], dtype=object)
            table = np.zeros(1, np.int32)
        return (ex.CodeLookup(col=i, table=table, out_type=STRING),
                Dictionary(uvals.astype(object)))

    def _aggregate(self, sel: P.Select, rel: Rel, resolver=None) -> Rel:
        # 1. collect aggregate calls across SELECT + HAVING + ORDER BY
        aggs: dict[P.FuncCall, str] = {}

        def collect(e: P.Node):
            for x in _walk(e):
                if isinstance(x, P.FuncCall) and x.name in AGG_FUNCS:
                    if x not in aggs:
                        aggs[x] = f"_agg{len(aggs)}"

        for it in sel.items:
            collect(it.expr)
        if sel.having is not None:
            collect(sel.having)
        for o in sel.order_by:
            collect(o.expr)

        # 2. group keys: group_by exprs; give names. A bare name that is a
        # select alias (and not an input column) refers to that expression
        alias_map = {it.alias: it.expr for it in sel.items if it.alias}
        group_items: list[tuple[str, P.Node]] = []
        for g in sel.group_by:
            if (isinstance(g, P.Ident) and g.table is None
                    and g.name not in rel.schema.names
                    and g.name in alias_map):
                group_items.append((g.name, alias_map[g.name]))
            elif isinstance(g, P.Ident):
                group_items.append((g.name, g))
            else:
                # find a select alias with the same expression
                alias = None
                for it in sel.items:
                    if it.expr == g and it.alias:
                        alias = it.alias
                if alias is None:
                    alias = f"_g{len(group_items)}"
                group_items.append((alias, g))

        # 3. pre-projection: group keys + agg inputs
        lower = ExprLowerer(rel, resolver=resolver)
        pre: list[tuple[str, ex.Expr]] = []
        for name, g in group_items:
            pre.append((name, lower.lower(g)))
        agg_specs: list[tuple[str, str, str | None]] = []
        distinct_aggs = [fc for fc in aggs if fc.distinct]
        if distinct_aggs:
            # DISTINCT aggregates: dedupe (group keys, arg) first, then
            # aggregate the deduped rows (the reference plans these as a
            # distinct stage under the aggregator). All distinct aggs must
            # share one argument for the single-dedupe rewrite to be sound.
            args = {fc.args[0] for fc in distinct_aggs}
            if len(args) > 1 or len(distinct_aggs) != len(aggs):
                raise BindError(
                    "DISTINCT aggregates must all share one argument and "
                    "cannot mix with plain aggregates"
                )
            in_name = "_distinct_in"
            pre.append((in_name, lower.lower(next(iter(args)))))
            for fc, name in aggs.items():
                if fc.name not in ("count", "sum", "min", "max", "avg"):
                    raise BindError(
                        f"DISTINCT {fc.name} not supported"
                    )
                agg_specs.append((name, fc.name, in_name))
            rel2 = rel.project(pre).distinct()
        else:
            for fc, name in aggs.items():
                func = _AGG_CANON.get(fc.name, fc.name)
                if func == "count" and (
                    not fc.args or isinstance(fc.args[0], P.Star)
                ):
                    agg_specs.append((name, "count_rows", None))
                    continue
                in_name = f"{name}_in"
                pre.append((in_name, lower.lower(fc.args[0])))
                if func == "string_agg":
                    if not group_items:
                        raise BindError(
                            "string_agg without GROUP BY is not supported"
                        )
                    sep = ","
                    if len(fc.args) > 1:
                        a = fc.args[1]
                        if not isinstance(a, P.StrLit):
                            raise BindError(
                                "string_agg separator must be a string "
                                "literal"
                            )
                        sep = a.value
                    agg_specs.append((name, func, in_name, sep))
                    continue
                agg_specs.append((name, func, in_name))
            rel2 = rel.project(pre)
        if group_items:
            g = rel2.groupby([n for n, _ in group_items], agg_specs)
        else:
            g = rel2.scalar_agg(agg_specs)

        # 4. HAVING (uncorrelated scalar subqueries fold to literals first)
        if sel.having is not None:
            having = self._replace_scalar_subqueries(sel.having)
            g = g.filter(self._lower_agg_expr(g, having, aggs, group_items))

        # 5. post-projection for the SELECT list
        post: list[tuple[str, ex.Expr]] = []
        expr_names: dict[P.Node, str] = {}
        used: set[str] = set()
        gnames = {n for n, _ in group_items}
        for it in sel.items:
            name = self._uniq(
                it.alias or self._default_name(it.expr, len(post)), used
            )
            if name in gnames:  # aliased group key: already a groupby column
                post.append((name, ex.ColRef(g.idx(name))))
            else:
                post.append((name, self._lower_agg_expr(
                    g, it.expr, aggs, group_items)))
            expr_names[it.expr] = name
        out_names = {n for n, _ in post}
        hidden: list[tuple[str, ex.Expr]] = []
        order_keys: list[tuple[str, bool]] = []
        for o in sel.order_by:
            if o.expr in expr_names:
                order_keys.append((expr_names[o.expr], o.desc))
            elif isinstance(o.expr, P.NumLit):
                order_keys.append((_positional(post, o.expr), o.desc))
            elif isinstance(o.expr, P.Ident) and o.expr.name in out_names:
                order_keys.append((o.expr.name, o.desc))
            elif (isinstance(o.expr, P.Ident)
                  and o.expr.name in g.schema.names):
                hn = self._uniq(o.expr.name, used)
                hidden.append((hn, ex.ColRef(g.idx(o.expr.name))))
                order_keys.append((hn, o.desc))
            elif isinstance(o.expr, P.FuncCall) and o.expr in aggs:
                # an aggregate ordered by but not selected: hidden column
                nm = self._uniq(aggs[o.expr], used)
                hidden.append((nm, ex.ColRef(g.idx(aggs[o.expr]))))
                order_keys.append((nm, o.desc))
            else:
                raise BindError(f"cannot order by {o.expr}")
        proj = g.project(post + hidden)
        proj._visible = len(post)
        proj._order_keys = order_keys
        return proj

    def _lower_agg_expr(self, g: Rel, e: P.Node, aggs, group_items,
                        name_ok: bool = False) -> ex.Expr:
        """Lower an expression over the groupby output: aggregate calls become
        references to their output columns, and any (sub)expression that IS a
        group-by expression references its group column (GROUP BY b * 2 with
        SELECT b * 2 must read the computed key, not re-derive it from
        columns the groupby output no longer carries)."""
        e = _fold(e)
        for gname, gexpr in group_items:
            if e == gexpr:
                return ex.ColRef(g.idx(gname))
        if isinstance(e, P.FuncCall) and e.name in AGG_FUNCS:
            return ex.ColRef(g.idx(aggs[e]))
        if isinstance(e, P.Ident):
            return ex.ColRef(g.idx(e.name))
        if isinstance(e, P.Bin) and e.op in ("and", "or"):
            return ex.BoolOp(e.op, (
                self._lower_agg_expr(g, e.left, aggs, group_items),
                self._lower_agg_expr(g, e.right, aggs, group_items),
            ))
        if isinstance(e, P.Bin):
            return ex.BinOp(e.op,
                            self._lower_agg_expr(g, e.left, aggs, group_items),
                            self._lower_agg_expr(g, e.right, aggs, group_items))
        if isinstance(e, P.Cmp):
            return ex.Cmp(e.op,
                          self._lower_agg_expr(g, e.left, aggs, group_items),
                          self._lower_agg_expr(g, e.right, aggs, group_items))
        if isinstance(e, P.NumLit):
            if isinstance(e.value, int):
                return ex.lit(int(e.value))
            return ex.Const(float(e.value), FLOAT64)
        # fall back to plain lowering over the groupby schema (strings etc.)
        return ExprLowerer(g).lower(e)

    def _default_name(self, e: P.Node, i: int) -> str:
        if isinstance(e, P.Ident):
            return e.name
        if isinstance(e, P.FuncCall):
            return e.name
        if isinstance(e, P.WindowCall):
            return e.func.name
        return f"col{i}"

    @staticmethod
    def _uniq(name: str, used: set[str]) -> str:
        out = name
        k = 1
        while out in used:
            out = f"{name}_{k}"
            k += 1
        used.add(out)
        return out

    def _order_limit(self, sel: P.Select, rel: Rel) -> Rel:
        visible = getattr(rel, "_visible", None)
        order_keys = getattr(rel, "_order_keys", None)
        if sel.order_by:
            if order_keys is None:  # e.g. DISTINCT re-wrapped the projection
                order_keys = []
                for o in sel.order_by:
                    if (isinstance(o.expr, P.Ident)
                            and o.expr.name in rel.schema.names):
                        order_keys.append((o.expr.name, o.desc))
                    elif isinstance(o.expr, P.NumLit):
                        order_keys.append(
                            (_positional(rel.schema.names, o.expr), o.desc))
                    else:
                        raise BindError(f"cannot order by {o.expr}")
            rel = rel.sort(order_keys)
        if sel.limit is not None or sel.offset:
            # OFFSET without LIMIT: a sentinel that stays inside the int32
            # row-position arithmetic of the limit operator
            limit = sel.limit if sel.limit is not None else (1 << 30)
            rel = rel.limit(limit, sel.offset)
        if visible is not None and visible < len(rel.schema):
            rel = rel.select(*rel.schema.names[:visible])
        return rel


@dataclass
class BoundQuery:
    rel: Rel
    sources: dict[int, Source]
    # (source index, column name) -> position in rel's joined schema. The
    # only sound resolution once self-joins duplicate column names.
    colmap: dict[tuple[int, str], int] | None = None


def sql(catalog: Catalog, text: str) -> Rel:
    """Parse + bind a SELECT statement into an executable Rel."""
    return Binder(catalog).bind(P.parse(text))
