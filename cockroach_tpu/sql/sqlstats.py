"""SQL statement statistics — the pkg/sql/sqlstats reduction.

Reference: every executed statement is fingerprinted (literals stripped),
and per-fingerprint execution counts, latency moments and row counts
accumulate in an in-memory container surfaced through
crdb_internal.statement_statistics and the console's SQL activity page.

Reduction: a per-Session (or shared) registry keyed by statement
fingerprint with count / total / min / max / mean latency and rows
returned, surfaced through ``SHOW STATEMENTS`` in the session and the
``/_status/statements`` admin endpoint. Fingerprinting lowercases
whitespace-normalized SQL and replaces literals with placeholders — the
reference's constants-removed shape."""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..utils import locks

_NUM = re.compile(r"\b\d+(?:\.\d+)?\b")
_STR = re.compile(r"'(?:[^']|'')*'")
_WS = re.compile(r"\s+")
# collapse IN/VALUES lists so differing row counts share a fingerprint
_TUPLES = re.compile(r"\(\s*_(?:\s*,\s*_)*\s*\)(?:\s*,\s*\(\s*_(?:\s*,\s*_)*\s*\))*")


def fingerprint(sql: str) -> str:
    """Literals -> '_', whitespace-normalized, lowercased (the
    reference's statement fingerprint shape)."""
    s = _STR.sub("_", sql.strip().rstrip(";"))
    s = _NUM.sub("_", s)
    s = _WS.sub(" ", s).lower()
    s = _TUPLES.sub("(_)", s)
    return s


# fixed log-scale latency buckets: 0.1ms doubling to ~52s; observations
# past the last edge land in the overflow slot. Fixed — not adaptive — so
# percentiles from two snapshots are comparable.
_LAT_BUCKETS: tuple[float, ...] = tuple(0.0001 * 2 ** i for i in range(20))

# fixed log-scale peak-memory buckets: 4 KiB doubling to 8 GiB — the
# per-fingerprint resource twin of the latency histogram, so statement
# pages can show p50/p99 peak HBM next to p50/p99 latency
_MEM_BUCKETS: tuple[float, ...] = tuple(float(4096 * 2 ** i)
                                        for i in range(22))


@dataclass
class StmtStats:
    fingerprint: str
    count: int = 0
    total_s: float = 0.0
    min_s: float = field(default=float("inf"))
    max_s: float = 0.0
    rows: int = 0
    errors: int = 0
    hist: list[int] = field(
        default_factory=lambda: [0] * (len(_LAT_BUCKETS) + 1))
    # query peak-memory accounting (monitor-tree high water per execution);
    # mem_count tracks executions that reported a peak (older recordings
    # and error paths may not), so percentiles stay truthful
    max_mem_bytes: int = 0
    spills: int = 0
    mem_count: int = 0
    mem_hist: list[int] = field(
        default_factory=lambda: [0] * (len(_MEM_BUCKETS) + 1))

    @property
    def mean_s(self) -> float:
        return self.total_s / self.count if self.count else 0.0

    def observe(self, elapsed_s: float) -> None:
        import bisect

        self.hist[bisect.bisect_left(_LAT_BUCKETS, elapsed_s)] += 1

    def observe_mem(self, peak_bytes: int) -> None:
        import bisect

        self.mem_count += 1
        self.max_mem_bytes = max(self.max_mem_bytes, int(peak_bytes))
        self.mem_hist[bisect.bisect_left(_MEM_BUCKETS,
                                         float(peak_bytes))] += 1

    def percentile(self, q: float) -> float:
        """Latency quantile in seconds from the bucket counts (upper bucket
        edge — the prometheus histogram_quantile convention, clamped to the
        observed max)."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.hist):
            seen += c
            if seen >= target:
                edge = (_LAT_BUCKETS[i] if i < len(_LAT_BUCKETS)
                        else self.max_s)
                return min(edge, self.max_s)
        return self.max_s

    def percentile_mem(self, q: float) -> float:
        """Peak-memory quantile in bytes (same convention as
        :meth:`percentile`, clamped to the observed max peak)."""
        if not self.mem_count:
            return 0.0
        target = q * self.mem_count
        seen = 0
        for i, c in enumerate(self.mem_hist):
            seen += c
            if seen >= target:
                edge = (_MEM_BUCKETS[i] if i < len(_MEM_BUCKETS)
                        else float(self.max_mem_bytes))
                return min(edge, float(self.max_mem_bytes))
        return float(self.max_mem_bytes)


class StatsRegistry:
    """Thread-safe per-fingerprint accumulation, capped like the
    reference's fingerprint memory budget: past `max_fingerprints`
    distinct entries, the cheapest half (by total time) is evicted —
    unbounded junk SQL over pgwire must not leak memory forever."""

    def __init__(self, max_fingerprints: int = 5000):
        self._lock = locks.lock("sql.stats")
        self._stats: dict[str, StmtStats] = {}
        self.max_fingerprints = max_fingerprints
        self.evicted = 0

    def record(self, sql: str, elapsed_s: float, rows: int,
               error: bool = False, fp: str | None = None,
               mem_bytes: int = 0, spills: int = 0) -> None:
        """Accumulate one execution. ``fp`` lets the plan cache supply the
        structural fingerprint of the entry that served the statement (its
        literal re-parameterization already proved `a=1` and `a=2` the
        same plan), collapsing textual variants the regex would split.
        ``mem_bytes`` is the execution's query-monitor peak (0 = the run
        reported none, e.g. a settings statement); ``spills`` the number
        of in-memory operators that swapped to external variants."""
        if fp is None:
            fp = fingerprint(sql)
        with self._lock:
            st = self._stats.get(fp)
            if st is None:
                if len(self._stats) >= self.max_fingerprints:
                    keep = sorted(self._stats.values(),
                                  key=lambda s: -s.total_s)
                    keep = keep[: self.max_fingerprints // 2]
                    self.evicted += len(self._stats) - len(keep)
                    self._stats = {s.fingerprint: s for s in keep}
                st = self._stats[fp] = StmtStats(fp)
            st.count += 1
            st.total_s += elapsed_s
            st.min_s = min(st.min_s, elapsed_s)
            st.max_s = max(st.max_s, elapsed_s)
            st.rows += rows
            st.observe(elapsed_s)
            if mem_bytes > 0:
                st.observe_mem(mem_bytes)
            st.spills += int(spills)
            if error:
                st.errors += 1

    def all(self) -> list[StmtStats]:
        """Snapshot COPIES (consistent under concurrent record())."""
        import dataclasses

        with self._lock:
            return sorted(
                (dataclasses.replace(s, hist=list(s.hist),
                                     mem_hist=list(s.mem_hist))
                 for s in self._stats.values()),
                key=lambda s: -s.total_s,
            )

    def rows_payload(self) -> list[dict]:
        """The one serialization SHOW STATEMENTS and the admin endpoint
        share (single source for the row shape)."""
        return [
            {"fingerprint": s.fingerprint, "count": s.count,
             "meanMs": round(s.mean_s * 1e3, 3),
             "maxMs": round(s.max_s * 1e3, 3),
             "p50Ms": round(s.percentile(0.50) * 1e3, 3),
             "p99Ms": round(s.percentile(0.99) * 1e3, 3),
             "rows": s.rows, "errors": s.errors,
             "maxMemMb": round(s.max_mem_bytes / (1 << 20), 3),
             "p50MemMb": round(s.percentile_mem(0.50) / (1 << 20), 3),
             "p99MemMb": round(s.percentile_mem(0.99) / (1 << 20), 3),
             "spills": s.spills}
            for s in self.all()
        ]

    def clear(self) -> None:
        with self._lock:
            self._stats.clear()


# process-default registry (Sessions feed it; the admin endpoint reads it —
# the reference similarly aggregates node-wide)
DEFAULT = StatsRegistry()
