"""SQL parser — the pkg/sql/parser analog (reference grammar: sql.y).

A hand-written recursive-descent parser for the SELECT dialect the engine
executes (TPC-H coverage: implicit and explicit joins, GROUP BY/HAVING,
ORDER BY/LIMIT, CASE, EXTRACT, CAST, BETWEEN, IN lists and subqueries,
EXISTS, LIKE, date/interval literal arithmetic, scalar subqueries). The
reference uses a goyacc grammar producing sem/tree ASTs; here the AST is a
small dataclass tree lowered to relational plans by sql/binder.py, the
optbuilder analog.
"""

from __future__ import annotations

import re
import dataclasses
from dataclasses import dataclass
from typing import Optional

# ---------------------------------------------------------------------------
# Tokens

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<num>\d+\.\d+|\.\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op>::|<=|>=|<>|!=|\|\||[-+*/%(),.;<>=@])
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "exists", "between", "like",
    "ilike", "intersect", "except", "filter",
    "is", "null", "case", "when", "then", "else", "end", "cast", "extract",
    "year", "month", "day", "date", "interval", "join", "inner", "left",
    "right", "outer", "on", "asc", "desc", "distinct", "all", "union",
    "substring", "for", "true", "false", "any", "some", "with",
    "create", "table", "primary", "key", "insert", "into", "values",
    "update", "set", "delete", "default", "alter", "add", "column", "drop",
    "index",
    "over", "partition", "rows", "range", "groups", "unbounded",
    "preceding", "following", "current", "row", "exclude", "no",
    "others", "ties",
}


@dataclass
class Token:
    kind: str  # name | kw | num | str | op | eof
    value: str
    pos: int


# structural keywords can never START an expression — letting them parse
# as identifiers turns typos like "select from t" into silent nonsense
# (important now that FROM itself is optional)
_STRUCTURAL_KW = {
    "from", "where", "group", "having", "order", "limit", "offset",
    "union", "intersect", "except", "on", "join", "inner", "when",
    "then", "else", "end", "and", "or", "as", "by", "asc", "desc",
    "into", "values", "set",
}


def tokenize(text: str) -> list[Token]:
    out = []
    i = 0
    while i < len(text):
        m = _TOKEN_RE.match(text, i)
        if not m:
            raise SyntaxError(f"cannot tokenize at {text[i:i+20]!r}")
        i = m.end()
        kind = m.lastgroup
        if kind in ("ws", "comment"):
            continue
        v = m.group()
        if kind == "name":
            low = v.lower()
            if low in KEYWORDS:
                out.append(Token("kw", low, m.start()))
            else:
                out.append(Token("name", v.lower(), m.start()))
        elif kind == "str":
            out.append(Token("str", v[1:-1].replace("''", "'"), m.start()))
        else:
            out.append(Token(kind, v, m.start()))
    out.append(Token("eof", "", len(text)))
    return out


# ---------------------------------------------------------------------------
# AST


class Node:
    pass


@dataclass(frozen=True)
class Ident(Node):
    table: Optional[str]  # qualifier or None
    name: str


@dataclass(frozen=True)
class NumLit(Node):
    value: float | int


@dataclass(frozen=True)
class StrLit(Node):
    value: str


@dataclass(frozen=True)
class DateLit(Node):
    value: str  # YYYY-MM-DD


@dataclass(frozen=True)
class IntervalLit(Node):
    n: int
    unit: str  # day | month | year


@dataclass(frozen=True)
class NullLit(Node):
    pass


@dataclass(frozen=True)
class Star(Node):
    pass


@dataclass(frozen=True)
class FuncCall(Node):
    name: str
    args: tuple[Node, ...]
    distinct: bool = False


@dataclass(frozen=True)
class WindowCall(Node):
    """<func>(args) OVER (PARTITION BY ... ORDER BY ... [ROWS BETWEEN
    <bound> AND <bound>]). frame: (preceding, following) row counts with
    None meaning UNBOUNDED; frame is None when no ROWS clause was given
    (the binder applies the SQL default)."""

    func: FuncCall
    partition_by: tuple[Node, ...] = ()
    order_by: tuple[tuple[Node, bool], ...] = ()  # (expr, desc)
    frame: tuple | None = None
    has_frame_clause: bool = False
    frame_kind: str = "rows"  # "rows" | "range" | "groups"
    exclude: str = "no_others"  # EXCLUDE clause


@dataclass(frozen=True)
class Bin(Node):
    op: str  # + - * / || and or
    left: Node
    right: Node


@dataclass(frozen=True)
class Cmp(Node):
    op: str  # lt le gt ge eq ne
    left: Node
    right: Node


@dataclass(frozen=True)
class Not(Node):
    arg: Node


@dataclass(frozen=True)
class Between(Node):
    arg: Node
    lo: Node
    hi: Node
    negated: bool = False


@dataclass(frozen=True)
class IsDistinct(Node):
    """a IS [NOT] DISTINCT FROM b — null-safe comparison."""

    left: Node
    right: Node
    negated: bool = False  # negated=True is IS NOT DISTINCT FROM


@dataclass(frozen=True)
class Like(Node):
    arg: Node
    pattern: str
    negated: bool = False
    ci: bool = False  # ILIKE


@dataclass(frozen=True)
class InList(Node):
    arg: Node
    items: tuple[Node, ...]
    negated: bool = False


@dataclass(frozen=True)
class InSelect(Node):
    arg: Node
    select: "Select"
    negated: bool = False


@dataclass(frozen=True)
class Exists(Node):
    select: "Select"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Node):
    select: "Select"


@dataclass(frozen=True)
class Case(Node):
    whens: tuple[tuple[Node, Node], ...]
    otherwise: Optional[Node]


@dataclass(frozen=True)
class Cast(Node):
    arg: Node
    to: str  # type name
    precision: int | None = None
    scale: int | None = None


@dataclass(frozen=True)
class Extract(Node):
    part: str  # year | month | day
    arg: Node


@dataclass(frozen=True)
class IsNull(Node):
    arg: Node
    negated: bool = False


@dataclass(frozen=True)
class SelectItem(Node):
    expr: Node
    alias: Optional[str]


@dataclass(frozen=True)
class TableRef(Node):
    name: str
    alias: Optional[str]


@dataclass(frozen=True)
class SubqueryRef(Node):
    select: "Select"
    alias: str


@dataclass(frozen=True)
class Join(Node):
    left: Node
    right: Node
    kind: str  # inner | left
    on: Optional[Node]


@dataclass(frozen=True)
class OrderItem(Node):
    expr: Node
    desc: bool


@dataclass(frozen=True)
class ColumnDef(Node):
    name: str
    type_name: str  # normalized lowercase
    precision: int | None = None
    scale: int | None = None
    primary_key: bool = False
    not_null: bool = False


@dataclass(frozen=True)
class CreateTable(Node):
    name: str
    columns: tuple[ColumnDef, ...]


@dataclass(frozen=True)
class AlterTable(Node):
    """ALTER TABLE <name> ADD COLUMN <def> [DEFAULT <lit>] | DROP COLUMN
    <col>. Reference grammar: sql.y alter_table_cmd."""

    name: str
    action: str  # "add" | "drop"
    column: ColumnDef | None = None  # add
    default: Node | None = None  # add: DEFAULT expression
    drop_name: str | None = None  # drop


@dataclass(frozen=True)
class CreateIndex(Node):
    """CREATE INDEX <name> ON <table> (<col>). Reference grammar: sql.y
    create_index_stmt (reduced: one column, no STORING/UNIQUE/partial)."""

    name: str
    table: str
    col: str


@dataclass(frozen=True)
class DropIndex(Node):
    """DROP INDEX <table>@<name> | DROP INDEX <name> ON <table>."""

    name: str
    table: str


@dataclass(frozen=True)
class Insert(Node):
    table: str
    columns: tuple[str, ...] | None  # None = all, in schema order
    rows: tuple[tuple[Node, ...], ...]  # VALUES literal rows
    select: Optional["Select"] = None  # INSERT INTO ... SELECT


@dataclass(frozen=True)
class Update(Node):
    table: str
    sets: tuple[tuple[str, Node], ...]
    where: Optional[Node]


@dataclass(frozen=True)
class Delete(Node):
    table: str
    where: Optional[Node]


@dataclass(frozen=True)
class Select(Node):
    items: tuple[SelectItem, ...]
    from_: tuple[Node, ...]  # TableRef | SubqueryRef | Join
    where: Optional[Node]
    group_by: tuple[Node, ...]
    having: Optional[Node]
    order_by: tuple[OrderItem, ...]
    limit: Optional[int]
    offset: int = 0
    distinct: bool = False
    ctes: tuple[tuple[str, "Select"], ...] = ()  # WITH name AS (select)
    # UNION [ALL] arms, left-associative: (is_all, select). ORDER BY /
    # LIMIT on a Select that has set_ops apply to the WHOLE union (the
    # parser hoists a trailing arm's order/limit up here).
    set_ops: tuple[tuple[bool, "Select"], ...] = ()


# ---------------------------------------------------------------------------
# Parser


class Parser:
    def __init__(self, text: str):
        self.toks = tokenize(text)
        self.i = 0

    # -- plumbing -----------------------------------------------------------

    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def eat_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.eat_kw(kw):
            t = self.peek()
            raise SyntaxError(f"expected {kw!r}, got {t.value!r} at {t.pos}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.eat_op(op):
            t = self.peek()
            raise SyntaxError(f"expected {op!r}, got {t.value!r} at {t.pos}")

    # -- entry --------------------------------------------------------------

    def parse_statement(self) -> Node:
        """Statement entry: SELECT (incl. WITH) | CREATE TABLE | INSERT |
        UPDATE | DELETE. Reference grammar: pkg/sql/parser/sql.y."""
        if self.at_kw("create"):
            if self.peek(1).value.lower() == "index":
                s = self.parse_create_index()
            else:
                s = self.parse_create_table()
        elif self.at_kw("drop"):
            s = self.parse_drop_index()
        elif self.at_kw("alter"):
            s = self.parse_alter_table()
        elif self.at_kw("insert"):
            s = self.parse_insert()
        elif self.at_kw("update"):
            s = self.parse_update()
        elif self.at_kw("delete"):
            s = self.parse_delete()
        else:
            return self.parse()
        self.eat_op(";")
        if self.peek().kind != "eof":
            t = self.peek()
            raise SyntaxError(f"trailing input at {t.pos}: {t.value!r}")
        return s

    def parse_create_index(self) -> CreateIndex:
        self.expect_kw("create")
        self.expect_kw("index")
        name = self.next().value
        self.expect_kw("on")
        table = self.next().value
        self.expect_op("(")
        col = self.next().value
        self.expect_op(")")
        return CreateIndex(name, table, col)

    def parse_drop_index(self) -> DropIndex:
        self.expect_kw("drop")
        self.expect_kw("index")
        first = self.next().value
        if self.eat_op("@"):  # table@index (the CRDB spelling)
            return DropIndex(self.next().value, first)
        self.expect_kw("on")
        return DropIndex(first, self.next().value)

    def parse_create_table(self) -> CreateTable:
        self.expect_kw("create")
        self.expect_kw("table")
        name = self.next().value
        self.expect_op("(")
        cols: list[ColumnDef] = []
        while True:
            if self.at_kw("primary"):  # table-level PRIMARY KEY (col)
                self.next()
                self.expect_kw("key")
                self.expect_op("(")
                pk = self.next().value
                self.expect_op(")")
                cols = [
                    dataclasses.replace(c, primary_key=(c.name == pk))
                    for c in cols
                ]
            else:
                cname = self.next().value
                tname = self.next().value.lower()
                prec = scale = None
                if self.eat_op("("):
                    prec = int(self.next().value)
                    if self.eat_op(","):
                        scale = int(self.next().value)
                    self.expect_op(")")
                pkey = nnull = False
                while True:
                    if self.eat_kw("primary"):
                        self.expect_kw("key")
                        pkey = True
                    elif self.eat_kw("not"):
                        self.expect_kw("null")
                        nnull = True
                    else:
                        break
                cols.append(ColumnDef(cname, tname, prec, scale, pkey, nnull))
            if not self.eat_op(","):
                break
        self.expect_op(")")
        return CreateTable(name, tuple(cols))

    def parse_alter_table(self) -> AlterTable:
        self.expect_kw("alter")
        self.expect_kw("table")
        name = self.next().value
        if self.eat_kw("add"):
            self.eat_kw("column")  # COLUMN is optional, like Postgres
            cname = self.next().value
            tname = self.next().value.lower()
            prec = scale = None
            if self.eat_op("("):
                prec = int(self.next().value)
                if self.eat_op(","):
                    scale = int(self.next().value)
                self.expect_op(")")
            default = None
            nnull = False
            while True:
                if self.eat_kw("default"):
                    default = self.parse_expr()
                elif self.eat_kw("not"):
                    self.expect_kw("null")
                    nnull = True
                else:
                    break
            col = ColumnDef(cname, tname, prec, scale, False, nnull)
            return AlterTable(name, "add", column=col, default=default)
        if self.eat_kw("drop"):
            self.eat_kw("column")
            return AlterTable(name, "drop", drop_name=self.next().value)
        t = self.peek()
        raise SyntaxError(
            f"expected ADD or DROP at {t.pos}: {t.value!r}"
        )

    def parse_insert(self) -> Insert:
        self.expect_kw("insert")
        self.expect_kw("into")
        table = self.next().value
        columns = None
        if self.eat_op("("):
            columns = [self.next().value]
            while self.eat_op(","):
                columns.append(self.next().value)
            self.expect_op(")")
        if self.at_kw("select", "with"):
            return Insert(table, tuple(columns) if columns else None, (),
                          select=self.parse())
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            vals = [self.parse_expr()]
            while self.eat_op(","):
                vals.append(self.parse_expr())
            self.expect_op(")")
            rows.append(tuple(vals))
            if not self.eat_op(","):
                break
        return Insert(table, tuple(columns) if columns else None,
                      tuple(rows))

    def parse_update(self) -> Update:
        self.expect_kw("update")
        table = self.next().value
        self.expect_kw("set")
        sets = []
        while True:
            col = self.next().value
            self.expect_op("=")
            sets.append((col, self.parse_expr()))
            if not self.eat_op(","):
                break
        where = self.parse_expr() if self.eat_kw("where") else None
        return Update(table, tuple(sets), where)

    def parse_delete(self) -> Delete:
        self.expect_kw("delete")
        self.expect_kw("from")
        table = self.next().value
        where = self.parse_expr() if self.eat_kw("where") else None
        return Delete(table, where)

    def parse(self) -> Select:
        ctes: list[tuple[str, Select]] = []
        if self.eat_kw("with"):
            while True:
                name = self.next().value
                self.expect_kw("as")
                self.expect_op("(")
                ctes.append((name, self.parse_select()))
                self.expect_op(")")
                if not self.eat_op(","):
                    break
        s = self.parse_select()
        if ctes:
            s = dataclasses.replace(s, ctes=tuple(ctes))
        self.eat_op(";")
        if self.peek().kind != "eof":
            t = self.peek()
            raise SyntaxError(f"trailing input at {t.pos}: {t.value!r}")
        return s

    def parse_select(self) -> Select:
        """Set-operation chains with SQL precedence: INTERSECT binds
        tighter than UNION/EXCEPT (both left-associative). A trailing
        ORDER BY / LIMIT parsed into the LAST arm is hoisted to the chain
        level (SQL: they order/limit the whole set operation)."""
        return self._parse_setop_chain(
            self._parse_intersect_chain, ("union", "except")
        )

    def _parse_intersect_chain(self) -> Select:
        return self._parse_setop_chain(
            self.parse_select_one, ("intersect",)
        )

    def _parse_setop_chain(self, sub, ops: tuple[str, ...]) -> Select:
        s = sub()
        arms: list[tuple] = []
        while any(self.at_kw(o) for o in ops):
            op = self.next().value
            is_all = bool(self.eat_kw("all"))
            if op != "union" and is_all:
                raise SyntaxError(
                    f"{op.upper()} ALL (bag semantics) is not supported"
                )
            arms.append((op, is_all, sub()))
        if not arms:
            return s
        # only the LAST arm's trailing ORDER BY/LIMIT is the chain's;
        # order/limit on any earlier arm needs parentheses (postgres
        # rejects the unparenthesized form too — accepting it silently
        # would truncate the whole chain to the first arm's LIMIT)
        if s.order_by or s.limit is not None or s.offset:
            raise SyntaxError(
                "ORDER BY/LIMIT on a set-operation arm requires "
                "parentheses; a trailing ORDER BY/LIMIT applies to "
                "the whole chain"
            )
        order_by: tuple = ()
        limit = None
        offset = 0
        last_op, last_all, last = arms[-1]
        if last.order_by or last.limit is not None or last.offset:
            order_by, limit, offset = last.order_by, last.limit, last.offset
            arms[-1] = (last_op, last_all, dataclasses.replace(
                last, order_by=(), limit=None, offset=0))
        if s.set_ops:
            # the first arm is itself a tighter chain (A intersect B
            # union C): wrap it as a subquery so this level's set_ops
            # don't clobber the inner ones — the binder recurses into
            # the FROM subquery before folding this chain
            s = Select(
                items=(SelectItem(Star(), None),),
                from_=(SubqueryRef(s, "__setop"),),
                where=None, group_by=(), having=None, order_by=(),
                limit=None,
            )
        return dataclasses.replace(
            s, set_ops=tuple(arms), order_by=order_by, limit=limit,
            offset=offset,
        )

    def parse_select_one(self) -> Select:
        self.expect_kw("select")
        distinct = bool(self.eat_kw("distinct"))
        self.eat_kw("all")
        items = [self.parse_select_item()]
        while self.eat_op(","):
            items.append(self.parse_select_item())
        from_: list = []
        if self.eat_kw("from"):  # FROM-less SELECT: one synthetic row
            from_.append(self.parse_table_expr())
            while self.eat_op(","):
                from_.append(self.parse_table_expr())
        where = self.parse_expr() if self.eat_kw("where") else None
        group_by: list[Node] = []
        if self.eat_kw("group"):
            self.expect_kw("by")
            group_by.append(self.parse_expr())
            while self.eat_op(","):
                group_by.append(self.parse_expr())
        having = self.parse_expr() if self.eat_kw("having") else None
        order_by: list[OrderItem] = []
        if self.eat_kw("order"):
            self.expect_kw("by")
            order_by.append(self.parse_order_item())
            while self.eat_op(","):
                order_by.append(self.parse_order_item())
        limit = None
        offset = 0
        if self.eat_kw("limit"):
            limit = int(self.next().value)
        if self.eat_kw("offset"):
            offset = int(self.next().value)
        return Select(
            items=tuple(items), from_=tuple(from_), where=where,
            group_by=tuple(group_by), having=having, order_by=tuple(order_by),
            limit=limit, offset=offset, distinct=distinct,
        )

    def parse_select_item(self) -> SelectItem:
        if self.at_op("*"):
            self.next()
            return SelectItem(Star(), None)
        e = self.parse_expr()
        alias = None
        if self.eat_kw("as"):
            alias = self.next().value
        elif self.peek().kind == "name":
            alias = self.next().value
        return SelectItem(e, alias)

    def parse_order_item(self) -> OrderItem:
        e = self.parse_expr()
        desc = False
        if self.eat_kw("desc"):
            desc = True
        else:
            self.eat_kw("asc")
        return OrderItem(e, desc)

    def parse_table_expr(self) -> Node:
        left = self.parse_table_primary()
        while True:
            kind = None
            if self.at_kw("join", "inner"):
                self.eat_kw("inner")
                self.expect_kw("join")
                kind = "inner"
            elif self.at_kw("left"):
                self.next()
                self.eat_kw("outer")
                self.expect_kw("join")
                kind = "left"
            else:
                return left
            right = self.parse_table_primary()
            on = None
            if self.eat_kw("on"):
                on = self.parse_expr()
            left = Join(left, right, kind, on)

    def parse_table_primary(self) -> Node:
        if self.eat_op("("):
            sub = self.parse_select()
            self.expect_op(")")
            self.eat_kw("as")
            alias = self.next().value
            return SubqueryRef(sub, alias)
        name = self.next().value
        # dotted names (crdb_internal.node_metrics): the qualified name is
        # one catalog key — no schema resolution layer in this build
        while self.eat_op("."):
            name += "." + self.next().value
        alias = None
        if self.eat_kw("as"):
            alias = self.next().value
        elif self.peek().kind == "name":
            alias = self.next().value
        return TableRef(name, alias)

    # -- expressions (precedence climbing) ----------------------------------

    def parse_expr(self) -> Node:
        return self.parse_or()

    def parse_or(self) -> Node:
        e = self.parse_and()
        while self.eat_kw("or"):
            e = Bin("or", e, self.parse_and())
        return e

    def parse_and(self) -> Node:
        e = self.parse_not()
        while self.eat_kw("and"):
            e = Bin("and", e, self.parse_not())
        return e

    def parse_not(self) -> Node:
        if self.eat_kw("not"):
            return Not(self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> Node:
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            sub = self.parse_select()
            self.expect_op(")")
            return Exists(sub)
        e = self.parse_additive()
        negated = bool(self.eat_kw("not"))
        if self.eat_kw("between"):
            lo = self.parse_additive()
            self.expect_kw("and")
            hi = self.parse_additive()
            return Between(e, lo, hi, negated)
        if self.eat_kw("like") or self.eat_kw("ilike"):
            ci = self.toks[self.i - 1].value == "ilike"
            pat = self.next()
            if pat.kind != "str":
                raise SyntaxError("LIKE pattern must be a string literal")
            return Like(e, pat.value, negated, ci)
        if self.eat_kw("in"):
            self.expect_op("(")
            if self.at_kw("select"):
                sub = self.parse_select()
                self.expect_op(")")
                return InSelect(e, sub, negated)
            items = [self.parse_expr()]
            while self.eat_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return InList(e, tuple(items), negated)
        if negated:
            raise SyntaxError("dangling NOT")
        if self.eat_kw("is"):
            neg = bool(self.eat_kw("not"))
            if self.eat_kw("distinct"):
                self.expect_kw("from")
                return IsDistinct(e, self.parse_additive(), negated=neg)
            self.expect_kw("null")
            return IsNull(e, neg)
        ops = {"<": "lt", "<=": "le", ">": "gt", ">=": "ge", "=": "eq",
               "<>": "ne", "!=": "ne"}
        t = self.peek()
        if t.kind == "op" and t.value in ops:
            self.next()
            # quantified comparison: = ANY/SOME (sub) is IN, <> ALL is
            # NOT IN (the only two shapes with clean IN reductions)
            if self.at_kw("any") or self.at_kw("some") or self.at_kw("all"):
                q = self.next().value
                self.expect_op("(")
                sub = self.parse_select()
                self.expect_op(")")
                if ops[t.value] == "eq" and q in ("any", "some"):
                    return InSelect(e, sub, False)
                if ops[t.value] == "ne" and q == "all":
                    return InSelect(e, sub, True)
                raise SyntaxError(
                    f"only = ANY(...) and <> ALL(...) quantified "
                    f"comparisons are supported (got {t.value} {q})"
                )
            rhs = self.parse_additive()
            return Cmp(ops[t.value], e, rhs)
        return e

    def parse_additive(self) -> Node:
        e = self.parse_multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().value
                e = Bin(op, e, self.parse_multiplicative())
            elif self.at_op("||"):
                self.next()
                e = Bin("||", e, self.parse_multiplicative())
            else:
                return e

    def parse_multiplicative(self) -> Node:
        e = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            e = Bin(op, e, self.parse_unary())
        return e

    def parse_unary(self) -> Node:
        if self.eat_op("-"):
            return Bin("-", NumLit(0), self.parse_unary())
        if self.eat_op("+"):
            return self.parse_unary()
        e = self.parse_primary()
        while self.eat_op("::"):  # postgres cast: expr::type
            to = self.next().value
            prec = scale = None
            if self.eat_op("("):  # (p[,s]) type parameters
                prec = int(self.next().value)
                if self.eat_op(","):
                    scale = int(self.next().value)
                self.expect_op(")")
            e = Cast(e, to, prec, scale)
        return e

    def parse_primary(self) -> Node:
        t = self.peek()
        if t.kind == "num":
            self.next()
            v = float(t.value) if "." in t.value else int(t.value)
            return NumLit(v)
        if t.kind == "str":
            self.next()
            return StrLit(t.value)
        if self.at_kw("null"):
            self.next()
            return NullLit()
        if self.at_kw("true"):
            self.next()
            return NumLit(1)
        if self.at_kw("false"):
            self.next()
            return NumLit(0)
        if self.at_kw("date"):
            self.next()
            lit = self.next()
            if lit.kind != "str":
                raise SyntaxError("date literal must be a string")
            return DateLit(lit.value)
        if self.at_kw("interval"):
            self.next()
            n = self.next()
            if n.kind == "str":
                # postgres forms: INTERVAL '1 day' and INTERVAL '3' day
                parts = n.value.split()
                if len(parts) == 2:
                    return IntervalLit(int(parts[0]),
                                       parts[1].rstrip("s"))
                if len(parts) == 1:
                    unit = self.next().value.rstrip("s")
                    return IntervalLit(int(parts[0]), unit)
                raise SyntaxError(
                    f"unsupported interval literal {n.value!r}"
                )
            unit = self.next().value.rstrip("s")
            return IntervalLit(int(n.value), unit)
        if self.at_kw("case"):
            return self.parse_case()
        if self.at_kw("cast"):
            self.next()
            self.expect_op("(")
            arg = self.parse_expr()
            self.expect_kw("as")
            to = self.next().value
            prec = scale = None
            if self.eat_op("("):  # (p[,s]) type parameters
                prec = int(self.next().value)
                if self.eat_op(","):
                    scale = int(self.next().value)
                self.expect_op(")")
            self.expect_op(")")
            return Cast(arg, to, prec, scale)
        if self.at_kw("extract"):
            self.next()
            self.expect_op("(")
            part = self.next().value
            self.expect_kw("from")
            arg = self.parse_expr()
            self.expect_op(")")
            return Extract(part, arg)
        if self.at_kw("substring"):
            # both standard forms: substring(s FROM i FOR n) and the
            # function-call shape substring(s, i, n)
            self.next()
            self.expect_op("(")
            arg = self.parse_expr()
            if self.eat_kw("from"):
                start = int(self.next().value)
                self.expect_kw("for")
                ln = int(self.next().value)
            else:
                self.expect_op(",")
                start = int(self.next().value)
                self.expect_op(",")
                ln = int(self.next().value)
            self.expect_op(")")
            return FuncCall("substring", (arg, NumLit(start), NumLit(ln)))
        if self.eat_op("("):
            if self.at_kw("select"):
                sub = self.parse_select()
                self.expect_op(")")
                return ScalarSubquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "name" or (t.kind == "kw"
                                and t.value not in _STRUCTURAL_KW):
            self.next()
            name = t.value
            if self.at_op("("):  # function call
                self.next()
                distinct = bool(self.eat_kw("distinct"))
                args: list[Node] = []
                if self.at_op("*"):
                    self.next()
                    args.append(Star())
                elif not self.at_op(")"):
                    args.append(self.parse_expr())
                    while self.eat_op(","):
                        args.append(self.parse_expr())
                self.expect_op(")")
                fc = FuncCall(name, tuple(args), distinct)
                if self.eat_kw("filter"):
                    # FILTER (WHERE p) desugars in place: agg(x) ->
                    # agg(CASE WHEN p THEN x END); count(*) counts a CASE
                    # over 1 — identical semantics, no new agg machinery
                    self.expect_op("(")
                    self.expect_kw("where")
                    pred = self.parse_expr()
                    self.expect_op(")")
                    if distinct:
                        raise SyntaxError(
                            "FILTER with DISTINCT aggregates is not "
                            "supported"
                        )
                    src = (NumLit(1) if not args
                           or isinstance(args[0], Star) else args[0])
                    guarded = Case(whens=((pred, src),), otherwise=None)
                    fname = "count" if (not args
                                        or isinstance(args[0], Star)
                                        ) and name == "count" else name
                    fc = FuncCall(fname, (guarded,) + tuple(args[1:]),
                                  distinct)
                if self.at_kw("over"):
                    return self.parse_over(fc)
                return fc
            if self.eat_op("."):
                col = self.next().value
                return Ident(name, col)
            return Ident(None, name)
        raise SyntaxError(f"unexpected token {t.value!r} at {t.pos}")

    def parse_over(self, fc: FuncCall) -> WindowCall:
        """OVER (PARTITION BY ... ORDER BY ... [ROWS BETWEEN a AND b])."""
        self.expect_kw("over")
        self.expect_op("(")
        parts: list[Node] = []
        order: list[tuple[Node, bool]] = []
        frame = None
        has_frame = False
        if self.eat_kw("partition"):
            self.expect_kw("by")
            parts.append(self.parse_expr())
            while self.eat_op(","):
                parts.append(self.parse_expr())
        if self.eat_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.eat_kw("desc"):
                    desc = True
                elif self.eat_kw("asc"):
                    pass
                order.append((e, desc))
                if not self.eat_op(","):
                    break
        frame_kind = "rows"
        exclude = "no_others"
        if (self.eat_kw("rows") or self.eat_kw("range")
                or self.eat_kw("groups")):
            if self.toks[self.i - 1].value in ("range", "groups"):
                frame_kind = self.toks[self.i - 1].value
            has_frame = True
            self.expect_kw("between")
            frame = (self._frame_bound(preceding=True, kind=frame_kind),
                     self._frame_bound(preceding=False, kind=frame_kind))
            # BETWEEN's middle AND
            if self.eat_kw("exclude"):
                if self.eat_kw("no"):
                    self.expect_kw("others")
                elif self.eat_kw("current"):
                    self.expect_kw("row")
                    exclude = "current"
                elif self.eat_kw("group"):
                    exclude = "group"
                else:
                    self.expect_kw("ties")
                    exclude = "ties"
        self.expect_op(")")
        return WindowCall(fc, tuple(parts), tuple(order), frame, has_frame,
                          frame_kind, exclude)

    def _frame_bound(self, preceding: bool, kind: str = "rows"):
        """One ROWS/RANGE bound -> offset relative to the current row
        (None = UNBOUNDED; ROWS counts rows, RANGE measures order-key
        values and admits non-integer offsets). The leading bound consumes
        the AND separator."""
        if self.eat_kw("unbounded"):
            # the start bound must say PRECEDING, the end bound FOLLOWING
            self.expect_kw("preceding" if preceding else "following")
            out = None
        elif self.eat_kw("current"):
            self.expect_kw("row")
            out = 0
        else:
            t = self.next()
            if t.kind != "num":
                raise SyntaxError(
                    f"expected a frame bound at {t.pos}: {t.value!r}"
                )
            n = float(t.value) if kind == "range" else int(t.value)
            if isinstance(n, float) and n.is_integer():
                n = int(n)
            if self.eat_kw("preceding"):
                out = n if preceding else -n
            else:
                self.expect_kw("following")
                out = -n if preceding else n
        if preceding:
            self.expect_kw("and")
        return out

    def parse_case(self) -> Case:
        self.expect_kw("case")
        whens = []
        while self.eat_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            val = self.parse_expr()
            whens.append((cond, val))
        otherwise = self.parse_expr() if self.eat_kw("else") else None
        self.expect_kw("end")
        return Case(tuple(whens), otherwise)


def parse(text: str) -> Select:
    return Parser(text).parse()


def parse_statement(text: str) -> Node:
    return Parser(text).parse_statement()
