"""Live session/query registries — the sessionRegistry role
(pkg/sql/conn_executor.go:2193 registerSession / ps.queries): every
Session registers itself at construction, every statement registers while
it runs with a phase that advances parse -> bind -> execute, and
crdb_internal.cluster_sessions / cluster_queries read the snapshots so
plain SQL can see what the process is doing right now.

Process-global on purpose: one pgwire server hosts many Sessions across
threads, and the registries are the cross-session view. Bounded — a leaked
session (a client that never closes) eventually falls off the oldest end
instead of growing the dict forever.
"""

from __future__ import annotations

import itertools
import threading
import time

_lock = threading.Lock()
_ids = itertools.count(1)
_sessions: dict[int, dict] = {}
_queries: dict[int, dict] = {}

MAX_SESSIONS = 512
MAX_QUERY_TEXT = 512


def register_session(application_name: str = "") -> int:
    sid = next(_ids)
    with _lock:
        while len(_sessions) >= MAX_SESSIONS:
            _sessions.pop(next(iter(_sessions)))
        _sessions[sid] = {"id": sid,
                          "application_name": str(application_name),
                          "start": time.time(), "active": 0}
    return sid


def set_application_name(sid: int, name: str) -> None:
    with _lock:
        s = _sessions.get(sid)
        if s is not None:
            s["application_name"] = str(name)


def deregister_session(sid: int) -> None:
    with _lock:
        _sessions.pop(sid, None)
        orphans = [q for q, info in _queries.items()
                   if info["session_id"] == sid]
        for q in orphans:
            _queries.pop(q, None)


def begin_query(sid: int, text: str) -> int:
    qid = next(_ids)
    with _lock:
        s = _sessions.get(sid)
        if s is not None:
            s["active"] += 1
        _queries[qid] = {"id": qid, "session_id": sid,
                         "query": str(text)[:MAX_QUERY_TEXT],
                         "phase": "parsing", "start": time.time()}
    return qid


def set_phase(qid: int, phase: str) -> None:
    with _lock:
        q = _queries.get(qid)
        if q is not None:
            q["phase"] = phase


def end_query(qid: int) -> None:
    with _lock:
        q = _queries.pop(qid, None)
        if q is not None:
            s = _sessions.get(q["session_id"])
            if s is not None:
                s["active"] = max(0, s["active"] - 1)


def sessions() -> list[dict]:
    """Snapshot, oldest first, with session_age_s computed at read time."""
    now = time.time()
    with _lock:
        return [{**s, "session_age_s": now - s["start"]}
                for s in _sessions.values()]


def queries() -> list[dict]:
    """Snapshot of in-flight statements with elapsed_s at read time."""
    now = time.time()
    with _lock:
        return [{**q, "elapsed_s": now - q["start"]}
                for q in _queries.values()]


def reset() -> None:
    """Tests only: drop all registrations."""
    with _lock:
        _sessions.clear()
        _queries.clear()
