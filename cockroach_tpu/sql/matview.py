"""Materialized views — standing grouped aggregates fed by the changefeed.

Reference: CockroachDB materialized views (pkg/sql/create_view.go with
``materialized=true``) are stored relations refreshed by full re-run
(``REFRESH MATERIALIZED VIEW``). Here the refresh is INCREMENTAL and
continuous: CREATE MATERIALIZED VIEW over a dense grouped-aggregate
query registers a standing view whose state is the fused pipeline's
fold accumulators, maintained from the table's changefeed event stream
by :mod:`..flow.viewmaint` (see that module for the delta algebra).

This module is the SQL surface:

- **DDL**: ``CREATE MATERIALIZED VIEW v AS SELECT ...`` /
  ``DROP MATERIALIZED VIEW v`` / ``REFRESH MATERIALIZED VIEW v``
  (regex-dispatched from Session like the other admin verbs);
- **read path**: the view is a plain catalog Table served like any
  host table; it lazily re-materializes from the standing device state
  when the state generation moved (``SELECT * FROM v`` never pays
  O(base table), only O(groups));
- **freshness**: reads refresh-on-read by default
  (``sql.matview.refresh_on_read.enabled``): statements naming a view
  first pump + flush its maintainer, so results are AS OF the resolved
  frontier at statement start — the changefeed resolved-timestamp bound,
  never a torn mid-flush state;
- **planner rewrite** (``sql.matview.rewrite.enabled``): a SELECT whose
  bound plan matches a registered view's parameterized shape AND literal
  values serves from the standing state (the Aggregate subtree becomes a
  TableScan of the view; trailing ORDER BY/LIMIT reapply unchanged) —
  EXPLAIN shows the substitution.

The registry hangs off the catalog (``catalog._matview_registry``, the
``_plan_cache`` idiom) so independent catalogs never share views.
"""

from __future__ import annotations

import dataclasses
import re

import numpy as np

from ..catalog import Table
from ..coldata.types import Family
from ..flow import viewmaint
from ..plan import spec as S
from ..utils import locks, metric, racesan, settings
from .binder import BindError, Binder
from . import parser as P

_CREATE_RE = re.compile(
    r"(?is)^create\s+materialized\s+view\s+([a-z_][a-z0-9_]*)\s+as\s+(.+)$")
_DROP_RE = re.compile(
    r"(?is)^drop\s+materialized\s+view\s+([a-z_][a-z0-9_]*)$")
_REFRESH_RE = re.compile(
    r"(?is)^refresh\s+materialized\s+view\s+([a-z_][a-z0-9_]*)$")


class MatviewError(BindError):
    pass


def _scaled_params(values, types) -> tuple:
    """Filter literals in the device domain — the exact ParamStore
    scaling (sql/plancache.py set_values), so a standing view's stored
    literals compare equal to a fresh statement's extracted ones."""
    out = []
    for v, t in zip(values, types):
        if t.family is Family.DECIMAL:
            v = int(round(float(v) * 10 ** t.scale))
        out.append(np.asarray(v, dtype=t.dtype))
    return tuple(out)


def _peel(plan):
    """Split ``plan`` into (order-preserving wrappers outermost-first,
    core). ORDER BY / LIMIT / TOP-K don't change the standing state —
    they reapply over the view scan."""
    wrappers = []
    while isinstance(plan, (S.Sort, S.TopK, S.Limit)):
        wrappers.append(plan)
        plan = plan.input
    return wrappers, plan


def _split_core(core):
    """(aggregate node, output column permutation) for a view core.

    The binder emits ``Project(names) -> Aggregate`` — a pure-ColRef
    rename/reorder of the aggregate outputs. The Project is part of the
    view's identity (it is in the class key) but at materialize time it
    is just a column permutation over the finalized state. Returns
    (None, None) when the core is not a maintainable shape."""
    from ..ops import expr as ex

    if isinstance(core, S.Project):
        if not all(isinstance(e, ex.ColRef) for e in core.exprs):
            return None, None
        agg = core.input
        perm = tuple(e.idx for e in core.exprs)
    else:
        agg = core
        perm = None
    if not isinstance(agg, S.Aggregate):
        return None, None
    if perm is None:
        perm = tuple(range(len(agg.group_cols) + len(agg.aggs)))
    return agg, perm


def _find_scan(plan):
    node = plan
    while node is not None and not isinstance(node, S.TableScan):
        node = getattr(node, "input", None)
    return node


class Registry:
    """Every materialized view of one catalog: name -> ViewState plus one
    :class:`~..flow.viewmaint.ViewMaintainer` per base table, all sharing
    one fan-out hub (the N-views-one-poll-loop shape)."""

    def __init__(self, catalog):
        self.catalog = catalog
        self._mu = locks.lock("sql.matview.registry")
        self.views: dict[str, viewmaint.ViewState] = {}
        self.maintainers: dict[str, viewmaint.ViewMaintainer] = {}
        self.hub = None

    # -- plumbing ---------------------------------------------------------

    def _hub_for(self, db):
        from ..kv.fanout import FanoutHub

        if self.hub is None:
            self.hub = FanoutHub(db, poll_interval_s=0.02, name="matview")
        return self.hub

    def _maintainer_for(self, base) -> viewmaint.ViewMaintainer:
        m = self.maintainers.get(base.name)
        if m is None:
            m = viewmaint.ViewMaintainer(
                base, self._hub_for(base.db), rebuild_cb=self._rebuild)
            self.maintainers[base.name] = m
        return m

    def _bind_pipeline(self, select_text: str):
        """Parse + bind the defining SELECT and carve out the maintainable
        pipeline. Returns (rel, wrappers, class key, pinfo, scaled
        values, param types, base KVTable)."""
        from ..kv.table import KVTable
        from . import plancache

        stmt = P.parse_statement(select_text)
        if not isinstance(stmt, P.Select):
            raise MatviewError("materialized views are defined by a SELECT")
        rel = Binder(self.catalog).bind(stmt)
        wrappers, core = _peel(rel.plan)
        agg, perm = _split_core(core)
        if agg is None:
            raise MatviewError(
                "materialized view query must be a grouped aggregate "
                "(optionally renamed/reordered) over one table scan")
        scan = _find_scan(agg)
        if scan is None:
            raise MatviewError(
                "materialized view query must scan exactly one table")
        base = self.catalog.tables.get(scan.table)
        if not isinstance(base, KVTable):
            raise MatviewError(
                f"materialized view base table {scan.table!r} must be "
                "KV-backed (CREATE TABLE) — it is the changefeed source")
        names = (scan.columns if scan.columns is not None
                 else base.schema.names)
        scan_schema = base.schema.select(
            tuple(base.schema.index(n) for n in names))
        try:
            # the class key covers the WHOLE core (rename project
            # included) so a statement's bound plan keys identically
            pcore, values, types = plancache.parameterize(core)
            key = plancache.plan_key(pcore)
        except Exception as e:
            raise MatviewError(
                f"materialized view query is not shape-cacheable: {e}")
        pagg = pcore.input if isinstance(pcore, S.Project) else pcore
        pinfo = viewmaint.extract_pipeline(pagg, scan_schema)
        if pinfo is None:
            raise MatviewError(
                "materialized view query must be a dense grouped "
                "aggregate (GROUP BY bounded keys, aggregates in "
                "sum/count/avg/min/max) over filters/projections of one "
                "table scan")
        return (rel, wrappers, key, pinfo, _scaled_params(values, types),
                tuple(types), base, perm)

    # -- DDL --------------------------------------------------------------

    def create(self, name: str, select_text: str) -> dict:
        if not settings.get("sql.matview.enabled"):
            raise MatviewError("materialized views are disabled "
                               "(sql.matview.enabled)")
        with self._mu:
            racesan.note_read(self, "views")
            if name in self.catalog.tables or name in self.views:
                raise MatviewError(f"relation {name!r} already exists")
        rel, _w, key, pinfo, vals, types, base, perm = self._bind_pipeline(
            select_text)
        tbl = Table(
            name=name,
            schema=rel.schema,
            columns={n: np.zeros((0,), dtype=t.dtype)
                     for n, t in zip(rel.schema.names, rel.schema.types)},
            dictionaries={rel.schema.names[i]: d
                          for i, d in rel.dicts.items()},
        )
        view = viewmaint.ViewState(
            name=name, select_text=select_text, values=vals,
            out_schema=rel.schema, table=tbl)
        view.param_types = types
        view.base_table = base.name
        view.out_perm = perm
        m = self._maintainer_for(base)
        m.add_view(view, key, pinfo, types)
        with self._mu:
            racesan.note_write(self, "views")
            self.views[name] = view
            metric.MATVIEW_VIEWS.set(len(self.views))
        self.catalog.add(tbl)  # bumps the catalog version
        self.materialize(view)
        return {"created_view": name, "frontier": view.frontier}

    def drop(self, name: str) -> dict:
        with self._mu:
            racesan.note_read(self, "views")
            view = self.views.get(name)
            if view is None:
                raise MatviewError(f"unknown materialized view {name!r}")
            racesan.note_write(self, "views")
            del self.views[name]
            metric.MATVIEW_VIEWS.set(len(self.views))
        m = self.maintainers.get(view.base_table)
        if m is not None:
            m.drop_view(view)
            if not any(v.base_table == view.base_table
                       for v in self.views.values()):
                m.close()
                del self.maintainers[view.base_table]
        self.catalog.tables.pop(name, None)
        self.catalog.bump_version()
        return {"dropped_view": name}

    def refresh(self, name: str) -> dict:
        with self._mu:
            racesan.note_read(self, "views")
            view = self.views.get(name)
        if view is None:
            raise MatviewError(f"unknown materialized view {name!r}")
        self.refresh_view(view)
        return {"refreshed": name, "frontier": view.frontier}

    # -- refresh + read surface -------------------------------------------

    def refresh_view(self, view) -> None:
        m = self.maintainers.get(view.base_table)
        if m is None:
            return
        m.pump()
        m.flush()
        self.materialize(view)

    def materialize(self, view) -> None:
        """Re-host the view's result table from its standing state when
        the state generation moved — O(groups), one dense_finalize, never
        a base-table scan. The in-place Table mutation plus a catalog
        version bump is the schema-change invalidation discipline
        (cached plans over the old rows re-key out of existence)."""
        cls = view.cls
        m = self.maintainers.get(view.base_table)
        if cls is None or m is None:
            return
        with m._mu:
            gen = (cls.gen, view.frontier)
            if getattr(view, "_mat_gen", None) == gen:
                return
            batch = cls.finalize_slot(view.slot)
            mask = np.asarray(batch.mask)
            tbl = view.table
            perm = getattr(view, "out_perm",
                           tuple(range(len(batch.cols))))
            # build the new generation aside, then swap whole dicts: a
            # concurrent reader holds either the old generation or the
            # new one (device_batch snapshots its host source), never a
            # mix of re-hosted and stale columns
            new_cols: dict[str, np.ndarray] = {}
            new_valids: dict[str, np.ndarray] = {}
            for n, ci in zip(view.out_schema.names, perm):
                col = batch.cols[ci]
                new_cols[n] = np.asarray(col.data)[mask]
                valid = np.asarray(col.valid)[mask]
                if not valid.all():
                    new_valids[n] = valid
            tbl.columns = new_cols
            tbl.valids = new_valids
            tbl._device = None
            tbl._stats = None
            if hasattr(tbl, "_dense_keys"):
                del tbl._dense_keys
            if hasattr(tbl, "table_stats"):
                del tbl.table_stats
            view._mat_gen = gen
            view.stale = False
        from . import plancache

        self.catalog.bump_version()
        plancache.cache_for(self.catalog).invalidate(self.catalog.version)

    def _rebuild(self, view) -> None:
        """Out-of-bounds group key (dictionary grew since CREATE): re-bind
        the defining SELECT — the fresh bind sees the grown dictionary,
        so the new dense layout holds every key — and repopulate by base
        rescan at the maintainer's frontier. Called by the maintainer
        post-commit, under its state lock (reentrant)."""
        m = self.maintainers.get(view.base_table)
        if m is None:
            return
        rel, _w, key, pinfo, vals, types, _base, perm = self._bind_pipeline(
            view.select_text)
        with m._mu:
            old = view.cls
            if old is not None:
                old.free_slot(view)
                if old.live_count() == 0:
                    m.classes.pop(old.key, None)
                    old.close()
            view.values = vals
            view.out_schema = rel.schema
            view.param_types = types
            view.out_perm = perm
            view.table.dictionaries = {
                rel.schema.names[i]: d for i, d in rel.dicts.items()}
            cls = m.class_for(key, pinfo, types)
            cls.alloc_slot(view)
            m._rescan_slot(view, m.frontier, commit=True)

    # -- introspection ----------------------------------------------------

    def rows(self) -> list[dict]:
        out = []
        with self._mu:
            racesan.note_read(self, "views")
            views = list(self.views.values())
        for v in views:
            cls = v.cls
            groups = 0
            if cls is not None and v.slot >= 0:
                groups = int((np.asarray(cls.rows[v.slot]) > 0).sum())
            out.append({
                "view": v.name,
                "base_table": getattr(v, "base_table", ""),
                "groups": groups,
                "frontier": v.frontier,
                "refresh_lag_s": v.last_lag_s,
                "minmax_rescans": v.minmax_rescans,
                "full_rescans": v.full_rescans,
                "stale": v.stale,
            })
        return out

    def close(self) -> None:
        for m in list(self.maintainers.values()):
            m.close()
        self.maintainers.clear()
        with self._mu:
            racesan.note_write(self, "views")
            self.views.clear()
        if self.hub is not None:
            self.hub.close()
            self.hub = None


# ---------------------------------------------------------------------------
# module surface (Session / explain / vtable entry points)


def registry_for(catalog, create: bool = False) -> Registry | None:
    reg = getattr(catalog, "_matview_registry", None)
    if reg is None and create:
        reg = catalog._matview_registry = Registry(catalog)
    return reg


def close_all(catalog) -> None:
    """Tear down the catalog's matview plane (tests: subscriber monitors
    and the hub poller must not outlive the store)."""
    reg = registry_for(catalog)
    if reg is not None:
        reg.close()
        catalog._matview_registry = None


def maybe_matview_stmt(session, text: str):
    """The DDL dispatch hook (Session._dispatch, before parse — the
    grammar lives here, not in the parser)."""
    t = text.strip().rstrip(";")
    m = _CREATE_RE.match(t)
    if m:
        if session._txn is not None:
            raise MatviewError(
                "DDL inside an explicit transaction is not supported")
        reg = registry_for(session.catalog, create=True)
        out = reg.create(m.group(1).lower(), m.group(2))
        session._invalidate_plans()
        return out
    m = _DROP_RE.match(t)
    if m:
        if session._txn is not None:
            raise MatviewError(
                "DDL inside an explicit transaction is not supported")
        reg = registry_for(session.catalog)
        if reg is None:
            raise MatviewError(
                f"unknown materialized view {m.group(1).lower()!r}")
        out = reg.drop(m.group(1).lower())
        session._invalidate_plans()
        return out
    m = _REFRESH_RE.match(t)
    if m:
        reg = registry_for(session.catalog)
        if reg is None:
            raise MatviewError(
                f"unknown materialized view {m.group(1).lower()!r}")
        return reg.refresh(m.group(1).lower())
    return None


def refresh_for_text(catalog, text: str) -> None:
    """Refresh-on-read: a statement that names a registered view flushes
    that view's maintainer first, so the read serves the resolved
    frontier as of statement start (cheap when the buffer is empty: one
    peek under the hub lock)."""
    reg = registry_for(catalog)
    if reg is None or not reg.views:
        return
    if not settings.get("sql.matview.refresh_on_read.enabled"):
        return
    low = text.lower()
    for view in list(reg.views.values()):
        if re.search(rf"\b{re.escape(view.name)}\b", low):
            reg.refresh_view(view)


def _match_view(reg: Registry, plan):
    """The registered view whose parameterized shape AND literal values
    match ``plan`` (a peeled core), or None."""
    from . import plancache

    agg, _perm = _split_core(plan)
    if agg is None:
        return None
    try:
        pplan, values, types = plancache.parameterize(plan)
        key = plancache.plan_key(pplan)
    except Exception:
        return None
    scaled = _scaled_params(values, types)
    for view in reg.views.values():
        if view.cls is None or view.cls.key != key:
            continue
        if len(view.values) == len(scaled) and all(
                np.array_equal(a, b)
                for a, b in zip(view.values, scaled)):
            return view
    return None


def maybe_rewrite(catalog, rel):
    """Planner rewrite: serve a SELECT whose plan matches a standing
    view from the view's state. Returns (rel, view|None) — the rewritten
    Rel scans the view table; trailing Sort/TopK/Limit reapply unchanged
    (the view schema IS the aggregate output schema)."""
    if not settings.get("sql.matview.rewrite.enabled"):
        return rel, None
    reg = registry_for(catalog)
    if reg is None or not reg.views:
        return rel, None
    wrappers, core = _peel(rel.plan)
    view = _match_view(reg, core)
    if view is None:
        return rel, None
    metric.MATVIEW_REWRITE_HITS.inc()
    reg.refresh_view(view)
    node: S.PlanNode = S.TableScan(
        table=view.name, columns=tuple(view.out_schema.names))
    for w in reversed(wrappers):
        node = dataclasses.replace(w, input=node)
    from .rel import Rel

    return Rel(catalog=catalog, plan=node, schema=rel.schema,
               dicts=rel.dicts), view


def explain_note(catalog, rel) -> str | None:
    """The EXPLAIN annotation: present when the statement would serve
    from a standing view — either FROM <view> directly or through the
    planner rewrite."""
    reg = registry_for(catalog)
    if reg is None or not reg.views:
        return None
    scan = _find_scan(rel.plan)
    if scan is not None and scan.table in reg.views:
        v = reg.views[scan.table]
        return (f"served from materialized view {v.name} "
                f"(frontier={v.frontier})")
    if not settings.get("sql.matview.rewrite.enabled"):
        return None
    _w, core = _peel(rel.plan)
    view = _match_view(reg, core)
    if view is None:
        return None
    return (f"served from materialized view {view.name} "
            f"(frontier={view.frontier}, rewrite)")
