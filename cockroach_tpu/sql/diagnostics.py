"""Statement diagnostics bundles — the stmtdiagnostics analog.

Reference: ``EXPLAIN ANALYZE (DEBUG)`` and the slow-query log both produce a
*statement bundle* (pkg/sql/stmtdiagnostics): a self-contained snapshot —
statement text, plan, full trace, and execution counters — that can be pulled
off the node later (``cockroach-tpu debug zip``, /_status/diagnostics) and
inspected without reproducing the workload.

Bundles live in a bounded on-disk ring (``sql.diagnostics.ring_size`` JSON
files under ``sql.diagnostics.dir``, default a per-process temp directory);
an in-memory index serves listings without touching disk. ``capture`` is
called from ``Session.execute``'s finally block — possibly with an exception
already in flight — so it must never raise.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from collections import OrderedDict

from ..utils import log, settings

_lock = threading.Lock()
_ids = itertools.count(1)
# bundle id -> summary (insertion-ordered: oldest first, for ring eviction)
_index: OrderedDict[int, dict] = OrderedDict()
_tmpdir: str | None = None

MAX_STMT = 2048


def _bundle_dir() -> str:
    global _tmpdir
    configured = settings.get("sql.diagnostics.dir")
    if configured:
        os.makedirs(configured, exist_ok=True)
        return configured
    if _tmpdir is None:
        # per-process scratch; tempfile registers no cleanup, but bundles
        # are diagnostic artifacts — leaving them behind is the point
        _tmpdir = tempfile.mkdtemp(prefix="crdb_tpu_diag_")
    return _tmpdir


def _plan_sections(session, text: str) -> dict:
    """Re-bind the statement to render its plan + cache status. Best-effort:
    the statement may be un-plannable (DDL, a bind error mid-exception)."""
    from . import parser, plancache
    from .binder import Binder
    from ..plan.explain import explain_plan

    out: dict = {}
    try:
        stmt = parser.parse_statement(text)
        rel = Binder(session.catalog).bind(stmt)
        out["plan"] = explain_plan(rel.optimized_plan())
        out["planCacheStatus"] = plancache.probe(rel)
    except Exception:  # crlint: allow-broad-except(bundle capture is best-effort; the statement may not plan)
        out["plan"] = None
        out["planCacheStatus"] = "unavailable"
    return out


def capture(session, text: str, *, elapsed_s: float, span=None,
            trigger: str = "manual", error: bool = False) -> dict:
    """Capture a statement bundle; returns its summary (always has "id").

    Never raises: this runs inside Session.execute's finally block, where a
    secondary exception would mask the statement's own failure.
    """
    try:
        return _capture(session, text, elapsed_s=elapsed_s, span=span,
                        trigger=trigger, error=error)
    except Exception as e:  # crlint: allow-broad-except(diagnostics must never mask the statement's own outcome)
        log.warning(log.SQL_EXEC, "diagnostics capture failed", error=str(e))
        return {"id": 0, "error": str(e)}


def _capture(session, text: str, *, elapsed_s: float, span,
             trigger: str, error: bool) -> dict:
    from ..flow import dispatch, memory

    bid = next(_ids)
    bundle = {
        "id": bid,
        "stmt": text.strip()[:MAX_STMT],
        "trigger": trigger,
        "error": bool(error),
        "elapsedMs": round(elapsed_s * 1e3, 3),
        "capturedAtMs": int(time.time() * 1e3),
        "fingerprint": getattr(session, "_last_fp", None),
        "counters": {
            "kernelDispatches": dispatch.total(),
            "kernelCompiles": dispatch.compiles(),
            "kernelCacheHits": dispatch.kernel_cache_hits(),
        },
        "memory": {
            # resource side of the bundle: node-level figures plus the
            # capturing session's monitor (the statement's own query
            # monitor has already closed by the time capture runs)
            "sqlMemCurrentBytes": memory.ROOT.used,
            "sqlMemPeakBytes": memory.ROOT.high_water,
            "sessionPeakBytes": getattr(
                getattr(session, "_mem_mon", None), "high_water", 0),
            "sessionSpills": getattr(
                getattr(session, "_mem_mon", None), "spills", 0),
            "device": memory.device_memory_stats(),
        },
        "settings": {
            name: s.get()
            for name, s in settings.all_settings().items()
            if s.value is not None  # only overrides: defaults are in code
        },
        "trace": span.to_dict() if span is not None else None,
    }
    bundle.update(_plan_sections(session, text))

    path = os.path.join(_bundle_dir(), f"bundle_{bid:06d}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(bundle, f, indent=1, default=str)

    summary = {
        "id": bid,
        "stmt": bundle["stmt"][:120],
        "trigger": trigger,
        "error": bundle["error"],
        "elapsedMs": bundle["elapsedMs"],
        "capturedAtMs": bundle["capturedAtMs"],
        "path": path,
    }
    ring = settings.get("sql.diagnostics.ring_size")
    with _lock:
        _index[bid] = summary
        while len(_index) > ring:
            _, old = _index.popitem(last=False)
            try:
                os.unlink(old["path"])
            except OSError:
                pass  # already gone; the index drop is what bounds the ring
    return summary


def bundles() -> list[dict]:
    """Ring listing, newest first (the /_status/diagnostics payload)."""
    with _lock:
        return [dict(s) for s in reversed(_index.values())]


def get(bundle_id: int) -> dict | None:
    """Full bundle by id (reads the JSON back off disk); None if evicted."""
    with _lock:
        summary = _index.get(bundle_id)
    if summary is None:
        return None
    try:
        with open(summary["path"], encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def reset() -> None:
    """Drop the in-memory index and delete ring files (tests)."""
    with _lock:
        for s in _index.values():
            try:
                os.unlink(s["path"])
            except OSError:
                pass  # best-effort cleanup
        _index.clear()
