"""crdb_internal virtual tables — the pkg/sql/crdb_internal.go reduction.

Reference: crdb_internal is a schema of virtual tables materialized on
read (crdb_internal.go:1346 node_statement_statistics, :1588
cluster_queries/cluster_sessions, :1745 node_metrics, :6090 hot_ranges);
every read reflects live registries, nothing is stored.

Here the catalog resolves any unknown ``crdb_internal.<name>`` through
:func:`build`, which materializes a plain :class:`~..catalog.Table` from
the process registries (sqlstats, activity, metric, tracing, range meta).
The binder and the plan builder each resolve the table once per
statement, so materializations are generation-cached: both resolutions
within one statement see the SAME Table object (string dictionary codes
must match between bind-time schema inference and build-time scan).
``begin_statement`` bumps the generation, so every statement gets a fresh
snapshot.

The plan cache never caches plans over these tables (sql/plancache.py
treats the prefix as volatile) — a cached snapshot would freeze time.
"""

from __future__ import annotations

import time

import numpy as np

from ..catalog import Table
from ..coldata import types as T

PREFIX = "crdb_internal."

_gen = 0
# (id(catalog), table name) -> (generation, materialized Table)
_cache: dict[tuple[int, str], tuple[int, Table]] = {}


def bump_generation() -> None:
    """New statement: drop cached materializations so the next read sees
    a fresh snapshot (called from binder.begin_statement)."""
    global _gen
    _gen += 1
    _cache.clear()


def _table(name: str, cols: list[tuple[str, object, np.ndarray]]) -> Table:
    names = tuple(c[0] for c in cols)
    types = tuple(c[1] for c in cols)
    raw = {c[0]: c[2] for c in cols}
    return Table.from_strings(name, T.Schema(names, types), raw)


def _strs(vals) -> np.ndarray:
    return np.array([str(v) for v in vals], dtype=object)


def _ints(vals) -> np.ndarray:
    return np.array([int(v) for v in vals], dtype=np.int64)


def _floats(vals) -> np.ndarray:
    return np.array([float(v) for v in vals], dtype=np.float64)


def _stmt_statistics(catalog) -> Table:
    from . import sqlstats

    rows = sqlstats.DEFAULT.all()
    return _table("crdb_internal.node_statement_statistics", [
        ("fingerprint", T.STRING, _strs(r.fingerprint for r in rows)),
        ("count", T.INT64, _ints(r.count for r in rows)),
        ("mean_ms", T.FLOAT64, _floats(r.mean_s * 1e3 for r in rows)),
        ("max_ms", T.FLOAT64, _floats(r.max_s * 1e3 for r in rows)),
        ("p50_ms", T.FLOAT64,
         _floats(r.percentile(0.50) * 1e3 for r in rows)),
        ("p99_ms", T.FLOAT64,
         _floats(r.percentile(0.99) * 1e3 for r in rows)),
        ("rows_returned", T.INT64, _ints(r.rows for r in rows)),
        ("errors", T.INT64, _ints(r.errors for r in rows)),
        ("max_mem_mb", T.FLOAT64,
         _floats(r.max_mem_bytes / (1 << 20) for r in rows)),
        ("mem_p50_mb", T.FLOAT64,
         _floats(r.percentile_mem(0.50) / (1 << 20) for r in rows)),
        ("mem_p99_mb", T.FLOAT64,
         _floats(r.percentile_mem(0.99) / (1 << 20) for r in rows)),
        ("spills", T.INT64, _ints(r.spills for r in rows)),
    ])


def _memory_monitors(catalog) -> Table:
    """The live mon.BytesMonitor tree, depth-first — the reference's
    crdb_internal.node_memory_monitors (crdb_internal.go's monitor walk)."""
    from ..flow import memory

    rows = memory.monitor_rows()
    return _table("crdb_internal.node_memory_monitors", [
        ("name", T.STRING, _strs(r["name"] for r in rows)),
        ("level", T.STRING, _strs(r["level"] for r in rows)),
        ("depth", T.INT64, _ints(r["depth"] for r in rows)),
        ("used_bytes", T.INT64, _ints(r["used"] for r in rows)),
        ("peak_bytes", T.INT64, _ints(r["peak"] for r in rows)),
        ("budget_bytes", T.INT64, _ints(r["budget"] for r in rows)),
        ("spills", T.INT64, _ints(r["spills"] for r in rows)),
    ])


def _cluster_load(catalog) -> Table:
    """One-row serving-load snapshot: sessions/queries in flight, the
    node's SQL memory figures, admission queue state, and the physical
    device cross-check where the backend reports it."""
    from . import activity
    from ..flow import memory
    from ..utils import admission, metric

    q = admission.sql_queue()
    dev = memory.device_memory_stats()
    sess = activity.sessions()
    queries = activity.queries()
    cols = {
        "active_sessions": len(sess),
        "active_queries": len(queries),
        "sql_mem_current_bytes": memory.ROOT.used,
        "sql_mem_peak_bytes": memory.ROOT.high_water,
        "sql_mem_budget_bytes": memory.root_budget(),
        "admission_slots": q.slots,
        "admission_slots_in_use": q.in_use,
        "admission_queue_depth": q.queue_depth,
        "admission_admitted": q.admitted,
        "admission_waited": q.waited,
        "admission_timeouts": q.timeouts,
        "device_bytes_in_use": dev.get("bytes_in_use", 0),
        "device_peak_bytes": dev.get("peak_bytes_in_use", 0),
        "queries_total": int(metric.QUERIES.value),
    }
    # storage read/ingest plane: block-cache absorption, bloom pruning,
    # and bulk-ingest volume for this node
    from ..storage import blockcache

    bc = blockcache.node_cache().stats()
    cols.update({
        "block_cache_hits": bc["hits"],
        "block_cache_misses": bc["misses"],
        "block_cache_evictions": bc["evictions"],
        "block_cache_bytes": bc["bytes"],
        "bloom_skipped_runs": int(metric.BLOOM_SKIPS.value),
        "bulk_ingest_rows": int(metric.INGEST_ROWS.value),
    })
    return _table("crdb_internal.cluster_load", [
        (k, T.INT64, _ints([v])) for k, v in cols.items()
    ])


def _node_tenant_admission(catalog) -> Table:
    """Per-tenant admission state (the tenant rate-limiter / fair-share
    surface): token bucket level + config, stride-scheduler virtual
    time, and admit/reject counters, one row per tenant the queue has
    seen. Shed state and per-lane queue depth ride along so one query
    answers "who is being refused, and why"."""
    from ..utils import admission

    q = admission.sql_queue()
    rows = q.tenant_rows()
    lanes = q.lane_depths()
    floor = admission.shed_floor()
    return _table("crdb_internal.node_tenant_admission", [
        ("tenant_id", T.INT64, _ints(r["tenant_id"] for r in rows)),
        ("tokens", T.FLOAT64, _floats(r["tokens"] for r in rows)),
        ("rate", T.FLOAT64, _floats(r["rate"] for r in rows)),
        ("burst", T.FLOAT64, _floats(r["burst"] for r in rows)),
        ("vtime", T.FLOAT64, _floats(r["vtime"] for r in rows)),
        ("weight", T.FLOAT64, _floats(r["weight"] for r in rows)),
        ("admitted", T.INT64, _ints(r["admitted"] for r in rows)),
        ("rejected", T.INT64, _ints(r["rejected"] for r in rows)),
        ("queue_interactive", T.INT64,
         _ints([lanes.get(admission.LANE_INTERACTIVE, 0)] * len(rows))),
        ("queue_analytical", T.INT64,
         _ints([lanes.get(admission.LANE_ANALYTICAL, 0)] * len(rows))),
        ("shed_floor", T.INT64, _ints([floor] * len(rows))),
    ])


def _cluster_queries(catalog) -> Table:
    from . import activity

    rows = activity.queries()
    return _table("crdb_internal.cluster_queries", [
        ("query_id", T.INT64, _ints(r["id"] for r in rows)),
        ("session_id", T.INT64, _ints(r["session_id"] for r in rows)),
        ("query", T.STRING, _strs(r["query"] for r in rows)),
        ("phase", T.STRING, _strs(r["phase"] for r in rows)),
        ("elapsed_ms", T.FLOAT64,
         _floats(r["elapsed_s"] * 1e3 for r in rows)),
    ])


def _cluster_sessions(catalog) -> Table:
    from . import activity

    rows = activity.sessions()
    return _table("crdb_internal.cluster_sessions", [
        ("session_id", T.INT64, _ints(r["id"] for r in rows)),
        ("application_name", T.STRING,
         _strs(r["application_name"] for r in rows)),
        ("active_queries", T.INT64, _ints(r["active"] for r in rows)),
        ("session_age_s", T.FLOAT64,
         _floats(r["session_age_s"] for r in rows)),
    ])


def _node_metrics(catalog) -> Table:
    from ..utils import metric

    names: list[str] = []
    values: list[float] = []
    for name, m in list(metric.DEFAULT._metrics.items()):
        if isinstance(m, (metric.Counter, metric.Gauge)):
            names.append(name)
            values.append(m.value)
        elif isinstance(m, metric.Histogram):
            names.append(name + "_sum")
            values.append(m.sum)
            names.append(name + "_count")
            values.append(float(m.n))
        elif isinstance(m, metric.LabeledCounter):
            for k, v in m.items():
                names.append(f'{name}{{{m.label}="{k}"}}')
                values.append(v)
    return _table("crdb_internal.node_metrics", [
        ("name", T.STRING, _strs(names)),
        ("value", T.FLOAT64, _floats(values)),
    ])


def _inflight_trace_spans(catalog) -> Table:
    from ..utils import tracing

    spans = tracing.inflight()
    now = time.perf_counter()
    return _table("crdb_internal.node_inflight_trace_spans", [
        ("trace_id", T.INT64, _ints(s.trace_id for s in spans)),
        ("span_id", T.INT64, _ints(s.span_id for s in spans)),
        ("parent_span_id", T.INT64, _ints(s.parent_id for s in spans)),
        ("operation", T.STRING, _strs(s.name for s in spans)),
        ("elapsed_ms", T.FLOAT64,
         _floats((now - s.start) * 1e3 for s in spans)),
    ])


def _hot_ranges_payload(catalog) -> list[dict]:
    """The /_status/hot_ranges row shape, sourced from whatever range
    infrastructure the session's environment carries: a stashed Node's
    RangeLifecycle, else the engine's meta descriptor table, else empty
    (single-range standalone sessions legitimately have no ranges)."""
    node = getattr(catalog, "_crdb_node", None)
    ranger = getattr(node, "ranger", None) if node is not None else None
    if ranger is not None:
        return ranger.hot_ranges().get("hotRanges", [])
    db = getattr(catalog, "_crdb_db", None)
    eng = getattr(db, "engine", None) if db is not None else None
    meta = getattr(eng, "meta", None) if eng is not None else None
    if meta is None:
        return []
    return [{"rangeId": d.range_id,
             "startKey": d.start_key.decode(errors="replace"),
             "endKey": (d.end_key.decode(errors="replace")
                       if d.end_key is not None else None),
             "storeId": d.store_id, "qps": 0.0, "writeBytesRate": 0.0,
             "sizeBytes": None, "leaseholder": None}
            for d in meta.snapshot()]


def _hot_ranges(catalog) -> Table:
    rows = _hot_ranges_payload(catalog)
    return _table("crdb_internal.hot_ranges", [
        ("range_id", T.INT64, _ints(r.get("rangeId", 0) for r in rows)),
        ("start_key", T.STRING, _strs(r.get("startKey", "") for r in rows)),
        ("end_key", T.STRING,
         _strs(r.get("endKey") or "" for r in rows)),
        ("store_id", T.INT64, _ints(r.get("storeId") or 0 for r in rows)),
        ("qps", T.FLOAT64, _floats(r.get("qps") or 0.0 for r in rows)),
        ("write_bytes_rate", T.FLOAT64,
         _floats(r.get("writeBytesRate") or 0.0 for r in rows)),
        ("size_bytes", T.INT64,
         _ints(r.get("sizeBytes") or 0 for r in rows)),
        ("leaseholder", T.INT64,
         _ints(r.get("leaseholder") or 0 for r in rows)),
    ])


def _node_changefeed_subscribers(catalog) -> Table:
    """Per-registration fan-out state (the changefeed observability
    surface): span, resolved frontier, buffered bytes/events, and the
    backpressure-ladder counters (coalesced, sheds), one row per live
    subscriber across every rangefeed hub on this node — so one query
    answers "who is behind, by how much, and what has the ladder already
    done about it"."""
    from ..kv import fanout

    rows = fanout.subscriber_rows()
    return _table("crdb_internal.node_changefeed_subscribers", [
        ("hub", T.STRING, _strs(r["hub"] for r in rows)),
        ("subscriber_id", T.INT64, _ints(r["subscriber_id"] for r in rows)),
        ("state", T.STRING, _strs(r["state"] for r in rows)),
        ("span_start", T.STRING, _strs(r["span_start"] for r in rows)),
        ("span_end", T.STRING, _strs(r["span_end"] for r in rows)),
        ("frontier", T.INT64, _ints(r["frontier"] for r in rows)),
        ("buffered_bytes", T.INT64,
         _ints(r["buffered_bytes"] for r in rows)),
        ("buffered_events", T.INT64,
         _ints(r["buffered_events"] for r in rows)),
        ("sent_events", T.INT64, _ints(r["sent_events"] for r in rows)),
        ("coalesced", T.INT64, _ints(r["coalesced"] for r in rows)),
        ("sheds", T.INT64, _ints(r["sheds"] for r in rows)),
        ("age_s", T.FLOAT64, _floats(r["age_s"] for r in rows)),
    ])


def _node_materialized_views(catalog) -> Table:
    """Per-view standing state (the incremental-matview observability
    surface): group count, resolved frontier, last refresh lag, and the
    two fallback counters — min/max retraction rescans (delta algebra
    couldn't answer) and full rebuilds (group key outgrew the dense
    layout) — one row per registered view on this catalog."""
    from . import matview

    reg = matview.registry_for(catalog)
    rows = reg.rows() if reg is not None else []
    return _table("crdb_internal.node_materialized_views", [
        ("view", T.STRING, _strs(r["view"] for r in rows)),
        ("base_table", T.STRING, _strs(r["base_table"] for r in rows)),
        ("groups", T.INT64, _ints(r["groups"] for r in rows)),
        ("frontier", T.INT64, _ints(r["frontier"] for r in rows)),
        ("refresh_lag_s", T.FLOAT64,
         _floats(r["refresh_lag_s"] for r in rows)),
        ("minmax_rescans", T.INT64,
         _ints(r["minmax_rescans"] for r in rows)),
        ("full_rescans", T.INT64, _ints(r["full_rescans"] for r in rows)),
        ("stale", T.STRING, _strs(r["stale"] for r in rows)),
    ])


def _node_warmup_menu(catalog) -> Table:
    """Ahead-of-time kernel menu state (sql/warmmenu.py): one row per
    menu item with its source course (explicit/hot/ladder), outcome
    (compiled/failed/skipped), kernels minted, build seconds, and
    serving-path hits — so EXPLAIN-reachable SQL can audit what the cold
    wall cost at startup and what it is saving now."""
    from . import warmmenu

    rows = warmmenu.menu_rows()
    return _table("crdb_internal.node_warmup_menu", [
        ("fingerprint", T.STRING, _strs(r["fingerprint"] for r in rows)),
        ("source", T.STRING, _strs(r["source"] for r in rows)),
        ("status", T.STRING, _strs(r["status"] for r in rows)),
        ("kernels", T.INT64, _ints(r["kernels"] for r in rows)),
        ("seconds", T.FLOAT64, _floats(r["seconds"] for r in rows)),
        ("hits", T.INT64, _ints(r["hits"] for r in rows)),
    ])


_BUILDERS = {
    "crdb_internal.node_statement_statistics": _stmt_statistics,
    "crdb_internal.cluster_queries": _cluster_queries,
    "crdb_internal.cluster_sessions": _cluster_sessions,
    "crdb_internal.node_metrics": _node_metrics,
    "crdb_internal.node_inflight_trace_spans": _inflight_trace_spans,
    "crdb_internal.hot_ranges": _hot_ranges,
    "crdb_internal.node_memory_monitors": _memory_monitors,
    "crdb_internal.cluster_load": _cluster_load,
    "crdb_internal.node_tenant_admission": _node_tenant_admission,
    "crdb_internal.node_changefeed_subscribers": _node_changefeed_subscribers,
    "crdb_internal.node_materialized_views": _node_materialized_views,
    "crdb_internal.node_warmup_menu": _node_warmup_menu,
}


def table_names() -> tuple[str, ...]:
    return tuple(sorted(_BUILDERS))


def is_virtual(name: str) -> bool:
    return name.startswith(PREFIX)


def build(catalog, name: str) -> Table:
    """Materialize (or return this statement's cached materialization of)
    one virtual table. Raises KeyError for unknown names — the binder
    surfaces that as its usual unknown-table error."""
    builder = _BUILDERS.get(name)
    if builder is None:
        raise KeyError(name)
    key = (id(catalog), name)
    hit = _cache.get(key)
    if hit is not None and hit[0] == _gen:
        return hit[1]
    t = builder(catalog)
    _cache[key] = (_gen, t)
    return t
