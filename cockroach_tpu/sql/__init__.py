"""SQL front end: parser (pkg/sql/parser analog), binder (optbuilder analog),
and the Rel fluent plan builder. ``sql(catalog, text)`` parses + plans a
SELECT into an executable Rel."""

from .binder import BindError, sql
from .rel import Rel
from .session import Session


def explain(catalog, text: str) -> str:
    """EXPLAIN / EXPLAIN ANALYZE / EXPLAIN (DISTSQL) over SQL text. Accepts
    the statement with or without the leading EXPLAIN keywords."""
    t = text.strip()
    low = t.lower()
    analyze = False
    distsql = False
    if low.startswith("explain"):
        t = t[len("explain"):].lstrip()
        if t.lower().startswith("(distsql)"):
            distsql = True
            t = t[len("(distsql)"):].lstrip()
        if t.lower().startswith("analyze"):
            analyze = True
            t = t[len("analyze"):].lstrip()
    rel = sql(catalog, t)
    if distsql:
        return rel.explain_distributed()
    if analyze:
        from . import plancache

        rendered, _ = rel.explain_analyze()
        # status a NORMAL execution of this statement would see (analyze
        # itself always runs a fresh instrumented tree)
        return rendered + f"\nplan cache: {plancache.probe(rel)}"
    return rel.explain()


__all__ = ["BindError", "Rel", "Session", "explain", "sql"]
