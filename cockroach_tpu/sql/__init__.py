"""SQL front end: parser (pkg/sql/parser analog), binder (optbuilder analog),
and the Rel fluent plan builder. ``sql(catalog, text)`` parses + plans a
SELECT into an executable Rel."""

from .binder import BindError, sql
from .rel import Rel

__all__ = ["BindError", "Rel", "sql"]
