"""SQL front end: parser (pkg/sql/parser analog), binder (optbuilder analog),
and the Rel fluent plan builder. ``sql(catalog, text)`` parses + plans a
SELECT into an executable Rel."""

from .binder import BindError, sql
from .rel import Rel
from .session import Session


def explain(catalog, text: str) -> str:
    """EXPLAIN / EXPLAIN ANALYZE [(DEBUG)] / EXPLAIN (DISTSQL) over SQL text.
    Accepts the statement with or without the leading EXPLAIN keywords.
    ANALYZE (DEBUG) additionally captures a statement diagnostics bundle
    (sql/diagnostics.py) and reports its id."""
    t = text.strip()
    low = t.lower()
    analyze = False
    distsql = False
    debug = False
    if low.startswith("explain"):
        t = t[len("explain"):].lstrip()
        if t.lower().startswith("(distsql)"):
            distsql = True
            t = t[len("(distsql)"):].lstrip()
        if t.lower().startswith("analyze"):
            analyze = True
            t = t[len("analyze"):].lstrip()
            if t.lower().startswith("(debug)"):
                debug = True
                t = t[len("(debug)"):].lstrip()
    rel = sql(catalog, t)
    from . import matview

    note = matview.explain_note(catalog, rel)
    prefix = (note + "\n") if note else ""
    if distsql:
        return prefix + rel.explain_distributed()
    if analyze:
        import time as _time
        from types import SimpleNamespace

        from . import plancache

        t0 = _time.perf_counter()
        rendered, _ = rel.explain_analyze()
        elapsed = _time.perf_counter() - t0
        # status a NORMAL execution of this statement would see (analyze
        # itself always runs a fresh instrumented tree)
        from ..storage import blockcache

        out = rendered + f"\nplan cache: {plancache.probe(rel)}"
        # storage read-path health alongside the plan status: how much of
        # this node's point/seek traffic the block cache absorbed
        out += f"\nblock cache: {blockcache.node_cache().describe()}"
        # serving-plane health: what admission a normal execution of this
        # statement would face right now (its lane, the queue, shed state)
        from ..utils import admission

        aq = admission.sql_queue()
        pri = admission.classify_statement(t)
        lanes = aq.lane_depths()
        out += (f"\nadmission: lane={admission.lane_for(pri)} "
                f"slots={aq.in_use}/{aq.slots} "
                f"queued={lanes[admission.LANE_INTERACTIVE]}i"
                f"+{lanes[admission.LANE_ANALYTICAL]}a "
                f"shed_floor={admission.shed_floor()} "
                f"rejected={aq.rejected}")
        if debug:
            from . import diagnostics
            from ..flow.runtime import last_trace_span

            bundle = diagnostics.capture(
                SimpleNamespace(catalog=catalog), t, elapsed_s=elapsed,
                span=last_trace_span(), trigger="explain_analyze_debug",
            )
            out += f"\ndiagnostics bundle: {bundle['id']}"
        return prefix + out
    return prefix + rel.explain()


__all__ = ["BindError", "Rel", "Session", "explain", "sql"]
