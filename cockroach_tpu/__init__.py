"""cockroach_tpu — a TPU-native vectorized distributed SQL execution framework.

Re-expresses the capability surface of CockroachDB's vectorized DistSQL engine
(reference: /root/reference, pkg/sql/colexec*, pkg/col, pkg/sql/colflow) as an
idiomatic JAX/XLA/Pallas design:

- ``coldata``   — Arrow-compatible columnar batches with static tile shapes and
                  validity masks (reference: pkg/col/coldata).
- ``ops``       — dtype-polymorphic jitted kernels replacing the 500k lines of
                  execgen-generated .eg.go operators (reference: pkg/sql/colexec).
- ``flow``      — the pull-based Operator contract and flow runtime
                  (reference: pkg/sql/colexecop/operator.go:21, pkg/sql/colflow).
- ``plan``      — physical plan IR, the execinfrapb.ProcessorSpec analog
                  (reference: pkg/sql/execinfrapb, colbuilder/execplan.go:736).
- ``parallel``  — mesh shuffles: the HashRouter/Outbox/Inbox gRPC shuffle becomes
                  an all-to-all over ICI (reference: pkg/sql/colflow/routers.go:420,
                  colrpc/outbox.go:44).
- ``storage``   — MVCC version-filter and LSM k-way merge kernels (reference:
                  pkg/storage/pebble_mvcc_scanner.go:381, pebble compaction).

Int64/float64 support is required for SQL semantics (DECIMAL as scaled int64,
TIMESTAMP as int64 micros), so x64 mode is enabled at import.
"""

import jax

jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
