"""Query error boundary — the colexecerror analog.

Reference: pkg/sql/colexecerror/error.go:45 CatchVectorizedRuntimeError
converts engine panics (index-out-of-range in generated kernels, internal
assertions) into SQL errors at the flow boundary so a bad kernel never
takes down the process with a raw stack. Here the boundary wraps the flow
pull loop and the distributed SPMD runner: any failure below it surfaces
as a typed QueryError carrying the failing operator/stage context, while
programming errors in the session layer (BindError and friends) pass
through untouched.
"""

from __future__ import annotations


class QueryError(Exception):
    """A query failed inside the execution engine. str() is user-facing;
    __cause__ keeps the original exception for debugging."""

    def __init__(self, stage: str, cause: BaseException):
        self.stage = stage
        super().__init__(
            f"query execution failed in {stage}: "
            f"{type(cause).__name__}: {cause}"
        )


class AdmissionRejectedError(Exception):
    """A statement was refused admission — wait queue at
    admission.sql.max_queue_depth, tenant token bucket empty, the node
    shedding this priority lane under overload, or the queue-wait
    deadline ran out. Maps to SQLSTATE 53300 ("too many connections" /
    server busy) at the pgwire boundary; ``retry_after_s`` is the hint
    clients should back off by (the tenant bucket's refill time when
    rate-limited, a queue-drain estimate otherwise)."""

    def __init__(self, reason: str, retry_after_s: float = 0.0,
                 tenant_id: int | None = None):
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.tenant_id = tenant_id
        msg = f"admission rejected: {reason}"
        if retry_after_s > 0:
            msg += f" (retry after {retry_after_s:.3f}s)"
        super().__init__(msg)


class SlowConsumerError(Exception):
    """A changefeed subscriber fell too far behind and was evicted from
    the fan-out plane (kvserver/rangefeed's BufferedSender eviction: the
    processor never blocks raft apply on one stuck registration). The
    error carries the subscriber's last durably-delivered resolved
    timestamp — ``frontier`` — which is the exact ``since`` a reconnect
    must present to resume without loss; events after the frontier may
    re-deliver and are deduplicated by (ts, key)."""

    def __init__(self, subscriber_id: int, reason: str, frontier: int = 0):
        self.subscriber_id = subscriber_id
        self.reason = reason
        self.frontier = frontier
        super().__init__(
            f"slow consumer {subscriber_id} evicted ({reason}); "
            f"reconnect with since={frontier}")


# exception types that are NOT engine failures and must pass through the
# boundary untouched (user-facing or control-flow exceptions)
_PASSTHROUGH: tuple[type, ...] = (QueryError, KeyboardInterrupt, SystemExit,
                                  AdmissionRejectedError)


def register_passthrough(exc_type: type) -> None:
    """Let a domain exception (e.g. kv.WriteIntentError) cross the boundary
    unwrapped — the analog of colexecerror.ExpectedError."""
    global _PASSTHROUGH
    if exc_type not in _PASSTHROUGH:
        _PASSTHROUGH = _PASSTHROUGH + (exc_type,)


def query_boundary(stage: str):
    """Decorator: wrap engine failures in QueryError (panic->error)."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            try:
                return fn(*args, **kwargs)
            except _PASSTHROUGH:
                raise
            except Exception as e:
                raise QueryError(stage, e) from e
        return wrapped

    return deco


def retry_past_intents(fn, deadline_s: float = 0.5):
    """Run a status-level read, retrying briefly past WriteIntentError:
    background loops (heartbeats, jobs adoption) commit constantly, and a
    status probe (admin HTTP endpoint, is_live check) must never fail just
    because a txn was mid-commit. The reference serves such reads from
    caches/gossip for the same reason. Raises the final WriteIntentError
    if the intent outlives the deadline (a genuinely wedged writer)."""
    import time

    from ..storage.lsm import WriteIntentError

    deadline = time.time() + deadline_s
    while True:
        try:
            return fn()
        except WriteIntentError:
            if time.time() >= deadline:
                raise
            time.sleep(0.005)
