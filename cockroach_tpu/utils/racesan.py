"""Runtime data-race sanitizer — the Eraser lockset algorithm over
control-plane shared state.

Reference: CockroachDB runs its race-prone packages under Go's TSan
(``make testrace``); crlint's static shared-state pass is the
ahead-of-time half of that discipline, and this module is the runtime
half: a lockset checker (Savage et al.'s Eraser) for the fields the
static pass cannot prove, armed only under chaos.

Per tracked field the sanitizer keeps a tiny state machine:

* **exclusive(owner)** — only one thread has touched the field so far.
  Single-threaded init and publish-before-spawn patterns never report.
* on the first access from a SECOND thread the field transfers to a
  shared state and its candidate lockset ``C`` is seeded from the locks
  that thread holds (``C := L``);
* every later access refines ``C ∩= L``.  The moment ``C`` goes empty on
  a write-involved access — a lockset-disjoint write/write or
  write-after-read-under-different-locks — :class:`DataRaceError` is
  raised at the access, naming both sides' threads and locksets.  A
  would-be heisenbug becomes a stack trace in the chaos suite.

The lockset is the per-thread held stack maintained by
``utils/locks.py``'s ordered wrappers (kept live under EITHER
``debug.lock_order.enabled`` or ``debug.race_detector.enabled``), so
"lock" here means a named control-plane OrderedLock — exactly the locks
the static passes reason about.  Bare hot-path locks are invisible by
design; fields guarded by them should not call into the sanitizer.

Product code instruments a shared field with one line at each access::

    racesan.note_write(self, "_conns")   # under the publishing lock
    racesan.note_read(self, "_conns")

Both are a single module-bool-equivalent settings check when the
detector is off — production paths pay one dict lookup, no tracking
state is ever allocated.  The chaos suite arms the detector for every
test via an autouse fixture (tests/test_chaos.py) and calls
:func:`reset` between tests so ownership transfer in one scenario cannot
leak candidate locksets into the next.
"""

from __future__ import annotations

import threading

from . import locks, settings

__all__ = ["DataRaceError", "note_read", "note_write", "reset", "armed"]


class DataRaceError(RuntimeError):
    """Two threads accessed a tracked field (at least one write) with no
    common lock ever held across the accesses."""


class _FieldState:
    __slots__ = ("mode", "owner", "written", "lockset",
                 "last_writer", "last_writer_locks")

    def __init__(self, owner: int):
        self.mode = "exclusive"     # exclusive | shared | shared_mod
        self.owner = owner
        self.written = False
        self.lockset: frozenset | None = None  # candidate set C
        self.last_writer: str | None = None
        self.last_writer_locks: frozenset = frozenset()


# keyed by (id(obj), field); the entry pins a strong ref to obj so the id
# cannot be recycled while armed. Bounded: tracking only allocates while
# the detector is on, and the chaos fixture reset()s between tests.
_mu = threading.Lock()  # leaf lock: never taken while calling out
_fields: dict[tuple[int, str], tuple[object, _FieldState]] = {}


def armed() -> bool:
    return bool(settings.get("debug.race_detector.enabled"))


def reset() -> None:
    """Drop all tracking state (test isolation)."""
    with _mu:
        _fields.clear()


def note_write(obj: object, field: str) -> None:
    """Record a write to ``obj.field`` by the current thread. Call at the
    assignment site, under whatever lock guards it."""
    if armed():
        _note(obj, field, True)


def note_read(obj: object, field: str) -> None:
    """Record a read of ``obj.field`` by the current thread."""
    if armed():
        _note(obj, field, False)


def _note(obj: object, field: str, is_write: bool) -> None:
    tid = threading.get_ident()
    held = frozenset(locks._held_stack())
    tname = threading.current_thread().name
    with _mu:
        key = (id(obj), field)
        entry = _fields.get(key)
        if entry is None:
            st = _FieldState(tid)
            _fields[key] = (obj, st)
        else:
            st = entry[1]
        if st.mode == "exclusive":
            if st.owner == tid:
                st.written = st.written or is_write
                if is_write:
                    st.last_writer, st.last_writer_locks = tname, held
                return
            # ownership transfer: second thread arrives. Seed C from ITS
            # lockset — the first thread's accesses are already history
            # (Eraser's refinement-starts-at-sharing rule, which is what
            # lets single-threaded init go unguarded without a report).
            st.mode = ("shared_mod" if (is_write or st.written)
                       else "shared")
            st.lockset = held
        else:
            if is_write:
                st.mode = "shared_mod"
            st.lockset = (held if st.lockset is None
                          else st.lockset & held)
        racy = st.mode == "shared_mod" and not st.lockset
        if is_write:
            prev = (st.last_writer, st.last_writer_locks)
            st.last_writer, st.last_writer_locks = tname, held
        else:
            prev = (st.last_writer, st.last_writer_locks)
        if not racy:
            return
        what = "write" if is_write else "read"
        other = (f"last write by thread {prev[0]!r} holding "
                 f"{sorted(prev[1]) or 'no locks'}" if prev[0]
                 else "an earlier unlocked access")
        raise DataRaceError(
            f"data race on {type(obj).__name__}.{field}: {what} by thread "
            f"{tname!r} holding {sorted(held) or 'no locks'} shares no "
            f"lock with {other} — no common lock ever guarded this field "
            "across threads"
        )
