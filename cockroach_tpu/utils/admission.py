"""Admission control — the pkg/util/admission reduction.

Reference: GrantCoordinator (grant_coordinator.go:297) grants slots/tokens
to a priority-ordered WorkQueue (work_queue.go:280); IO tokens refill from
Pebble L0 health (io_load_listener.go) so writers slow down before the LSM
inverts. Here the same two pieces at single-process scale:

- ``WorkQueue``: bounded concurrency slots granted strictly by (priority,
  arrival) order; released slots wake the highest-priority waiter. Grant
  vs timeout-withdrawal is decided atomically under the queue lock via an
  explicit per-waiter grant flag: a waiter that times out while a grant
  is racing in HANDS THE SLOT BACK (re-granted to the next waiter or
  freed) and returns False — a timed-out admit never silently holds a
  slot, and a granted slot is never leaked.
- ``IOGovernor``: watches the engine's L0 run count AND the node's memory
  pressure (flow/memory.py root monitor vs sql.mem.root_budget_bytes) and
  computes a token delay for write work once either falls behind (the
  io_load_listener shape: back-pressure proportional to overload).

The process-wide SQL queue (``sql_queue()`` / ``sql_slot()``) sits under
sql/session.py: every statement takes a slot before executing, exporting
queue depth / slots-in-use gauges and the admission_wait_seconds
histogram (admission.sql.enabled / admission.sql.slots).
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
import threading
import time

from . import locks, metric

# work priorities (admissionpb ordering)
LOW = 0
NORMAL = 10
HIGH = 20


class _Waiter:
    """Queue entry. ``granted``/``withdrawn`` transitions happen only
    under the WorkQueue lock, so exactly one of the two ever wins."""

    __slots__ = ("event", "granted", "withdrawn")

    def __init__(self):
        self.event = threading.Event()
        self.granted = False
        self.withdrawn = False


class WorkQueue:
    """Priority-ordered admission with bounded slots (WorkQueue +
    slot-based GrantCoordinator). ``instrument=True`` exports the shared
    admission gauges/histogram (only the process SQL queue sets it, so
    test-local queues don't fight over the node metrics)."""

    def __init__(self, slots: int = 4, instrument: bool = False):
        self._slots = slots
        self._used = 0
        self._lock = locks.lock("admission")
        # heap of (-priority, seq, _Waiter); withdrawn entries are skipped
        # lazily at grant time instead of O(n) heap surgery on timeout
        self._waiters: list = []
        self._nwaiting = 0
        self._seq = itertools.count()
        self._instrument = instrument
        self.admitted = 0
        self.waited = 0
        self.timeouts = 0
        if instrument:
            metric.ADMISSION_SQL_SLOTS.set(slots)
            self._publish()

    @property
    def slots(self) -> int:
        return self._slots

    @property
    def in_use(self) -> int:
        return self._used

    @property
    def queue_depth(self) -> int:
        return self._nwaiting

    def _publish(self) -> None:
        # called under self._lock
        if self._instrument:
            metric.ADMISSION_SQL_SLOTS_IN_USE.set(self._used)
            metric.ADMISSION_SQL_QUEUE_DEPTH.set(self._nwaiting)

    def refresh_gauges(self) -> None:
        """Re-publish gauges (background metrics scraper hook)."""
        with self._lock:
            if self._instrument:
                metric.ADMISSION_SQL_SLOTS.set(self._slots)
            self._publish()

    def _grant_locked(self) -> bool:
        """Hand the freed slot to the highest-priority live waiter; False
        when no live waiter remains (caller frees the slot instead)."""
        while self._waiters:
            _, _, w = heapq.heappop(self._waiters)
            if w.withdrawn:
                continue  # timed out earlier; already uncounted
            w.granted = True
            w.event.set()
            self._nwaiting -= 1
            return True
        return False

    def admit(self, priority: int = NORMAL, timeout: float | None = None
              ) -> bool:
        """Block until a slot is granted (higher priority first). Returns
        False only on timeout, in which case NO slot is held — a grant
        racing the timeout is handed back under the lock."""
        t0 = time.perf_counter()
        with self._lock:
            if self._used < self._slots and not self._waiters:
                self._used += 1
                self.admitted += 1
                if self._instrument:
                    # fast-path admissions observe too: the wait histogram
                    # must count EVERY admission so queue-wait percentiles
                    # reflect the workload, not just its queued tail
                    metric.ADMISSION_WAIT_SECONDS.observe(
                        time.perf_counter() - t0)
                self._publish()
                return True
            w = _Waiter()
            heapq.heappush(self._waiters, (-priority, next(self._seq), w))
            self._nwaiting += 1
            self.waited += 1
            self._publish()
        granted = w.event.wait(timeout)
        with self._lock:
            if not w.granted:
                # pure timeout: withdraw (lazily — the heap entry is
                # skipped at the next grant) and hold nothing
                w.withdrawn = True
                self._nwaiting -= 1
                self.timeouts += 1
                if self._instrument:
                    metric.ADMISSION_SQL_TIMEOUTS.inc()
                self._publish()
                return False
            if not granted and timeout is not None:
                # the race: our event was set concurrently with the
                # timeout expiring. The grant is definitive (flag set
                # under this lock), but the caller asked for a deadline —
                # hand the slot to the next waiter (or free it) and
                # report the timeout instead of silently keeping it
                if not self._grant_locked():
                    self._used = max(0, self._used - 1)
                self.timeouts += 1
                if self._instrument:
                    metric.ADMISSION_SQL_TIMEOUTS.inc()
                self._publish()
                return False
            self.admitted += 1
            if self._instrument:
                metric.ADMISSION_WAIT_SECONDS.observe(
                    time.perf_counter() - t0)
            self._publish()
        return True

    def release(self) -> None:
        with self._lock:
            if not self._grant_locked():
                self._used = max(0, self._used - 1)
            self._publish()

    def __enter__(self):
        self.admit()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


# -- the process SQL admission queue (session statements) -------------------

_SQL_QUEUE: WorkQueue | None = None
_SQL_QUEUE_LOCK = threading.Lock()
_TLS = threading.local()


def sql_queue() -> WorkQueue:
    """The node's shared statement-admission queue, sized by
    admission.sql.slots at first use."""
    global _SQL_QUEUE
    with _SQL_QUEUE_LOCK:
        if _SQL_QUEUE is None:
            from . import settings

            _SQL_QUEUE = WorkQueue(
                slots=int(settings.get("admission.sql.slots")),
                instrument=True)
        return _SQL_QUEUE


def refresh_gauges() -> None:
    """Background metrics scraper hook: keep the admission gauges live
    even when no statement has run since the last scrape."""
    q = _SQL_QUEUE
    if q is not None:
        q.refresh_gauges()


@contextlib.contextmanager
def sql_slot(priority: int = NORMAL):
    """Hold one SQL admission slot for the duration (Session.execute wraps
    every statement in this). Yields the seconds spent queued. No-op when
    admission.sql.enabled is off, and re-entrant per thread so a nested
    statement (diagnostics re-run, internal executor) never deadlocks on
    its own session's slot."""
    from . import settings

    if not settings.get("admission.sql.enabled"):
        yield 0.0
        return
    depth = getattr(_TLS, "depth", 0)
    if depth > 0:
        _TLS.depth = depth + 1
        try:
            yield 0.0
        finally:
            _TLS.depth = depth
        return
    q = sql_queue()
    t0 = time.perf_counter()
    q.admit(priority)
    wait = time.perf_counter() - t0
    _TLS.depth = 1
    try:
        yield wait
    finally:
        _TLS.depth = 0
        q.release()


class IOGovernor:
    """L0-health + memory-pressure write back-pressure (io_load_listener
    reduction): when the engine's run count exceeds the healthy threshold,
    or the node's memory monitor runs hot against its budget, write work
    pays a delay proportional to the overload before proceeding."""

    # memory pressure past this fraction of sql.mem.root_budget_bytes
    # starts adding write delay (full budget = 10 runs' worth of delay)
    MEM_PRESSURE_FLOOR = 0.85

    def __init__(self, engine, healthy_runs: int | None = None,
                 delay_per_run_s: float = 0.001):
        self.engine = engine
        # default BELOW the compaction trigger: the engine compacts once
        # runs exceed l0_trigger, so pacing must engage while the LSM is
        # catching up, not only after (io_load_listener's point is to slow
        # writers BEFORE the inversion)
        self.healthy_runs = (healthy_runs if healthy_runs is not None
                             else max(1, engine.l0_trigger // 2))
        self.delay_per_run_s = delay_per_run_s
        self.throttled = 0
        # compaction pacing state (pace_compaction/note_compaction)
        self.compactions_deferred = 0
        self._last_compaction_t = 0.0
        self._pacing_wait_start: float | None = None

    def mem_delay_s(self) -> float:
        from ..flow import memory as flowmem

        p = flowmem.mem_pressure()
        over = p - self.MEM_PRESSURE_FLOOR
        if over <= 0:
            return 0.0
        # scales 0 -> 10 runs' worth of delay across the remaining
        # headroom, so a nearly-full monitor brakes writes hard
        return (over / (1.0 - self.MEM_PRESSURE_FLOOR)
                ) * 10 * self.delay_per_run_s

    def write_delay_s(self) -> float:
        over = len(self.engine.runs) - self.healthy_runs
        return max(0, over) * self.delay_per_run_s + self.mem_delay_s()

    def pace_write(self) -> float:
        """The single admission gate for engine write paths (put/ingest):
        checks the cluster setting here so callers cannot diverge."""
        from . import settings

        if not settings.get("admission.io_pacing.enabled"):
            return 0.0
        d = self.write_delay_s()
        if d > 0:
            self.throttled += 1
            time.sleep(d)
        return d

    def compaction_debt(self) -> int:
        """Runs past the L0 compaction trigger — the backlog the pacing
        loop amortizes."""
        return max(0, len(self.engine.runs) - self.engine.l0_trigger)

    def pace_compaction(self) -> bool:
        """Should the pending size-tiered compaction run NOW? The pacing
        loop: while debt stays at or under
        storage.compaction.pacing.max_debt_runs, compactions respect a
        minimum inter-compaction interval so back-to-back merges can't
        monopolize the device against foreground reads; past max debt the
        pacer steps aside — read amplification at that depth starves
        reads worse than any compaction pause. Deferred compactions are
        counted, and the eventual run records how long pacing held it
        (storage_compaction_pacing_delay_seconds)."""
        from . import settings

        if not settings.get("storage.compaction.pacing.enabled"):
            return True
        debt = self.compaction_debt()
        if debt <= 0:
            return False
        if debt > settings.get("storage.compaction.pacing.max_debt_runs"):
            return True
        min_iv = settings.get(
            "storage.compaction.pacing.min_interval_ms") / 1e3
        if min_iv <= 0:
            return True
        if time.monotonic() - self._last_compaction_t >= min_iv:
            return True
        self.compactions_deferred += 1
        if self._pacing_wait_start is None:
            self._pacing_wait_start = time.monotonic()
        return False

    def note_compaction(self) -> None:
        """Engine hook: a compaction just ran. Resets the pacing clock
        and, if pacing had been holding this compaction back, records the
        total deferral."""
        from . import metric

        now = time.monotonic()
        if self._pacing_wait_start is not None:
            metric.COMPACTION_PACING_DELAY.observe(
                now - self._pacing_wait_start)
            self._pacing_wait_start = None
        self._last_compaction_t = now
