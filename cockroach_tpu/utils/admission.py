"""Admission control — the pkg/util/admission reduction.

Reference: GrantCoordinator (grant_coordinator.go:297) grants slots/tokens
to a priority-ordered WorkQueue (work_queue.go:280); IO tokens refill from
Pebble L0 health (io_load_listener.go) so writers slow down before the LSM
inverts. Here the same two pieces at single-process scale:

- ``WorkQueue``: bounded concurrency slots granted strictly by (priority,
  arrival) order; released slots wake the highest-priority waiter.
- ``IOGovernor``: watches the engine's L0 run count and computes a token
  delay for write work once the LSM falls behind compaction (the
  io_load_listener shape: back-pressure proportional to overload).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time

from . import locks

# work priorities (admissionpb ordering)
LOW = 0
NORMAL = 10
HIGH = 20


class WorkQueue:
    """Priority-ordered admission with bounded slots (WorkQueue +
    slot-based GrantCoordinator)."""

    def __init__(self, slots: int = 4):
        self._slots = slots
        self._used = 0
        self._lock = locks.lock("admission")
        self._waiters: list = []  # heap of (-priority, seq, event)
        self._seq = itertools.count()
        self.admitted = 0
        self.waited = 0

    def admit(self, priority: int = NORMAL, timeout: float | None = None
              ) -> bool:
        """Block until a slot is granted (higher priority first)."""
        with self._lock:
            if self._used < self._slots and not self._waiters:
                self._used += 1
                self.admitted += 1
                return True
            ev = threading.Event()
            heapq.heappush(self._waiters,
                           (-priority, next(self._seq), ev))
            self.waited += 1
        if not ev.wait(timeout):
            with self._lock:
                # withdraw if still queued (timeout)
                for i, (_, _, w) in enumerate(self._waiters):
                    if w is ev:
                        self._waiters.pop(i)
                        heapq.heapify(self._waiters)
                        return False
            # granted between timeout and lock: keep the slot
            self.admitted += 1
            return True
        with self._lock:
            self.admitted += 1
        return True

    def release(self) -> None:
        with self._lock:
            if self._waiters:
                _, _, ev = heapq.heappop(self._waiters)
                ev.set()  # hand the slot directly to the waiter
            else:
                self._used = max(0, self._used - 1)

    def __enter__(self):
        self.admit()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class IOGovernor:
    """L0-health write back-pressure (io_load_listener reduction): when the
    engine's run count exceeds the healthy threshold, write work pays a
    delay proportional to the overload before proceeding."""

    def __init__(self, engine, healthy_runs: int | None = None,
                 delay_per_run_s: float = 0.001):
        self.engine = engine
        # default BELOW the compaction trigger: the engine compacts once
        # runs exceed l0_trigger, so pacing must engage while the LSM is
        # catching up, not only after (io_load_listener's point is to slow
        # writers BEFORE the inversion)
        self.healthy_runs = (healthy_runs if healthy_runs is not None
                             else max(1, engine.l0_trigger // 2))
        self.delay_per_run_s = delay_per_run_s
        self.throttled = 0

    def write_delay_s(self) -> float:
        over = len(self.engine.runs) - self.healthy_runs
        return max(0, over) * self.delay_per_run_s

    def pace_write(self) -> float:
        """The single admission gate for engine write paths (put/ingest):
        checks the cluster setting here so callers cannot diverge."""
        from . import settings

        if not settings.get("admission.io_pacing.enabled"):
            return 0.0
        d = self.write_delay_s()
        if d > 0:
            self.throttled += 1
            time.sleep(d)
        return d
