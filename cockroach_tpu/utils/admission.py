"""Admission control — the pkg/util/admission reduction.

Reference: GrantCoordinator (grant_coordinator.go:297) grants slots/tokens
to a priority-ordered WorkQueue (work_queue.go:280); IO tokens refill from
Pebble L0 health (io_load_listener.go) so writers slow down before the LSM
inverts. Here the same pieces at single-process scale, grown into a full
overload-survival plane:

- ``WorkQueue``: bounded concurrency slots granted by (priority lane,
  tenant fair-share, arrival) order; released slots wake the chosen
  waiter. Grant vs timeout-withdrawal is decided atomically under the
  queue lock via an explicit per-waiter grant flag: a waiter that times
  out while a grant is racing in HANDS THE SLOT BACK (re-granted to the
  next waiter or freed) and returns False — a timed-out admit never
  silently holds a slot, and a granted slot is never leaked.
- **Per-tenant token buckets** (``admission.tenant.{rate,burst}``): each
  tenant id (kv/tenant.py) refills tokens at ``rate``/s up to ``burst``;
  an admit with no token is refused immediately with a retry-after hint
  computed from the refill time — the tenant rate limiter half of the
  reference's tenant cost controller.
- **Priority lanes**: interactive (point/DML — HIGH/NORMAL) and
  analytical (LOW). Within a lane, slots are granted by stride-scheduled
  weighted fair share across tenants (each grant advances the tenant's
  virtual time by 1/weight; the tenant with the least virtual time wins),
  so a noisy neighbor queuing hundreds of statements cannot starve a
  well-behaved tenant's occasional one.
- **Queue-depth backpressure** (``admission.sql.max_queue_depth``): past
  the bound, admit fails fast with :class:`AdmissionRejectedError`
  instead of queuing to collapse; server/pgwire.py maps it to SQLSTATE
  53300 "server busy" so clients back off and retry.
- **Graceful shedding**: when flow/memory.py mem_pressure or the engine
  IOGovernor's L0 health crosses the ``admission.shed.*`` thresholds the
  queue sheds analytical work first (reject LOW, then NORMAL; HIGH —
  COMMIT/ROLLBACK — is shed last), the "degrade to a bounded-cost mode
  deliberately" discipline: slow death becomes a fast, observable
  refusal.
- ``IOGovernor``: watches the engine's L0 run count AND the node's memory
  pressure (flow/memory.py root monitor vs sql.mem.root_budget_bytes) and
  computes a token delay for write work once either falls behind (the
  io_load_listener shape: back-pressure proportional to overload). Its
  ``l0_overload()`` doubles as the shed ladder's IO-health input via
  :func:`set_io_health_provider`.

The process-wide SQL queue (``sql_queue()`` / ``sql_slot()``) sits under
sql/session.py: every statement takes a slot before executing — carrying
its session's tenant id, its lane (classify_statement), and the statement
deadline so queue-wait counts against statement_timeout — exporting queue
depth / slots-in-use / per-lane depth / per-tenant token gauges and the
admission_wait_seconds histogram.

Chaos: ``admission.grant.stall`` (a queued waiter's grant stalls or is
lost; error-kind withdraws the waiter and surfaces the typed busy) and
``admission.bucket.refill`` (token refill fails; typed busy with
retry-after) are registered in utils/faults.py and swept by the chaos
matrix with the race sanitizer armed.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time

from . import locks, metric, racesan
from .errors import AdmissionRejectedError

# work priorities (admissionpb ordering)
LOW = 0
NORMAL = 10
HIGH = 20

# priority lanes: interactive serves point/DML traffic (NORMAL and the
# txn-control HIGH), analytical serves the scan/aggregate tail (LOW).
# Shedding rejects analytical first — see shed_floor().
LANE_INTERACTIVE = "interactive"
LANE_ANALYTICAL = "analytical"


def lane_for(priority: int) -> str:
    return LANE_ANALYTICAL if priority < NORMAL else LANE_INTERACTIVE


# analytical-lane shape: scan/aggregate/join statements — the work shed
# first under overload. Point reads, DML and DDL stay interactive.
_ANALYTIC_RE = None
_TXN_CTL_RE = None


def classify_statement(text: str) -> int:
    """Admission priority for a SQL statement (the lane classifier):

    - txn control (COMMIT/ROLLBACK/END) -> HIGH: shed dead last, so
      in-flight transactions can always wind down and release intents
      (session.py short-circuits these before admission anyway; HIGH
      covers internal callers);
    - SELECTs carrying joins or aggregation -> LOW (analytical lane);
    - everything else (point SELECT, DML, DDL, SET/SHOW) -> NORMAL.
    """
    global _ANALYTIC_RE, _TXN_CTL_RE
    if _ANALYTIC_RE is None:
        import re

        _ANALYTIC_RE = re.compile(
            r"(?is)\b(group\s+by|join|sum\s*\(|count\s*\(|avg\s*\("
            r"|min\s*\(|max\s*\()")
        _TXN_CTL_RE = re.compile(r"(?is)^\s*(commit|rollback|abort|end)\b")
    if _TXN_CTL_RE.match(text):
        return HIGH
    t = text.lstrip()[:8].lower()
    if (t.startswith("select") or t.startswith("explain")) \
            and _ANALYTIC_RE.search(text):
        return LOW
    return NORMAL


# kv/tenant.py's SYSTEM_TENANT_ID — hardcoded (not imported) so the utils
# layer does not depend on kv; kv/tenant.py asserts the two stay equal.
SYSTEM_TENANT_ID = 1


class TokenBucket:
    """Per-tenant refillable token bucket (tenant rate limiter shape).
    rate <= 0 means unlimited (the default: operators opt tenants into
    rate limits via admission.tenant.rate). All methods are called under
    the owning WorkQueue's lock."""

    __slots__ = ("rate", "burst", "tokens", "_t_last")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self._t_last = time.monotonic()

    def take(self, now: float) -> float:
        """Consume one token. Returns 0.0 on success, else the seconds
        until one refills (the rejection's retry-after hint)."""
        if self.rate <= 0:
            return 0.0
        elapsed = now - self._t_last
        if elapsed > 0:
            self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
            self._t_last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return max(1e-3, (1.0 - self.tokens) / self.rate)

    def retry_after_s(self) -> float:
        """Seconds until the next token refills (no consumption)."""
        if self.rate <= 0:
            return 0.0
        return max(1e-3, (1.0 - min(self.tokens, 1.0)) / self.rate)


class _TenantState:
    """Per-tenant admission state: token bucket + stride-scheduler
    virtual time + counters. Lives in WorkQueue._tenants, guarded by the
    queue lock (racesan-instrumented)."""

    __slots__ = ("tenant_id", "bucket", "weight", "vtime",
                 "admitted", "rejected", "waits")

    # per-tenant queue-wait sample cap: enough for any bench window's
    # percentiles without unbounded growth on a long-lived node
    MAX_WAIT_SAMPLES = 65536

    def __init__(self, tenant_id: int, bucket: TokenBucket,
                 weight: float = 1.0, vtime: float = 0.0):
        self.tenant_id = tenant_id
        self.bucket = bucket
        self.weight = max(1e-6, weight)
        self.vtime = vtime
        self.admitted = 0
        self.rejected = 0
        # queue-wait seconds of this tenant's admitted statements (the
        # per-tenant half of admission_wait_seconds: the isolation oracle
        # reads p99 per tenant, which a global histogram cannot answer)
        self.waits: list[float] = []

    def note_wait(self, seconds: float) -> None:
        if len(self.waits) < self.MAX_WAIT_SAMPLES:
            self.waits.append(seconds)


class _Waiter:
    """Queue entry. ``granted``/``withdrawn`` transitions happen only
    under the WorkQueue lock, so exactly one of the two ever wins."""

    __slots__ = ("event", "granted", "withdrawn", "tenant", "lane")

    def __init__(self, tenant: _TenantState | None = None,
                 lane: str = LANE_INTERACTIVE):
        self.event = threading.Event()
        self.granted = False
        self.withdrawn = False
        self.tenant = tenant
        self.lane = lane


# shed ladder IO input: a zero-arg callable returning the node engine's
# L0 overload score (0 = healthy, 1.0 = at the shed-LOW threshold,
# >= 2.0 sheds NORMAL too). server/node.py points this at its engine
# governor's l0_overload; None (default, and in unit tests) reads as 0.
_IO_HEALTH = None
_IO_HEALTH_LOCK = threading.Lock()


def set_io_health_provider(fn) -> None:
    """Install (or with None, clear) the L0-health input of the shed
    ladder — one per process, the serving node's engine."""
    global _IO_HEALTH
    with _IO_HEALTH_LOCK:
        _IO_HEALTH = fn


def io_overload() -> float:
    with _IO_HEALTH_LOCK:
        fn = _IO_HEALTH
    if fn is None:
        return 0.0
    try:
        return max(0.0, float(fn()))
    except Exception:  # crlint: allow-broad-except(health probe of a possibly mid-close engine must degrade to "healthy", never take admission down)
        return 0.0


def shed_floor() -> int:
    """The minimum priority currently admitted (the graceful-degradation
    ladder). Healthy -> LOW (everything admitted). Memory pressure past
    admission.shed.mem_low, or IO overload >= 1, sheds the analytical
    lane (floor NORMAL); past admission.shed.mem_high, or IO overload
    >= 2, only HIGH (txn control) still lands."""
    from . import settings
    from ..flow import memory as flowmem

    p = flowmem.mem_pressure()
    io = io_overload()
    if p >= settings.get("admission.shed.mem_high") or io >= 2.0:
        return HIGH
    if p >= settings.get("admission.shed.mem_low") or io >= 1.0:
        return NORMAL
    return LOW


class WorkQueue:
    """Priority/fair-share admission with bounded slots and a bounded
    wait queue (WorkQueue + slot-based GrantCoordinator).
    ``instrument=True`` exports the shared admission gauges/histogram
    (only the process SQL queue sets it, so test-local queues don't fight
    over the node metrics). ``max_queue_depth=0`` leaves the wait queue
    unbounded (standalone/test queues); the process SQL queue takes it
    from admission.sql.max_queue_depth."""

    def __init__(self, slots: int = 4, instrument: bool = False,
                 max_queue_depth: int = 0):
        self._slots = slots
        self._used = 0
        self._max_queue_depth = max_queue_depth
        self._lock = locks.lock("admission")
        # list of (-priority, seq, _Waiter); granted/withdrawn entries are
        # skipped (and periodically compacted) at grant time instead of
        # O(n) surgery on every timeout. Grant order is decided by a scan
        # — highest live priority, then least tenant virtual time, then
        # arrival — so fairness reflects vtime AT GRANT TIME, not at
        # enqueue (a tenant hammering the queue advances its vtime with
        # every grant and loses the next tie).
        self._waiters: list = []
        self._nwaiting = 0
        self._lane_waiting = {LANE_INTERACTIVE: 0, LANE_ANALYTICAL: 0}
        self._seq = itertools.count()
        self._instrument = instrument
        # per-tenant buckets/vtime/counters; mutated only under _lock
        # (racesan-instrumented: the next control-plane shared state)
        self._tenants: dict[int, _TenantState] = {}
        self._vtime_floor = 0.0
        self.admitted = 0
        self.waited = 0
        self.timeouts = 0
        self.rejected = 0
        self.rejections_by_reason: dict[str, int] = {}
        if instrument:
            metric.ADMISSION_SQL_SLOTS.set(slots)
            self._publish()

    @property
    def slots(self) -> int:
        return self._slots

    @property
    def in_use(self) -> int:
        return self._used

    @property
    def queue_depth(self) -> int:
        return self._nwaiting

    @property
    def max_queue_depth(self) -> int:
        return self._max_queue_depth

    def lane_depths(self) -> dict[str, int]:
        with self._lock:
            racesan.note_read(self, "_lane_waiting")
            return dict(self._lane_waiting)

    def _publish(self) -> None:
        # called under self._lock
        if self._instrument:
            metric.ADMISSION_SQL_SLOTS_IN_USE.set(self._used)
            metric.ADMISSION_SQL_QUEUE_DEPTH.set(self._nwaiting)
            for lane, n in self._lane_waiting.items():
                metric.ADMISSION_LANE_QUEUE_DEPTH.set(lane, n)

    def _publish_tenant(self, st: _TenantState) -> None:
        # called under self._lock
        if self._instrument:
            metric.ADMISSION_TENANT_TOKENS.set(
                st.tenant_id,
                st.bucket.tokens if st.bucket.rate > 0 else -1.0)

    def refresh_gauges(self) -> None:
        """Re-publish gauges (background metrics scraper hook)."""
        with self._lock:
            if self._instrument:
                metric.ADMISSION_SQL_SLOTS.set(self._slots)
                racesan.note_read(self, "_tenants")
                for st in self._tenants.values():
                    self._publish_tenant(st)
            self._publish()

    # -- tenant state -------------------------------------------------------

    def _tenant_locked(self, tenant_id: int) -> _TenantState:
        """The tenant's admission state, created on first sight with the
        cluster-default bucket and its vtime clamped to the scheduler's
        floor (an idle tenant re-entering must not replay banked lag)."""
        racesan.note_read(self, "_tenants")
        st = self._tenants.get(tenant_id)
        if st is None:
            from . import settings

            st = _TenantState(
                tenant_id,
                TokenBucket(settings.get("admission.tenant.rate"),
                            settings.get("admission.tenant.burst")),
                vtime=self._vtime_floor)
            racesan.note_write(self, "_tenants")
            self._tenants[tenant_id] = st
        else:
            st.vtime = max(st.vtime, self._vtime_floor)
        return st

    def configure_tenant(self, tenant_id: int, rate: float | None = None,
                         burst: float | None = None,
                         weight: float | None = None) -> None:
        """Override one tenant's bucket/weight past the cluster defaults
        (the tenant-capability hook: sql/session.py applies a tenant's
        admission_rate / admission_burst / admission_weight caps here at
        bind time; benches and tests call it directly)."""
        with self._lock:
            st = self._tenant_locked(tenant_id)
            if rate is not None:
                st.bucket.rate = float(rate)
            if burst is not None:
                st.bucket.burst = max(1.0, float(burst))
                st.bucket.tokens = min(st.bucket.tokens, st.bucket.burst)
            if weight is not None:
                st.weight = max(1e-6, float(weight))
            self._publish_tenant(st)

    def tenant_wait_samples(self, tenant_id: int) -> list[float]:
        """Copy of the tenant's queue-wait samples (seconds, admitted
        statements only) — the per-tenant p99 isolation oracle's input."""
        with self._lock:
            racesan.note_read(self, "_tenants")
            st = self._tenants.get(tenant_id)
            return [] if st is None else list(st.waits)

    def tenant_rows(self) -> list[dict]:
        """Per-tenant admission snapshot (crdb_internal / /_status/load)."""
        with self._lock:
            racesan.note_read(self, "_tenants")
            rows = []
            for tid in sorted(self._tenants):
                st = self._tenants[tid]
                rows.append({
                    "tenant_id": tid,
                    "tokens": round(st.bucket.tokens, 3),
                    "rate": st.bucket.rate,
                    "burst": st.bucket.burst,
                    "vtime": round(st.vtime, 6),
                    "weight": st.weight,
                    "admitted": st.admitted,
                    "rejected": st.rejected,
                })
            return rows

    def _reject_locked(self, reason: str, tenant: _TenantState | None,
                       retry_after_s: float) -> AdmissionRejectedError:
        self.rejected += 1
        self.rejections_by_reason[reason] = (
            self.rejections_by_reason.get(reason, 0) + 1)
        tid = None
        if tenant is not None:
            tenant.rejected += 1
            tid = tenant.tenant_id
        if self._instrument:
            metric.ADMISSION_REJECTIONS.inc(
                tid if tid is not None else "untenanted")
        return AdmissionRejectedError(reason, retry_after_s=retry_after_s,
                                      tenant_id=tid)

    def suggest_retry_after(self, tenant_id: int | None = None) -> float:
        """Retry-after hint for a rejection: the tenant's bucket refill
        time when it is rate-limited, else a queue-drain guess (waiters
        ahead / slot turnover — bounded to stay a hint, not a promise)."""
        with self._lock:
            if tenant_id is not None:
                racesan.note_read(self, "_tenants")
                st = self._tenants.get(tenant_id)
                if st is not None and st.bucket.rate > 0:
                    return round(st.bucket.retry_after_s(), 4)
            return round(min(5.0, 0.05 * (1 + self._nwaiting)), 4)

    # -- grant path ---------------------------------------------------------

    def _grant_locked(self) -> bool:
        """Hand the freed slot to the best live waiter — highest priority
        first, least tenant virtual time within it (stride fair share),
        arrival order within a tenant; False when no live waiter remains
        (caller frees the slot instead)."""
        best = None
        best_key = None
        for entry in self._waiters:
            negp, seq, w = entry
            if w.withdrawn or w.granted:
                continue
            vt = w.tenant.vtime if w.tenant is not None else 0.0
            key = (negp, vt, seq)
            if best_key is None or key < best_key:
                best, best_key = w, key
        if best is None:
            self._waiters.clear()
            return False
        best.granted = True
        best.event.set()
        self._nwaiting -= 1
        racesan.note_write(self, "_lane_waiting")
        self._lane_waiting[best.lane] -= 1
        if best.tenant is not None:
            self._charge_locked(best.tenant)
        # compact once dead entries dominate (lazy-withdrawal bound)
        if len(self._waiters) > 2 * self._nwaiting + 16:
            self._waiters = [e for e in self._waiters
                             if not (e[2].withdrawn or e[2].granted)]
        return True

    def _charge_locked(self, st: _TenantState) -> None:
        """Advance the granted tenant's virtual time by 1/weight and drag
        the scheduler floor along so newly-arriving tenants start level."""
        self._vtime_floor = max(self._vtime_floor, st.vtime)
        st.vtime += 1.0 / st.weight
        st.admitted += 1

    def admit(self, priority: int = NORMAL, timeout: float | None = None,
              tenant_id: int | None = None) -> bool:
        """Block until a slot is granted (higher priority first, tenant
        fair share within a priority). Returns False only on timeout, in
        which case NO slot is held — a grant racing the timeout is handed
        back under the lock. Raises :class:`AdmissionRejectedError`
        without blocking when the node is shedding this priority, the
        tenant's token bucket is empty, or the wait queue is at
        max_queue_depth (tenant-aware callers only: ``tenant_id=None``
        keeps the raw slots-and-priorities behavior)."""
        from . import faults

        t0 = time.perf_counter()
        tenant_aware = tenant_id is not None
        if tenant_aware:
            # overload shed: the cheapest refusal, before any queue state
            floor = shed_floor()
            if priority < floor:
                with self._lock:
                    st = self._tenant_locked(tenant_id)
                    raise self._reject_locked(
                        f"overloaded: shedding {lane_for(priority)}-lane "
                        "work (mem pressure / L0 health past threshold)",
                        st, self.suggest_retry_after_locked(st))
            # tenant token bucket (admission.bucket.refill chaos site:
            # fired outside the lock so a delay-kind stall cannot wedge
            # the grant path for everyone else)
            try:
                faults.fire("admission.bucket.refill")
            except faults.InjectedFault as e:
                with self._lock:
                    st = self._tenant_locked(tenant_id)
                    raise self._reject_locked(
                        "tenant token-bucket refill failed",
                        st, st.bucket.retry_after_s()) from e
        with self._lock:
            st = self._tenant_locked(tenant_id) if tenant_aware else None
            if st is not None:
                retry = st.bucket.take(time.monotonic())
                self._publish_tenant(st)
                if retry > 0:
                    raise self._reject_locked(
                        "tenant rate limit: token bucket empty", st, retry)
            if self._used < self._slots and not self._nwaiting:
                self._used += 1
                self.admitted += 1
                if st is not None:
                    self._charge_locked(st)
                    st.note_wait(time.perf_counter() - t0)
                if self._instrument:
                    # fast-path admissions observe too: the wait histogram
                    # must count EVERY admission so queue-wait percentiles
                    # reflect the workload, not just its queued tail
                    metric.ADMISSION_WAIT_SECONDS.observe(
                        time.perf_counter() - t0)
                self._publish()
                return True
            # queue-depth backpressure: past the bound, fail fast with a
            # typed busy instead of queuing toward collapse
            if (self._max_queue_depth
                    and self._nwaiting >= self._max_queue_depth):
                raise self._reject_locked(
                    f"admission queue full "
                    f"(depth {self._nwaiting} >= "
                    f"admission.sql.max_queue_depth)",
                    st, self.suggest_retry_after_locked(st))
            w = _Waiter(st, lane_for(priority))
            self._waiters.append((-priority, next(self._seq), w))
            self._nwaiting += 1
            racesan.note_write(self, "_lane_waiting")
            self._lane_waiting[w.lane] += 1
            self.waited += 1
            self._publish()
        # admission.grant.stall chaos site: a stall (delay kind) just
        # holds this waiter — the grant still lands; a lost grant (error
        # kind) withdraws the waiter cleanly and surfaces the typed busy
        try:
            faults.fire("admission.grant.stall")
        except faults.InjectedFault as e:
            with self._lock:
                if w.granted:
                    # the grant raced in: hand the slot back, exactly the
                    # timeout-race discipline (never leak it)
                    if not self._grant_locked():
                        self._used = max(0, self._used - 1)
                else:
                    w.withdrawn = True
                    self._nwaiting -= 1
                    racesan.note_write(self, "_lane_waiting")
                    self._lane_waiting[w.lane] -= 1
                err = self._reject_locked(
                    "admission grant stalled/lost while queued", st,
                    self.suggest_retry_after_locked(st))
                self._publish()
            raise err from e
        granted = w.event.wait(timeout)
        with self._lock:
            if not w.granted:
                # pure timeout: withdraw (lazily — the entry is skipped
                # at the next grant) and hold nothing
                w.withdrawn = True
                self._nwaiting -= 1
                racesan.note_write(self, "_lane_waiting")
                self._lane_waiting[w.lane] -= 1
                self.timeouts += 1
                if self._instrument:
                    metric.ADMISSION_SQL_TIMEOUTS.inc()
                self._publish()
                return False
            if not granted and timeout is not None:
                # the race: our event was set concurrently with the
                # timeout expiring. The grant is definitive (flag set
                # under this lock), but the caller asked for a deadline —
                # hand the slot to the next waiter (or free it) and
                # report the timeout instead of silently keeping it
                if not self._grant_locked():
                    self._used = max(0, self._used - 1)
                self.timeouts += 1
                if self._instrument:
                    metric.ADMISSION_SQL_TIMEOUTS.inc()
                self._publish()
                return False
            self.admitted += 1
            if st is not None:
                st.note_wait(time.perf_counter() - t0)
            if self._instrument:
                metric.ADMISSION_WAIT_SECONDS.observe(
                    time.perf_counter() - t0)
            self._publish()
        return True

    def suggest_retry_after_locked(self, st: _TenantState | None) -> float:
        # under self._lock
        if st is not None and st.bucket.rate > 0:
            return round(st.bucket.retry_after_s(), 4)
        return round(min(5.0, 0.05 * (1 + self._nwaiting)), 4)

    def release(self) -> None:
        with self._lock:
            if not self._grant_locked():
                self._used = max(0, self._used - 1)
            self._publish()

    def __enter__(self):
        self.admit()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


# -- the process SQL admission queue (session statements) -------------------

_SQL_QUEUE: WorkQueue | None = None
_SQL_QUEUE_LOCK = threading.Lock()
_TLS = threading.local()


def sql_queue() -> WorkQueue:
    """The node's shared statement-admission queue, sized by
    admission.sql.slots / admission.sql.max_queue_depth at first use."""
    global _SQL_QUEUE
    with _SQL_QUEUE_LOCK:
        if _SQL_QUEUE is None:
            from . import settings

            _SQL_QUEUE = WorkQueue(
                slots=int(settings.get("admission.sql.slots")),
                instrument=True,
                max_queue_depth=int(
                    settings.get("admission.sql.max_queue_depth")))
        return _SQL_QUEUE


def refresh_gauges() -> None:
    """Background metrics scraper hook: keep the admission gauges live
    even when no statement has run since the last scrape."""
    q = _SQL_QUEUE
    if q is not None:
        q.refresh_gauges()


@contextlib.contextmanager
def sql_slot(priority: int = NORMAL, tenant_id: int | None = None,
             deadline: float | None = None):
    """Hold one SQL admission slot for the duration (Session.execute wraps
    every statement in this). Yields the seconds spent queued. No-op when
    admission.sql.enabled is off, and re-entrant per thread so a nested
    statement (diagnostics re-run, internal executor) never deadlocks on
    its own session's slot.

    ``deadline`` is a time.monotonic() instant (the statement deadline:
    queue-wait counts against statement_timeout); without one the wait is
    bounded by admission.sql.queue_timeout_s. Either way a wait that runs
    out raises :class:`AdmissionRejectedError` (SQLSTATE 53300 at the
    wire) — the old behavior of discarding admit()'s verdict and running
    WITHOUT a slot on a full queue is gone."""
    from . import settings

    if not settings.get("admission.sql.enabled"):
        yield 0.0
        return
    depth = getattr(_TLS, "depth", 0)
    if depth > 0:
        _TLS.depth = depth + 1
        try:
            yield 0.0
        finally:
            _TLS.depth = depth
        return
    q = sql_queue()
    if tenant_id is None:
        tenant_id = SYSTEM_TENANT_ID
    if deadline is not None:
        timeout = deadline - time.monotonic()
        if timeout <= 0:
            raise AdmissionRejectedError(
                "statement deadline expired before admission",
                retry_after_s=q.suggest_retry_after(tenant_id),
                tenant_id=tenant_id)
    else:
        backstop = float(settings.get("admission.sql.queue_timeout_s"))
        timeout = backstop if backstop > 0 else None
    t0 = time.perf_counter()
    if not q.admit(priority, timeout=timeout, tenant_id=tenant_id):
        raise AdmissionRejectedError(
            "queue-wait deadline exceeded"
            + (" (statement deadline)" if deadline is not None else ""),
            retry_after_s=q.suggest_retry_after(tenant_id),
            tenant_id=tenant_id)
    wait = time.perf_counter() - t0
    _TLS.depth = 1
    try:
        yield wait
    finally:
        _TLS.depth = 0
        q.release()


class IOGovernor:
    """L0-health + memory-pressure write back-pressure (io_load_listener
    reduction): when the engine's run count exceeds the healthy threshold,
    or the node's memory monitor runs hot against its budget, write work
    pays a delay proportional to the overload before proceeding."""

    # memory pressure past this fraction of sql.mem.root_budget_bytes
    # starts adding write delay (full budget = 10 runs' worth of delay)
    MEM_PRESSURE_FLOOR = 0.85

    def __init__(self, engine, healthy_runs: int | None = None,
                 delay_per_run_s: float = 0.001):
        self.engine = engine
        # default BELOW the compaction trigger: the engine compacts once
        # runs exceed l0_trigger, so pacing must engage while the LSM is
        # catching up, not only after (io_load_listener's point is to slow
        # writers BEFORE the inversion)
        self.healthy_runs = (healthy_runs if healthy_runs is not None
                             else max(1, engine.l0_trigger // 2))
        self.delay_per_run_s = delay_per_run_s
        self.throttled = 0
        # compaction pacing state (pace_compaction/note_compaction)
        self.compactions_deferred = 0
        self._last_compaction_t = 0.0
        self._pacing_wait_start: float | None = None

    def mem_delay_s(self) -> float:
        from ..flow import memory as flowmem

        p = flowmem.mem_pressure()
        over = p - self.MEM_PRESSURE_FLOOR
        if over <= 0:
            return 0.0
        # scales 0 -> 10 runs' worth of delay across the remaining
        # headroom, so a nearly-full monitor brakes writes hard
        return (over / (1.0 - self.MEM_PRESSURE_FLOOR)
                ) * 10 * self.delay_per_run_s

    def l0_overload(self) -> float:
        """Shed-ladder input (set_io_health_provider): 0 while the run
        count is at or under the COMPACTION trigger, reaching 1.0 (shed
        LOW) one healthy-threshold past it and 2.0 (shed NORMAL) two —
        admission sheds only once the LSM is genuinely behind, while
        write pacing (write_delay_s) engages earlier."""
        over = len(self.engine.runs) - self.engine.l0_trigger
        return max(0.0, over / max(1, self.healthy_runs))

    def write_delay_s(self) -> float:
        over = len(self.engine.runs) - self.healthy_runs
        return max(0, over) * self.delay_per_run_s + self.mem_delay_s()

    def pace_write(self) -> float:
        """The single admission gate for engine write paths (put/ingest):
        checks the cluster setting here so callers cannot diverge."""
        from . import settings

        if not settings.get("admission.io_pacing.enabled"):
            return 0.0
        d = self.write_delay_s()
        if d > 0:
            self.throttled += 1
            time.sleep(d)
        return d

    def compaction_debt(self) -> int:
        """Runs past the L0 compaction trigger — the backlog the pacing
        loop amortizes."""
        return max(0, len(self.engine.runs) - self.engine.l0_trigger)

    def pace_compaction(self) -> bool:
        """Should the pending size-tiered compaction run NOW? The pacing
        loop: while debt stays at or under
        storage.compaction.pacing.max_debt_runs, compactions respect a
        minimum inter-compaction interval so back-to-back merges can't
        monopolize the device against foreground reads; past max debt the
        pacer steps aside — read amplification at that depth starves
        reads worse than any compaction pause. Deferred compactions are
        counted, and the eventual run records how long pacing held it
        (storage_compaction_pacing_delay_seconds)."""
        from . import settings

        if not settings.get("storage.compaction.pacing.enabled"):
            return True
        debt = self.compaction_debt()
        if debt <= 0:
            return False
        if debt > settings.get("storage.compaction.pacing.max_debt_runs"):
            return True
        min_iv = settings.get(
            "storage.compaction.pacing.min_interval_ms") / 1e3
        if min_iv <= 0:
            return True
        if time.monotonic() - self._last_compaction_t >= min_iv:
            return True
        self.compactions_deferred += 1
        if self._pacing_wait_start is None:
            self._pacing_wait_start = time.monotonic()
        return False

    def note_compaction(self) -> None:
        """Engine hook: a compaction just ran. Resets the pacing clock
        and, if pacing had been holding this compaction back, records the
        total deferral."""
        from . import metric

        now = time.monotonic()
        if self._pacing_wait_start is not None:
            metric.COMPACTION_PACING_DELAY.observe(
                now - self._pacing_wait_start)
            self._pacing_wait_start = None
        self._last_compaction_t = now
