"""Metrics registry — the pkg/util/metric analog.

Reference: metric.go:326 defines prometheus-backed Gauge/Counter/Histogram
types collected into a Registry and exported at /_status/vars; subsystems
register their metrics at construction. Here the registry is process-local
(the HTTP exporter arrives with the server layer) with the same three
types, a prometheus-text dump for scraping/tests, and the engine + flow
wired in as the first producers.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field


@dataclass
class Counter:
    """Monotonically increasing value (metric.Counter)."""

    name: str
    help: str = ""
    _value: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def inc(self, delta: float = 1.0) -> None:
        with self._lock:
            self._value += delta

    @property
    def value(self) -> float:
        return self._value


@dataclass
class Gauge:
    """Set-to-current value (metric.Gauge)."""

    name: str
    help: str = ""
    _value: float = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Fixed-bucket histogram (metric.Histogram reduced: no windowing)."""

    def __init__(self, name: str, help: str = "",
                 buckets: tuple[float, ...] = (
                     0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.n = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            i = bisect.bisect_left(self.buckets, v)
            self.counts[i] += 1
            self.sum += v
            self.n += 1


class LabeledCounter:
    """Counter family keyed by one label (metric.Counter vector reduced).

    Mirrors the reference's per-range metric families: one logical name,
    one label dimension (e.g. range), a child Counter per observed label
    value. scrape() renders ``name{label="v"} n`` lines."""

    def __init__(self, name: str, label: str, help: str = ""):
        self.name = name
        self.label = label
        self.help = help
        self._children: dict[str, Counter] = {}
        self._lock = threading.Lock()

    def child(self, label_value) -> Counter:
        key = str(label_value)
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._children[key] = Counter(self.name)
            return c

    def inc(self, label_value, delta: float = 1.0) -> None:
        self.child(label_value).inc(delta)

    def value(self, label_value) -> float:
        return self.child(label_value).value

    def total(self) -> float:
        with self._lock:
            return sum(c.value for c in self._children.values())

    def items(self) -> list[tuple[str, float]]:
        with self._lock:
            return sorted((k, c.value) for k, c in self._children.items())


class LabeledGauge:
    """Gauge family keyed by one label (the gauge half of the labeled
    families: per-tenant admission tokens, per-lane queue depth). One
    logical name, one label dimension, a child Gauge per observed label
    value. scrape() renders ``name{label="v"} n`` lines."""

    def __init__(self, name: str, label: str, help: str = ""):
        self.name = name
        self.label = label
        self.help = help
        self._children: dict[str, Gauge] = {}
        self._lock = threading.Lock()

    def child(self, label_value) -> Gauge:
        key = str(label_value)
        with self._lock:
            g = self._children.get(key)
            if g is None:
                g = self._children[key] = Gauge(self.name)
            return g

    def set(self, label_value, v: float) -> None:
        self.child(label_value).set(v)

    def value(self, label_value) -> float:
        return self.child(label_value).value

    def items(self) -> list[tuple[str, float]]:
        with self._lock:
            return sorted((k, g.value) for k, g in self._children.items())


class Registry:
    """Named metric collection (metric.Registry). Subsystems register at
    construction; scrape() renders prometheus text exposition."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_add(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_add(name, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "", **kw) -> Histogram:
        return self._get_or_add(name, lambda: Histogram(name, help, **kw))

    def labeled_counter(self, name: str, label: str,
                        help: str = "") -> LabeledCounter:
        return self._get_or_add(
            name, lambda: LabeledCounter(name, label, help))

    def labeled_gauge(self, name: str, label: str,
                      help: str = "") -> LabeledGauge:
        return self._get_or_add(
            name, lambda: LabeledGauge(name, label, help))

    def _get_or_add(self, name: str, make):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = make()
            return m

    def scrape(self) -> str:
        """Prometheus text exposition (the /_status/vars shape)."""
        out: list[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if isinstance(m, Counter):
                out.append(f"# TYPE {name} counter")
                out.append(f"{name} {m.value:g}")
            elif isinstance(m, Gauge):
                out.append(f"# TYPE {name} gauge")
                out.append(f"{name} {m.value:g}")
            elif isinstance(m, LabeledCounter):
                out.append(f"# TYPE {name} counter")
                for k, v in m.items():
                    out.append(f'{name}{{{m.label}="{k}"}} {v:g}')
            elif isinstance(m, LabeledGauge):
                out.append(f"# TYPE {name} gauge")
                for k, v in m.items():
                    out.append(f'{name}{{{m.label}="{k}"}} {v:g}')
            elif isinstance(m, Histogram):
                out.append(f"# TYPE {name} histogram")
                cum = 0
                for b, c in zip(m.buckets, m.counts):
                    cum += c
                    out.append(f'{name}_bucket{{le="{b:g}"}} {cum}')
                cum += m.counts[-1]
                out.append(f'{name}_bucket{{le="+Inf"}} {cum}')
                out.append(f"{name}_sum {m.sum:g}")
                out.append(f"{name}_count {m.n}")
        return "\n".join(out) + "\n"


# the process-default registry (subsystems use this unless injected)
DEFAULT = Registry()

# engine + flow metrics (first producers; names mirror the reference's
# storage.*/sql.* metric families)
ENGINE_FLUSHES = DEFAULT.counter(
    "storage_flushes", "memtable flushes to sorted runs")
ENGINE_COMPACTIONS = DEFAULT.counter(
    "storage_compactions", "size-tiered compaction passes")
ENGINE_INGESTS = DEFAULT.counter(
    "storage_ingests", "bulk ingests (AddSSTable path)")
ENGINE_WRITES = DEFAULT.counter(
    "storage_writes", "KV write operations (put/delete)")
ENGINE_SCANS = DEFAULT.counter("storage_scans", "KV scan operations")
ENGINE_RUNS = DEFAULT.gauge("storage_runs", "sorted runs in the LSM")
QUERIES = DEFAULT.counter("sql_queries", "queries executed by run_operator")
PG_CONNS = DEFAULT.counter("pgwire_conns", "pgwire connections accepted")
QUERY_SECONDS = DEFAULT.histogram(
    "sql_query_seconds", "end-to-end query latency")
TXN_COMMITS = DEFAULT.counter("txn_commits", "committed transactions")
TXN_RETRIES = DEFAULT.counter("txn_retries", "transaction retries")
RANGE_SPLITS = DEFAULT.counter("range_splits", "admin range splits")
BLOOM_SKIPS = DEFAULT.counter(
    "storage_bloom_skips", "runs skipped by bloom filters on point reads")
BLOOM_CORRUPTIONS = DEFAULT.counter(
    "storage_bloom_corruptions",
    "bloom filters disabled after their lazy CRC verification failed on "
    "a first negative (the filter answers maybe forever after; reads "
    "stay correct, just unfiltered)")
BLOCKCACHE_HITS = DEFAULT.counter(
    "storage_blockcache_hits",
    "point/seek read windows served from the node block cache")
BLOCKCACHE_MISSES = DEFAULT.counter(
    "storage_blockcache_misses",
    "block-cache lookups that fell through to a device window slice")
BLOCKCACHE_EVICTIONS = DEFAULT.counter(
    "storage_blockcache_evictions",
    "cached windows evicted by the clock sweep under budget pressure")
BLOCKCACHE_BYTES = DEFAULT.gauge(
    "storage_blockcache_bytes",
    "bytes of decoded KVBlock windows resident in the node block cache")
INGEST_ROWS = DEFAULT.counter(
    "storage_ingest_rows",
    "rows landed as device-built runs through the bulk-ingest path")
INGEST_BYTES = DEFAULT.counter(
    "storage_ingest_bytes",
    "logical key+value bytes landed through the bulk-ingest path")
COMPACTION_PACING_DELAY = DEFAULT.histogram(
    "storage_compaction_pacing_delay_seconds",
    "how long the IOGovernor's pacing loop deferred a pending "
    "size-tiered compaction before it ran",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0))
EXTERNAL_AGG_SPILLS = DEFAULT.counter(
    "sql_external_agg_spills", "aggregations spilled to Grace partitions")
RANGE_MOVES = DEFAULT.counter(
    "range_moves", "range relocations between stores")
RPC_RETRIES = DEFAULT.counter(
    "rpc_retries", "RPC attempts retried past transient errors")
RPC_TIMEOUTS = DEFAULT.counter(
    "rpc_timeouts", "RPCs that exceeded their per-call deadline")
FAULTS_INJECTED = DEFAULT.counter(
    "faults_injected", "chaos faults fired by utils/faults.py")
DIST_DEGRADED = DEFAULT.counter(
    "distsql_degraded_queries",
    "cross-host queries re-planned onto surviving hosts or run locally "
    "after a host became unreachable")
DIST_FLOWS_CANCELLED = DEFAULT.counter(
    "distsql_flows_cancelled",
    "remote flow registrations torn down by gateway cancellation")
BREAKER_TRIPS = DEFAULT.counter(
    "rpc_breaker_trips", "circuit breakers opened by failure reports")
RANGE_CACHE_EVICTIONS = DEFAULT.counter(
    "range_cache_evictions",
    "stale range-descriptor cache entries evicted after mismatches")
REPLAY_CACHE_HITS = DEFAULT.counter(
    "kv_replay_cache_hits",
    "retried mutation batches deduplicated by the server replay cache")
AMBIGUOUS_RESULTS = DEFAULT.counter(
    "kv_ambiguous_results",
    "mutation batches whose apply state was unknowable after retries")
RPC_RETRIES_BY_RANGE = DEFAULT.labeled_counter(
    "rpc_retries_by_range", "range",
    "RPC retries attributed to the range being addressed")
RPC_RETRY_BUDGET_EXHAUSTED = DEFAULT.counter(
    "rpc_retry_budget_exhausted",
    "RPCs abandoned because their range's retry budget ran dry")
LEASE_FAILOVERS = DEFAULT.counter(
    "kv_lease_failovers",
    "range leases transferred after epoch-fencing an expired holder")
GOSSIP_INFOS_EVICTED = DEFAULT.counter(
    "gossip_infos_evicted",
    "gossip infos dropped by the bound or by liveness-epoch expiry")
REPLICATION_RECONNECTS = DEFAULT.counter(
    "replication_stream_reconnects",
    "replication streams re-subscribed after a transport error")
KV_RANGE_SPLITS = DEFAULT.counter(
    "kv_range_splits",
    "load/size-driven range splits applied by the split queue "
    "(distinct from range_splits, which counts admin splits)")
KV_RANGE_MERGES = DEFAULT.counter(
    "kv_range_merges",
    "cold adjacent ranges absorbed by the merge queue")
KV_LEASE_TRANSFERS = DEFAULT.counter(
    "kv_lease_transfers",
    "range leases moved to underfull stores by the rebalancer")
RANGE_MERGES = DEFAULT.counter(
    "range_merges", "range boundary removals (meta merge_at applications)")
RANGE_CACHE_COALESCED = DEFAULT.counter(
    "range_cache_coalesced_lookups",
    "authoritative meta lookups answered by an in-flight peer lookup "
    "instead of stampeding the meta range (single-flight)")
CONTENTION_RECORD_ERRORS = DEFAULT.counter(
    "contention_record_errors",
    "failures recording a contention event into the registry (the "
    "conflict path continues; the event is lost to observability)")
KERNEL_DISPATCHES = DEFAULT.counter(
    "sql_kernel_dispatches",
    "XLA executable dispatches issued by the flow layer (each jitted "
    "kernel call is one accelerator round trip; flow/dispatch.py)")
FUSED_PIPELINE_LENGTHS = DEFAULT.histogram(
    "sql_fused_pipeline_lengths",
    "operators collapsed into each FusedPipeline segment by the "
    "plan-build fusion pass (flow/fuse.py)",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32))
KERNEL_COMPILES = DEFAULT.counter(
    "sql_kernel_compiles",
    "new XLA traces/compiles issued through flow/dispatch.jit (each is a "
    "fresh executable specialization; the zero-recompile serving path "
    "holds this flat on repeat queries)")
KERNEL_CACHE_HITS = DEFAULT.counter(
    "sql_kernel_cache_hits",
    "kernel constructions answered by the process-global dispatch.jit "
    "key= cache (structurally identical kernels share one wrapper)")
SQL_WARMUP_KERNELS_COMPILED = DEFAULT.counter(
    "sql_warmup_kernels_compiled",
    "kernels compiled ahead of time by the warm menu (sql/warmmenu.py) "
    "before the node advertised readiness — cold-wall compiles paid off "
    "the serving path")
SQL_WARMUP_MENU_HITS = DEFAULT.counter(
    "sql_warmup_menu_hits",
    "serving-path plan-cache hits on statements the warm menu had "
    "already compiled (a first-ever foreground execution that skipped "
    "the cold compile wall)")
KV_BATCH_COALESCED = DEFAULT.counter(
    "kv_batch_coalesced",
    "point reads/writes that rode a coalesced multi-op KV batch "
    "(kv/coalesce.py) instead of a solo engine pass — each is a saved "
    "WAL record/lock acquisition")
SQL_SHARED_SCAN_ATTACHED = DEFAULT.counter(
    "sql_shared_scan_attached",
    "scans that attached to an already-live shared tile stream "
    "(flow/sharedscan.py) instead of slicing their own tiles")
SQL_SHARED_SCAN_DISPATCHES_SAVED = DEFAULT.counter(
    "sql_shared_scan_dispatches_saved",
    "tile slice dispatches avoided because a subscriber consumed a tile "
    "another query had already sliced on the shared stream")
PLAN_CACHE_HITS = DEFAULT.counter(
    "sql_plan_cache_hits",
    "statements served by a cached prepared plan (build->fuse->compile "
    "skipped; literals rebound into the cached operator tree)")
PLAN_CACHE_MISSES = DEFAULT.counter(
    "sql_plan_cache_misses",
    "cacheable statements that built a fresh plan (first sight, schema "
    "change, or settings change)")
PLAN_CACHE_EVICTIONS = DEFAULT.counter(
    "sql_plan_cache_evictions",
    "prepared plans dropped by LRU capacity or catalog-version bumps "
    "(DDL invalidation)")
SQL_MEM_CURRENT = DEFAULT.gauge(
    "sql_mem_current",
    "logical SQL bytes currently reserved against the node's root memory "
    "monitor (flow/memory.py BytesMonitor tree)")
SQL_MEM_MAX = DEFAULT.gauge(
    "sql_mem_max",
    "high water of sql_mem_current since process start (the root "
    "monitor's peak reservation)")
SQL_MEM_QUERY_PEAK = DEFAULT.histogram(
    "sql_mem_query_peak_bytes",
    "per-query peak logical memory at query-monitor close (bytes)",
    buckets=(1 << 12, 1 << 16, 1 << 20, 1 << 22, 1 << 24, 1 << 26,
             1 << 28, 1 << 30, 1 << 32, 1 << 34))
SQL_MEM_QUERY_LEAKS = DEFAULT.counter(
    "sql_mem_query_leaks",
    "query memory monitors that closed with bytes still reserved (an "
    "operator failed to release its account — always a bug; "
    "scripts/check_no_leaks.py asserts this stays flat)")
EXTERNAL_SORT_SPILLS = DEFAULT.counter(
    "sql_external_sort_spills",
    "sorts that exceeded workmem and spilled to the external "
    "range-partitioned sort")
GRACE_JOIN_SPILLS = DEFAULT.counter(
    "sql_grace_join_spills",
    "hash joins whose build side exceeded workmem and spilled to the "
    "Grace hash join")
GRACE_JOIN_MERGE_PARTS = DEFAULT.counter(
    "sql_grace_join_merge_parts",
    "Grace join partitions whose build side alone exceeded workmem and "
    "degraded to chunked sorted-run merge probing instead of one "
    "in-memory hash table")
GRACE_JOIN_SKEW_ROUTED = DEFAULT.counter(
    "sql_grace_join_skew_rows",
    "probe rows routed through the resident heavy-hitter build table "
    "instead of host partitions during a Grace hash join")
ADMISSION_SQL_SLOTS = DEFAULT.gauge(
    "admission_sql_slots",
    "configured concurrency slots of the SQL admission WorkQueue "
    "(admission.sql.slots)")
ADMISSION_SQL_SLOTS_IN_USE = DEFAULT.gauge(
    "admission_sql_slots_in_use",
    "SQL admission slots currently granted to executing statements")
ADMISSION_SQL_QUEUE_DEPTH = DEFAULT.gauge(
    "admission_sql_queue_depth",
    "statements waiting in the SQL admission queue for a slot")
ADMISSION_WAIT_SECONDS = DEFAULT.histogram(
    "admission_wait_seconds",
    "time statements spent queued in SQL admission before their slot "
    "was granted",
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5,
             10, 60))
ADMISSION_SQL_TIMEOUTS = DEFAULT.counter(
    "admission_sql_timeouts",
    "admission waits that hit their timeout and withdrew (any "
    "concurrently granted slot is handed back, never leaked)")
ADMISSION_LANE_QUEUE_DEPTH = DEFAULT.labeled_gauge(
    "admission_lane_queue_depth", "lane",
    "statements waiting in the SQL admission queue by priority lane "
    "(interactive = point/DML at NORMAL/HIGH, analytical = LOW — the "
    "lane shed first under overload)")
ADMISSION_TENANT_TOKENS = DEFAULT.labeled_gauge(
    "admission_tenant_tokens", "tenant",
    "admission token-bucket level by tenant id (admission.tenant.rate/"
    "burst); -1 when the tenant is not rate-limited")
CHANGEFEED_SUBSCRIBERS = DEFAULT.gauge(
    "changefeed_subscribers",
    "rangefeed fan-out subscribers currently registered across all hubs "
    "on this node (live + in catch-up)")
CHANGEFEED_EVENTS_EMITTED = DEFAULT.counter(
    "changefeed_events_emitted",
    "event frames delivered to fan-out subscribers (catch-up scan "
    "events included; checkpoints excluded)")
CHANGEFEED_EVENTS_COALESCED = DEFAULT.counter(
    "changefeed_events_coalesced",
    "buffered events dropped by duplicate-key coalescing — rung one of "
    "the slow-consumer backpressure ladder (the subscriber still sees "
    "the newest version of every key)")
CHANGEFEED_SHEDS = DEFAULT.counter(
    "changefeed_sheds",
    "subscriber buffers shed to catch-up-scan — rung two of the ladder: "
    "the buffer is dropped and the subscriber is re-fed by an engine "
    "scan from its frontier instead of from memory")
CHANGEFEED_EVICTIONS = DEFAULT.counter(
    "changefeed_evictions",
    "subscribers evicted with SlowConsumerError (send deadline "
    "exceeded, dead socket, or repeated sheds without draining)")
CHANGEFEED_BUFFER_BYTES = DEFAULT.gauge(
    "changefeed_buffer_bytes",
    "bytes currently buffered across all fan-out subscribers (the "
    "changefeed staging account under the node monitor root)")
CHANGEFEED_SEND_LAG_SECONDS = DEFAULT.histogram(
    "changefeed_send_lag_seconds",
    "per-event delay from hub enqueue to subscriber socket send — the "
    "fan-out plane's delivery-lag distribution")
MATVIEW_VIEWS = DEFAULT.gauge(
    "matview_views",
    "materialized views currently registered on this node")
MATVIEW_FLUSHES = DEFAULT.counter(
    "matview_flushes",
    "view-maintenance flushes: each drains a base table's buffered "
    "changefeed delta into every standing view in a handful of fused "
    "dispatches and advances the shared resolved frontier")
MATVIEW_DELTA_EVENTS = DEFAULT.counter(
    "matview_delta_events",
    "changefeed events (inserts, updates, tombstones) applied to "
    "standing view state incrementally — the work a full rescan never "
    "has to do")
MATVIEW_FULL_RESCANS = DEFAULT.counter(
    "matview_full_rescans",
    "views rebuilt by a base-table rescan instead of delta work: "
    "initial population at CREATE, restart recovery, and the "
    "out-of-bounds group-key fallback (a group key outside the dense "
    "layout minted since CREATE)")
MATVIEW_MINMAX_RESCANS = DEFAULT.counter(
    "matview_minmax_rescans",
    "per-view re-scan fallbacks after a retraction hit a group's "
    "current min/max extremum — the one aggregate family that cannot "
    "retract natively")
MATVIEW_REWRITE_HITS = DEFAULT.counter(
    "matview_rewrite_hits",
    "SELECTs whose plan matched a registered view's defining query and "
    "were served from standing state by the settings-gated planner "
    "rewrite (sql.matview.rewrite.enabled)")
MATVIEW_REFRESH_LAG_SECONDS = DEFAULT.histogram(
    "matview_refresh_lag_seconds",
    "per-flush staleness closed by view maintenance: wall-clock age of "
    "the oldest buffered event when its flush lands")
ADMISSION_REJECTIONS = DEFAULT.labeled_counter(
    "admission_rejections", "tenant",
    "statements refused admission by tenant id (queue full, rate "
    "limit, overload shed, or queue-wait deadline) — surfaced to "
    "clients as SQLSTATE 53300 'server busy' with a retry-after hint")
