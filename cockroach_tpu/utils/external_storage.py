"""External storage — the pkg/cloud ExternalStorage reduction.

Reference: BACKUP/RESTORE/IMPORT/changefeed sinks address storage by URI
(s3://, gs://, azure-blob://, nodelocal://, userfile://, http://); each
scheme resolves to an ExternalStorage implementation with a common
read/write/list/delete surface (pkg/cloud/external_storage.go).

Reduction: the same scheme registry and surface over implementations the
zero-egress build can host — ``nodelocal://`` (a per-process base
directory, the reference's node-local store) and ``file://`` (absolute
paths). Cloud schemes register as explicit stubs whose error says what is
missing, so a BACKUP TO 's3://…' fails with configuration guidance
rather than a parse error. Consumers that need a directory on local disk
(the engine checkpoint) use ``as_local_dir()``, available on any
local-backed implementation.
"""

from __future__ import annotations

import os
from urllib.parse import urlparse

# nodelocal:// resolves under this base (settable for tests/servers; the
# reference's equivalent is the store's "extern" dir)
_NODELOCAL_BASE = os.environ.get("COCKROACH_TPU_EXTERN_DIR", ".extern")


def set_nodelocal_base(path: str) -> None:
    global _NODELOCAL_BASE
    _NODELOCAL_BASE = path


def _check_under(base: str, path: str, shown) -> None:
    """Reject paths outside the storage root. commonpath, NOT a string
    prefix: '/d/.extern-evil' shares the prefix of '/d/.extern' but is a
    sibling, not a child."""
    b = os.path.abspath(base)
    p = os.path.abspath(path)
    if os.path.commonpath([b, p]) != b:
        raise ValueError(f"path escapes storage root: {shown!r}")


class ExternalStorage:
    """Common surface (pkg/cloud/external_storage.go reduction)."""

    def write_file(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def read_file(self, name: str) -> bytes:
        raise NotImplementedError

    def list(self, prefix: str = "") -> list[str]:
        raise NotImplementedError

    def delete(self, name: str) -> None:
        raise NotImplementedError

    def as_local_dir(self) -> str:
        """Local directory behind this storage, for consumers that write
        directory trees directly (engine checkpoints). Remote
        implementations would stage through a temp dir instead."""
        raise NotImplementedError


class LocalStorage(ExternalStorage):
    def __init__(self, base: str):
        self.base = base

    def _path(self, name: str) -> str:
        p = os.path.normpath(os.path.join(self.base, name))
        _check_under(self.base, p, name)
        return p

    def write_file(self, name: str, data: bytes) -> None:
        p = self._path(name)
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)

    def read_file(self, name: str) -> bytes:
        with open(self._path(name), "rb") as f:
            return f.read()

    def list(self, prefix: str = "") -> list[str]:
        out = []
        root = self.base
        for dirpath, _, files in os.walk(root):
            for fn in files:
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def delete(self, name: str) -> None:
        os.unlink(self._path(name))

    def as_local_dir(self) -> str:
        os.makedirs(self.base, exist_ok=True)
        return self.base


class UnconfiguredStorage(ExternalStorage):
    """Cloud schemes the zero-egress build cannot reach: every operation
    fails with guidance (the reference fails similarly when credentials
    or implementations are absent)."""

    def __init__(self, scheme: str):
        self.scheme = scheme

    def _no(self):
        raise RuntimeError(
            f"{self.scheme}:// storage is not configured in this build "
            "(no cloud egress); use nodelocal:// or file://"
        )

    write_file = read_file = list = delete = as_local_dir = (
        lambda self, *a, **k: self._no()
    )


def from_uri(uri: str) -> tuple[ExternalStorage, str]:
    """URI -> (storage, path-within-storage). Plain paths (no scheme)
    stay plain local paths for compatibility."""
    u = urlparse(uri)
    if not u.scheme or len(u.scheme) == 1:  # '', or a windows drive letter
        return LocalStorage(os.path.dirname(uri) or "."), os.path.basename(
            uri)
    if u.scheme == "nodelocal":
        # nodelocal://self/<path> | nodelocal://1/<path>
        return (LocalStorage(_NODELOCAL_BASE), u.path.lstrip("/"))
    if u.scheme == "file":
        return LocalStorage(os.path.dirname(u.path) or "/"), \
            os.path.basename(u.path)
    if u.scheme in ("s3", "gs", "azure-blob", "http", "https", "userfile"):
        return UnconfiguredStorage(u.scheme), u.path.lstrip("/")
    raise ValueError(f"unknown storage scheme {u.scheme!r} in {uri!r}")


def resolve_dir_uri(uri: str) -> str:
    """URI -> a local directory path (for directory-tree consumers like
    the engine checkpoint). Raises for unconfigured cloud schemes."""
    storage, path = from_uri(uri)
    base = storage.as_local_dir()
    full = os.path.normpath(os.path.join(base, path))
    _check_under(base, full, uri)
    os.makedirs(os.path.dirname(full) or ".", exist_ok=True)
    return full
