"""JAX backend defense shared by driver scripts and tests.

The environment injects a TPU PJRT plugin (sitecustomize on PYTHONPATH) that
opens a hardware tunnel even under JAX_PLATFORMS=cpu, adding ~100s startup and
hanging forever when the tunnel is wedged. Backend init is lazy, so before
anything touches a device we can force the cpu platform and drop every other
backend factory. Used by tests/conftest.py, __graft_entry__.py, and bench.py.
"""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str | None = None) -> str:
    """Enable JAX's persistent compilation cache.

    On the TPU backend every lax.sort instantiation costs ~17-20s of XLA
    compile time (measured, v5e tunnel) regardless of shape; the disk cache
    makes that a one-time cost per (kernel, shape) across processes AND
    across bench rounds. The engine's canonical packed-key sort (ops/keys.py)
    keeps the set of distinct kernels small so the cache stays effective.
    """
    if cache_dir is None:
        cache_dir = os.environ.get(
            "JAX_COMPILE_CACHE_DIR",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), ".jax_cache"),
        )
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover - older jax flag names
        pass
    return cache_dir


_F64_BITCAST_OK: bool | None = None


def float_bitcast_ok() -> bool:
    """One-time probe: does this backend compile f64<->u32 bitcasts
    CORRECTLY? The axon TPU X64 rewriter has been observed to miscompile
    them for negative doubles (values collapse to f32-NaN bit patterns), so
    float-keyed joins/hashes must fail LOUDLY rather than silently match
    wrong rows. CPU and healthy TPU backends pass."""
    global _F64_BITCAST_OK
    if _F64_BITCAST_OK is not None:
        return _F64_BITCAST_OK
    import jax
    import jax.numpy as jnp
    import numpy as np

    vals = np.array([-1.5, -0.0, 2.5e-308, -1e300, 3.25], dtype=np.float64)
    want = vals.view(np.uint64)
    try:
        def roundtrip(x):
            parts = jax.lax.bitcast_convert_type(x, jnp.uint32)  # [..., 2]
            u = (parts[..., 1].astype(jnp.uint64) << jnp.uint64(32)
                 ) | parts[..., 0].astype(jnp.uint64)
            back = jax.lax.bitcast_convert_type(parts, jnp.float64)
            return u, back

        u, back = jax.jit(roundtrip)(jnp.asarray(vals))  # crlint: allow-raw-jit(one-shot import-time backend probe, not a query kernel)
        ok = (np.array_equal(np.asarray(u), want)
              and np.array_equal(np.asarray(back).view(np.uint64), want))
    except Exception:
        ok = False
    _F64_BITCAST_OK = bool(ok)
    return _F64_BITCAST_OK


def require_float_bitcast(what: str) -> None:
    """Raise a clear error when a float-keyed kernel would miscompile."""
    if not float_bitcast_ok():
        raise NotImplementedError(
            f"{what}: this backend miscompiles f64 bitcasts (negative "
            "doubles collapse); float join/group keys are disabled on it. "
            "Cast the key to DECIMAL or INT instead."
        )


def force_cpu_backend(n_devices: int | None = None) -> None:
    """Force jax onto the CPU backend, with an optional virtual device count.

    Safe to call whether or not jax is already imported; also evicts any
    already-initialized backend so the switch takes effect even after a
    device touch.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        # replace any pre-existing count (don't silently keep it: backends
        # are evicted below, so the requested mesh size must win)
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "", flags
        )
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    # jax may already be imported (sitecustomize), freezing jax_platforms at
    # the env value — override the live config, not just the env var.
    jax.config.update("jax_platforms", "cpu")
    try:
        # pallas/checkify register MLIR lowerings for the "tpu" platform at
        # import; once the factory pop below makes that platform unknown,
        # any LATER pallas import raises. Import them now, while "tpu" is
        # still a known platform (interpret-mode tests need pallas on CPU).
        import jax.experimental.pallas  # noqa: F401
        from jax._src import checkify  # noqa: F401  # crlint: allow-unused-import(presence probe: import success is the signal)
    except Exception:  # pragma: no cover - pallas absent/changed
        pass
    try:
        from jax._src import xla_bridge as _xb

        for name in list(getattr(_xb, "_backend_factories", {})):
            if name not in ("cpu",):
                _xb._backend_factories.pop(name, None)
    except Exception:  # pragma: no cover - defensive: jax internals moved
        pass
    # evict any backend initialized before the scrub (config updates and
    # factory pops do not touch the cache)
    try:
        jax.extend.backend.clear_backends()
    except Exception:  # pragma: no cover
        pass
