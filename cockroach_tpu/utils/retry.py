"""Retry/backoff/deadline discipline — the util/retry.Options analog.

Reference: CockroachDB wraps every network-facing loop in
pkg/util/retry (retry.go:30 Options{InitialBackoff, MaxBackoff,
Multiplier, MaxRetries} driving an exponential-with-jitter iterator);
DistSender leans on it to re-send batches past transient send errors
(kvcoord/dist_sender.go), and the breaker's cooldown turns a fast-fail
peer back into a retryable target. The discipline here is the same,
reduced:

- ``Backoff``: deterministic-given-rng exponential backoff with jitter
  and an optional overall deadline (monotonic clock).
- ``is_retryable``: the one shared classification of transient vs hard
  errors. WriteIntentError (retry after the writer finishes), socket
  timeouts and connection drops (re-dial and re-send), and
  BreakerOpenError (retryable only AFTER the breaker's cooldown — the
  caller backs off long enough for the half-open probe window) are
  transient; everything else is a hard error and must surface.
- ``call``: run a callable under that policy, re-raising the last error
  when attempts or the deadline run out.
"""

from __future__ import annotations

import random
import socket
import time

from . import locks


class RPCDeadlineError(ConnectionError):
    """A single RPC exceeded its deadline (DeadlineExceeded analog).
    Subclasses ConnectionError: a timed-out send leaves the stream in an
    unknown framing state, so callers must re-dial like a drop."""


class Backoff:
    """Exponential backoff with jitter + optional overall deadline.

    attempts() yields attempt indices, sleeping between them; it stops
    yielding when max_attempts or the deadline is exhausted. Durations
    use the monotonic clock. `rng` makes the jitter deterministic for
    tests (the chaos harness seeds it)."""

    def __init__(self, max_attempts: int = 4, initial_s: float = 0.01,
                 multiplier: float = 2.0, max_backoff_s: float = 1.0,
                 jitter: float = 0.25, deadline_s: float | None = None,
                 rng: random.Random | None = None):
        assert max_attempts >= 1
        self.max_attempts = max_attempts
        self.initial_s = initial_s
        self.multiplier = multiplier
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.rng = rng if rng is not None else random

    def attempts(self):
        start = time.monotonic()
        pause = self.initial_s
        for i in range(self.max_attempts):
            yield i
            if i == self.max_attempts - 1:
                return
            if self.deadline_s is not None and (
                    time.monotonic() - start + pause > self.deadline_s):
                return
            # jitter spreads synchronized retriers (retry.go's Mult+jitter)
            frac = 1.0 + self.jitter * (2.0 * self.rng.random() - 1.0)
            time.sleep(pause * frac)
            pause = min(pause * self.multiplier, self.max_backoff_s)


def is_retryable(e: BaseException) -> bool:
    """Shared transient-vs-hard classification for the distributed plane."""
    from ..kv.dialer import BreakerOpenError
    from ..storage.lsm import WriteIntentError

    if isinstance(e, WriteIntentError):
        return True  # the writer will commit/abort; wait and re-read
    if isinstance(e, (socket.timeout, TimeoutError, RPCDeadlineError)):
        return True  # deadline: re-dial (stream framing state unknown)
    if isinstance(e, (ConnectionError, BrokenPipeError)):
        return True  # drop: re-dial and re-send
    if isinstance(e, OSError):
        return True  # refused/reset during (re)connect of a restarting peer
    if isinstance(e, BreakerOpenError):
        # retryable-after-cooldown: the backoff must outlast the breaker's
        # cooldown for the half-open probe to be admitted
        return True
    return False


class RetryBudgetExhausted(RuntimeError):
    """A range's retry budget ran dry — the caller must degrade (or
    surface the last transport error) instead of hammering the range.
    Deliberately NOT a ConnectionError: an exhausted budget is a hard
    stop, never itself retried."""

    def __init__(self, range_id: int, spent: int):
        super().__init__(
            f"retry budget exhausted for r{range_id} after {spent} retries")
        self.range_id = range_id
        self.spent = spent


class RangeRetryBudget:
    """Per-range retry accounting (moves the budget off the client).

    Reference: kvcoord tracks send failures per range/replica rather than
    per client, so one hot range cannot starve every other range's
    retries and a single range's flapping is visible in metrics. Each
    range gets `budget` retry tokens refilled at `refill_per_s`; spending
    past zero raises RetryBudgetExhausted and bumps
    `rpc_retry_budget_exhausted`. Every spend is attributed to the range
    in the `rpc_retries_by_range` labeled counter."""

    def __init__(self, budget: float = 16.0, refill_per_s: float = 4.0):
        self.budget = float(budget)
        self.refill_per_s = float(refill_per_s)
        self._tokens: dict[int, float] = {}
        self._stamp: dict[int, float] = {}
        self._lock = locks.lock("retry.budget")

    def _refill(self, range_id: int, now: float) -> float:
        tokens = self._tokens.get(range_id, self.budget)
        last = self._stamp.get(range_id, now)
        tokens = min(self.budget, tokens + (now - last) * self.refill_per_s)
        self._stamp[range_id] = now
        return tokens

    def spend(self, range_id: int) -> None:
        """Account one retry against the range. Raises when dry."""
        from . import metric

        with self._lock:
            now = time.monotonic()
            tokens = self._refill(range_id, now)
            if tokens < 1.0:
                metric.RPC_RETRY_BUDGET_EXHAUSTED.inc()
                spent = int(self.budget)
                self._tokens[range_id] = tokens
                raise RetryBudgetExhausted(range_id, spent)
            self._tokens[range_id] = tokens - 1.0
        metric.RPC_RETRIES_BY_RANGE.inc(range_id)

    def remaining(self, range_id: int) -> float:
        with self._lock:
            return self._refill(range_id, time.monotonic())


def call(fn, policy: Backoff | None = None, retryable=is_retryable,
         on_retry=None):
    """Run fn() under the retry policy. Transient errors (per `retryable`)
    retry with backoff; hard errors and exhaustion re-raise."""
    from . import metric

    policy = policy if policy is not None else Backoff()
    last: BaseException | None = None
    for attempt in policy.attempts():
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            if not retryable(e):
                raise
            last = e
            metric.RPC_RETRIES.inc()
            if on_retry is not None:
                on_retry(attempt, e)
    assert last is not None
    raise last
