"""Retry/backoff/deadline discipline — the util/retry.Options analog.

Reference: CockroachDB wraps every network-facing loop in
pkg/util/retry (retry.go:30 Options{InitialBackoff, MaxBackoff,
Multiplier, MaxRetries} driving an exponential-with-jitter iterator);
DistSender leans on it to re-send batches past transient send errors
(kvcoord/dist_sender.go), and the breaker's cooldown turns a fast-fail
peer back into a retryable target. The discipline here is the same,
reduced:

- ``Backoff``: deterministic-given-rng exponential backoff with jitter
  and an optional overall deadline (monotonic clock).
- ``is_retryable``: the one shared classification of transient vs hard
  errors. WriteIntentError (retry after the writer finishes), socket
  timeouts and connection drops (re-dial and re-send), and
  BreakerOpenError (retryable only AFTER the breaker's cooldown — the
  caller backs off long enough for the half-open probe window) are
  transient; everything else is a hard error and must surface.
- ``call``: run a callable under that policy, re-raising the last error
  when attempts or the deadline run out.
"""

from __future__ import annotations

import random
import socket
import time


class RPCDeadlineError(ConnectionError):
    """A single RPC exceeded its deadline (DeadlineExceeded analog).
    Subclasses ConnectionError: a timed-out send leaves the stream in an
    unknown framing state, so callers must re-dial like a drop."""


class Backoff:
    """Exponential backoff with jitter + optional overall deadline.

    attempts() yields attempt indices, sleeping between them; it stops
    yielding when max_attempts or the deadline is exhausted. Durations
    use the monotonic clock. `rng` makes the jitter deterministic for
    tests (the chaos harness seeds it)."""

    def __init__(self, max_attempts: int = 4, initial_s: float = 0.01,
                 multiplier: float = 2.0, max_backoff_s: float = 1.0,
                 jitter: float = 0.25, deadline_s: float | None = None,
                 rng: random.Random | None = None):
        assert max_attempts >= 1
        self.max_attempts = max_attempts
        self.initial_s = initial_s
        self.multiplier = multiplier
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self.deadline_s = deadline_s
        self.rng = rng if rng is not None else random

    def attempts(self):
        start = time.monotonic()
        pause = self.initial_s
        for i in range(self.max_attempts):
            yield i
            if i == self.max_attempts - 1:
                return
            if self.deadline_s is not None and (
                    time.monotonic() - start + pause > self.deadline_s):
                return
            # jitter spreads synchronized retriers (retry.go's Mult+jitter)
            frac = 1.0 + self.jitter * (2.0 * self.rng.random() - 1.0)
            time.sleep(pause * frac)
            pause = min(pause * self.multiplier, self.max_backoff_s)


def is_retryable(e: BaseException) -> bool:
    """Shared transient-vs-hard classification for the distributed plane."""
    from ..kv.dialer import BreakerOpenError
    from ..storage.lsm import WriteIntentError

    if isinstance(e, WriteIntentError):
        return True  # the writer will commit/abort; wait and re-read
    if isinstance(e, (socket.timeout, TimeoutError, RPCDeadlineError)):
        return True  # deadline: re-dial (stream framing state unknown)
    if isinstance(e, (ConnectionError, BrokenPipeError)):
        return True  # drop: re-dial and re-send
    if isinstance(e, OSError):
        return True  # refused/reset during (re)connect of a restarting peer
    if isinstance(e, BreakerOpenError):
        # retryable-after-cooldown: the backoff must outlast the breaker's
        # cooldown for the half-open probe to be admitted
        return True
    return False


def call(fn, policy: Backoff | None = None, retryable=is_retryable,
         on_retry=None):
    """Run fn() under the retry policy. Transient errors (per `retryable`)
    retry with backoff; hard errors and exhaustion re-raise."""
    from . import metric

    policy = policy if policy is not None else Backoff()
    last: BaseException | None = None
    for attempt in policy.attempts():
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - classified below
            if not retryable(e):
                raise
            last = e
            metric.RPC_RETRIES.inc()
            if on_retry is not None:
                on_retry(attempt, e)
    assert last is not None
    raise last
