"""Tracing — the pkg/util/tracing analog (Tracer tracer.go:289, Span
span.go:46): always-cheap structured spans forming a tree per operation,
with structured payloads. DistSQL propagates spans through flows and folds
per-processor ComponentStats into EXPLAIN ANALYZE via
execstats/traceanalyzer.go; here the flow runtime opens a span per query and
operators attach their stats to it.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any


@dataclass
class Span:
    name: str
    start: float = 0.0
    duration: float = 0.0
    tags: dict[str, Any] = field(default_factory=dict)
    records: list[Any] = field(default_factory=list)
    children: list["Span"] = field(default_factory=list)

    def record(self, payload: Any) -> None:
        """Attach a structured payload (ComponentStats etc.)."""
        self.records.append(payload)

    def tree(self, indent: int = 0) -> str:
        out = [f"{'  ' * indent}{self.name}: {self.duration*1e3:.2f}ms"
               + (f" {self.tags}" if self.tags else "")]
        for c in self.children:
            out.append(c.tree(indent + 1))
        return "\n".join(out)


MAX_FINISHED = 64  # ring of recent root spans (the span registry's cap)


class Tracer:
    """Per-process tracer; spans nest via a stack (single-threaded flows;
    the pull loop is sequential by design). Finished root spans are kept in
    a bounded ring so a long-lived process doesn't accumulate them."""

    def __init__(self):
        self._stack: list[Span] = []
        self.finished: list[Span] = []

    @contextmanager
    def span(self, name: str, **tags):
        s = Span(name=name, start=time.perf_counter(), tags=dict(tags))
        if self._stack:
            self._stack[-1].children.append(s)
        self._stack.append(s)
        try:
            yield s
        finally:
            s.duration = time.perf_counter() - s.start
            self._stack.pop()
            if not self._stack:
                self.finished.append(s)
                if len(self.finished) > MAX_FINISHED:
                    del self.finished[: -MAX_FINISHED]

    def current(self) -> Span | None:
        return self._stack[-1] if self._stack else None


# process-global default tracer (the reference hangs one off every Server)
DEFAULT = Tracer()


def span(name: str, **tags):
    return DEFAULT.span(name, **tags)


def current() -> Span | None:
    return DEFAULT.current()
