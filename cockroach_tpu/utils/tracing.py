"""Tracing — the pkg/util/tracing analog (Tracer tracer.go:289, Span
span.go:46): always-cheap structured spans forming a tree per operation,
with structured payloads. DistSQL propagates spans through flows and folds
per-processor ComponentStats into EXPLAIN ANALYZE via
execstats/traceanalyzer.go; here every layer seam opens a span (parse/bind/
plan-cache, flow pull, KV batch send, WAL append, compaction) and remote
recordings graft back into the caller's tree (the snowball-trace shape).

Concurrency model: the "current span" lives in a ``contextvars.ContextVar``
so concurrent sessions — one thread per pgwire connection — keep disjoint
span trees. A new thread starts with an empty context, so its first span is
a new root; nothing ever needs to lock a shared stack. The inflight-span
registry (crdb_internal.node_inflight_trace_spans / tracing/service's
inflight collection) and the finished-root ring are the only shared state,
each under its own lock.

Wire shape: ``context()`` exports the Dapper-style ``(trace_id, span_id)``
pair; a server opens its span with ``remote_span(name, ctx)`` and ships the
finished recording (``Span.to_dict``) back in its response; the client
calls ``graft(payload)`` to attach the remote subtree to its own span.

Creation discipline (enforced by the crlint ``tracing-api`` pass): spans
are only born through ``Tracer.span``/``remote_span``/``synthetic_span`` —
no direct Span() construction or current-context mutation outside this
module, so every span is guaranteed to close, unregister from the inflight
table, and land in exactly one tree.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field, is_dataclass
from typing import Any

_ids = itertools.count(1)
_id_lock = threading.Lock()


def _next_id() -> int:
    with _id_lock:
        return next(_ids)


def _jsonable(v: Any):
    """Best-effort JSON projection for tags/records (ComponentStats and
    friends carry __slots__; unknown objects degrade to repr)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(i) for i in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    slots = getattr(type(v), "__slots__", None)
    if slots:
        return {s: _jsonable(getattr(v, s, None)) for s in slots}
    if is_dataclass(v) and not isinstance(v, type):
        import dataclasses

        return {f.name: _jsonable(getattr(v, f.name))
                for f in dataclasses.fields(v)}
    return repr(v)


@dataclass
class Span:
    name: str
    trace_id: int = 0
    span_id: int = 0
    parent_id: int = 0
    start: float = 0.0       # perf_counter seconds (durations)
    start_wall: float = 0.0  # epoch seconds (cross-process alignment)
    duration: float = 0.0
    tags: dict[str, Any] = field(default_factory=dict)
    records: list[Any] = field(default_factory=list)
    children: list["Span"] = field(default_factory=list)
    remote: bool = False     # grafted from another node's recording
    error: str | None = None

    def record(self, payload: Any) -> None:
        """Attach a structured payload (ComponentStats etc.)."""
        self.records.append(payload)

    def add_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def inc_tag(self, key: str, delta: float) -> None:
        """Accumulate a numeric tag (per-call costs folded into one
        number: jit dispatch time, readback time, retry counts)."""
        self.tags[key] = self.tags.get(key, 0) + delta

    def tree(self, indent: int = 0) -> str:
        mark = " [remote]" if self.remote else ""
        err = f" error={self.error}" if self.error else ""
        out = [f"{'  ' * indent}{self.name}: {self.duration*1e3:.2f}ms"
               + mark + (f" {self.tags}" if self.tags else "") + err]
        for c in self.children:
            out.append(c.tree(indent + 1))
        return "\n".join(out)

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def to_dict(self) -> dict:
        """JSON-serializable recording (the wire/bundle shape)."""
        d = {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentId": self.parent_id,
            "startWallMs": round(self.start_wall * 1e3, 3),
            "durationMs": round(self.duration * 1e3, 4),
            "tags": _jsonable(self.tags),
            "children": [c.to_dict() for c in self.children],
        }
        if self.records:
            d["records"] = _jsonable(self.records)
        if self.remote:
            d["remote"] = True
        if self.error:
            d["error"] = self.error
        return d

    @staticmethod
    def from_dict(d: dict) -> "Span":
        s = Span(
            name=str(d.get("name", "?")),
            trace_id=int(d.get("traceId", 0)),
            span_id=int(d.get("spanId", 0)),
            parent_id=int(d.get("parentId", 0)),
            start_wall=float(d.get("startWallMs", 0.0)) / 1e3,
            duration=float(d.get("durationMs", 0.0)) / 1e3,
            tags=dict(d.get("tags") or {}),
            records=list(d.get("records") or ()),
            remote=True,
            error=d.get("error"),
        )
        s.children = [Span.from_dict(c) for c in d.get("children", ())]
        return s


MAX_FINISHED = 64   # ring of recent root spans (the span registry's cap)
MAX_CHILDREN = 128  # per-span child cap (hot leaf sites: WAL appends)


class Tracer:
    """Per-process tracer; the current span rides a ContextVar so every
    thread (pgwire session, flow server conn, background queue) nests its
    own tree. Finished root spans are kept in a bounded ring; open spans
    are visible through ``inflight()`` for crdb_internal."""

    def __init__(self):
        self._current: ContextVar[Span | None] = ContextVar(
            f"crdb_tpu_trace_{id(self)}", default=None)
        self.finished: list[Span] = []
        self._fin_lock = threading.Lock()
        self._inflight: dict[int, Span] = {}
        self._if_lock = threading.Lock()

    # -- span lifecycle ----------------------------------------------------

    @contextmanager
    def span(self, name: str, **tags):
        yield from self._run_span(Span(name=name, tags=dict(tags)), None)

    @contextmanager
    def remote_span(self, name: str, ctx: dict | None, **tags):
        """Server-side half of propagation: open a span whose parent is
        the REMOTE caller's span (``ctx`` from :func:`context`). With
        ``ctx=None`` this is a no-op context yielding None — so wire
        handlers stay unconditional. The finished recording (``to_dict``)
        is what the server ships back for grafting."""
        if ctx is None:
            yield None
            return
        s = Span(name=name, tags=dict(tags))
        remote = (int(ctx.get("traceId", 0)), int(ctx.get("spanId", 0)))
        yield from self._run_span(s, remote)

    @contextmanager
    def leaf_span(self, name: str, **tags):
        """A span that only exists when an operation is already being
        traced (hot sites: WAL appends, KV sends from background threads
        must not flood the finished ring with root spans). Yields None
        when no span is active."""
        if self._current.get() is None:
            yield None
            return
        yield from self._run_span(Span(name=name, tags=dict(tags)), None)

    def _run_span(self, s: Span, remote_parent: tuple[int, int] | None):
        parent = self._current.get()
        s.span_id = _next_id()
        s.start = time.perf_counter()
        s.start_wall = time.time()
        if remote_parent is not None:
            s.trace_id, s.parent_id = remote_parent
        elif parent is not None:
            s.trace_id = parent.trace_id
            s.parent_id = parent.span_id
            if len(parent.children) < MAX_CHILDREN:
                parent.children.append(s)
            else:
                parent.inc_tag("dropped_children", 1)
        else:
            s.trace_id = s.span_id
        with self._if_lock:
            self._inflight[s.span_id] = s
        token = self._current.set(s)
        try:
            yield s
        except BaseException as e:
            if s.error is None:
                s.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            s.duration = time.perf_counter() - s.start
            self._current.reset(token)
            with self._if_lock:
                self._inflight.pop(s.span_id, None)
            if parent is None:
                with self._fin_lock:
                    self.finished.append(s)
                    if len(self.finished) > MAX_FINISHED:
                        del self.finished[: -MAX_FINISHED]

    def synthetic_span(self, parent: Span, name: str, duration_s: float,
                       **tags) -> Span:
        """Attach an already-measured child span (execstats folding: per-
        operator ComponentStats become spans after the pull loop ran).
        The ONE sanctioned way to make a span without entering it."""
        s = Span(name=name, trace_id=parent.trace_id,
                 span_id=_next_id(), parent_id=parent.span_id,
                 start_wall=parent.start_wall, duration=duration_s,
                 tags=dict(tags))
        parent.children.append(s)
        return s

    # -- context + recordings ----------------------------------------------

    def current(self) -> Span | None:
        return self._current.get()

    def context(self) -> dict | None:
        """The wire-propagated (trace_id, span_id) of the current span —
        None when nothing is being traced (callers then skip the field)."""
        s = self._current.get()
        if s is None:
            return None
        return {"traceId": s.trace_id, "spanId": s.span_id}

    def graft(self, payload: dict | None,
              into: Span | None = None) -> Span | None:
        """Attach a remote recording (a ``to_dict`` dict shipped back by
        a server) under the current span — or under ``into``, for streams
        whose trailer arrives on a different thread than the span owner
        (flow inboxes pulled by puller threads). No-op outside a span or
        for a None/bad payload — error paths call this unconditionally."""
        if not payload:
            return None
        cur = into if into is not None else self._current.get()
        if cur is None:
            return None
        try:
            s = Span.from_dict(payload)
        except (TypeError, ValueError, KeyError):
            return None
        cur.children.append(s)
        return s

    def inflight(self) -> list[Span]:
        """Open spans, oldest first (node_inflight_trace_spans). The
        returned Span objects are live — readers must not mutate them."""
        with self._if_lock:
            return sorted(self._inflight.values(), key=lambda s: s.start)


# process-global default tracer (the reference hangs one off every Server)
DEFAULT = Tracer()


def span(name: str, **tags):
    return DEFAULT.span(name, **tags)


def remote_span(name: str, ctx: dict | None, **tags):
    return DEFAULT.remote_span(name, ctx, **tags)


def leaf_span(name: str, **tags):
    return DEFAULT.leaf_span(name, **tags)


def current() -> Span | None:
    return DEFAULT.current()


def context() -> dict | None:
    return DEFAULT.context()


def graft(payload: dict | None, into: Span | None = None) -> Span | None:
    return DEFAULT.graft(payload, into)


def inflight() -> list[Span]:
    return DEFAULT.inflight()


def synthetic_span(parent: Span, name: str, duration_s: float,
                   **tags) -> Span:
    return DEFAULT.synthetic_span(parent, name, duration_s, **tags)
