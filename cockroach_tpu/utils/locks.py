"""Order-checked lock wrappers — the runtime half of crlint's lock-order pass.

Reference: CockroachDB wires syncutil.Mutex with a deadlock-detection build
tag (sasha-s/go-deadlock) that records the global lock-acquisition order and
crashes on an inversion instead of deadlocking in production. Here the same
discipline is a pair of checks:

  * static  — ``cockroach_tpu/lint/lockorder.py`` walks every module's
    with-stacks and the lock-held call graph and fails CI on a cycle;
  * runtime — this module's ``OrderedLock`` family records, under
    ``debug.lock_order.enabled``, the edge "held A, acquired B" into one
    process-wide graph and raises :class:`LockOrderError` the moment an
    acquisition would close a cycle (any length, across threads), turning
    a would-be deadlock hang in the chaos suite into a stack trace.

The wrappers are drop-in for ``threading.Lock`` / ``RLock`` / ``Condition``
(context manager, ``acquire``/``release``/``wait``/``notify``). With the
setting off (the default) the only cost over a bare lock is one settings
read per acquire; control-plane locks use these wrappers, per-dispatch hot
locks (flow/dispatch, utils/metric, utils/log) deliberately stay bare.

Checking is edge-recording, not lock-holding: the graph accumulates every
ordering ever observed, so an A->B in one thread and B->A in another is
caught even when the two never race — exactly what a chaos run wants.
"""

from __future__ import annotations

import threading

from . import settings

__all__ = [
    "LockOrderError", "OrderedLock", "OrderedRLock", "OrderedCondition",
    "lock", "rlock", "condition", "reset",
]


class LockOrderError(RuntimeError):
    """An acquisition would invert the observed global lock order."""


# process-wide order graph: _edges[a] = {b: (a_site, b_site)} meaning some
# thread acquired b while holding a. Guarded by _graph_mu (itself never
# held while user locks are taken, so it cannot participate in a cycle).
_graph_mu = threading.Lock()
_edges: dict[str, dict[str, str]] = {}
_tls = threading.local()


def reset() -> None:
    """Forget every recorded ordering (test isolation)."""
    with _graph_mu:
        _edges.clear()


def _held_stack() -> list[str]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _reachable(src: str, dst: str) -> list[str] | None:
    """Path src -> ... -> dst in the edge graph, or None. Caller holds
    _graph_mu."""
    seen = {src}
    frontier = [(src, [src])]
    while frontier:
        node, path = frontier.pop()
        for nxt in _edges.get(node, ()):
            if nxt == dst:
                return path + [nxt]
            if nxt not in seen:
                seen.add(nxt)
                frontier.append((nxt, path + [nxt]))
    return None


def _note_acquire(name: str) -> None:
    # the held stack serves two debug consumers: the order graph below
    # (debug.lock_order) and the race sanitizer's locksets
    # (utils/racesan.py reads _held_stack under debug.race_detector) —
    # graph edges and cycle checks stay gated on lock_order alone
    st = _held_stack()
    if st and st[-1] != name \
            and settings.get("debug.lock_order.enabled"):
        prev = st[-1]
        with _graph_mu:
            back = _reachable(name, prev)
            if back is not None:
                raise LockOrderError(
                    f"lock order inversion: acquiring {name!r} while "
                    f"holding {prev!r}, but the opposite order "
                    f"{' -> '.join(back)} -> {prev!r} was already observed; "
                    "two threads interleaving these paths deadlock"
                )
            _edges.setdefault(prev, {}).setdefault(name, "")
    st.append(name)


def _note_release(name: str) -> None:
    st = _held_stack()
    # release order need not be LIFO (lock handoff patterns); drop the
    # most recent matching entry
    for i in range(len(st) - 1, -1, -1):
        if st[i] == name:
            del st[i]
            return


class OrderedLock:
    """``threading.Lock`` with order checking under debug.lock_order."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str):
        self.name = name
        self._lk = self._factory()

    def _checking(self) -> bool:
        # either debug mode needs the per-thread held stack maintained
        return bool(settings.get("debug.lock_order.enabled")
                    or settings.get("debug.race_detector.enabled"))

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        check = self._checking()
        if check:
            _note_acquire(self.name)
        got = self._lk.acquire(blocking, timeout)
        if check and not got:
            _note_release(self.name)
        return got

    def release(self) -> None:
        self._lk.release()
        if self._checking():
            _note_release(self.name)

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class OrderedRLock(OrderedLock):
    """``threading.RLock`` variant; re-entry is not an inversion because
    _note_acquire skips a self-edge when the same name tops the stack."""

    _factory = staticmethod(threading.RLock)

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        if self._lk.acquire(blocking=False):
            self._lk.release()
            return False
        return True


class OrderedCondition:
    """``threading.Condition`` over an OrderedRLock. ``wait`` releases the
    underlying lock, so the held-stack entry is dropped for the duration —
    re-acquisition on wakeup is a fresh ordered acquire."""

    def __init__(self, name: str):
        self.name = name
        self._lock = OrderedRLock(name)
        self._cond = threading.Condition(self._lock._lk)

    def acquire(self, *a, **kw) -> bool:
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "OrderedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: float | None = None) -> bool:
        checking = self._lock._checking()
        if checking:
            _note_release(self.name)
        try:
            return self._cond.wait(timeout)
        finally:
            if checking:
                _note_acquire(self.name)

    def wait_for(self, predicate, timeout: float | None = None):
        # reimplemented over self.wait so the held-stack bookkeeping above
        # applies to every sleep, not just the first
        import time

        result = predicate()
        if result:
            return result
        end = None if timeout is None else time.monotonic() + timeout
        while not result:
            if end is not None:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    break
                self.wait(remaining)
            else:
                self.wait()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"<OrderedCondition {self.name!r}>"


# factories mirroring threading's callables — these are what the static
# pass (lint/lockorder.py _LOCK_CTORS) recognizes as lock definitions
def lock(name: str) -> OrderedLock:
    return OrderedLock(name)


def rlock(name: str) -> OrderedRLock:
    return OrderedRLock(name)


def condition(name: str) -> OrderedCondition:
    return OrderedCondition(name)
