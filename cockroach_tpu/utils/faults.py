"""Fault-injection registry — settings-gated, deterministic chaos hooks.

Reference mapping (each named site's CockroachDB analogue):

- ``kv.rpc.client.batch``   — DistSender send errors (kvcoord/
  dist_sender.go's sendError paths): the request is dropped/delayed on
  the wire before the server evaluates it.
- ``kv.rpc.server.eval``    — replica-side evaluation failure
  (kvserver's TestingEvalFilter knobs): the server errors/hangs before
  touching the store, the client sees a severed stream.
- ``flow.host.setup``       — SetupFlow RPC failure (distsql/server.go
  SetupFlow returning an error to the gateway).
- ``flow.host.stream``      — FlowStream attach/stream failure
  (flowinfra's ConnectInboundStream timeout/error discipline).
- ``kv.dialer.dial``        — nodedialer connect failures (rpc/
  nodedialer's breaker-tracked dials).
- ``storage.wal.append``    — pebble WAL write errors (vfs error
  injection, pebble's errorfs): delay models a stalling disk, `partial`
  models a torn append (half a record hits the platter before the
  crash), error models EIO.
- ``storage.wal.fsync``     — fsync stall/failure (pebble's
  WALFailover trigger condition).
- ``kv.rpc.server.respond`` — the server applied the batch but the
  response never reached the client (the classic ambiguous-result
  window: kvcoord's sendError after a successful proposal). `drop`
  severs the stream post-apply.
- ``liveness.heartbeat``    — node-liveness heartbeat failures
  (liveness.go's heartbeat RPC timing out / losing the disk). Sites
  also fire a node-scoped variant ``liveness.heartbeat.n<id>`` so a
  test can blackhole ONE node's heartbeats while others stay live.
- ``liveness.epoch_bump``   — the IncrementEpoch CPut failing
  (liveness.go's IncrementEpoch contention path). Node-scoped variant
  ``liveness.epoch_bump.n<id>`` keyed by the node DOING the bump.
- ``gossip.broadcast``      — gossip exchange failures (gossip.go's
  client connect/send errors). Node-scoped ``gossip.broadcast.n<id>``.
- ``kv.rangefeed.subscribe`` — rangefeed (re)subscription failures
  (kvclient/rangefeed's restart-on-error discipline).
- ``ranger.split.apply``     — split-queue crash AFTER the meta write
  but BEFORE bookkeeping (lease carry / cache repair / load handoff) —
  the splitTrigger's partial-application window. Queue purgatory
  retries must converge.
- ``ranger.merge.apply``     — merge-queue crash after the boundary is
  removed from meta but before bookkeeping (mergeTrigger window).
- ``ranger.lease.transfer``  — the range's data moved but the lease
  transfer write was lost (AdminTransferLease's in-flight window);
  retry must be a no-op move + lease stamp.
- ``storage.ingest.link``    — AddSSTable crash window: the bulk-ingest
  run's side file is durable but the WAL link record never lands
  (cmd_add_sstable's link-don't-copy torn-link case). The run must stay
  invisible — replay sees no record — and a retry must land it cleanly;
  the orphaned side file is cleaned at the next checkpoint.
- ``storage.compaction.swap`` — crash between a compaction's run-set
  swap and its cache/bloom bookkeeping: block-cache invalidation for the
  replaced runs must still happen or readers could be served stale
  cached windows.
- ``flow.spill.partition_write`` — a host spill-partition write failing
  mid-stage (colcontainer's disk queue enqueue erroring,
  diskqueue.go's write path): the spilling operator's query fails but
  the staging account must not retain bytes for rows never staged,
  and monitors must still drain to zero.
- ``flow.spill.merge_probe`` — an oversized Grace-join partition's
  sorted-run merge-probe failing between runs (the external joiner's
  partition-processing window): partial join output may already have
  streamed downstream; the query must surface the error and a clean
  re-run must produce complete, correct output.
- ``storage.bloom.build``    — bloom filter construction failure.
  `error` models an allocation/build crash (the run serves reads
  filterless — correct, just unpruned); `partial` models silent bit
  corruption after the build checksum was taken — the lazy CRC verify
  must disable the filter on its first negative answer, preserving the
  zero-false-negative guarantee.

- ``changefeed.fanout.enqueue`` — fan-out buffer enqueue failure
  (kvserver/rangefeed's BufferedSender overflow path): the batch never
  reaches the subscriber's buffer; the subscriber sheds to a
  catch-up-scan from its frontier, so nothing is lost and no bytes leak.
- ``changefeed.subscriber.send`` — subscriber stream send failure
  mid-event (the MuxRangeFeed per-stream error discipline): the
  subscriber is evicted and resumes by reconnecting from its frontier.
- ``changefeed.frontier.checkpoint`` — resolved-timestamp checkpoint
  write/send failure (changefeedccl's frontier persistence): the
  frontier stays stale, so a resume re-delivers (idempotent by (ts,
  key)) rather than ever skipping events.
- ``kv.batch.coalesce``     — the coalesced-batch flush failing between
  collection and apply (the group-commit leader's window): every rider
  degrades to its own per-session solo batch — bit-identical results,
  typed per-key errors preserved, nothing applied twice (the merged
  batch's (cid, seq) stamp never reached the WAL).
- ``flow.sharedscan.attach`` — attaching a scan to a shared tile stream
  failing (the fan-out attach window): the query falls back to a solo
  scan of its own tiles; results identical, only the dispatch saving is
  lost.
- ``sql.warmup.compile``    — an ahead-of-time menu item's compile
  failing at server start (warmup is best-effort): the item is recorded
  as failed in crdb_internal.node_warmup_menu and serving compiles that
  kernel on first use instead — never blocks readiness.
- ``matview.flush`` / ``matview.delta.apply`` /
  ``matview.frontier.checkpoint`` — materialized-view maintenance
  failures at flush start, inside a delta-kernel apply, and between
  compute and the frontier/state swap. All three leave the buffered
  delta in place and the standing state untouched, so the retrying
  flush re-applies the identical delta from the old frontier —
  bit-identical to a fresh full scan, nothing lost or duplicated.

Discipline: everything is OFF unless ``fault.injection.enabled`` is set
AND the test armed specs via :func:`arm`. Firing decisions come from ONE
seeded ``random.Random`` so a chaos run replays exactly given its seed.
Sites call :func:`fire` which is a cheap no-op (one module-bool check)
when disarmed — production paths pay nothing.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field


# Machine-readable site registry — the docstring above is the prose; THIS
# is what tooling consumes. The fault-coverage lint pass parses this dict
# literal (site -> one-line description) and enforces that (a) every
# fire()/fire_scoped()/partial_fraction() call in product code names a
# registered site, (b) every registered site has a product fire call, and
# (c) every registered site is exercised by at least one chaos test —
# scripts/run_chaos_matrix.py fails on uncovered sites. Keep this a pure
# literal: the linter reads it with ast.literal_eval, never by import.
SITES: dict[str, str] = {
    "kv.rpc.client.batch": "DistSender send error before evaluation",
    "kv.rpc.server.eval": "replica-side evaluation failure",
    "kv.rpc.server.respond": "response lost after apply (ambiguous result)",
    "flow.host.setup": "SetupFlow RPC failure at the gateway",
    "flow.host.stream": "FlowStream attach/stream failure",
    "kv.dialer.dial": "nodedialer connect failure (breaker-tracked)",
    "storage.wal.append": "WAL write error/stall/torn append",
    "storage.wal.fsync": "fsync stall or failure",
    "liveness.heartbeat": "node-liveness heartbeat failure (node-scoped)",
    "liveness.epoch_bump": "IncrementEpoch CPut failure (node-scoped)",
    "gossip.broadcast": "gossip exchange failure (node-scoped)",
    "kv.rangefeed.subscribe": "rangefeed (re)subscription failure",
    "ranger.split.apply": "split partially applied before bookkeeping",
    "ranger.merge.apply": "merge partially applied before bookkeeping",
    "ranger.lease.transfer": "lease transfer write lost in flight",
    "storage.ingest.link": "bulk-ingest side file durable, link lost",
    "flow.spill.partition_write": "host spill-partition write failure",
    "flow.spill.merge_probe": "oversized-partition merge-probe run failure",
    "storage.compaction.swap": "crash between run swap and bookkeeping",
    "storage.bloom.build": "bloom build crash or silent bit corruption",
    "admission.grant.stall": "queued admission grant stalls (delay) or is "
                             "lost (error: waiter withdraws, typed busy)",
    "admission.bucket.refill": "tenant token-bucket refill failure "
                               "(typed busy with retry-after hint)",
    "changefeed.fanout.enqueue": "fan-out buffer enqueue failure: the "
                                 "batch is not buffered, the subscriber "
                                 "sheds to catch-up-scan (no gap, no "
                                 "leaked bytes)",
    "changefeed.subscriber.send": "subscriber socket send failure "
                                  "mid-stream: the consumer is evicted "
                                  "and must reconnect from its frontier",
    "changefeed.frontier.checkpoint": "resolved-frontier checkpoint "
                                      "failure (job progress write or "
                                      "subscriber checkpoint frame): "
                                      "resume re-delivers past the stale "
                                      "frontier, never skips",
    "kv.batch.coalesce": "coalesced-batch flush failure: every rider "
                         "degrades to its own per-session solo batch, "
                         "bit-identical, nothing applied twice",
    "flow.sharedscan.attach": "shared tile stream attach failure: the "
                              "scan falls back to slicing its own tiles "
                              "(identical results, dispatch saving lost)",
    "sql.warmup.compile": "ahead-of-time menu compile failure at server "
                          "start: item marked failed, serving compiles "
                          "on first use, readiness never blocked",
    "matview.delta.apply": "materialized-view delta kernel failure "
                           "mid-flush: no state swapped, buffered delta "
                           "retained, retry from frontier is bit-exact",
    "matview.flush": "materialized-view flush failure before any "
                     "apply: events stay buffered at the subscription, "
                     "next flush resumes from the frontier",
    "matview.frontier.checkpoint": "materialized-view frontier "
                                   "checkpoint failure after compute, "
                                   "before swap: retry re-applies the "
                                   "same delta, nothing lost or doubled",
}


class InjectedFault(ConnectionError):
    """Raised by `error`/`drop` faults. Subclasses ConnectionError so the
    retry layer classifies an injected drop exactly like a real one."""

    def __init__(self, site: str, kind: str):
        super().__init__(f"injected {kind} at {site}")
        self.site = site
        self.kind = kind


@dataclass
class FaultSpec:
    """What can happen at one site.

    kind: 'error' | 'drop' | 'delay' | 'partial'
      - error/drop raise InjectedFault (drop = the wire died; error = the
        peer answered with a failure) — sites may translate further.
      - delay sleeps `delay_s` then proceeds (slow disk / slow peer).
      - partial is site-interpreted (WAL: append a torn half-record).
    p:         firing probability per pass through the site.
    max_fires: stop firing after this many hits (so a retrying caller
               eventually succeeds — the chaos harness's "transient"
               knob). None = unlimited (a dead-forever peer).
    """

    kind: str = "error"
    p: float = 1.0
    delay_s: float = 0.01
    max_fires: int | None = None
    fires: int = field(default=0, compare=False)


_lock = threading.Lock()
_armed = False
_rng = random.Random(0)
_specs: dict[str, FaultSpec] = {}
_log: list[tuple[str, str]] = []  # (site, kind) of every fired fault


def arm(seed: int, specs: dict[str, FaultSpec]) -> None:
    """Enable injection with a fixed seed (also flips the gating setting
    so `fire` sites are live). Tests pair this with `disarm` in finally."""
    from . import settings

    global _armed, _rng
    # The chaos matrix runner (scripts/run_chaos_matrix.py) perturbs every
    # in-test seed through the environment so one pytest invocation can be
    # replayed across N distinct seeds without editing the tests.
    seed += int(os.environ.get("CHAOS_SEED_OFFSET", "0"))
    with _lock:
        _rng = random.Random(seed)
        _specs.clear()
        _specs.update(specs)
        _log.clear()
        _armed = True
    settings.set("fault.injection.enabled", True)


def disarm() -> None:
    from . import settings

    global _armed
    with _lock:
        _armed = False
        _specs.clear()
        _log.clear()
    settings.set("fault.injection.enabled", False)


def fired() -> list[tuple[str, str]]:
    """(site, kind) of every fault that actually fired, in order."""
    with _lock:
        return list(_log)


def fire(site: str) -> None:
    """Called at an instrumented site. Raises InjectedFault for error/drop
    faults, sleeps for delay faults, no-ops when disarmed or the die-roll
    misses. `partial` never fires here — sites with a partial-capable
    action consult :func:`partial_fraction` instead."""
    if not _armed:
        return
    spec = _roll(site)
    if spec is None or spec.kind == "partial":
        return
    if spec.kind == "delay":
        time.sleep(spec.delay_s)
        return
    raise InjectedFault(site, spec.kind)


def fire_scoped(site: str, node_id: int) -> None:
    """Fire a site that exists per-node: checks the generic name AND the
    node-scoped ``<site>.n<id>`` variant. Tests arm whichever granularity
    they need — the generic name hits every node, the scoped name
    blackholes exactly one (the registry is process-global, so without
    scoping a heartbeat fault would kill every node in a multi-node
    test)."""
    fire(site)
    fire(f"{site}.n{node_id}")


def partial_fraction(site: str) -> float | None:
    """For sites that can tear a write: returns the fraction of the write
    to persist (then the site raises as if the disk died mid-append), or
    None when no partial fault fires."""
    if not _armed:
        return None
    spec = _roll(site, kinds=("partial",))
    if spec is None:
        return None
    return 0.5


def _roll(site: str, kinds: tuple[str, ...] | None = None):
    from . import metric

    with _lock:
        if not _armed:
            return None
        spec = _specs.get(site)
        if spec is None:
            return None
        if kinds is not None and spec.kind not in kinds:
            return None
        if kinds is None and spec.kind == "partial":
            return None
        if spec.max_fires is not None and spec.fires >= spec.max_fires:
            return None
        if _rng.random() >= spec.p:
            return None
        spec.fires += 1
        _log.append((site, spec.kind))
    metric.FAULTS_INJECTED.inc()
    return spec
