"""Structured logging — the pkg/util/log analog.

Reference: channelized structured logs (log/channels.go: DEV, OPS, HEALTH,
STORAGE, SQL_EXEC, ...), JSON sinks with redactable strings, severity
filtering. Here: the same channel/severity shape over JSON lines, a
process-default sink (stderr or file), and redaction markers — reduced to
what a single process needs (fluent/http sinks and the event-proto schema
arrive with the server layer).

    from cockroach_tpu.utils import log
    log.info(log.STORAGE, "compaction finished", runs=3, rows=1024)
"""

from __future__ import annotations

import json
import sys
import threading
import time

# channels (log/channels.go)
DEV = "DEV"
OPS = "OPS"
HEALTH = "HEALTH"
STORAGE = "STORAGE"
SQL_EXEC = "SQL_EXEC"
SENSITIVE_ACCESS = "SENSITIVE_ACCESS"

_SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")


class Redactable(str):
    """A value that redacts in logs unless redaction is off — the
    redact.RedactableString discipline (values wrapped, not formatted)."""


class _Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self._file = None
        self.min_severity = "INFO"
        self.redact = False

    def set_file(self, path: str | None) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
            self._file = open(path, "a") if path else None

    def emit(self, record: dict) -> None:
        line = json.dumps(record, default=str)
        with self._lock:
            out = self._file if self._file is not None else sys.stderr
            print(line, file=out, flush=True)


_sink = _Sink()


def set_file(path: str | None) -> None:
    """Route logs to a file (None = stderr)."""
    _sink.set_file(path)


def set_min_severity(sev: str) -> None:
    assert sev in _SEVERITIES
    _sink.min_severity = sev


def _log(sev: str, channel: str, msg: str, kw: dict) -> None:
    if _SEVERITIES.index(sev) < _SEVERITIES.index(_sink.min_severity):
        return
    fields = {}
    for k, v in kw.items():
        if _sink.redact and isinstance(v, Redactable):
            fields[k] = "<redacted>"
        else:
            fields[k] = v
    _sink.emit({
        "ts": round(time.time(), 3),
        "sev": sev,
        "ch": channel,
        "msg": msg,
        **fields,
    })


def debug(channel: str, msg: str, **kw) -> None:
    _log("DEBUG", channel, msg, kw)


def info(channel: str, msg: str, **kw) -> None:
    _log("INFO", channel, msg, kw)


def warning(channel: str, msg: str, **kw) -> None:
    _log("WARNING", channel, msg, kw)


def error(channel: str, msg: str, **kw) -> None:
    _log("ERROR", channel, msg, kw)
