"""Cluster settings registry — the pkg/settings analog.

Reference: pkg/settings/registry.go holds typed, documented, SQL-updatable
settings (RegisterBoolSetting bool.go:138 etc.); test builds randomize
"metamorphic constants" (pkg/util/metamorphic/constants.go:82) such as
coldata-batch-size so unit tests sweep the tuning space. Here settings are
process-local (single-process framework; gossip distribution is the control
plane's job when multi-host arrives), typed, validated, resettable, and
metamorphically randomizable for tests.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any


@dataclass
class Setting:
    name: str
    default: Any
    kind: str  # bool | int | float | string | enum
    desc: str
    choices: tuple | None = None
    lo: float | None = None
    hi: float | None = None
    # metamorphic: (lo, hi) or choices to randomize within for test builds
    metamorphic: bool = False
    value: Any = None

    def get(self):
        return self.default if self.value is None else self.value


_REGISTRY: dict[str, Setting] = {}


def _register(s: Setting) -> Setting:
    if s.name in _REGISTRY:
        raise ValueError(f"duplicate setting {s.name}")
    # crlint: allow-shared-state(registration happens at import time, before any worker thread exists; runtime mutation goes through Setting.value) # crlint: allow-race-coverage(dict inserts happen only at import time, before any worker thread exists; runtime SET rebinds Setting.value — a GIL-atomic rebind read via Setting.get — and never touches the dict, so there is no post-startup write for a lock or racesan to witness)
    _REGISTRY[s.name] = s
    return s


def register_bool(name: str, default: bool, desc: str,
                  metamorphic: bool = False) -> Setting:
    return _register(Setting(name, default, "bool", desc,
                             metamorphic=metamorphic))


def register_int(name: str, default: int, desc: str, lo: int | None = None,
                 hi: int | None = None, metamorphic: bool = False) -> Setting:
    return _register(Setting(name, default, "int", desc, lo=lo, hi=hi,
                             metamorphic=metamorphic))


def register_float(name: str, default: float, desc: str,
                   lo: float | None = None, hi: float | None = None) -> Setting:
    return _register(Setting(name, default, "float", desc, lo=lo, hi=hi))


def register_enum(name: str, default: str, desc: str,
                  choices: tuple[str, ...],
                  metamorphic: bool = False) -> Setting:
    return _register(Setting(name, default, "enum", desc, choices=choices,
                             metamorphic=metamorphic))


def register_string(name: str, default: str, desc: str) -> Setting:
    return _register(Setting(name, default, "string", desc))


def get(name: str):
    return _REGISTRY[name].get()


def set(name: str, value) -> None:  # noqa: A001 - SQL SET semantics
    s = _REGISTRY[name]
    if s.kind == "bool":
        if not isinstance(value, bool):
            raise TypeError(f"{name} wants bool, got {value!r}")
    elif s.kind == "int":
        value = int(value)
        if s.lo is not None and value < s.lo:
            raise ValueError(f"{name}: {value} < min {s.lo}")
        if s.hi is not None and value > s.hi:
            raise ValueError(f"{name}: {value} > max {s.hi}")
    elif s.kind == "float":
        value = float(value)
        if s.lo is not None and value < s.lo:
            raise ValueError(f"{name}: {value} < min {s.lo}")
        if s.hi is not None and value > s.hi:
            raise ValueError(f"{name}: {value} > max {s.hi}")
    elif s.kind == "enum":
        if value not in s.choices:
            raise ValueError(f"{name}: {value!r} not in {s.choices}")
    s.value = value
    _notify(name, value)


_CHANGE_LISTENERS: list = []
# bare threading.Lock, not utils.locks: locks.py reads its settings from
# this module, so the ordered-lock machinery can't be imported here
_LISTENERS_MU = threading.Lock()


def on_change(cb) -> None:
    """Subscribe cb(name, value) to every settings.set — the gossip bridge
    (the reference gossips updated cluster settings to every node,
    settings/updater.go); Node wires this to publish into its infostore."""
    with _LISTENERS_MU:
        _CHANGE_LISTENERS.append(cb)


def remove_on_change(cb) -> None:
    with _LISTENERS_MU:
        if cb in _CHANGE_LISTENERS:
            _CHANGE_LISTENERS.remove(cb)


def _notify(name: str, value) -> None:
    with _LISTENERS_MU:
        snapshot = list(_CHANGE_LISTENERS)
    for cb in snapshot:
        cb(name, value)


def reset(name: str | None = None) -> None:
    # a RESET is a value change like any SET: listeners (the gossip bridge)
    # must see it, or peers keep the overridden value forever
    if name is None:
        for s in _REGISTRY.values():
            if s.value is not None:
                s.value = None
                _notify(s.name, s.get())
    else:
        s = _REGISTRY[name]
        s.value = None
        _notify(name, s.get())


def all_settings() -> dict[str, Setting]:
    return dict(_REGISTRY)


def randomize_metamorphic(rng) -> dict[str, Any]:
    """Randomize metamorphic settings (test builds only) — the
    metamorphic-constants analog. Returns what was chosen."""
    chosen = {}
    for s in _REGISTRY.values():
        if not s.metamorphic:
            continue
        if s.kind == "int":
            lo = int(s.lo if s.lo is not None else 1)
            hi = int(s.hi if s.hi is not None else 4096)
            # bias to powers of two (tile sizes)
            pows = [p for p in (256, 512, 1024, 2048, 4096) if lo <= p <= hi]
            v = int(rng.choice(pows)) if pows else int(rng.integers(lo, hi + 1))
        elif s.kind == "bool":
            v = bool(rng.integers(0, 2))
        elif s.kind == "enum":
            v = s.choices[int(rng.integers(len(s.choices)))]
        else:
            continue
        set(s.name, v)
        chosen[s.name] = v
    return chosen


# ---------------------------------------------------------------------------
# The framework's own settings (the ~700-setting registry's seed)

TILE_SIZE = register_int(
    "sql.distsql.tile_size", 1 << 20,
    "static tile capacity for scan batches (coldata batch size analog). "
    "Large tiles amortize XLA dispatch latency (~70ms/round over the TPU "
    "tunnel) and keep sorts/gathers wide; resident tables pad to a tile "
    "multiple so no kernel ever compiles at full-table shape",
    lo=128, hi=1 << 24, metamorphic=True,
)
L0_COMPACTION = register_int(
    "storage.l0_compaction_threshold", 4,
    "number of L0 runs that triggers a compaction "
    "(DefaultPebbleOptions L0CompactionThreshold analog)",
    lo=1, hi=64,
)
WORKMEM_ROWS = register_int(
    "sql.distsql.workmem_rows", 1 << 21,
    "device-tile row budget for buffering operators; exceeding it swaps in "
    "the external (host-partitioned) variant — the workmem/disk-spill "
    "threshold (disk_spiller.go:103 analog)",
    lo=1024,
)
WORKMEM_BYTES = register_int(
    "sql.distsql.workmem_bytes", 2 << 30,
    "per-operator device-byte budget for buffering spools (colmem.Allocator "
    "against mon.BytesMonitor analog); exceeding it swaps in the external "
    "operator variant (disk_spiller.go:103)",
    lo=1 << 16,
)
GRACE_SKEW_SAMPLE = register_int(
    "sql.distsql.grace_skew_sample", 1024,
    "reservoir size for build-side key-hash sampling while a Grace hash "
    "join partitions its input; heavy hitters detected in the sample keep "
    "their build rows resident on device and their probe rows route "
    "through a dedicated hot lane instead of one oversized partition "
    "(0 disables sampling)",
    lo=0, hi=1 << 20,
)
GRACE_SKEW_FRAC = register_float(
    "sql.distsql.grace_skew_frac", 0.05,
    "fraction of the build-side key sample one key hash must own to count "
    "as a heavy hitter for Grace-join skew routing (0 disables routing)",
    lo=0.0, hi=1.0,
)
PALLAS_FILTER = register_enum(
    "storage.pallas_filter", "auto",
    "MVCC window scan-filter implementation: 'auto' uses the fused Pallas "
    "kernel on TPU and the jnp composition everywhere else (the kernel's "
    "tiling targets Mosaic; the GPU/Triton lowering is unexercised); 'on' "
    "forces Pallas — compiled on TPU, interpret mode on CPU for parity "
    "testing, unsupported on GPU; 'off' forces jnp",
    choices=("auto", "on", "off"),
)
PALLAS_MERGE = register_enum(
    "storage.pallas_merge", "auto",
    "LSM compaction merge implementation: 'auto' uses the bitonic-merge "
    "Pallas kernel on TPU for VMEM-sized merges (log2(N) compare-exchange "
    "stages exploiting run pre-sortedness) and the concat+lax.sort "
    "composition everywhere else; 'on' forces the kernel (interpret mode "
    "on CPU, for parity testing); 'off' forces concat+sort",
    choices=("auto", "on", "off"),
)
SQL_ADMISSION = register_bool(
    "admission.sql.enabled", True,
    "SQL admission control: every session statement takes a slot from the "
    "shared WorkQueue before executing (work_queue.go role); queue depth "
    "and wait land in admission_sql_queue_depth / admission_wait_seconds",
)
SQL_ADMISSION_SLOTS = register_int(
    "admission.sql.slots", 64,
    "concurrency slots of the SQL admission WorkQueue (the slot-based "
    "GrantCoordinator's size); statements past this run in (priority, "
    "arrival) order as slots free up",
    lo=1,
)
SQL_ADMISSION_MAX_QUEUE_DEPTH = register_int(
    "admission.sql.max_queue_depth", 512,
    "bound on the SQL admission wait queue: past this many queued "
    "statements, admit fails fast with AdmissionRejectedError (SQLSTATE "
    "53300 'server busy' at pgwire) instead of queuing toward collapse. "
    "0 = unbounded",
    lo=0,
)
SQL_ADMISSION_QUEUE_TIMEOUT = register_float(
    "admission.sql.queue_timeout_s", 30.0,
    "backstop deadline on SQL admission queue-wait for statements with "
    "no statement_timeout: past it the wait converts to a typed 53300 "
    "rejection with a retry-after hint (statements WITH a timeout count "
    "queue-wait against it instead). 0 = wait forever",
    lo=0.0,
)
TENANT_RATE = register_float(
    "admission.tenant.rate", 0.0,
    "per-tenant admission token refill rate (statements/s): each tenant "
    "id consumes one token per admitted statement from a bucket "
    "refilling at this rate; an empty bucket rejects with SQLSTATE "
    "53300 + retry-after = refill time. 0 = unlimited (no per-tenant "
    "rate limiting; the fair-share scheduler still applies)",
    lo=0.0,
)
TENANT_BURST = register_int(
    "admission.tenant.burst", 64,
    "per-tenant admission token bucket capacity: an idle tenant banks "
    "up to this many statements' worth of tokens before "
    "admission.tenant.rate throttles it",
    lo=1,
)
SHED_MEM_LOW = register_float(
    "admission.shed.mem_low", 0.90,
    "memory-pressure fraction (flow/memory.py mem_pressure) past which "
    "admission sheds the analytical lane: LOW-priority statements are "
    "rejected with 53300 while interactive traffic still lands",
    lo=0.0, hi=1.0,
)
SHED_MEM_HIGH = register_float(
    "admission.shed.mem_high", 0.97,
    "memory-pressure fraction past which admission sheds NORMAL "
    "priority too — only HIGH (txn control: COMMIT/ROLLBACK) is still "
    "admitted, so in-flight transactions can wind down",
    lo=0.0, hi=1.0,
)
SQL_MEM_ROOT_BUDGET = register_int(
    "sql.mem.root_budget_bytes", 0,
    "node-level logical-byte budget for the root memory monitor "
    "(--max-sql-memory role). 0 = unlimited: the tree still tracks "
    "usage/peaks, and mem_pressure() (read by the IOGovernor) reports 0",
    lo=0,
)
IO_PACING = register_bool(
    "admission.io_pacing.enabled", True,
    "write admission control: engine writes pay a delay proportional to "
    "L0 overload (io_load_listener role) so compaction catches up before "
    "read amplification inverts",
)
BULK_INGEST = register_bool(
    "storage.bulk_ingest.enabled", True,
    "route bulk loads (IMPORT, index backfill, bench loaders) through "
    "the AddSSTable-style run builder (storage/ingest.py): column "
    "batches sort and dedup device-side and link into the LSM as whole "
    "runs — one WAL link record per run instead of per-key WAL appends. "
    "Off falls back to the per-row write path",
)
BLOCK_CACHE_BYTES = register_int(
    "storage.block_cache.size_bytes", 256 << 20,
    "budget for the node-wide block cache of decoded KVBlock windows "
    "(storage/blockcache.py), accounted as a cache-level child of the "
    "root memory monitor tree. 0 disables caching entirely",
    lo=0,
)
COMPACTION_PACING = register_bool(
    "storage.compaction.pacing.enabled", True,
    "schedule size-tiered compactions through the IOGovernor's pacing "
    "loop instead of compacting inline the instant the L0 trigger "
    "trips: small-debt compactions may be deferred (min_interval_ms) so "
    "back-to-back merges can't starve foreground reads",
)
COMPACTION_PACING_INTERVAL = register_int(
    "storage.compaction.pacing.min_interval_ms", 0,
    "minimum milliseconds between paced size-tiered compactions while "
    "debt stays at or under storage.compaction.pacing.max_debt_runs; "
    "0 compacts as eagerly as the unpaced engine",
    lo=0, hi=60_000,
)
COMPACTION_PACING_MAX_DEBT = register_int(
    "storage.compaction.pacing.max_debt_runs", 8,
    "compaction debt (runs past the L0 trigger) above which pacing is "
    "bypassed and compaction runs immediately — read amplification past "
    "this point starves foreground reads worse than the compaction "
    "pause would",
    lo=1, hi=256,
)
DENSE_LUT_BITS = register_int(
    "sql.distsql.dense_lut_bits", 24,
    "max packed-key bits for the dense direct-addressing join index "
    "(ops/join.py): probes become one gather instead of a log2(n) binary "
    "search. 24 bits = a 64MiB int32 position table, far cheaper than the "
    "probe gathers it saves on any TPC-H-scale join",
    lo=0, hi=30,
)
SCAN_STREAM_ROWS = register_int(
    "sql.distsql.scan_stream_rows", 1 << 23,
    "tables larger than this stream host->device tile by tile with "
    "double-buffered async transfers instead of materializing wholly in "
    "HBM (the host half of SURVEY §7's pipelining hard part)",
    lo=1024,
)
MAX_FUSED_JOINS = register_int(
    "sql.distsql.max_fused_joins", 4,
    "maximum join probes composed into one fused streaming segment; deeper "
    "pipelines split into separate jits to bound XLA program size",
    lo=0, hi=64,
)
DENSE_AGG = register_bool(
    "sql.distsql.dense_agg.enabled", True,
    "allow the dense-code small-group aggregation specialization "
    "(falls back to the general sort-groupby path when off)",
    metamorphic=True,
)
JOIN_COMPACT_EMIT = register_bool(
    "sql.distsql.join_compact_emit", True,
    "adaptively compact selective join probe output in-kernel (learned "
    "sticky capacity, overflow-checked once per query)",
    metamorphic=True,
)
FUSION_GENERAL_PROBE = register_bool(
    "sql.distsql.fusion.general_probe", True,
    "fuse duplicate-key inner/left join probes as speculative streaming "
    "emitters (static learned capacity, totals validated once per query) "
    "instead of per-tile host-synced capacity retries",
    metamorphic=True,
)
DENSE_AGG_STATES = register_int(
    "sql.distsql.dense_agg_states", 1 << 23,
    "maximum dense group-code space (product of per-key bounds) for the "
    "scatter-based dense aggregation path; larger key spaces use the "
    "general sort-groupby path",
    lo=64, hi=1 << 28,
)
DENSE_AGG_ACCEL_STATES = register_int(
    "sql.distsql.dense_agg.accel_max_states", 1 << 19,
    "tighter dense-state budget on accelerator backends: XLA:TPU scatters "
    "serialize on the VPU (~100ms per 1M-row segment op, measured), so "
    "big-G dense aggregation loses to the sort+segmented-scan path there "
    "while staying the right choice on CPU (cheap serial scatters)",
    lo=64, hi=1 << 28,
)
DCN_IO_TIMEOUT = register_float(
    "flow.dcn.io_timeout_s", 30.0,
    "deadline on cross-host control-plane socket I/O: flow/gossip/"
    "rangefeed dials, stream handshakes, and per-read waits on "
    "established DCN streams. Generous by design — it is a liveness "
    "backstop against silent peers and half-open TCP, not a latency "
    "SLO; chaos-injected stalls shorter than this must not become "
    "typed failures",
    lo=0.1, hi=600.0,
)
COLLECT_STATS = register_bool(
    "sql.stats.collect_execution_stats", False,
    "collect per-operator ComponentStats on every query; stats are recorded "
    "on the active tracing span (EXPLAIN ANALYZE always collects)",
)
JOIN_ORDER = register_enum(
    "sql.opt.join_order", "heuristic",
    "multi-way join ordering: 'heuristic' starts at the largest estimated "
    "source and greedily joins the smallest connected build side; 'cost' "
    "runs a Selinger-style left-deep DP over the equi-join graph for 2..6 "
    "sources (reorder_joins_limit analog), falling back to the heuristic "
    "when the DP declines",
    choices=("heuristic", "cost"),
)
FAULT_INJECTION = register_bool(
    "fault.injection.enabled", False,
    "arm the chaos fault-injection registry (utils/faults.py); test builds "
    "only — the testing-knobs analog, never enabled in production",
)
RPC_DEADLINE_S = register_float(
    "rpc.batch.deadline_s", 5.0,
    "per-RPC deadline for KV Batch calls (DeadlineExceeded analog); a "
    "timed-out RPC re-dials and retries under rpc.batch.max_retries",
    lo=0.05, hi=300.0,
)
RPC_MAX_RETRIES = register_int(
    "rpc.batch.max_retries", 4,
    "attempts per KV Batch RPC against transient errors (drops, timeouts) "
    "before the failure surfaces (util/retry MaxRetries analog)",
    lo=1, hi=64,
)
BREAKER_TRIP = register_int(
    "rpc.breaker.trip_threshold", 3,
    "consecutive reported RPC failures that open a peer's circuit breaker "
    "(rpc/peer.go reduction)",
    lo=1, hi=100,
)
BREAKER_COOLDOWN_S = register_float(
    "rpc.breaker.cooldown_s", 5.0,
    "open-breaker cooldown before the half-open probe is admitted",
    lo=0.01, hi=600.0,
)
FLOW_DEADLINE_S = register_float(
    "sql.distsql.flow_deadline_s", 30.0,
    "end-to-end deadline for a cross-host distributed query (setup + "
    "stream drain); on expiry remote flows are cancelled and the gateway "
    "degrades or errors (flowinfra timeout discipline)",
    lo=0.1, hi=3600.0,
)
SPLIT_QPS_THRESHOLD = register_float(
    "kv.range.split_qps_threshold", 2500.0,
    "decayed per-range QPS above which the split queue cuts the range at "
    "a sampled mid-load key (kv.range_split.load_qps_threshold analog)",
    lo=0.001, hi=1e9,
)
RANGE_MAX_BYTES = register_int(
    "kv.range.max_bytes", 64 << 20,
    "authoritative logical size above which the split queue cuts a range "
    "regardless of load (zone-config range_max_bytes analog); ranges whose "
    "combined size stays under half of this are merge candidates",
    lo=256,
)
RANGE_MERGE_ENABLED = register_bool(
    "kv.range.merge_enabled", True,
    "let the merge queue absorb a cold range into its cold left neighbor "
    "(kv.range_merge.queue_enabled analog); disable to freeze boundaries",
)
ALLOCATOR_ENABLED = register_bool(
    "kv.allocator.enabled", True,
    "run the range-lifecycle queues (split/merge/rebalance) on node start; "
    "the queues are also constructible standalone for deterministic tests",
)
FUSION_ENABLED = register_bool(
    "sql.distsql.fusion.enabled", True,
    "collapse contiguous stateless per-tile operator chains (filter / "
    "project / hash-bucket / fusable join probes) into single-kernel "
    "FusedPipeline segments at plan build (flow/fuse.py), so XLA fuses "
    "each chain into one dispatch and intermediate padded tiles never "
    "materialize; off runs the classic one-jit-per-operator pull path",
    metamorphic=True,
)
LOCK_ORDER_CHECKS = register_bool(
    "debug.lock_order.enabled", False,
    "make every utils/locks.OrderedLock acquisition verify the global "
    "lock-acquisition order (deadlock_detection analog): acquiring B "
    "while holding A records edge A->B, and an acquisition that would "
    "close a cycle raises LockOrderError instead of deadlocking; off "
    "(default) the wrappers are plain locks with no per-acquire overhead",
)
RACE_DETECTOR = register_bool(
    "debug.race_detector.enabled", False,
    "arm the runtime data-race sanitizer (utils/racesan.py): tracked "
    "control-plane fields run the Eraser lockset algorithm — a "
    "lockset-disjoint write/write or write/read across threads raises "
    "DataRaceError at the access instead of corrupting state; also keeps "
    "the per-thread held-lock stack live. Off (default) every "
    "note_read/note_write is a single settings check",
)
READBACK_OVERLAP = register_bool(
    "sql.distsql.readback_overlap", True,
    "double-buffer the root pull loop (flow/runtime.py): tile k's "
    "device->host readback is issued asynchronously (copy_to_host_async) "
    "and materialized while tile k+1 computes, overlapping the slow "
    "readback tunnel with device work instead of serializing after it",
    metamorphic=True,
)
SHAPE_BUCKETS_ENABLED = register_bool(
    "sql.distsql.shape_buckets.enabled", True,
    "pad sub-tile resident tables up the canonical pow2 shape ladder "
    "(catalog.SHAPE_BUCKETS: 1k/8k/64k/512k/2M) instead of to their own "
    "1024-aligned cardinality, so kernels over small tables compile at a "
    "handful of process-shared shapes; masks keep padded rows dead, so "
    "results are bit-identical either way (tested)",
    metamorphic=True,
)
PLAN_CACHE_ENABLED = register_bool(
    "sql.plan_cache.enabled", True,
    "serve repeat statements (same structure, any numeric literals) from "
    "the prepared-plan LRU (sql/plancache.py): the cached operator tree "
    "rebinds literals as jit arguments, so the second execution performs "
    "zero new XLA compiles — the pgwire extended-protocol fast path",
)
PLAN_CACHE_SIZE = register_int(
    "sql.plan_cache.size", 128,
    "maximum prepared plans held by the per-catalog plan cache before "
    "LRU eviction (each entry pins a built operator tree and its "
    "compiled kernels)",
    lo=1, hi=1 << 16,
)
COMPILE_CACHE_ENABLED = register_bool(
    "sql.compile_cache.enabled", False,
    "persist XLA compilations to disk (jax compilation cache, L3 of the "
    "cache hierarchy) so process restarts reuse executables instead of "
    "recompiling the fleet; directory from sql.compile_cache.dir",
)
COMPILE_CACHE_DIR = register_string(
    "sql.compile_cache.dir", "",
    "on-disk XLA compilation cache directory; empty uses "
    "JAX_COMPILE_CACHE_DIR or <repo>/.jax_cache (utils/backend.py)",
)
PLAN_WARMUP_ENABLED = register_bool(
    "sql.plan_cache.warmup.enabled", False,
    "background warmup thread: speculatively re-trace hot cached plans "
    "(by sqlstats fingerprint) off the serving path after DDL or process "
    "start, so the first foreground execution finds warm kernels",
)
WARMUP_MENU_ENABLED = register_bool(
    "sql.warmup.menu.enabled", False,
    "ahead-of-time kernel menu (sql/warmmenu.py): at server start compile "
    "the canonical shape-ladder operator templates plus sqlstats-ranked "
    "hot statements through flow/dispatch.jit into the process-global "
    "kernel cache BEFORE the node advertises readiness, so a fresh node "
    "serves first-ever queries without the cold compile wall",
)
WARMUP_MENU_BUDGET_S = register_float(
    "sql.warmup.menu.budget_s", 30.0,
    "wall-clock budget for the ahead-of-time kernel menu build; when it "
    "expires the remaining menu items are skipped (recorded as 'skipped' "
    "in crdb_internal.node_warmup_menu) and the node starts serving",
    lo=0.0,
)
WARMUP_MENU_MAX_KERNELS = register_int(
    "sql.warmup.menu.max_kernels", 512,
    "cap on new kernel compilations the warm menu may mint; menu items "
    "past the cap are skipped (a runaway template enumeration must not "
    "exhaust compile-cache or startup time)",
    lo=1,
)
KV_COALESCE_ENABLED = register_bool(
    "kv.batch.coalesce.enabled", False,
    "inter-query batching (kv/coalesce.py): concurrent same-range "
    "non-transactional point reads/writes from different sessions merge "
    "into one stamped KV batch (group commit through the (cid,seq) "
    "replay cache — one WAL record, one engine pass) with per-session "
    "result demux and typed per-key errors",
)
KV_COALESCE_MAX_OPS = register_int(
    "kv.batch.coalesce.max_ops", 128,
    "cap on point ops merged into one coalesced KV batch; arrivals past "
    "the cap start the next batch train (bounds WAL record size and "
    "per-key error fan-out)",
    lo=2,
)
SHAREDSCAN_ENABLED = register_bool(
    "sql.distsql.sharedscan.enabled", False,
    "shared tile streams (flow/sharedscan.py): concurrent resident scans "
    "of the same table attach to one stream — one query slices each tile "
    "(one dispatch), attached queries consume the shared tile and apply "
    "their own filter masks downstream",
)
SHAREDSCAN_WINDOW = register_int(
    "sql.distsql.sharedscan.window", 8,
    "shared-scan buffer window in tiles: a subscriber lagging more than "
    "this many tiles behind the head is detached to a solo scan "
    "(slow-consumer eviction), bounding the staging account",
    lo=1,
)
SLOW_QUERY_THRESHOLD = register_float(
    "sql.log.slow_query.latency_threshold", 0.0,
    "when > 0, any statement slower than this many seconds is logged to "
    "the SQL_EXEC channel and a statement diagnostics bundle (trace, "
    "plan, counters — sql/diagnostics.py) is captured to the bounded "
    "on-disk ring; 0 disables",
    lo=0.0,
)
XLA_PROFILE = register_bool(
    "sql.trace.xla_profile", False,
    "annotate query execution with jax.profiler.TraceAnnotation so "
    "device timelines captured by an external profiler carry query "
    "boundaries; off by default — the profiler is optional and queries "
    "must run without it",
)
DIAG_RING_SIZE = register_int(
    "sql.diagnostics.ring_size", 16,
    "maximum statement diagnostics bundles retained on disk before the "
    "oldest is evicted (sql/diagnostics.py ring); each bundle is a JSON "
    "file with the trace, plan, and counter snapshot",
    lo=1, hi=1 << 12,
)
DIAG_DIR = register_string(
    "sql.diagnostics.dir", "",
    "directory for statement diagnostics bundles; empty uses a "
    "per-process temporary directory cleaned up on interpreter exit",
)
TS_RETENTION_SECONDS = register_float(
    "ts.retention_seconds", 600.0,
    "timeseries retention horizon: the background metrics scraper "
    "(server/node.py) prunes kv/tsdb.py samples older than this after "
    "each scrape tick; 0 disables pruning",
    lo=0.0,
)
CHANGEFEED_FANOUT_BUFFER_BYTES = register_int(
    "changefeed.fanout.buffer_bytes", 1 << 20,
    "per-subscriber fan-out buffer budget (bytes), charged to the "
    "node's changefeed staging account; the backpressure ladder "
    "(coalesce -> shed -> evict) engages against this bound",
    lo=4096,
)
CHANGEFEED_FANOUT_HIGHWATER_FRAC = register_float(
    "changefeed.fanout.highwater_frac", 0.5,
    "fraction of the per-subscriber buffer budget at which duplicate-key "
    "events start coalescing to newest-version-per-key",
    lo=0.05, hi=1.0,
)
CHANGEFEED_FANOUT_SEND_DEADLINE_S = register_float(
    "changefeed.fanout.send_deadline_s", 5.0,
    "liveness bound on a subscriber connection: a send that blocks "
    "longer than this, or a subscriber with pending work and no "
    "successful send within it, is evicted (SlowConsumerError) and its "
    "sender thread reaped",
    lo=0.05,
)
CHANGEFEED_FANOUT_HEARTBEAT_S = register_float(
    "changefeed.fanout.heartbeat_s", 1.0,
    "idle-connection heartbeat: a subscriber with no new events still "
    "receives a resolved-timestamp checkpoint this often, so a dead "
    "socket is detected within heartbeat + send deadline",
    lo=0.05,
)
CHANGEFEED_FANOUT_MAX_SUBSCRIBERS = register_int(
    "changefeed.fanout.max_subscribers", 4096,
    "bound on concurrently registered fan-out subscribers per hub; "
    "past it new subscriptions are refused with a typed error frame "
    "instead of degrading everyone",
    lo=1,
)
MATVIEW_ENABLED = register_bool(
    "sql.matview.enabled", True,
    "master switch for the materialized-view subsystem: CREATE "
    "MATERIALIZED VIEW is refused when off (existing views keep "
    "serving their last refreshed state)",
)
MATVIEW_REWRITE_ENABLED = register_bool(
    "sql.matview.rewrite.enabled", True,
    "planner rewrite: a SELECT whose parameterized plan matches a "
    "registered materialized view's defining query is served from the "
    "standing state (AS OF the view's resolved frontier) instead of "
    "rescanning the base table",
)
MATVIEW_REFRESH_ON_READ = register_bool(
    "sql.matview.refresh_on_read.enabled", True,
    "drain pending changefeed deltas into a view's standing state "
    "before a statement that reads it; off = reads serve the state as "
    "of the last flush (the AS OF freshness bound is the frontier)",
)
MATVIEW_STAGING_BYTES = register_int(
    "sql.matview.staging_bytes", 4 << 20,
    "budget for a view maintainer's delta-tile staging account (the "
    "columnar insert/retract tiles built per flush are charged here "
    "before the fused maintenance dispatch)",
    lo=4096,
)
CHANGEFEED_FANOUT_MAX_SHEDS = register_int(
    "changefeed.fanout.max_consecutive_sheds", 3,
    "a subscriber whose buffer is shed to catch-up-scan this many times "
    "in a row without ever draining is evicted (the terminal rung of "
    "the backpressure ladder)",
    lo=1,
)
TS_SCRAPE_INTERVAL = register_float(
    "ts.scrape_interval_seconds", 10.0,
    "seconds between background metrics-scraper ticks on a server node "
    "(each tick records every registry counter/gauge into the "
    "timeseries store under cr.node.*)",
    lo=0.1,
)
