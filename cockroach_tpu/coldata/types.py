"""SQL type system and canonical device representations.

Mirrors the role of pkg/sql/types + pkg/col/typeconv in the reference: every SQL
type maps to a *canonical type family* with a fixed device representation, so
kernels are written once per canonical family and XLA's dtype polymorphism
replaces execgen's per-type code generation (reference:
pkg/col/typeconv, pkg/sql/colexec/execgen).

Canonical device representations (all fixed-width; TPU-first):

| family    | device dtype | notes                                                |
|-----------|--------------|------------------------------------------------------|
| BOOL      | bool_        |                                                      |
| INT       | int16/32/64  | width from SQL type                                  |
| FLOAT     | float64      | SQL DOUBLE; float32 available via width=32           |
| DECIMAL   | int64        | scaled fixed-point, scale in the type (TPC-H policy; |
|           |              | divergence from arbitrary-precision apd documented)  |
| DATE      | int32        | days since epoch                                     |
| TIMESTAMP | int64        | microseconds since epoch                             |
| INTERVAL  | int64        | microseconds                                         |
| STRING    | int32        | dictionary code; dictionary lives host-side in the   |
|           |              | column's Dictionary (see batch.py)                   |
| BYTES     | uint8[N,W]   | fixed-width padded buffer + int32 length column      |

Selection vectors become masks: TPUs hate gathers, so the reference's
``sel []int`` (pkg/col/coldata/batch.go) is replaced by a boolean liveness mask
over a static-capacity tile, compacted only at operator boundaries that need it.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


class Family(enum.Enum):
    BOOL = "bool"
    INT = "int"
    FLOAT = "float"
    DECIMAL = "decimal"
    DATE = "date"
    TIMESTAMP = "timestamp"
    INTERVAL = "interval"
    STRING = "string"
    BYTES = "bytes"
    JSON = "json"  # datum-backed fallback; host-side only


@dataclass(frozen=True)
class SQLType:
    """A SQL column type. Hashable and static — safe to close over in jit."""

    family: Family
    width: int = 64  # bit width for INT/FLOAT; max byte width for BYTES
    precision: int = 0  # DECIMAL precision (informational)
    scale: int = 0  # DECIMAL scale: value = data / 10**scale

    def __repr__(self) -> str:
        if self.family is Family.DECIMAL:
            return f"DECIMAL({self.precision},{self.scale})"
        if self.family is Family.INT:
            return f"INT{self.width}"
        if self.family is Family.FLOAT:
            return f"FLOAT{self.width}"
        return self.family.name

    @property
    def dtype(self) -> np.dtype:
        """Canonical device dtype for this SQL type."""
        f = self.family
        if f is Family.BOOL:
            return np.dtype(np.bool_)
        if f is Family.INT:
            return np.dtype({16: np.int16, 32: np.int32, 64: np.int64}[self.width])
        if f is Family.FLOAT:
            return np.dtype({32: np.float32, 64: np.float64}[self.width])
        if f is Family.DECIMAL:
            return np.dtype(np.int64)
        if f is Family.DATE:
            return np.dtype(np.int32)
        if f in (Family.TIMESTAMP, Family.INTERVAL):
            return np.dtype(np.int64)
        if f is Family.STRING:
            return np.dtype(np.int32)  # dictionary code
        if f is Family.BYTES:
            return np.dtype(np.uint8)
        raise TypeError(f"no canonical device dtype for {f}")

    @property
    def is_numeric(self) -> bool:
        return self.family in (Family.INT, Family.FLOAT, Family.DECIMAL)

    @property
    def comparable_on_device(self) -> bool:
        """Whether < / > on the raw device representation matches SQL ordering.

        Dictionary-coded strings need a host-prepared rank table (see
        batch.Dictionary.ranks); everything else orders natively.
        """
        return self.family is not Family.STRING


# Convenience constructors / singletons.
BOOL = SQLType(Family.BOOL)
INT16 = SQLType(Family.INT, width=16)
INT32 = SQLType(Family.INT, width=32)
INT64 = SQLType(Family.INT, width=64)
FLOAT32 = SQLType(Family.FLOAT, width=32)
FLOAT64 = SQLType(Family.FLOAT, width=64)
DATE = SQLType(Family.DATE)
TIMESTAMP = SQLType(Family.TIMESTAMP)
INTERVAL = SQLType(Family.INTERVAL)
STRING = SQLType(Family.STRING)


def DECIMAL(precision: int = 19, scale: int = 2) -> SQLType:
    return SQLType(Family.DECIMAL, precision=precision, scale=scale)


def BYTES(width: int = 64) -> SQLType:
    return SQLType(Family.BYTES, width=width)


@dataclass(frozen=True)
class Schema:
    """Ordered, named column types. Static plan-side metadata (never traced)."""

    names: tuple[str, ...]
    types: tuple[SQLType, ...]

    def __post_init__(self):
        assert len(self.names) == len(self.types)

    def __len__(self) -> int:
        return len(self.types)

    def index(self, name: str) -> int:
        return self.names.index(name)

    def type_of(self, name: str) -> SQLType:
        return self.types[self.index(name)]

    def select(self, idxs: tuple[int, ...]) -> "Schema":
        return Schema(
            tuple(self.names[i] for i in idxs), tuple(self.types[i] for i in idxs)
        )

    def concat(self, other: "Schema") -> "Schema":
        return Schema(self.names + other.names, self.types + other.types)

    def rename(self, names: tuple[str, ...]) -> "Schema":
        return Schema(tuple(names), self.types)

    @staticmethod
    def of(**cols: SQLType) -> "Schema":
        return Schema(tuple(cols.keys()), tuple(cols.values()))


def zeros_like_type(t: SQLType, capacity: int):
    """A device array of `capacity` zero values in t's canonical representation."""
    if t.family is Family.BYTES:
        return jnp.zeros((capacity, t.width), dtype=jnp.uint8)
    return jnp.zeros((capacity,), dtype=t.dtype)
