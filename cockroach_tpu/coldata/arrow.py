"""Apache Arrow interchange — the colserde analog.

Reference: pkg/col/colserde serializes coldata.Batch as Arrow record
batches for the wire (arrowbatchconverter.go:126 BatchToArrow / :386
ArrowToBatch); Arrow is also the natural host<->accelerator boundary
format here, since every canonical column representation maps 1:1:

| engine                       | arrow                                 |
|------------------------------|---------------------------------------|
| INT16/32/64                  | int16/32/64 (zero-copy both ways)     |
| FLOAT32/64                   | float32/64 (zero-copy)                |
| BOOL                         | bool_                                 |
| DATE (int32 days)            | date32 (zero-copy)                    |
| TIMESTAMP (int64 us)         | timestamp("us") (zero-copy)           |
| INTERVAL (int64 us)          | duration("us") (zero-copy)            |
| DECIMAL (scaled int64)       | decimal128(38, scale) — the scaled    |
|                              | int IS decimal128's unscaled storage  |
| STRING (codes + Dictionary)  | dictionary(int32, utf8)               |
| BYTES (uint8[N,W] + len)     | fixed_size_binary(W)                  |

NULLs ride Arrow validity bitmaps. Fixed-width columns interchange
zero-copy; decimal widening to 128-bit and dictionary re-encoding are the
only copies.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from .batch import Batch, Dictionary
from .types import Family, Schema, SQLType


def type_to_arrow(t: SQLType) -> pa.DataType:
    f = t.family
    if f is Family.BOOL:
        return pa.bool_()
    if f is Family.INT:
        return {16: pa.int16(), 32: pa.int32(), 64: pa.int64()}[t.width]
    if f is Family.FLOAT:
        return {32: pa.float32(), 64: pa.float64()}[t.width]
    if f is Family.DECIMAL:
        return pa.decimal128(38, t.scale)
    if f is Family.DATE:
        return pa.date32()
    if f is Family.TIMESTAMP:
        return pa.timestamp("us")
    if f is Family.INTERVAL:
        return pa.duration("us")
    if f is Family.STRING:
        return pa.dictionary(pa.int32(), pa.utf8())
    if f is Family.BYTES:
        return pa.binary(t.width)
    raise TypeError(f"no arrow mapping for {t}")


def type_from_arrow(at: pa.DataType) -> SQLType:
    from . import types as T

    if pa.types.is_boolean(at):
        return T.BOOL
    if pa.types.is_int16(at):
        return T.INT16
    if pa.types.is_int32(at):
        return T.INT32
    if pa.types.is_int64(at):
        return T.INT64
    if pa.types.is_float32(at):
        return T.FLOAT32
    if pa.types.is_float64(at):
        return T.FLOAT64
    if pa.types.is_decimal(at):
        return T.DECIMAL(precision=at.precision, scale=at.scale)
    if pa.types.is_date32(at):
        return T.DATE
    if pa.types.is_timestamp(at):
        return T.TIMESTAMP
    if pa.types.is_duration(at):
        return T.INTERVAL
    if pa.types.is_dictionary(at) or pa.types.is_string(at):
        return T.STRING
    if pa.types.is_fixed_size_binary(at):
        return T.BYTES(at.byte_width)
    raise TypeError(f"no engine mapping for arrow type {at}")


def _decimal_from_scaled(scaled: np.ndarray, scale: int) -> pa.Array:
    """Scaled int64 -> decimal128: the int64 IS the low half of
    decimal128's little-endian unscaled storage (sign-extended high half)."""
    n = len(scaled)
    buf = np.zeros((n, 2), dtype=np.int64)
    buf[:, 0] = scaled
    buf[:, 1] = np.where(scaled < 0, -1, 0)  # sign extension
    return pa.Array.from_buffers(
        pa.decimal128(38, scale), n,
        [None, pa.py_buffer(buf.tobytes())],
    )


def _decimal_to_scaled(arr: pa.Array, scale: int) -> np.ndarray:
    """decimal128 -> scaled int64 (values must fit 64 bits; TPC-H does —
    the documented divergence from arbitrary-precision apd)."""
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    target = pa.decimal128(38, scale)
    if not arr.type.equals(target):
        arr = arr.cast(target)
    buf = np.frombuffer(arr.buffers()[1], dtype=np.int64)
    off = arr.offset
    view = buf.reshape(-1, 2)[off: off + len(arr)]
    lo, hi = view[:, 0], view[:, 1]
    expect_hi = np.where(lo < 0, -1, 0)
    # null slots' storage is unspecified by the Arrow format: only validate
    # valid rows (foreign writers / slice kernels may leave garbage there)
    valid = (np.ones(len(arr), bool) if arr.null_count == 0
             else ~np.asarray(arr.is_null()))
    if not np.array_equal(hi[valid], expect_hi[valid]):
        raise OverflowError("decimal128 value exceeds the scaled-int64 range")
    return np.where(valid, lo, 0)


# -- column-level conversion ------------------------------------------------


def column_to_arrow(data: np.ndarray, valid: np.ndarray, t: SQLType,
                    dictionary: Dictionary | None = None) -> pa.Array:
    mask = None if valid.all() else ~valid
    if t.family is Family.DECIMAL:
        arr = _decimal_from_scaled(np.asarray(data, np.int64), t.scale)
        if mask is not None:
            # rebuild with a validity bitmap (from_buffers path has none)
            arr = pa.Array.from_buffers(
                arr.type, len(arr),
                [pa.py_buffer(np.packbits(valid, bitorder="little")),
                 arr.buffers()[1]],
            )
        return arr
    if t.family is Family.STRING:
        assert dictionary is not None, "STRING needs its Dictionary"
        codes = pa.array(np.asarray(data, np.int32), mask=mask)
        values = pa.array([str(v) for v in dictionary.values],
                          type=pa.utf8())
        return pa.DictionaryArray.from_arrays(codes, values)
    if t.family is Family.BYTES:
        flat = np.ascontiguousarray(np.asarray(data, np.uint8))
        arr = pa.Array.from_buffers(
            pa.binary(t.width), len(flat),
            [pa.py_buffer(np.packbits(valid, bitorder="little")),
             pa.py_buffer(flat.tobytes())],
        )
        return arr
    return pa.array(np.asarray(data), type=type_to_arrow(t), mask=mask)


def column_from_arrow(arr) -> tuple[np.ndarray, np.ndarray,
                                    Dictionary | None]:
    """-> (canonical data, valid bitmap, Dictionary or None). Fixed-width
    numeric columns come back zero-copy when the source has no nulls."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    t = type_from_arrow(arr.type)
    n = len(arr)
    valid = np.ones(n, dtype=bool) if arr.null_count == 0 else \
        ~np.asarray(arr.is_null())
    if t.family is Family.DECIMAL:
        return _decimal_to_scaled(arr, t.scale), valid, None
    if t.family is Family.STRING:
        if pa.types.is_dictionary(arr.type):
            codes = np.asarray(arr.indices.fill_null(0), dtype=np.int32)
            values = np.asarray(
                [v.as_py() for v in arr.dictionary], dtype=object)
        else:  # plain utf8: dictionary-encode
            enc = arr.dictionary_encode()
            codes = np.asarray(enc.indices.fill_null(0), dtype=np.int32)
            values = np.asarray(
                [v.as_py() for v in enc.dictionary], dtype=object)
        return codes, valid, Dictionary(values)
    if t.family is Family.BYTES:
        w = arr.type.byte_width
        raw = np.frombuffer(arr.buffers()[1], dtype=np.uint8)
        data = raw[arr.offset * w: (arr.offset + n) * w].reshape(n, w)
        return data, valid, None
    if t.family in (Family.DATE, Family.TIMESTAMP, Family.INTERVAL):
        # temporal types: reinterpret as their integer storage (zero-copy
        # view) instead of letting pyarrow build datetime64 objects
        arr = arr.view(pa.int32() if t.family is Family.DATE else pa.int64())
    if arr.null_count == 0:
        data = arr.to_numpy(zero_copy_only=t.family is not Family.BOOL)
    else:
        data = np.asarray(arr.fill_null(0))
    return np.asarray(data).astype(t.dtype, copy=False), valid, None


# -- table / batch level ----------------------------------------------------


def table_to_arrow(table) -> pa.Table:
    """catalog.Table -> pyarrow Table (host columns, no device touch)."""
    arrays, fields = [], []
    for name, t in zip(table.schema.names, table.schema.types):
        data = np.asarray(table.columns[name])
        valid = table.valids.get(name)
        if valid is None:
            valid = np.ones(len(data), dtype=bool)
        arrays.append(column_to_arrow(
            data, np.asarray(valid, bool), t,
            table.dictionaries.get(name)))
        fields.append(pa.field(name, arrays[-1].type))
    return pa.Table.from_arrays(arrays, schema=pa.schema(fields))


def table_from_arrow(name: str, at: pa.Table):
    """pyarrow Table -> catalog.Table (the Arrow ingest path the bench and
    any external loader ride)."""
    from ..catalog import Table

    names = tuple(at.column_names)
    types, cols, valids, dicts = [], {}, {}, {}
    for cname in names:
        data, valid, d = column_from_arrow(at.column(cname))
        types.append(type_from_arrow(at.schema.field(cname).type))
        cols[cname] = data
        if not valid.all():
            valids[cname] = valid
        if d is not None:
            dicts[cname] = d
    return Table(
        name=name,
        schema=Schema(names, tuple(types)),
        columns=cols,
        valids=valids,
        dictionaries=dicts,
    )


def batch_to_arrow(batch: Batch, schema: Schema,
                   dictionaries: dict[int, Dictionary] | None = None
                   ) -> pa.RecordBatch:
    """Device Batch -> Arrow record batch of the LIVE rows (the Outbox
    serialization direction, outbox.go:280)."""
    dictionaries = dictionaries or {}
    mask = np.asarray(batch.mask)
    arrays, fields = [], []
    for i, (name, t) in enumerate(zip(schema.names, schema.types)):
        data = np.asarray(batch.cols[i].data)[mask]
        valid = np.asarray(batch.cols[i].valid)[mask]
        arrays.append(column_to_arrow(data, valid, t, dictionaries.get(i)))
        fields.append(pa.field(name, arrays[-1].type))
    return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))


def batch_from_arrow(rb) -> tuple[Batch, Schema, dict[int, Dictionary]]:
    """Arrow record batch -> device Batch (the Inbox direction)."""
    from .batch import from_host

    names = tuple(rb.schema.names)
    types, arrays, valids, dicts = [], {}, {}, {}
    for i, cname in enumerate(names):
        data, valid, d = column_from_arrow(rb.column(i))
        types.append(type_from_arrow(rb.schema.field(cname).type))
        arrays[cname] = data
        if not valid.all():
            valids[cname] = valid
        if d is not None:
            dicts[i] = d
    schema = Schema(names, tuple(types))
    return from_host(schema, arrays, valids=valids), schema, dicts
