"""Columnar batch format — the coldata.Batch analog, TPU-first.

Reference semantics (pkg/col/coldata/batch.go:24, vec.go:43, nulls.go:35):
a Batch is a vector of typed columns + a selection vector + a length, with a
default capacity of 1024 and max 4096. The TPU redesign keeps the same logical
model but makes every shape static:

- capacity is a *static* tile size (default 4096 == coldata.MaxBatchSize,
  pkg/col/coldata/batch.go:102); jit specializes per capacity.
- the selection vector becomes a boolean liveness ``mask`` over the tile;
  logical length is ``mask.sum()`` (a traced scalar, never a Python int).
- each column carries an Arrow-convention ``valid`` bitmap (True = non-NULL),
  like Vec.Nulls but inverted to match Arrow (pkg/col/colserde ships Arrow on
  the wire already — arrowbatchconverter.go:126).

A Batch is a registered pytree whose leaves are device arrays, so it flows
through jit / shard_map / collectives directly. All schema information
(types, dictionaries) is static plan-side metadata and never enters the pytree.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .types import Family, Schema, zeros_like_type

DEFAULT_CAPACITY = 4096  # coldata.MaxBatchSize (pkg/col/coldata/batch.go:102)


def pack_be_words(data: jax.Array) -> jax.Array:
    """[N, W] uint8 -> [N, ceil(W/8)] big-endian uint64 word lanes.

    Tuple order over the word lanes equals bytewise lexicographic order of
    the rows; widths not a multiple of 8 are zero-padded on the right
    (order-preserving for the zero-padded fixed-width representation).
    The single canonical byte->word packing — storage key encoding and
    BYTES sort keys both ride this."""
    n, w = data.shape
    if w % 8:
        data = jnp.pad(data, ((0, 0), (0, 8 - w % 8)))
        w = data.shape[1]
    groups = data.reshape(n, w // 8, 8).astype(jnp.uint64)
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint64) * jnp.uint64(8)
    return jnp.sum(groups << shifts, axis=-1, dtype=jnp.uint64)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class Column:
    """One typed column over a static-capacity tile.

    data  : [cap] canonical-dtype array ([cap, W] uint8 for BYTES)
    valid : [cap] bool, True = non-NULL (Arrow convention)
    """

    data: jax.Array
    valid: jax.Array

    @property
    def capacity(self) -> int:
        return self.data.shape[0]


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class Batch:
    """cols: one Column per schema field; mask: [cap] bool row liveness."""

    cols: tuple[Column, ...]
    mask: jax.Array

    @property
    def capacity(self) -> int:
        return self.mask.shape[0]

    def length(self) -> jax.Array:
        """Logical row count — a traced int32 scalar."""
        return jnp.sum(self.mask, dtype=jnp.int32)

    def col(self, i: int) -> Column:
        return self.cols[i]

    def with_cols(self, cols: tuple[Column, ...]) -> "Batch":
        return Batch(cols=cols, mask=self.mask)

    def with_mask(self, mask: jax.Array) -> "Batch":
        return Batch(cols=self.cols, mask=mask)

    def select(self, idxs: tuple[int, ...]) -> "Batch":
        return Batch(cols=tuple(self.cols[i] for i in idxs), mask=self.mask)


class Dictionary:
    """Host-side string dictionary for a STRING column (codes on device).

    Cross-table string operations are pre-bridged on the host and become
    gathers on device:
      - ``hashes``: code -> 64-bit hash of the underlying bytes, so string
        group-by/join keys hash identically across tables with different
        dictionaries.
      - ``ranks``: code -> rank in sorted byte order, so ORDER BY / range
        predicates on strings become integer comparisons.
    """

    def __init__(self, values: np.ndarray):
        self.values = np.asarray(values, dtype=object)
        order = np.argsort(self.values.astype(str))
        ranks = np.empty(len(self.values), dtype=np.int32)
        ranks[order] = np.arange(len(self.values), dtype=np.int32)
        self.ranks = ranks
        self.hashes = _fnv64_batch(self.values)

    def __len__(self) -> int:
        return len(self.values)

    def reset(self, values: np.ndarray) -> None:
        """Rebuild this dictionary IN PLACE. Operators whose string output
        values exist only at runtime (string_agg) pre-create an empty
        Dictionary at plan-build time — so parent operators hold the
        reference — and fill it here when the values materialize."""
        self.__init__(values)

    def code_of(self, value: str) -> int:
        """Code for a literal value, or -1 if absent (predicate is then false)."""
        hits = np.nonzero(self.values.astype(str) == value)[0]
        return int(hits[0]) if len(hits) else -1

    def decode(self, codes: np.ndarray) -> np.ndarray:
        out = np.empty(codes.shape, dtype=object)
        in_range = (codes >= 0) & (codes < len(self.values))
        out[in_range] = self.values[codes[in_range]]
        out[~in_range] = None
        return out


def _fnv64_batch(values: np.ndarray) -> np.ndarray:
    """FNV-1a 64-bit over utf-8 bytes for an array of strings, vectorized:
    one masked pass per byte position over the whole dictionary.
    Deterministic across processes (unlike Python's hash())."""
    encoded = [str(v).encode("utf-8") for v in values]
    n = len(encoded)
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    lens = np.array([len(b) for b in encoded], dtype=np.int64)
    maxlen = max(1, int(lens.max()))
    # Sort by length descending so byte-position i only touches a prefix:
    # total work is O(sum of lengths), immune to one long outlier string.
    order = np.argsort(-lens, kind="stable")
    flat = np.frombuffer(b"".join(encoded[j] for j in order), dtype=np.uint8)
    sorted_lens = lens[order]
    starts = np.concatenate([[0], np.cumsum(sorted_lens[:-1])])
    # rows with len > i form the prefix [0, counts[i])
    asc = sorted_lens[::-1]
    counts = n - np.searchsorted(asc, np.arange(maxlen), side="right")
    h = np.full(n, 0xCBF29CE484222325, dtype=np.uint64)
    prime = np.uint64(0x100000001B3)
    with np.errstate(over="ignore"):
        for i in range(maxlen):
            c = int(counts[i])
            if c == 0:
                break
            h[:c] = (h[:c] ^ flat[starts[:c] + i]) * prime
    out = np.empty_like(h)
    out[order] = h
    return out


def empty_batch(schema: Schema, capacity: int = DEFAULT_CAPACITY) -> Batch:
    cols = tuple(
        Column(
            data=zeros_like_type(t, capacity),
            valid=jnp.zeros((capacity,), dtype=jnp.bool_),
        )
        for t in schema.types
    )
    return Batch(cols=cols, mask=jnp.zeros((capacity,), dtype=jnp.bool_))


def from_host(
    schema: Schema,
    arrays: dict[str, np.ndarray],
    valids: dict[str, np.ndarray] | None = None,
    capacity: int | None = None,
) -> Batch:
    """Build a Batch from host numpy columns, padding to capacity.

    STRING columns must already be dictionary codes (int32); encoding raw
    string arrays happens at table-load time (see bench/tpch.py).
    """
    valids = valids or {}
    n = len(next(iter(arrays.values())))
    cap = capacity if capacity is not None else max(DEFAULT_CAPACITY, n)
    cols = []
    for name, t in zip(schema.names, schema.types):
        a = np.asarray(arrays[name])
        assert len(a) == n, f"column {name} length {len(a)} != {n}"
        if t.family is Family.BYTES:
            buf = np.zeros((cap, t.width), dtype=np.uint8)
            buf[:n] = a
            data = jnp.asarray(buf)
        else:
            buf = np.zeros((cap,), dtype=t.dtype)
            buf[:n] = a.astype(t.dtype)
            data = jnp.asarray(buf)
        v = np.zeros((cap,), dtype=np.bool_)
        v[:n] = valids.get(name, np.ones(n, dtype=np.bool_))
        cols.append(Column(data=data, valid=jnp.asarray(v)))
    mask = np.zeros((cap,), dtype=np.bool_)
    mask[:n] = True
    return Batch(cols=tuple(cols), mask=jnp.asarray(mask))


def to_host(
    batch: Batch, schema: Schema, dictionaries: dict[int, Dictionary] | None = None
) -> dict[str, np.ndarray]:
    """Materialize live rows to host numpy (the Materializer analog,
    pkg/sql/colexec/materializer.go:30). Decodes STRING via dictionaries
    (column index -> Dictionary); NULLs become None in object arrays."""
    dictionaries = dictionaries or {}
    mask = np.asarray(batch.mask)
    out: dict[str, np.ndarray] = {}
    for i, (name, t) in enumerate(zip(schema.names, schema.types)):
        data = np.asarray(batch.cols[i].data)[mask]
        valid = np.asarray(batch.cols[i].valid)[mask]
        if t.family is Family.STRING and i in dictionaries:
            vals = dictionaries[i].decode(data)
            vals[~valid] = None
            out[name] = vals
        elif t.family is Family.DECIMAL:
            res = data.astype(np.float64) / (10.0**t.scale)
            obj = res.astype(object)
            obj[~valid] = None
            out[name] = obj if not valid.all() else res
        else:
            if valid.all():
                out[name] = data
            else:
                obj = data.astype(object)
                obj[~valid] = None
                out[name] = obj
    return out


@functools.partial(jax.jit, static_argnames=("capacity",))  # crlint: allow-raw-jit(shared helper: call sites count via dispatch.note)
def compact(batch: Batch, capacity: int | None = None) -> Batch:
    """Pack live rows to the front of a (possibly smaller) tile.

    The reference compacts via selection vectors; here each column GATHERS
    its live rows through one shared nonzero index — O(cap_in) once for the
    index plus O(cap_out) per column, so compacting a sparse 1M-row tile to
    1k costs index-scan + a few tiny gathers, not a full-width scatter per
    column (the prior design, measured as the dominant cost of selective
    spool merges)."""
    cap_out = capacity or batch.capacity
    cap_in = batch.capacity
    mask = batch.mask
    n = jnp.sum(mask, dtype=jnp.int32)
    size = min(cap_in, cap_out)
    (idx,) = jnp.nonzero(mask, size=size, fill_value=cap_in)

    def move(col: Column) -> Column:
        data = jnp.take(col.data, idx, axis=0, mode="fill", fill_value=0)
        valid = jnp.take(col.valid, idx, mode="fill", fill_value=False)
        if cap_out > size:
            pad = cap_out - size
            if data.ndim == 2:
                data = jnp.concatenate(
                    [data, jnp.zeros((pad, data.shape[1]), data.dtype)]
                )
            else:
                data = jnp.concatenate([data, jnp.zeros((pad,), data.dtype)])
            valid = jnp.concatenate([valid, jnp.zeros((pad,), jnp.bool_)])
        return Column(data=data, valid=valid)

    new_mask = jnp.arange(cap_out, dtype=jnp.int32) < n
    return Batch(cols=tuple(move(c) for c in batch.cols), mask=new_mask)


def concat(batches: list[Batch], capacity: int) -> Batch:
    """Concatenate batches' LIVE rows into one compacted tile of `capacity`
    (must fit; caller checks). Each source batch gathers its live rows once
    (per-batch nonzero index) and scatters them at its running offset —
    never materializing the full-capacity concatenation the previous design
    paid for (O(sum cap_in) per column)."""
    if len(batches) == 1:
        return compact(batches[0], capacity)
    ncols = len(batches[0].cols)
    lives = [jnp.sum(b.mask, dtype=jnp.int32) for b in batches]
    offs = []
    acc = jnp.int32(0)
    for lv in lives:
        offs.append(acc)
        acc = acc + lv
    total = acc
    idxs = []
    for b in batches:
        size = min(b.capacity, capacity)
        (idx,) = jnp.nonzero(b.mask, size=size, fill_value=b.capacity)
        idxs.append(idx)

    cols = []
    for i in range(ncols):
        first = batches[0].cols[i].data
        if first.ndim == 2:
            data = jnp.zeros((capacity, first.shape[1]), first.dtype)
        else:
            data = jnp.zeros((capacity,), first.dtype)
        valid = jnp.zeros((capacity,), jnp.bool_)
        for b, idx, off, lv in zip(batches, idxs, offs, lives):
            rows = jnp.take(b.cols[i].data, idx, axis=0, mode="fill",
                            fill_value=0)
            vrows = jnp.take(b.cols[i].valid, idx, mode="fill",
                             fill_value=False)
            pos = jnp.arange(idx.shape[0], dtype=jnp.int32)
            dest = jnp.where(pos < lv, off + pos, capacity)
            data = data.at[dest].set(rows, mode="drop")
            valid = valid.at[dest].set(vrows, mode="drop")
        cols.append(Column(data=data, valid=valid))
    mask = jnp.arange(capacity, dtype=jnp.int32) < total
    return Batch(cols=tuple(cols), mask=mask)
