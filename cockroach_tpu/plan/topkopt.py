"""Top-k pushdown — rewrite Limit(Sort) into Limit(TopK).

Reference: the optimizer's GenerateLimitedScans / ordering-aware limit
rules let a LIMIT under an ORDER BY plan as a top-k sorter
(pkg/sql/colexec/sorttopk.go keeps a K-row heap) instead of a full sort
followed by truncation.

Here the rewrite swaps the Sort under a Limit for a TopK node carrying
k = limit + offset; flow/operators.TopKOp folds a per-tile stable
k-selection over the input so the query neither spools nor fully sorts
it. The Limit stays on top and applies the OFFSET over the sorted top-k
tile — bit-identical to the Sort + Limit plan it replaces (TopK's output
is the stable sort order's first k rows, exactly the rows Limit keeps).

Gate: k must stay under ``sql.opt.topk.max_k`` — a huge LIMIT makes the
O(k) accumulator no better than the sort spool it replaces.
"""

from __future__ import annotations

import dataclasses

from ..utils import settings
from . import spec as S

TOPK_ENABLED = settings.register_bool(
    "sql.opt.topk.enabled", True,
    "plan ORDER BY ... LIMIT k as a device top-k selection instead of a "
    "full sort + truncate", metamorphic=True,
)
TOPK_MAX_K = settings.register_int(
    "sql.opt.topk.max_k", 65536,
    "largest limit+offset planned as a top-k selection; beyond this the "
    "O(k) accumulator loses to the sort spool", lo=1,
)


def push_topk(plan: S.PlanNode) -> S.PlanNode:
    """Recursively rewrite eligible Limit(Sort) subtrees."""
    if not settings.get("sql.opt.topk.enabled"):
        return plan
    return _rewrite(plan)


def _rewrite(plan):
    if (isinstance(plan, S.Limit)
            and isinstance(plan.input, S.Sort)
            and plan.limit + plan.offset <= settings.get(
                "sql.opt.topk.max_k")):
        srt = plan.input
        return S.Limit(
            S.TopK(_rewrite(srt.input), srt.keys,
                   plan.limit + plan.offset),
            plan.limit, plan.offset,
        )
    # generic recursion over PlanNode dataclass fields
    if not dataclasses.is_dataclass(plan):
        return plan
    changes = {}
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, S.PlanNode):
            nv = _rewrite(v)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and v and isinstance(v[0], S.PlanNode):
            nv = tuple(_rewrite(x) for x in v)
            if any(a is not b for a, b in zip(nv, v)):
                changes[f.name] = nv
    return dataclasses.replace(plan, **changes) if changes else plan
