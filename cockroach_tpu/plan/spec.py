"""Physical plan IR — the execinfrapb.ProcessorSpec analog.

Reference: pkg/sql/execinfrapb/processors*.proto defines ProcessorSpec (core +
post-processing) wired by stream edges into a FlowSpec; colbuilder's
NewColOperator (pkg/sql/colexec/colbuilder/execplan.go:736) maps each spec to
an operator. Here the IR is a tree of frozen dataclasses; plan/builder.py maps
it to flow operators. Distribution nodes (Exchange) mirror OutputRouterSpec /
InputSyncSpec (execinfrapb/data.proto:111,149).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..coldata.types import Schema
from ..ops.aggregation import AggSpec
from ..ops.expr import Expr
from ..ops.join import JoinSpec
from ..ops.sort import SortKey


class PlanNode:
    pass


@dataclass(frozen=True)
class TableScan(PlanNode):
    table: str
    columns: tuple[str, ...] | None = None  # None = all
    # cross-host partitioned read: this scan covers row range
    # [i*rows//n, (i+1)*rows//n) of the table — the TableReader span
    # partitioning a SetupFlow ships to each node (PartitionSpans role)
    shard: tuple[int, int] | None = None  # (shard index, shard count)


@dataclass(frozen=True)
class IndexScan(PlanNode):
    """Index-backed read: scan the secondary index keyspace for values in
    [lo, hi], then fetch the matched primary rows through the Streamer
    (joinreader/kvstreamer role). Output capacity is sized by the match
    count, not the table."""

    table: str
    index: str  # IndexDesc.name
    lo: int | None  # inclusive value bounds in the indexed column's
    hi: int | None  # int-encoded domain (None = unbounded)
    columns: tuple[str, ...] | None = None


@dataclass(frozen=True)
class HashBucket(PlanNode):
    """Keep only rows whose key-hash bucket equals `part` of `n_parts` —
    one outgoing stream of a HashRouter (colflow/routers.go:420): a
    producer plans one HashBucket per consumer over the same input."""

    input: PlanNode
    keys: tuple[int, ...]
    n_parts: int
    part: int


@dataclass(frozen=True)
class RemoteStream(PlanNode):
    """Leaf that attaches to a peer host's registered flow stream and
    yields its batches — the StreamEndpointSpec REMOTE type
    (execinfrapb/data.proto) + Inbox (colrpc/inbox.go:48)."""

    addr: tuple  # (host, port)
    flow_id: str
    stream_id: int
    schema: Schema


@dataclass(frozen=True)
class StreamUnion(PlanNode):
    """Unordered fan-in of several inputs with one puller thread per
    input (ParallelUnorderedSynchronizer role) — used for inbound remote
    streams so hosts stream concurrently."""

    inputs: tuple[PlanNode, ...]


@dataclass(frozen=True)
class Filter(PlanNode):
    input: PlanNode
    predicate: Expr


@dataclass(frozen=True)
class Project(PlanNode):
    input: PlanNode
    exprs: tuple[Expr, ...]
    names: tuple[str, ...]
    # (output index, Dictionary) pairs for STRING outputs whose dictionary
    # the expr machinery cannot infer (e.g. host-side string transforms)
    dict_overrides: tuple = ()


@dataclass(frozen=True)
class Aggregate(PlanNode):
    input: PlanNode
    group_cols: tuple[int, ...]
    aggs: tuple[AggSpec, ...]
    # "complete" | "partial" | "final" — partial/final mirror CRDB's
    # local/final aggregation stages around a shuffle
    mode: str = "complete"
    # planner hint: every group key is a dense code of known cardinality
    # (dictionary size); enables the sort-free dense-state aggregation path
    key_sizes: tuple[int, ...] | None = None
    # for mode="final": the schema the original aggs/group_cols were written
    # against (the partial stage's input), needed to recompute the shared
    # partial-state layout on the far side of an Exchange
    base_schema: Schema | None = None


@dataclass(frozen=True)
class HashJoin(PlanNode):
    probe: PlanNode
    build: PlanNode
    probe_keys: tuple[int, ...]
    build_keys: tuple[int, ...]
    spec: JoinSpec = JoinSpec()


@dataclass(frozen=True)
class Sort(PlanNode):
    input: PlanNode
    keys: tuple[SortKey, ...]


@dataclass(frozen=True)
class Limit(PlanNode):
    input: PlanNode
    limit: int
    offset: int = 0


@dataclass(frozen=True)
class TopK(PlanNode):
    """ORDER BY ... LIMIT k as a device k-selection (sorttopk.go analog):
    fold a per-tile stable top-k over the input instead of spooling and
    fully sorting it. Output is the sorted first-k rows — bit-identical
    to Sort + Limit, which plan/topkopt.py rewrites into this node."""

    input: PlanNode
    keys: tuple[SortKey, ...]
    k: int


@dataclass(frozen=True)
class Distinct(PlanNode):
    input: PlanNode
    cols: tuple[int, ...] | None = None  # None = all columns


@dataclass(frozen=True)
class Exchange(PlanNode):
    """Repartition rows across the mesh by key hash — the HashRouter +
    Outbox/Inbox shuffle (colflow/routers.go:420, colrpc) as an ICI
    all-to-all. No-op on a single device."""

    input: PlanNode
    keys: tuple[int, ...]


@dataclass(frozen=True)
class Broadcast(PlanNode):
    """Replicate the input on every device (all_gather over the mesh) —
    the broadcast-join placement the reference's planner picks for small
    build sides (PhysicalPlan mergeResultStreams to every node)."""

    input: PlanNode


@dataclass(frozen=True)
class Gather(PlanNode):
    """Collect all partitions onto every device (all_gather) — the
    final-stage fan-in to the gateway node (DistSQLReceiver role) for
    globally-ordered operators (Sort/Limit at the plan root)."""

    input: PlanNode


@dataclass(frozen=True)
class ScalarAggregate(PlanNode):
    """Aggregation without GROUP BY: always exactly one output row."""

    input: PlanNode
    aggs: tuple[AggSpec, ...]
    mode: str = "complete"


@dataclass(frozen=True)
class Window(PlanNode):
    """Window functions over (partition, order) — colexecwindow analog.
    specs are ops.window.WindowSpec; output appends one column per spec."""

    input: PlanNode
    partition_cols: tuple[int, ...]
    order_keys: tuple[SortKey, ...]
    specs: tuple = ()


@dataclass(frozen=True)
class Union(PlanNode):
    """UNION ALL: concatenation of same-schema inputs (execinfrapb's
    unordered synchronizer fan-in role for plan-level unions)."""

    inputs: tuple[PlanNode, ...]


@dataclass(frozen=True)
class MergeJoin(PlanNode):
    """Merge join over order-preserving key lanes (mergejoiner.go analog).
    probe_key/build_key: one column index or a tuple of them (composite
    ordered keys, compared lexicographically)."""

    probe: PlanNode
    build: PlanNode
    probe_key: int | tuple[int, ...]
    build_key: int | tuple[int, ...]
    spec: JoinSpec = JoinSpec()
