"""Index selection — rewrite Filter(TableScan) into IndexScan.

Reference: the optimizer's GenerateIndexScans / GenerateConstrainedScans
exploration rules turn filtered full scans into constrained index scans
when a filter conjunct constrains an indexed column
(pkg/sql/opt/xform/select_funcs.go); the execbuilder then plans an index
join to fetch unindexed columns (pkg/sql/rowexec/joinreader.go).

Reduction: single-column indexes, conjuncts of the form
``col <cmp> literal`` (and BETWEEN, which the binder lowers to two
conjuncts — possibly as separate stacked Filter nodes, which the rewrite
walks as one chain). The whole original predicate stays as a residual
filter over the fetched rows — re-applying the bound conjunct is one fused mask op,
and it keeps boundary/NULL semantics independent of the span math.

Selectivity gate: the scan flips to the index only when the constrained
value range is estimated under ``sql.opt.index_scan_max_frac`` of the
column's (lo, hi) span from table statistics — a full-table IndexScan
would be strictly worse than the resident columnar scan."""

from __future__ import annotations

from ..ops import expr as ex
from ..utils import settings
from . import spec as S

INDEX_SCAN_ENABLED = settings.register_bool(
    "sql.opt.index_scan.enabled", True,
    "plan index-backed reads for selective filters on indexed columns",
)
INDEX_SCAN_MAX_FRAC = settings.register_float(
    "sql.opt.index_scan.max_frac", 0.25,
    "estimated selected fraction above which a filtered full scan beats "
    "an index scan + fetch", lo=0.0, hi=1.0,
)


def _conjuncts(e: ex.Expr) -> list[ex.Expr]:
    if isinstance(e, ex.BoolOp) and e.op == "and":
        out = []
        for part in e.args:
            out.extend(_conjuncts(part))
        return out
    return [e]


def _col_bound(c: ex.Expr) -> tuple[int, str, int] | None:
    """(scan column index, cmp op, literal) for `col <cmp> int-literal`
    conjuncts, normalized so the column is on the left."""
    if not isinstance(c, ex.Cmp) or c.op == "ne":
        return None
    flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
    left, right, op = c.left, c.right, c.op
    if isinstance(right, ex.ColRef) and isinstance(left, ex.Const):
        left, right, op = right, left, flip[op]
    if not (isinstance(left, ex.ColRef) and isinstance(right, ex.Const)):
        return None
    v = right.value
    if isinstance(v, bool) or not (
            isinstance(v, int) or hasattr(v, "__index__")):
        return None
    return left.idx, op, int(v)


def _bounds_for(conjs, names, indexed: dict[str, object]):
    """Tightest (index, lo, hi) over the conjuncts, or None."""
    best: dict[str, list] = {}
    for c in conjs:
        m = _col_bound(c)
        if m is None:
            continue
        i, op, v = m
        if i >= len(names) or names[i] not in indexed:
            continue
        lo, hi = best.setdefault(names[i], [None, None])
        if op == "eq":
            nlo, nhi = v, v
        elif op == "lt":
            nlo, nhi = None, v - 1
        elif op == "le":
            nlo, nhi = None, v
        elif op == "gt":
            nlo, nhi = v + 1, None
        else:  # ge
            nlo, nhi = v, None
        b = best[names[i]]
        b[0] = nlo if b[0] is None else (b[0] if nlo is None else max(b[0], nlo))
        b[1] = nhi if b[1] is None else (b[1] if nhi is None else min(b[1], nhi))
    for col, (lo, hi) in best.items():
        if lo is not None or hi is not None:
            return indexed[col], lo, hi
    return None


def _selective_enough(table, ix, lo, hi) -> bool:
    if lo is not None and hi is not None and hi < lo:
        return True  # empty span: the index scan is free
    stats = table.col_stats()
    b = stats.get(ix.col)
    if b is None:
        # no statistics: only a two-sided constraint is trusted
        return lo is not None and hi is not None
    clo, chi = int(b[0]), int(b[1])
    width = max(1, chi - clo + 1)
    elo = clo if lo is None else max(clo, lo)
    ehi = chi if hi is None else min(chi, hi)
    frac = max(0, ehi - elo + 1) / width
    return frac <= settings.get("sql.opt.index_scan.max_frac")


def use_indexes(plan: S.PlanNode, catalog) -> S.PlanNode:
    """Recursively rewrite eligible Filter(TableScan) subtrees."""
    if not settings.get("sql.opt.index_scan.enabled"):
        return plan
    return _rewrite(plan, catalog)


def _rewrite(plan, catalog):
    from ..kv.table import KVTable

    if isinstance(plan, S.Filter):
        # The binder pushes WHERE conjuncts down one at a time, so a
        # two-sided bound (k >= 30 AND k <= 36) arrives as STACKED Filter
        # nodes over the scan. Walk the whole chain and size the span over
        # the union of every level's conjuncts; the residual filters are
        # re-applied unchanged over the IndexScan.
        preds = [plan.predicate]
        inner = plan.input
        while isinstance(inner, S.Filter):
            preds.append(inner.predicate)
            inner = inner.input
        if isinstance(inner, S.TableScan):
            scan = inner
            table = catalog.tables.get(scan.table)
            if (isinstance(table, KVTable) and table.indexes
                    and scan.shard is None):
                names = scan.columns or table.schema.names
                indexed = {ix.col: ix for ix in table.indexes}
                conjs = [c for p in preds for c in _conjuncts(p)]
                got = _bounds_for(conjs, names, indexed)
                if got is not None:
                    ix, lo, hi = got
                    if _selective_enough(table, ix, lo, hi):
                        node: S.PlanNode = S.IndexScan(
                            scan.table, ix.name, lo, hi, scan.columns)
                        for p in reversed(preds):
                            node = S.Filter(node, p)
                        return node
    # generic recursion over PlanNode dataclass fields
    import dataclasses

    if not dataclasses.is_dataclass(plan):
        return plan
    changes = {}
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, S.PlanNode):
            nv = _rewrite(v, catalog)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and v and isinstance(v[0], S.PlanNode):
            nv = tuple(_rewrite(x, catalog) for x in v)
            if any(a is not b for a, b in zip(nv, v)):
                changes[f.name] = nv
    return dataclasses.replace(plan, **changes) if changes else plan
