"""Distribution planner — rewrite a single-node plan for the mesh.

Reference: pkg/sql/distsql_physical_planner.go decides, per plan node, how to
spread work across nodes: partitioned TableReaders per leaseholder
(PartitionSpans), local/final aggregation staged around a hash-router
shuffle, both-sides-hash-routed joins (or broadcast of a small side), and a
final merge onto the gateway. Here the same decisions become explicit plan
nodes — Exchange (ICI all-to-all), Broadcast / Gather (all_gather) — that
parallel/planner.py lowers into ONE SPMD program over the mesh.

Every rewrite rule returns (node, replicated): `replicated` tracks whether
the subtree's output is identical on every device (post-Gather/Broadcast) or
row-sharded. Replicated inputs need no further distribution machinery.
"""

from __future__ import annotations

from ..catalog import Catalog
from . import spec as S

# build sides at or below this row estimate replicate to every device
# instead of shuffling both join sides (the reference's stats-driven
# broadcast-join choice, made here from catalog cardinalities)
BROADCAST_ROWS_DEFAULT = 1 << 17


def estimated_rows(plan: S.PlanNode, catalog: Catalog) -> int:
    """Crude upper-bound cardinality from catalog tables (the stats stand-in
    for the reference's cost model)."""
    if isinstance(plan, S.TableScan):
        return catalog.get(plan.table).estimated_rows()
    if isinstance(plan, (S.HashJoin, S.MergeJoin)):
        return max(estimated_rows(plan.probe, catalog),
                   estimated_rows(plan.build, catalog))
    if isinstance(plan, S.Limit):
        return min(plan.limit + plan.offset,
                   estimated_rows(plan.input, catalog))
    if isinstance(plan, S.TopK):
        return min(plan.k, estimated_rows(plan.input, catalog))
    if isinstance(plan, S.Union):
        return sum(estimated_rows(k, catalog) for k in plan.inputs)
    if hasattr(plan, "input"):
        return estimated_rows(plan.input, catalog)
    return 1 << 30


def distribute(
    plan: S.PlanNode,
    catalog: Catalog,
    broadcast_rows: int | None = None,
) -> S.PlanNode:
    """Rewrite `plan` with explicit distribution stages for SPMD lowering.
    broadcast_rows=None means BROADCAST_ROWS_DEFAULT — resolved HERE, the
    one source of truth for every caller."""
    if broadcast_rows is None:
        broadcast_rows = BROADCAST_ROWS_DEFAULT
    node, _ = _rewrite(plan, catalog, broadcast_rows)
    return node


def _gather(node: S.PlanNode, replicated: bool) -> S.PlanNode:
    return node if replicated else S.Gather(node)


def _broadcast(node: S.PlanNode, replicated: bool) -> S.PlanNode:
    return node if replicated else S.Broadcast(node)


def _rewrite(plan, catalog, broadcast_rows):
    if isinstance(plan, S.TableScan):
        return plan, False

    if isinstance(plan, (S.Filter, S.Project)):
        child, rep = _rewrite(plan.input, catalog, broadcast_rows)
        return type(plan)(child, *_rest_fields(plan)), rep

    if isinstance(plan, S.Aggregate):
        # (string_agg never reaches here: DistributedQuery._needs_local
        # routes such plans to local operator execution before distribute)
        child, rep = _rewrite(plan.input, catalog, broadcast_rows)
        if plan.key_sizes is not None:
            # dense-state path: positionally-aligned [G] states merge with
            # psum/pmin/pmax collectives — no shuffle, replicated output
            return S.Aggregate(child, plan.group_cols, plan.aggs,
                               key_sizes=plan.key_sizes), True
        if rep:
            return S.Aggregate(child, plan.group_cols, plan.aggs), True
        # local/final staging around a hash shuffle on the group keys
        # (distsql_physical_planner.go aggregation planning)
        partial = S.Aggregate(child, plan.group_cols, plan.aggs,
                              mode="partial")
        k = len(plan.group_cols)
        exch = S.Exchange(partial, tuple(range(k)))
        final = S.Aggregate(exch, plan.group_cols, plan.aggs, mode="final",
                            base_schema=schema_of(plan.input, catalog))
        return final, False

    if isinstance(plan, S.ScalarAggregate):
        child, rep = _rewrite(plan.input, catalog, broadcast_rows)
        # lowering merges partial scalar states with psum/pmin/pmax
        return S.ScalarAggregate(child, plan.aggs), True

    if isinstance(plan, S.Distinct):
        child, rep = _rewrite(plan.input, catalog, broadcast_rows)
        if rep:
            return S.Distinct(child, plan.cols), True
        # local distinct -> shuffle on the distinct cols -> local distinct
        local = S.Distinct(child, plan.cols)
        k = len(plan.cols) if plan.cols else _schema_len(plan.input, catalog)
        exch = S.Exchange(local, tuple(range(k)))
        return S.Distinct(exch, None), False

    if isinstance(plan, S.HashJoin):
        probe, prep = _rewrite(plan.probe, catalog, broadcast_rows)
        build, brep = _rewrite(plan.build, catalog, broadcast_rows)
        if prep:  # replicated probe: replicate build too, join locally
            return S.HashJoin(probe, _broadcast(build, brep), plan.probe_keys,
                              plan.build_keys, plan.spec), True
        if brep or estimated_rows(plan.build, catalog) <= broadcast_rows:
            return S.HashJoin(probe, _broadcast(build, brep), plan.probe_keys,
                              plan.build_keys, plan.spec), False
        # both-sides hash-routed shuffle join (colflow router placement)
        return S.HashJoin(
            S.Exchange(probe, plan.probe_keys),
            S.Exchange(build, plan.build_keys),
            plan.probe_keys, plan.build_keys, plan.spec,
        ), False

    if isinstance(plan, S.MergeJoin):
        probe, prep = _rewrite(plan.probe, catalog, broadcast_rows)
        build, brep = _rewrite(plan.build, catalog, broadcast_rows)
        # merge join keeps probe-side order: broadcast the build side
        return (S.MergeJoin(probe, _broadcast(build, brep), plan.probe_key,
                            plan.build_key, plan.spec), prep)

    if isinstance(plan, S.Limit) and isinstance(plan.input, S.TopK):
        # distributed top-k with the device k-selection: each device folds
        # its shard down to k rows, the gather moves D*k rows, and one
        # final replicated TopK + Limit merges them (sorttopk.go +
        # OrderedSynchronizer roles)
        tk = plan.input
        child, rep = _rewrite(tk.input, catalog, broadcast_rows)
        if rep:
            return S.Limit(S.TopK(child, tk.keys, tk.k), plan.limit,
                           plan.offset), True
        local = S.TopK(child, tk.keys, tk.k)
        merged = S.TopK(S.Gather(local), tk.keys, tk.k)
        return S.Limit(merged, plan.limit, plan.offset), True

    if isinstance(plan, S.TopK):
        child, rep = _rewrite(plan.input, catalog, broadcast_rows)
        if rep:
            return S.TopK(child, plan.keys, plan.k), True
        local = S.TopK(child, plan.keys, plan.k)
        return S.TopK(S.Gather(local), plan.keys, plan.k), True

    if isinstance(plan, S.Limit) and isinstance(plan.input, S.Sort):
        # distributed top-k (sorttopk.go + OrderedSynchronizer roles): each
        # device sorts ITS shard and keeps only limit+offset rows, the
        # gather moves D*(limit+offset) rows instead of the full result,
        # and one final sorted-merge + limit runs replicated
        sort = plan.input
        child, rep = _rewrite(sort.input, catalog, broadcast_rows)
        if rep:
            return S.Limit(S.Sort(child, sort.keys), plan.limit,
                           plan.offset), True
        k = plan.limit + plan.offset
        local = S.Limit(S.Sort(child, sort.keys), k, 0)
        merged = S.Sort(S.Gather(local), sort.keys)
        return S.Limit(merged, plan.limit, plan.offset), True

    if isinstance(plan, S.Sort):
        child, rep = _rewrite(plan.input, catalog, broadcast_rows)
        return S.Sort(_gather(child, rep), plan.keys), True

    if isinstance(plan, S.Limit):
        child, rep = _rewrite(plan.input, catalog, broadcast_rows)
        return S.Limit(_gather(child, rep), plan.limit, plan.offset), True

    if isinstance(plan, S.Window):
        child, rep = _rewrite(plan.input, catalog, broadcast_rows)
        if rep:
            return S.Window(child, plan.partition_cols, plan.order_keys,
                            plan.specs), True
        if plan.partition_cols:
            # co-locate each partition via shuffle, then window locally
            exch = S.Exchange(child, plan.partition_cols)
            return S.Window(exch, plan.partition_cols, plan.order_keys,
                            plan.specs), False
        return S.Window(S.Gather(child), plan.partition_cols,
                        plan.order_keys, plan.specs), True

    if isinstance(plan, S.Union):
        kids = [_rewrite(k, catalog, broadcast_rows) for k in plan.inputs]
        if all(rep for _, rep in kids):
            return S.Union(tuple(k for k, _ in kids)), True
        if any(rep for _, rep in kids):
            # mixing a replicated child with sharded ones would duplicate
            # its rows D times; gather everything instead
            return S.Union(tuple(_gather(k, rep) for k, rep in kids)), True
        return S.Union(tuple(k for k, _ in kids)), False

    if isinstance(plan, (S.Exchange, S.Broadcast, S.Gather)):
        raise TypeError(f"plan already distributed: {type(plan).__name__}")

    raise TypeError(f"cannot distribute plan node {type(plan).__name__}")


def _rest_fields(plan):
    """Positional fields after `input` for Filter/Project reconstruction."""
    if isinstance(plan, S.Filter):
        return (plan.predicate,)
    return (plan.exprs, plan.names, plan.dict_overrides)


def schema_of(plan: S.PlanNode, catalog: Catalog):
    """Output schema of a plan subtree — a lightweight metadata walk (no
    operator construction, no dictionary bridges)."""
    from ..coldata.types import Schema
    from ..ops import aggregation as agg_ops
    from ..ops import expr as ex
    from ..ops import join as join_ops
    from ..ops import window as win_ops

    if isinstance(plan, S.TableScan):
        t = catalog.get(plan.table)
        names = plan.columns or t.schema.names
        return t.schema.select(tuple(t.schema.index(n) for n in names))
    if isinstance(plan, (S.Filter, S.Sort, S.Limit, S.TopK,
                         S.Exchange, S.Broadcast, S.Gather)):
        return schema_of(plan.input, catalog)
    if isinstance(plan, S.Union):
        return schema_of(plan.inputs[0], catalog)
    if isinstance(plan, S.Project):
        base = schema_of(plan.input, catalog)
        return Schema(tuple(plan.names),
                      tuple(ex.expr_type(e, base) for e in plan.exprs))
    if isinstance(plan, S.Distinct):
        base = schema_of(plan.input, catalog)
        cols = plan.cols or tuple(range(len(base)))
        return base.select(cols)
    if isinstance(plan, (S.Aggregate, S.ScalarAggregate)):
        gcols = getattr(plan, "group_cols", ())
        mode = getattr(plan, "mode", "complete")
        base = (plan.base_schema if mode == "final"
                else schema_of(plan.input, catalog))
        return agg_ops.agg_output_schema(base, gcols, plan.aggs, mode)
    if isinstance(plan, (S.HashJoin, S.MergeJoin)):
        return join_ops.join_output_schema(
            schema_of(plan.probe, catalog),
            schema_of(plan.build, catalog), plan.spec,
        )
    if isinstance(plan, S.Window):
        return win_ops.window_output_schema(
            schema_of(plan.input, catalog), plan.specs
        )
    if isinstance(plan, S.HashBucket):
        return schema_of(plan.input, catalog)
    if isinstance(plan, S.RemoteStream):
        return plan.schema
    if isinstance(plan, S.StreamUnion):
        return schema_of(plan.inputs[0], catalog)
    if isinstance(plan, S.IndexScan):
        t = catalog.get(plan.table)
        names = plan.columns or t.schema.names
        return t.schema.select(tuple(t.schema.index(n) for n in names))
    raise TypeError(f"no schema rule for {type(plan).__name__}")


# back-compat private alias (pre-public-API callers)
_schema_of = schema_of


def _schema_len(plan: S.PlanNode, catalog: Catalog) -> int:
    return len(schema_of(plan, catalog))
