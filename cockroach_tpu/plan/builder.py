"""Plan -> operator tree — the colbuilder.NewColOperator analog
(reference: pkg/sql/colexec/colbuilder/execplan.go:736, core dispatch at
:153-270). Walks the PlanNode tree and instantiates flow operators, threading
catalog tables and host-side dictionary bridges."""

from __future__ import annotations

from ..catalog import Catalog
from ..flow import operators as ops
from ..flow.operator import Operator
from ..utils import settings
from . import spec as S


def _plan_dense_agg(child: Operator, group_cols, aggs):
    """(key_sizes, key_lows) for the dense scatter aggregation when every
    group key is bounded — by catalog/ANALYZE stats (integer families) or
    dictionary size (strings) — and the packed code space fits the
    sql.distsql.dense_agg_states budget. The dense code replaces the hash
    table slot (reference: colexechash hashtable.go:215) collision-free."""
    from ..coldata.types import Family
    from ..ops.aggregation import STAT_FUNCS

    if not settings.get("sql.distsql.dense_agg.enabled"):
        return None
    for spec in aggs:
        # dense states cover the decomposable aggregates; avg/var decompose
        # in partial_layout, so only truly unsupported funcs bail
        if spec.func not in ("sum", "count", "count_rows", "min", "max",
                             "avg", "any_not_null") + STAT_FUNCS:
            return None
    sizes, lows = [], []
    G = 1
    budget = settings.get("sql.distsql.dense_agg_states")
    import jax

    if jax.default_backend() != "cpu":
        # scatters serialize on the TPU VPU: big-G dense states lose to
        # sort+segscan there (q18's 6M-wide orderkey space is the prime
        # suspect in its 4.0s-TPU vs 0.31s-CPU gap; .drive_q18ab.py A/Bs
        # the two paths on the chip)
        budget = min(
            budget, settings.get("sql.distsql.dense_agg.accel_max_states")
        )
    for gi in group_cols:
        t = child.output_schema.types[gi]
        if t.family is Family.STRING and gi in child.dictionaries:
            if getattr(child.dictionaries[gi], "_runtime", False):
                return None  # fills at runtime: size unknown at plan time
            size, lo = len(child.dictionaries[gi]), 0
        elif t.family in (Family.FLOAT, Family.BYTES, Family.JSON,
                          Family.STRING):
            return None
        else:
            st = child.col_stats.get(gi)
            if st is None:
                return None
            lo, hi = int(st[0]), int(st[1])
            size = hi - lo + 1
            if size <= 0:
                return None
        sizes.append(size)
        lows.append(lo)
        G *= size + 1  # +1: the per-key NULL code (dense_layout)
        if G > budget:
            return None
    return tuple(sizes), tuple(lows)


def _clustered_input(plan: S.PlanNode, group_cols, catalog: Catalog):
    """(ordered, prefix_live) for an Aggregate's input chain: ordered when
    the walk down Project/Filter reaches a TableScan whose Table.ordering
    prefix IS the group key set — equal keys then arrive adjacent and the
    grouping can skip its key sort (colexec orderedAggregator role).
    prefix_live when no Filter interleaves dead rows (pure scan tiles are
    live-prefix), dropping the compaction sort too."""
    from ..ops import expr as ex

    cols = list(group_cols)
    prefix_live = True
    node = plan
    while True:
        if isinstance(node, S.Project):
            mapped = []
            for c in cols:
                e = node.exprs[c]
                if not isinstance(e, ex.ColRef):
                    return False, False
                mapped.append(e.idx)
            cols = mapped
            node = node.input
        elif isinstance(node, S.Filter):
            prefix_live = False
            node = node.input
        elif isinstance(node, S.TableScan):
            table = catalog.get(node.table)
            ordering = tuple(getattr(table, "ordering", ()) or ())
            if not ordering or len(cols) > len(ordering):
                return False, False
            names = tuple(node.columns or table.schema.names)
            try:
                keynames = {names[c] for c in cols}
            except IndexError:
                return False, False
            if keynames == set(ordering[: len(cols)]):
                return True, prefix_live
            return False, False
        else:
            return False, False


def build(plan: S.PlanNode, catalog: Catalog, params=None) -> Operator:
    """Instantiate the operator tree for `plan`, then collapse contiguous
    stateless per-tile chains into single-kernel FusedPipeline segments
    (flow/fuse.py) unless sql.distsql.fusion.enabled is off.

    ``params`` (a sql/plancache.ParamStore) reaches FilterOps whose
    predicates carry ex.Param leaves, so cached plans rebind literals as
    jit arguments instead of retracing (the prepared-plan fast path)."""
    op = _build(plan, catalog, params)
    if settings.get("sql.distsql.fusion.enabled"):
        from ..flow import fuse

        op = fuse.fuse_operators(op)
    return op


def _build(plan: S.PlanNode, catalog: Catalog, params=None) -> Operator:
    if isinstance(plan, S.TableScan):
        return ops.ScanOp(
            catalog.get(plan.table), plan.columns,
            tile=settings.get("sql.distsql.tile_size"),
            shard=plan.shard,
        )
    if isinstance(plan, S.IndexScan):
        return ops.IndexScanOp(
            catalog.get(plan.table), plan.index, plan.lo, plan.hi,
            plan.columns,
        )
    if isinstance(plan, S.HashBucket):
        return ops.HashBucketOp(_build(plan.input, catalog, params), plan.keys,
                                plan.n_parts, plan.part)
    if isinstance(plan, S.RemoteStream):
        return ops.RemoteStreamOp(plan.addr, plan.flow_id, plan.stream_id,
                                  plan.schema)
    if isinstance(plan, S.StreamUnion):
        return ops.ParallelUnorderedSyncOp(
            tuple(_build(p, catalog, params) for p in plan.inputs))
    if isinstance(plan, S.Filter):
        return ops.FilterOp(_build(plan.input, catalog, params),
                            plan.predicate, params=params)
    if isinstance(plan, S.Project):
        return ops.ProjectOp(_build(plan.input, catalog, params), plan.exprs,
                             plan.names, plan.dict_overrides)
    if isinstance(plan, S.Aggregate):
        child = _build(plan.input, catalog, params)
        if plan.key_sizes is not None and plan.mode == "complete":
            return ops.SmallGroupAggregateOp(
                child, plan.group_cols, plan.aggs, plan.key_sizes
            )
        if plan.mode == "complete":
            dense = _plan_dense_agg(child, plan.group_cols, plan.aggs)
            if dense is not None:
                sizes, lows = dense
                return ops.SmallGroupAggregateOp(
                    child, plan.group_cols, plan.aggs, sizes, key_lows=lows
                )
        ordered, prefix_live = (
            _clustered_input(plan.input, plan.group_cols, catalog)
            if plan.mode in ("complete", "partial") else (False, False)
        )
        return ops.AggregateOp(child, plan.group_cols, plan.aggs, plan.mode,
                               ordered=ordered, prefix_live=prefix_live)
    if isinstance(plan, S.ScalarAggregate):
        return ops.ScalarAggregateOp(_build(plan.input, catalog, params), plan.aggs)
    if isinstance(plan, S.Sort):
        return ops.SortOp(_build(plan.input, catalog, params), plan.keys)
    if isinstance(plan, S.TopK):
        return ops.TopKOp(_build(plan.input, catalog, params), plan.keys,
                          plan.k)
    if isinstance(plan, S.Limit):
        return ops.LimitOp(_build(plan.input, catalog, params), plan.limit, plan.offset)
    if isinstance(plan, S.Distinct):
        return ops.DistinctOp(_build(plan.input, catalog, params), plan.cols)
    if isinstance(plan, S.Window):
        return ops.WindowOp(
            _build(plan.input, catalog, params), plan.partition_cols,
            plan.order_keys, plan.specs,
        )
    if isinstance(plan, S.MergeJoin):
        return ops.MergeJoinOp(
            _build(plan.probe, catalog, params),
            _build(plan.build, catalog, params),
            plan.probe_key,
            plan.build_key,
            plan.spec,
        )
    if isinstance(plan, S.HashJoin):
        return ops.HashJoinOp(
            _build(plan.probe, catalog, params),
            _build(plan.build, catalog, params),
            plan.probe_keys,
            plan.build_keys,
            plan.spec,
        )
    if isinstance(plan, S.Union):
        return ops.UnionOp(tuple(_build(p, catalog, params) for p in plan.inputs))
    if isinstance(plan, S.Exchange):
        # single-device build: the shuffle is the identity; the multi-device
        # path lives in parallel/shuffle.py and is planned by parallel/dist.py
        return _build(plan.input, catalog, params)
    raise TypeError(f"unknown plan node {plan}")
