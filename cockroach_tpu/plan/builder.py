"""Plan -> operator tree — the colbuilder.NewColOperator analog
(reference: pkg/sql/colexec/colbuilder/execplan.go:736, core dispatch at
:153-270). Walks the PlanNode tree and instantiates flow operators, threading
catalog tables and host-side dictionary bridges."""

from __future__ import annotations

from ..catalog import Catalog
from ..flow import operators as ops
from ..flow.operator import Operator
from ..utils import settings
from . import spec as S


def build(plan: S.PlanNode, catalog: Catalog) -> Operator:
    if isinstance(plan, S.TableScan):
        return ops.ScanOp(
            catalog.get(plan.table), plan.columns,
            tile=settings.get("sql.distsql.tile_size"),
            shard=plan.shard,
        )
    if isinstance(plan, S.Filter):
        return ops.FilterOp(build(plan.input, catalog), plan.predicate)
    if isinstance(plan, S.Project):
        return ops.ProjectOp(build(plan.input, catalog), plan.exprs,
                             plan.names, plan.dict_overrides)
    if isinstance(plan, S.Aggregate):
        child = build(plan.input, catalog)
        if plan.key_sizes is not None and plan.mode == "complete":
            return ops.SmallGroupAggregateOp(
                child, plan.group_cols, plan.aggs, plan.key_sizes
            )
        return ops.AggregateOp(child, plan.group_cols, plan.aggs, plan.mode)
    if isinstance(plan, S.ScalarAggregate):
        return ops.ScalarAggregateOp(build(plan.input, catalog), plan.aggs)
    if isinstance(plan, S.Sort):
        return ops.SortOp(build(plan.input, catalog), plan.keys)
    if isinstance(plan, S.Limit):
        return ops.LimitOp(build(plan.input, catalog), plan.limit, plan.offset)
    if isinstance(plan, S.Distinct):
        return ops.DistinctOp(build(plan.input, catalog), plan.cols)
    if isinstance(plan, S.Window):
        return ops.WindowOp(
            build(plan.input, catalog), plan.partition_cols,
            plan.order_keys, plan.specs,
        )
    if isinstance(plan, S.MergeJoin):
        return ops.MergeJoinOp(
            build(plan.probe, catalog),
            build(plan.build, catalog),
            plan.probe_key,
            plan.build_key,
            plan.spec,
        )
    if isinstance(plan, S.HashJoin):
        return ops.HashJoinOp(
            build(plan.probe, catalog),
            build(plan.build, catalog),
            plan.probe_keys,
            plan.build_keys,
            plan.spec,
        )
    if isinstance(plan, S.Union):
        return ops.UnionOp(tuple(build(p, catalog) for p in plan.inputs))
    if isinstance(plan, S.Exchange):
        # single-device build: the shuffle is the identity; the multi-device
        # path lives in parallel/shuffle.py and is planned by parallel/dist.py
        return build(plan.input, catalog)
    raise TypeError(f"unknown plan node {plan}")
