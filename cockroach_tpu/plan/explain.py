"""EXPLAIN / EXPLAIN ANALYZE — plan pretty-printing + ComponentStats folding.

Reference: EXPLAIN renders the optimizer plan tree; EXPLAIN ANALYZE runs the
query with per-processor ComponentStats collection and folds the stats into
the rendered tree (pkg/sql/execstats/traceanalyzer.go over
execinfrapb/component_stats.proto). Here the operator tree mirrors the plan
tree one-to-one, so stats attach directly to plan lines.
"""

from __future__ import annotations

from . import spec as S


def _node_label(n: S.PlanNode) -> str:
    if isinstance(n, S.TableScan):
        cols = f" columns={list(n.columns)}" if n.columns else ""
        return f"scan {n.table}{cols}"
    if isinstance(n, S.IndexScan):
        lo = "-inf" if n.lo is None else n.lo
        hi = "+inf" if n.hi is None else n.hi
        return f"index-scan {n.table}@{n.index} [{lo}, {hi}]"
    if isinstance(n, S.Filter):
        return f"filter {n.predicate}"
    if isinstance(n, S.Project):
        return f"project {list(n.names)}"
    if isinstance(n, S.Aggregate):
        aggs = [f"{a.func}({a.col if a.col is not None else '*'})"
                for a in n.aggs]
        mode = f" mode={n.mode}" if n.mode != "complete" else ""
        dense = " dense" if n.key_sizes else ""
        return f"group-by keys={list(n.group_cols)} aggs={aggs}{mode}{dense}"
    if isinstance(n, S.ScalarAggregate):
        aggs = [f"{a.func}({a.col if a.col is not None else '*'})"
                for a in n.aggs]
        return f"scalar-group-by aggs={aggs}"
    if isinstance(n, S.HashJoin):
        u = " (unique build)" if n.spec.build_unique else ""
        return (f"hash-join ({n.spec.join_type}) "
                f"probe={list(n.probe_keys)} build={list(n.build_keys)}{u}")
    if isinstance(n, S.Sort):
        keys = [f"{k.col}{' desc' if k.desc else ''}" for k in n.keys]
        return f"sort keys={keys}"
    if isinstance(n, S.Limit):
        off = f" offset={n.offset}" if n.offset else ""
        return f"limit {n.limit}{off}"
    if isinstance(n, S.TopK):
        keys = [f"{k.col}{' desc' if k.desc else ''}" for k in n.keys]
        return f"top-k k={n.k} keys={keys}"
    if isinstance(n, S.Distinct):
        return f"distinct on={list(n.cols) if n.cols else 'all'}"
    if isinstance(n, S.Exchange):
        return f"exchange (all-to-all) keys={list(n.keys)}"
    if isinstance(n, S.Union):
        return f"union-all ({len(n.inputs)} inputs)"
    if isinstance(n, S.Broadcast):
        return "broadcast (all-gather)"
    if isinstance(n, S.Gather):
        return "gather (all-gather)"
    if isinstance(n, S.MergeJoin):
        return (f"merge-join ({n.spec.join_type}) "
                f"probe={n.probe_key} build={n.build_key}")
    if isinstance(n, S.Window):
        fns = [s.func for s in n.specs]
        return (f"window {fns} partition={list(n.partition_cols)} "
                f"order={[k.col for k in n.order_keys]}")
    return type(n).__name__


def _children(n: S.PlanNode) -> list[S.PlanNode]:
    if isinstance(n, (S.HashJoin, S.MergeJoin)):
        return [n.probe, n.build]
    if isinstance(n, S.Union):
        return list(n.inputs)
    if hasattr(n, "input"):
        return [n.input]
    return []


def _fusion_groups(plan: S.PlanNode) -> dict[int, int]:
    """id(plan node) -> fused pipeline group (empty when fusion is off).
    Members of one group collapse into a single per-tile kernel at
    execution (flow/fuse.py + the spool fusion in flow/operators.py)."""
    from ..utils import settings

    if not settings.get("sql.distsql.fusion.enabled"):
        return {}
    from ..flow.fuse import plan_fusion_groups

    return plan_fusion_groups(plan)


def _group_tag(groups: dict[int, int], n: S.PlanNode) -> str:
    g = groups.get(id(n))
    return f"  [pipeline {g}]" if g is not None else ""


def explain_plan(plan: S.PlanNode) -> str:
    """Render the plan tree (EXPLAIN)."""
    lines: list[str] = []
    groups = _fusion_groups(plan)

    def walk(n: S.PlanNode, depth: int):
        lines.append(
            "  " * depth + "-> " + _node_label(n) + _group_tag(groups, n))
        for c in _children(n):
            walk(c, depth + 1)

    walk(plan, 0)
    return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    """Human byte figure for EXPLAIN ANALYZE memory lines (KiB below one
    MiB, else MiB — mirroring the reference's humanizeutil sizes)."""
    n = int(n)
    if n < 1 << 20:
        return f"{n / 1024:.1f} KiB"
    return f"{n / (1 << 20):.1f} MiB"


def explain_analyze(plan: S.PlanNode, root_op) -> str:
    """Render the plan tree with executed ComponentStats (EXPLAIN ANALYZE).
    `root_op` must have been run with collect_stats(True)."""
    from ..flow.fuse import unwrap

    lines: list[str] = []
    groups = _fusion_groups(plan)

    def walk(n: S.PlanNode, op, depth: int):
        if isinstance(n, S.Exchange):
            # single-device builds elide the exchange operator
            walk(n.input, op, depth)
            return
        # fusion-pass wrappers sit between plan nodes; see through them so
        # the plan-node/operator walk stays one-to-one
        op = unwrap(op)
        st = op.stats
        excl = st.exclusive(op.children())
        # memory-account annotations (mon.BoundAccount high-water): only
        # buffering operators open accounts, so most lines carry neither
        mem = (f" max mem={_fmt_bytes(st.max_mem_bytes)}"
               if getattr(st, "max_mem_bytes", 0) else "")
        spill = " spilled" if getattr(st, "spilled", False) else ""
        lines.append(
            "  " * depth + "-> " + _node_label(n)
            + f"  [rows={st.rows} batches={st.batches} "
            f"bytes={st.bytes} "
            f"time={st.time_s*1e3:.1f}ms self={excl*1e3:.1f}ms{mem}{spill}]"
            + _group_tag(groups, n)
        )
        for c, co in zip(_children(n), op.children()):
            walk(c, co, depth + 1)

    walk(plan, root_op, 0)
    # span tree from the traced run (flow/runtime.py attaches it): operator
    # wall times plus the seams ComponentStats cannot see (pull attempts,
    # readback, KV round-trips grafted from remote nodes); the plan tree
    # keeps its root on line 1 and the dispatch footer its last two lines
    # (consumers parse both)
    tsp = getattr(root_op, "_trace_span", None)
    if tsp is not None:
        lines.append("trace:")
        lines.append(tsp.tree(indent=1))
    # query peak-memory footer (the statement monitor's high water, set by
    # flow/runtime.py) BEFORE the dispatch lines, which stay last
    peak = getattr(root_op, "_query_mem_peak", 0)
    if peak:
        spills = getattr(root_op, "_query_mem_spills", 0)
        suffix = f" (spills: {spills})" if spills else ""
        lines.append(f"query peak memory: {_fmt_bytes(peak)}{suffix}")
    kd = getattr(getattr(root_op, "stats", None), "kernel_dispatches", 0)
    if kd:
        lines.append(f"kernel dispatches: {kd}")
        kc = getattr(root_op.stats, "kernel_compiles", 0)
        lines.append(f"kernel compiles: {kc} (cached: {kd - kc})")
    return "\n".join(lines)
