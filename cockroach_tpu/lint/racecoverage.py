"""race-coverage pass: multi-thread state is locked or racesan-sees it.

The Eraser-style runtime sanitizer (``utils/racesan.py``) only catches
races on state it is TOLD about — each ``note_read``/``note_write``
call is hand-placed. ROADMAP carried an un-gated chore ("extend racesan
as control-plane state grows"); this pass turns it into an enforced
gate by joining the shared-state escape analysis with the sanitizer's
instrumentation map:

- every state the whole-program analysis proves **multi-thread-
  reachable** (accessed under two or more entry points, with at least
  one non-init, non-GIL-atomic write) must be either

  1. **consistently lock-guarded** — one recognized lock common to the
     lockset of EVERY live access site (stricter than the shared-state
     pass, which only requires pairwise overlap on conflicting pairs),
     or
  2. **sanitizer-instrumented** — a ``racesan.note_read``/``note_write``
     call in the defining module naming the field as a string literal,
     so ``debug.race_detector.enabled`` runs actually check it.

New subsystems (coalesce trains, sharedscan subscriber maps, warm-menu
registries) therefore cannot land shared state the sanitizer never
sees: the lint gate trips until the state is either provably guarded or
instrumented. Deliberately lock-free structures that neither hold nor
want instrumentation carry
``# crlint: allow-race-coverage(<why safe>)`` on any access site (the
``__init__`` assignment is the ergonomic spot), same as shared-state.

``coverage_map`` exposes the full field↔site map — every analyzed
state with its status, guard and access sites — printed by the CLI via
``python -m cockroach_tpu.lint --race-map``.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile, attr_chain
from .sharedstate import Access, program

RULE = "race-coverage"

_NOTE_FUNCS = {"note_read", "note_write"}


def _instrumented_fields(files: list[SourceFile]) -> dict[str, set[str]]:
    """rel -> field names carrying a racesan note_* call with a string-
    literal field name in that module."""
    out: dict[str, set[str]] = {}
    for f in files:
        fields: set[str] = set()
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            name = chain[-1] if chain else None
            if name not in _NOTE_FUNCS:
                continue
            if chain and len(chain) > 1 and chain[-2] != "racesan":
                continue
            if len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                fields.add(node.args[1].value)
        if fields:
            out[f.rel] = fields
    return out


def coverage_map(files: list[SourceFile], cache=None) -> list[dict]:
    """The field↔site map: one row per shared state the whole-program
    analysis sees, with its coverage status.

    status is one of:

    - ``locked`` — a common lock guards every live access (``guard``
      names it);
    - ``instrumented`` — racesan note_read/note_write calls name the
      field in its module;
    - ``atomic-publish`` — every non-init write is a plain GIL-atomic
      rebind (the documented lock-free pattern);
    - ``init-only`` — written only during construction;
    - ``single-entry`` — never reachable from two entry points;
    - ``read-only`` — no writes at all;
    - ``waived`` — would be UNCOVERED but an access site carries a
      reasoned ``allow-race-coverage`` pragma;
    - ``UNCOVERED`` — multi-thread-reachable writes with neither a
      common lock nor instrumentation: the race-coverage finding.
    """
    prog = program(files, cache)
    if prog is None:
        return []
    noted = _instrumented_fields(files)
    by_rel = {f.rel: f for f in files}

    by_state: dict[str, list[Access]] = {}
    for rec in prog.funcs.values():
        for a in rec.accesses:
            by_state.setdefault(a.state, []).append(a)

    rows: list[dict] = []
    for state, accesses in sorted(by_state.items()):
        live = [a for a in accesses if not a.in_init]
        writes = [a for a in live if a.kind == "w"]
        rel = accesses[0].rel
        field = state.rsplit(".", 1)[-1]
        entries: set = set()
        for a in live:
            entries |= prog.entries_of(a.func)
        guard: str | None = None
        if not writes:
            status = "read-only"
        elif len(entries) < 2:
            status = "single-entry"
        elif all(w.wkind == "rebind" and not w.rmw for w in writes):
            status = "atomic-publish"
        else:
            common = None
            for a in live:
                ls = prog.lockset(a)
                common = ls if common is None else (common & ls)
            if common:
                status = "locked"
                guard = sorted(common)[0]
            elif field in noted.get(rel, ()):
                status = "instrumented"
            else:
                status = "UNCOVERED"
        if not live and any(a.in_init for a in accesses):
            status = "init-only"
        sites = sorted({(a.rel, a.line, a.kind) for a in accesses},
                       key=lambda s: (s[0], s[1], s[2]))
        if status == "UNCOVERED":
            # state-wide pragma on ANY access site (incl. __init__),
            # same ergonomics as shared-state
            for srel, sline, _kind in sites:
                src = by_rel.get(srel)
                if src is not None and src.allows(RULE, sline):
                    status = "waived"
                    break
        rows.append({
            "state": state, "status": status, "guard": guard,
            "field": field, "rel": rel,
            "entries": sorted(str(e) for e in entries),
            "sites": sites,
        })
    return rows


def render_map(rows: list[dict]) -> str:
    """Human-readable field↔site map (the CLI's --race-map output)."""
    out = []
    for r in rows:
        guard = f" guard={r['guard']}" if r["guard"] else ""
        sites = ", ".join(f"{rel}:{line}({kind})"
                          for rel, line, kind in r["sites"])
        out.append(f"{r['state']}: {r['status']}{guard} — {sites}")
    return "\n".join(out)


def check(files: list[SourceFile], cache=None) -> list[Finding]:
    rows = coverage_map(files, cache)
    out: list[Finding] = []
    for r in rows:
        if r["status"] != "UNCOVERED":
            continue
        wsites = [s for s in r["sites"] if s[2] == "w"]
        anchor = wsites[0] if wsites else r["sites"][0]
        sites = ", ".join(f"{rel}:{line}" for rel, line, _k in r["sites"])
        out.append(Finding(
            RULE, anchor[0], anchor[1],
            f"{r['state']} is written from multiple thread entry points "
            "with no common lock across all access sites and no racesan "
            f"note_read/note_write instrumentation (sites: {sites}) — "
            "guard every access with one utils/locks lock, or add "
            f"racesan.note_* calls naming {r['field']!r} so the runtime "
            "race detector sees it, or waive with "
            "allow-race-coverage(reason)"))
    return sorted(out, key=lambda f: (f.path, f.line, f.message))
