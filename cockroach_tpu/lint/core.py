"""crlint framework: file loading, pragma handling, rule registry, reporters.

A rule is a function ``check(file: SourceFile) -> list[Finding]`` (per-file
rules) or ``check(files: list[SourceFile]) -> list[Finding]`` (tree rules —
the lock-order pass needs the whole cross-module graph). Findings are
suppressed by an inline pragma on the finding line or the line directly
above it::

    x = int(count)  # crlint: allow-host-sync(one sync at query end, by design)

The reason is mandatory: a bare ``allow-<rule>()`` does not suppress (the
pragma exists to document WHY the invariant is waived, not to mute it).
Findings with ``suppressible=False`` (silent ``except: pass`` swallows)
ignore pragmas entirely.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
import time
import tokenize
from dataclasses import dataclass, field

_PRAGMA = re.compile(r"#\s*crlint:\s*allow-([a-z0-9_-]+)\(([^()]*)\)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # canonical package-relative posix path
    line: int
    message: str
    suppressible: bool = True

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    path: pathlib.Path      # on-disk location
    rel: str                # canonical key rules match on (posix)
    text: str
    tree: ast.AST
    # line -> {rule: reason} pragmas (comments only — string literals that
    # happen to contain the pattern don't suppress)
    pragmas: dict[int, dict[str, str]] = field(default_factory=dict)
    # (start, end, rule) ranges from def/class-line pragmas: a pragma on a
    # function's `def` line (or the line above it) waives the rule for the
    # whole body — for functions that are host-side by design, one
    # documented waiver instead of one per statement
    scoped: list[tuple[int, int, str]] = field(default_factory=list)

    def __post_init__(self):
        import ast as _ast
        for node in _ast.walk(self.tree):
            if not isinstance(node, (_ast.FunctionDef,
                                     _ast.AsyncFunctionDef, _ast.ClassDef)):
                continue
            for ln in (node.lineno, node.lineno - 1):
                for rule, reason in self.pragmas.get(ln, {}).items():
                    if reason:
                        self.scoped.append(
                            (node.lineno, node.end_lineno or node.lineno,
                             rule))

    @property
    def modname(self) -> str:
        return self.rel[:-3].replace("/", ".") if self.rel.endswith(".py") \
            else self.rel.replace("/", ".")

    def allows(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            reason = self.pragmas.get(ln, {}).get(rule)
            if reason:  # empty reason does not suppress
                return True
        return any(start <= line <= end and rule == r
                   for start, end, r in self.scoped)


def _canonical_rel(path: pathlib.Path, root: pathlib.Path) -> str:
    """Path key rules match on: anchored at the last ``cockroach_tpu`` or
    ``scripts``/``tests`` component so fixture trees under tmp dirs scope
    exactly like the real tree."""
    parts = path.resolve().parts
    for anchor in ("cockroach_tpu", "scripts", "tests"):
        if anchor in parts[:-1]:
            i = len(parts) - 2 - parts[:-1][::-1].index(anchor)
            return "/".join(parts[i:])
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.name


def _collect_pragmas(text: str) -> dict[int, dict[str, str]]:
    pragmas: dict[int, dict[str, str]] = {}
    try:
        tokens = tokenize.generate_tokens(iter(text.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            for m in _PRAGMA.finditer(tok.string):
                pragmas.setdefault(tok.start[0], {})[m.group(1)] = \
                    m.group(2).strip()
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return pragmas


def load_files(paths: list[str | pathlib.Path]) -> list[SourceFile]:
    """Expand files/directories into parsed SourceFiles (sorted, deduped;
    __pycache__ skipped). Unparseable files raise — a syntax error in the
    tree is itself a finding-worthy failure, loudly."""
    roots = [pathlib.Path(p) for p in paths]
    seen: dict[pathlib.Path, SourceFile] = {}
    for root in roots:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        base = root if root.is_dir() else root.parent
        for path in files:
            rp = path.resolve()
            if rp in seen or "__pycache__" in path.parts:
                continue
            text = path.read_text()
            seen[rp] = SourceFile(
                path=path,
                rel=_canonical_rel(path, base),
                text=text,
                tree=ast.parse(text, filename=str(path)),
                pragmas=_collect_pragmas(text),
            )
    return sorted(seen.values(), key=lambda f: f.rel)


class TreeCache:
    """Single-parse whole-program cache shared by the tree passes.

    The tree is parsed exactly once per ``run_lint`` (``load_files``);
    this cache extends that sharing to the DERIVED structures the graph
    passes each need: per-module symbol indexes (lock tables, function
    tables — ``lockorder._ModuleIndex``) and the whole-program
    thread-entry/call-graph analysis (``sharedstate.program``). Before
    it existed, lock-order and shared-state each rebuilt every module
    index, and the three graph passes (shared-state, untimed-wait,
    race-coverage) would each have re-run the ~same multi-second escape
    analysis — the cache is what keeps the 13-pass suite inside the
    10-pass wall-time budget.

    Keys are arbitrary hashables; ``memo`` runs ``build`` once and
    returns the cached value thereafter. A cache instance is only valid
    for the one file list it was built with (``run_lint`` constructs a
    fresh one per invocation).
    """

    def __init__(self, files: list[SourceFile]):
        self.files = files
        self.by_rel = {f.rel: f for f in files}
        self._memo: dict = {}

    def memo(self, key, build):
        if key not in self._memo:
            self._memo[key] = build()
        return self._memo[key]

    def index(self, f: SourceFile):
        """Memoized ``lockorder._ModuleIndex`` for one file, with
        ``mod_globals`` populated (the shared-state extension)."""
        def build():
            from .lockorder import _ModuleIndex
            from .sharedstate import _mod_globals

            idx = _ModuleIndex(f)
            idx.mod_globals = _mod_globals(f, idx)
            return idx
        return self.memo(("idx", f.rel), build)


def attr_chain(node: ast.AST) -> tuple[str, ...] | None:
    """('jax','jit') for ``jax.jit``; None when the base isn't a Name."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _rules():
    # late import: the rule modules import core for helpers
    from . import (blocking, errdiscipline, faultcoverage, hostsync,
                   lockorder, memaccounting, racecoverage, rawjit,
                   sharedstate, tracepurity, tracingapi, unusedimport)
    per_file = {
        "host-sync": hostsync.check,
        "raw-jit": rawjit.check,
        "broad-except": errdiscipline.check,
        "unused-import": unusedimport.check,
        "tracing-api": tracingapi.check,
        "mem-accounting": memaccounting.check,
        "recompile-hazard": tracepurity.check,
    }
    tree = {
        "lock-order": lockorder.check,
        "shared-state": sharedstate.check,
        "fault-coverage": faultcoverage.check,
        "untimed-wait": blocking.check,
        "race-coverage": racecoverage.check,
    }
    return per_file, tree


ALL_RULES = ("host-sync", "raw-jit", "broad-except", "unused-import",
             "lock-order", "tracing-api", "shared-state", "mem-accounting",
             "fault-coverage", "untimed-wait", "recompile-hazard",
             "race-coverage", "unknown-pragma")


def _unknown_pragmas(files: list[SourceFile]) -> list[Finding]:
    """A pragma naming a rule crlint doesn't know suppresses NOTHING —
    usually a typo ('alow-host-sync', 'mem-acounting') silently leaving
    the author convinced a finding is waived. That near-miss is itself a
    finding."""
    known = set(ALL_RULES)
    out = []
    for f in files:
        for ln in sorted(f.pragmas):
            for rule in f.pragmas[ln]:
                if rule not in known:
                    out.append(Finding(
                        "unknown-pragma", f.rel, ln,
                        f"pragma waives unknown rule {rule!r} — no such "
                        "pass exists, so this suppresses nothing "
                        f"(known rules: {', '.join(sorted(known))})",
                    ))
    return out


def run_lint(paths: list[str | pathlib.Path],
             rules: tuple[str, ...] | None = None,
             timings: dict[str, float] | None = None) -> list[Finding]:
    """Run the selected passes; returns unsuppressed findings sorted by
    location. When ``timings`` is a dict it is filled with per-pass wall
    seconds (plus the one-time ``load/parse`` cost) so regressions in
    any single pass are attributable."""
    t0 = time.perf_counter()
    files = load_files(paths)
    cache = TreeCache(files)
    if timings is not None:
        timings["load/parse"] = time.perf_counter() - t0
    per_file, tree = _rules()
    wanted = set(rules or ALL_RULES)
    findings: list[Finding] = []
    by_rel = cache.by_rel

    def timed(name, run):
        t = time.perf_counter()
        out = run()
        if timings is not None:
            timings[name] = time.perf_counter() - t
        return out

    for name, check in per_file.items():
        if name not in wanted:
            continue
        findings.extend(timed(
            name, lambda c=check: [fd for f in files for fd in c(f)]))
    for name, check in tree.items():
        if name in wanted:
            findings.extend(timed(
                name, lambda c=check: c(files, cache=cache)))
    if "unknown-pragma" in wanted:
        findings.extend(timed(
            "unknown-pragma", lambda: _unknown_pragmas(files)))
    live = []
    for fd in findings:
        src = by_rel.get(fd.path)
        if fd.suppressible and src is not None and src.allows(fd.rule, fd.line):
            continue
        live.append(fd)
    # fully deterministic order (message included: two findings of one
    # rule can share a line) — reporters and CI diffs rely on stability
    return sorted(live, key=lambda f: (f.path, f.line, f.rule, f.message))


def report_text(findings: list[Finding]) -> str:
    return "\n".join(f.render() for f in findings)


def report_json(findings: list[Finding]) -> str:
    return json.dumps(
        [{"rule": f.rule, "path": f.path, "line": f.line,
          "message": f.message} for f in findings],
        indent=2,
    )
