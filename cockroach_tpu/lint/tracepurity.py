"""recompile-hazard pass: static guard on the zero-recompile guarantee.

PR 6's serving-path contract — repeat queries trace ZERO new kernels —
is enforced at runtime by ``scripts/check_recompiles.py``, but only for
the query shapes that script happens to run. This pass catches the
hazard classes statically, at every call site:

1. **kernel-key impurity** — arguments to ``dispatch.kernel_key(...)``
   whose value is not a stable function of the traced computation:
   f-strings and ``repr``/``id``/``hash`` of runtime objects (two
   structurally identical kernels get different keys → cache miss →
   retrace), and unsorted dict iteration (``.keys()``/``.values()``/
   ``.items()`` outside ``sorted(...)`` — two equal schemas built in
   different insertion orders key differently);
2. **keyless jit of a closure on a per-call path** — ``dispatch.jit``
   applied to a lambda/nested def OUTSIDE construction-time methods
   (``__init__``/``__post_init__``/``open``) with neither a ``key=``
   (process-global kernel cache) nor memoization evidence in the
   enclosing function (``setdefault``/``lru_cache``/a ``*cache*``
   name): every call builds a fresh wrapper and re-traces;
3. **non-bucketed shapes feeding jit** (hot modules only) — a value
   bound to a ``cap``/``capacity`` name (the static-argname shape
   convention) derived directly from data sizes (``len(...)``,
   ``.shape``, ``.size``, ``.num_rows``) with no canonical-bucketing
   evidence (``_canonical_cap``/``_bucket_cap``/``SHAPE_BUCKETS``/a
   power-of-two ladder): per-row-count shapes mint one executable per
   cardinality instead of one per rung.

Waive with ``# crlint: allow-recompile-hazard(<why stable>)`` on the
line or the def line. Scope: ``cockroach_tpu/`` (check 3 further
scoped to the flow/ops/parallel hot modules, where shapes reach jit).
"""

from __future__ import annotations

import ast
import re

from .core import Finding, SourceFile, attr_chain

RULE = "recompile-hazard"

# modules whose capacities parameterize jitted kernels (static argnames
# / padded buffer shapes) — the canonical-bucketing discipline applies
SHAPE_HOT = (
    "cockroach_tpu/flow/operators.py",
    "cockroach_tpu/flow/external.py",
    "cockroach_tpu/flow/fuse.py",
    "cockroach_tpu/flow/viewmaint.py",
    "cockroach_tpu/flow/sharedscan.py",
    "cockroach_tpu/ops/merge_join.py",
    "cockroach_tpu/ops/sort.py",
    "cockroach_tpu/parallel/shuffle.py",
    "cockroach_tpu/parallel/dist.py",
)

_CAP_NAME = re.compile(r"(^|_)(cap|capacity)$")
# construction-time lifecycle methods: run once per operator INSTANCE,
# and instances outlive queries (the plan cache shares operator trees
# across repeats — that reuse is exactly why check_recompiles holds
# zero). A keyless closure jit here compiles once per instance, not per
# call; the hazard this pass hunts is the same jit on a per-CALL path.
_CONSTRUCTION_FUNCS = {"__init__", "__post_init__", "__new__", "open",
                       "init"}
_BUCKET_EVIDENCE = {"_canonical_cap", "_bucket_cap", "bucket_cap",
                    "_bucket", "next_pow2", "SHAPE_BUCKETS"}
_IMPURE_CALLS = {"repr", "id", "hash"}
_DICT_ITERS = {"keys", "values", "items"}


def _is_kernel_key_call(node: ast.Call) -> bool:
    chain = attr_chain(node.func)
    if chain and chain[-1] == "kernel_key":
        return len(chain) == 1 or chain[-2] == "dispatch"
    return False


def _is_dispatch_jit(node: ast.AST) -> bool:
    chain = attr_chain(node)
    return bool(chain) and chain[-2:] == ("dispatch", "jit")


def _key_hazards(arg: ast.AST, in_sorted: bool = False):
    """(node, description) impurities inside one kernel-key argument."""
    if isinstance(arg, ast.JoinedStr):
        yield (arg, "an f-string (formatting mixes runtime values and "
                    "object reprs into the key)")
        return
    if isinstance(arg, ast.Call):
        f = arg.func
        name = None
        if isinstance(f, ast.Name):
            name = f.id
        elif isinstance(f, ast.Attribute):
            name = f.attr
        if name in _IMPURE_CALLS:
            yield (arg, f"{name}() of a runtime object (identity/"
                        "address-dependent: two equal kernels key "
                        "differently)")
            return
        if (name in _DICT_ITERS and isinstance(f, ast.Attribute)
                and not in_sorted and not arg.args):
            yield (arg, f".{name}() iteration order (two structurally "
                        "equal dicts built in different orders key "
                        "differently — wrap in sorted(...))")
            return
        if name == "sorted":
            in_sorted = True
    for child in ast.iter_child_nodes(arg):
        yield from _key_hazards(child, in_sorted)


def _own_calls(fn: ast.AST) -> list[ast.Call]:
    """Calls in the function body excluding nested def/lambda bodies."""
    out: list[ast.Call] = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _memo_evidence(fn: ast.AST) -> bool:
    """The enclosing function already memoizes its jit wrappers: a cache
    lookup/insert (setdefault), functools.lru_cache, kernel_key use, or
    any *cache* name."""
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr == "setdefault":
                return True
            if _is_kernel_key_call(n):
                return True
        if isinstance(n, ast.Name) and "cache" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "cache" in n.attr.lower():
            return True
        chain = attr_chain(n) if isinstance(n, ast.Attribute) else None
        if chain and chain[-1] == "lru_cache":
            return True
    return False


def _dynamic_size(expr: ast.AST) -> bool:
    """The expression derives directly from data cardinality."""
    for n in ast.walk(expr):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return True
        if isinstance(n, ast.Attribute) \
                and n.attr in ("shape", "size", "num_rows", "nbytes"):
            return True
    return False


def _bucket_evidence(expr: ast.AST) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and n.id in _BUCKET_EVIDENCE:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _BUCKET_EVIDENCE:
            return True
        if isinstance(n, ast.BinOp) and isinstance(n.op, ast.LShift):
            return True
    return False


def _cap_target_name(t: ast.AST) -> str | None:
    if isinstance(t, ast.Name):
        return t.id
    if isinstance(t, ast.Attribute):
        return t.attr
    return None


def check(file: SourceFile) -> list[Finding]:
    if not file.rel.startswith("cockroach_tpu/"):
        return []
    # textual prefilter: hazard 1 needs a kernel_key call, hazard 2 a
    # dispatch.jit reference, hazard 3 a shape-hot module — files with
    # none of those cannot trip, so skip their AST walks entirely
    has_key = "kernel_key" in file.text
    has_jit = "jit" in file.text
    if not has_key and not has_jit and file.rel not in SHAPE_HOT:
        return []
    findings: list[Finding] = []
    tree = file.tree

    # 1. kernel-key impurity — anywhere in the package
    for node in ast.walk(tree) if has_key else ():
        if isinstance(node, ast.Call) and _is_kernel_key_call(node):
            args = list(node.args) + [kw.value for kw in node.keywords]
            for arg in args:
                for bad, why in _key_hazards(arg):
                    findings.append(Finding(
                        RULE, file.rel, bad.lineno,
                        f"kernel_key argument uses {why}; kernel keys "
                        "must be pure structural functions of the "
                        "traced computation — fix the key, or waive "
                        "with allow-recompile-hazard(reason)"))

    # 2. keyless jit of a closure outside construction
    def scan_fn(fn: ast.AST, where: str):
        if fn.name.split(".")[-1] in _CONSTRUCTION_FUNCS:
            return
        nested = {n.name for n in ast.iter_child_nodes(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # also: nested defs decorated with a keyless dispatch.jit
        for n in ast.iter_child_nodes(fn):
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in n.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _is_dispatch_jit(target) or (
                        isinstance(dec, ast.Call) and dec.args
                        and _is_dispatch_jit(dec.args[0])):
                    keyed = isinstance(dec, ast.Call) and any(
                        kw.arg == "key" for kw in dec.keywords)
                    if not keyed and not _memo_evidence(fn):
                        findings.append(Finding(
                            RULE, file.rel, dec.lineno,
                            f"{where} jits the nested def {n.name!r} "
                            "with no key= on a per-call path — every "
                            "invocation builds a fresh wrapper and "
                            "re-traces; key it through "
                            "dispatch.kernel_key, hoist to "
                            "construction, or waive with "
                            "allow-recompile-hazard(reason)"))
        for call in _own_calls(fn):
            if not _is_dispatch_jit(call.func):
                continue
            if any(kw.arg == "key" for kw in call.keywords):
                continue
            if not call.args:
                continue
            arg0 = call.args[0]
            closure = isinstance(arg0, ast.Lambda) or (
                isinstance(arg0, ast.Name) and arg0.id in nested)
            if closure and not _memo_evidence(fn):
                findings.append(Finding(
                    RULE, file.rel, call.lineno,
                    f"{where} calls dispatch.jit on a closure with no "
                    "key= on a per-call path — every invocation builds "
                    "a fresh wrapper and re-traces; key it through "
                    "dispatch.kernel_key, hoist to construction, or "
                    "waive with allow-recompile-hazard(reason)"))

    def walk_scope(body, cls: str | None):
        for node in body:
            if isinstance(node, ast.ClassDef):
                walk_scope(node.body, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                where = (f"{file.modname}."
                         f"{(cls + '.') if cls else ''}{node.name}")
                scan_fn(node, where)

    if has_jit:
        walk_scope(tree.body, None)

    # 3. non-bucketed capacities in the shape-hot modules
    if file.rel in SHAPE_HOT:
        for node in ast.walk(tree):
            targets: list[tuple[str, ast.AST, int]] = []
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    name = _cap_target_name(t)
                    if name and _CAP_NAME.search(name):
                        targets.append((name, node.value, node.lineno))
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg and _CAP_NAME.search(kw.arg):
                        targets.append((kw.arg, kw.value, kw.value.lineno))
            for name, value, line in targets:
                if _dynamic_size(value) and not _bucket_evidence(value):
                    findings.append(Finding(
                        RULE, file.rel, line,
                        f"{name!r} is derived from a data size "
                        "(len/.shape/.size) with no canonical-bucketing "
                        "evidence (_canonical_cap/_bucket_cap/"
                        "SHAPE_BUCKETS) in a shape-hot module — "
                        "per-cardinality shapes mint one executable per "
                        "row count; bucket the capacity, or waive with "
                        "allow-recompile-hazard(reason)"))
    return findings
