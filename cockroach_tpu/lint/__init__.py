"""crlint — repo-specific AST static analysis (the pkg/testutils/lint analog).

The reference enforces project invariants nobody can hold in their head with
a lint package full of custom passes (pkg/testutils/lint/lint_test.go: no
direct os.Exit, forbidden imports, timeutil discipline ...). The invariants
this engine's last PRs established by hand are exactly that shape, so they
are machine-checked here on every run:

- **host-sync**: no implicit device->host transfer (``int()``/``float()``/
  ``bool()`` on traced values, ``.item()``, ``np.asarray``, truth tests on
  traced expressions) inside the hot-path tile pull loop. One stray sync
  reintroduces the per-tile stall the overlapped-readback work removed.
- **raw-jit**: every ``jax.jit``/``jax.pmap``/``jax.shard_map`` reference
  outside ``flow/dispatch.py`` must route through ``dispatch.jit`` so the
  ``sql_kernel_dispatches`` accounting and the dispatch-budget guard cannot
  be silently bypassed.
- **lock-order**: the cross-module lock acquisition graph (extracted from
  lock attributes and the lock-held call graph) must be acyclic. The
  runtime half lives in ``utils/locks.py`` (debug-mode OrderedLock).
- **broad-except**: ``except Exception`` in ``kv/``, ``flow/``, ``server/``
  must re-raise, raise a typed error, or carry a pragma; a bare ``pass``
  handler is a hard error no pragma can excuse.
- **unused-import**: imported names never referenced are dead surface area.

Suppression is per-line and must carry a reason::

    risky()  # crlint: allow-<rule>(why this one is fine)

Run locally::

    python -m cockroach_tpu.lint cockroach_tpu scripts

This package imports only the stdlib (no jax) so it runs anywhere, fast.
"""

from __future__ import annotations

from .core import Finding, SourceFile, load_files, run_lint  # noqa: F401
