"""raw-jit pass: every jit entry point routes through flow/dispatch.jit.

flow/dispatch.py wraps ``jax.jit`` so every call of a compiled kernel bumps
``sql_kernel_dispatches`` — the metric the dispatch-budget guard
(scripts/check_dispatch_budget.py) and EXPLAIN ANALYZE's
``kernel dispatches:`` line are built on. A raw ``jax.jit`` anywhere else
creates kernels invisible to that accounting: the budget guard keeps
passing while real dispatch count regresses. (This is exactly how the
SPMD plane drifted: parallel/{shuffle,dist,planner}.py jitted raw, so
distributed kernels never counted until this pass flagged them.)

Flagged: any reference (call, ``functools.partial`` argument, assignment)
to ``jax.jit``, ``jax.pmap``, ``jax.shard_map``, or those names imported
from jax directly. ``shard_map`` alone is a transform, not an entry point
— it only dispatches once jitted, so it is flagged only as ``jax.shard_map``
reference when used to build a callable outside dispatch.

Exempt: cockroach_tpu/flow/dispatch.py (the wrapper itself). Kernels that
deliberately stay outside flow accounting (storage-plane compaction/MVCC
kernels, the coldata compact helper counted via ``dispatch.note``) carry
``# crlint: allow-raw-jit(<why>)``.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile, attr_chain

RULE = "raw-jit"

EXEMPT = ("cockroach_tpu/lint/", "cockroach_tpu/flow/dispatch.py")
_ENTRY = {("jax", "jit"), ("jax", "pmap"), ("jax", "shard_map")}
_FROM_JAX = {"jit", "pmap"}


def check(src: SourceFile) -> list[Finding]:
    if src.rel.startswith(EXEMPT[0]) or src.rel == EXEMPT[1]:
        return []
    # names imported straight off jax: `from jax import jit as J` binds J
    from_jax: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module == "jax":
            for a in node.names:
                if a.name in _FROM_JAX:
                    from_jax.add(a.asname or a.name)
    out: list[Finding] = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            if chain in _ENTRY:
                out.append(Finding(
                    RULE, src.rel, node.lineno,
                    f"raw {'.'.join(chain)} bypasses flow/dispatch "
                    "accounting — route through dispatch.jit so "
                    "sql_kernel_dispatches and the budget guard see it"))
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id in from_jax:
                out.append(Finding(
                    RULE, src.rel, node.lineno,
                    f"raw jax {node.func.id}() bypasses flow/dispatch "
                    "accounting — route through dispatch.jit"))
    return out
