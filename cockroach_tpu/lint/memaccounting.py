"""mem-accounting pass: hot-path materializations must hit the monitor tree.

PR 8's memory-monitor tree and PR 12's block-cache budget only deliver
their guarantees if large allocations actually route through them. This
pass walks the flow/storage hot-path modules and flags any ``np.*``/
``jnp.*`` materializing constructor whose size cannot be shown small at
lint time, unless the enclosing function — or another method of the same
class (operators reserve in open()/spool and materialize in next()) —
shows accounting evidence: a ``reserve``/``reserve_batch``/``release``/
``note_spill``/``would_exceed`` call, an ``Allocator(...)`` construction,
or a ``flowmem``/``memory`` module reference.

Statically exempt (below the threshold, or already accounted by the
source array's own charge):

- literal shapes whose element product is <= ``SMALL_ELEMS`` (a fixed
  small header/mask buffer is not a budget event);
- literal element lists (``np.array([...])``) — their length is visible;
- shapes taken from an existing array (``x.shape``, ``x.size``,
  ``len(x)``, ``x.capacity``? no — only ``.shape``/``.size``): an
  alloc-like-existing transient duplicates a batch the monitor already
  charged when that batch was reserved.

Everything else is a finding at the call line; waive with
``# crlint: allow-mem-accounting(reason)`` on the line or the def line.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

RULE = "mem-accounting"

# flow/storage hot paths: the modules whose allocations move query- or
# ingest-sized data. Cold paths (planner, catalog, pgwire) stay out of
# scope — their arrays are row-count-of-metadata sized. utils/admission
# stays out too: the serving plane queues WAITERS (events + per-tenant
# scalars, a bounded float list of wait samples), never batches/tiles —
# there is nothing monitor-sized to account.
HOT_PATHS = (
    "cockroach_tpu/flow/operators.py",
    "cockroach_tpu/flow/runtime.py",
    "cockroach_tpu/flow/fuse.py",
    "cockroach_tpu/flow/external.py",
    "cockroach_tpu/ops/merge_join.py",
    "cockroach_tpu/ops/sort.py",
    "cockroach_tpu/parallel/shuffle.py",
    "cockroach_tpu/storage/ingest.py",
    "cockroach_tpu/storage/blockcache.py",
    "cockroach_tpu/storage/lsm.py",
    # the changefeed fan-out plane buffers and coalesces event frames
    # sized by the write stream — its scans and per-subscriber queues
    # must charge the node's changefeed staging account
    "cockroach_tpu/kv/changefeed.py",
    "cockroach_tpu/kv/fanout.py",
    # the matview plane stages delta tiles and rebuilds standing [V, G]
    # state arrays sized by the write stream and the view population —
    # both must charge the matview staging account
    "cockroach_tpu/flow/viewmaint.py",
    "cockroach_tpu/sql/matview.py",
    # the serving-path coalescing planes buffer cross-session state —
    # pending write payloads and shared tile windows — sized by load;
    # both must charge their staging accounts
    "cockroach_tpu/kv/coalesce.py",
    "cockroach_tpu/flow/sharedscan.py",
    # warm-menu compilation workers materialize exemplar batches (one
    # per menu rung) to drive AOT lowering — rung capacities are
    # bucketed but still monitor-sized, so warming must account them
    "cockroach_tpu/sql/warmmenu.py",
)

# materializing constructors: allocate fresh host/device buffers sized by
# their arguments. Views/wrappers (asarray on an ndarray, reshape) and
# elementwise math are not listed — they don't create unaccounted bytes.
_CTORS = {
    "zeros", "empty", "ones", "full", "arange", "concatenate", "stack",
    "vstack", "hstack", "tile", "repeat", "fromiter", "array",
}
# NOT listed: frombuffer (zero-copy view over an existing buffer) and
# asarray (no copy when the input is already an ndarray)

_EVIDENCE_CALLS = {
    "reserve", "reserve_batch", "release", "note_spill", "would_exceed",
    "staged", "staging_monitor", "charge_object",
}

SMALL_ELEMS = 4096  # literal shapes up to this many elements are exempt


def _literal_elems(node: ast.AST) -> int | None:
    """Element count if the shape/content argument is fully literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return max(node.value, 0)
    if isinstance(node, (ast.Tuple, ast.List)):
        if not node.elts:
            return 0
        total = 1
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                total *= max(e.value, 0)
            elif isinstance(e, ast.Constant):
                # literal element list: np.array([1.0, "x"]) — count is
                # the list length, already folded in via the loop count
                return len(node.elts)
            else:
                return None
        return total
    return None


def _shape_of_existing(node: ast.AST) -> bool:
    """True for ``x.shape`` / ``x.shape[0]`` / ``x.size`` / ``len(x)`` —
    an allocation sized like an array that already exists (and was
    charged when its batch was reserved)."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and node.attr in ("shape", "size"):
        return True
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "len" and node.args):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return bool(node.elts) and all(
            _shape_of_existing(e) or _literal_elems(e) is not None
            for e in node.elts)
    return False


def _is_exempt(call: ast.Call) -> bool:
    if not call.args:
        return True  # np.array() etc. — degenerate, empty
    first = call.args[0]
    n = _literal_elems(first)
    if n is not None and n <= SMALL_ELEMS:
        return True
    if _shape_of_existing(first):
        return True
    # np.full(shape, fill): shape is the size-bearing arg — handled above;
    # np.arange(stop) literal:
    if (isinstance(first, ast.Constant) and isinstance(first.value, int)
            and first.value <= SMALL_ELEMS):
        return True
    return False


def _is_jitted(fn: ast.AST) -> bool:
    """jnp ctors inside a ``@jax.jit`` kernel are XLA temporaries fused
    into the compiled program — the monitor charges the kernel's output
    batch at the operator boundary, not each traced intermediate."""
    from .core import attr_chain

    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        chain = attr_chain(target)
        if chain and chain[-2:] == ("jax", "jit"):
            return True
        if (chain and chain[-1] == "partial" and isinstance(dec, ast.Call)
                and dec.args):
            inner = attr_chain(dec.args[0])
            if inner and inner[-2:] == ("jax", "jit"):
                return True
    return False


def _materializations(fn: ast.AST) -> list[ast.Call]:
    out = []
    for sub in ast.walk(fn):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id in ("np", "jnp", "numpy")
                and sub.func.attr in _CTORS
                and not _is_exempt(sub)):
            out.append(sub)
    return out


def _has_evidence(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Attribute) and f.attr in _EVIDENCE_CALLS:
                return True
            if isinstance(f, ast.Name) and f.id == "Allocator":
                return True
        if isinstance(sub, ast.Name) and sub.id in ("flowmem", "memory"):
            return True
        if (isinstance(sub, ast.Attribute) and isinstance(sub.value, ast.Name)
                and sub.value.id in ("flowmem",)):
            return True
    return False


def check(file: SourceFile) -> list[Finding]:
    if file.rel not in HOT_PATHS:
        return []
    findings: list[Finding] = []

    def walk(body, cls: str | None, class_evidence: bool):
        for node in body:
            if isinstance(node, ast.ClassDef):
                evid = any(_has_evidence(m) for m in node.body
                           if isinstance(m, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)))
                walk(node.body, node.name, evid)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _is_jitted(node):
                    continue
                mats = _materializations(node)
                if not mats:
                    continue
                if _has_evidence(node) or class_evidence:
                    continue
                where = f"{cls}.{node.name}" if cls else node.name
                for call in mats:
                    findings.append(Finding(
                        RULE, file.rel, call.lineno,
                        f"{file.modname}.{where} materializes "
                        f"{call.func.value.id}.{call.func.attr} with a "
                        "non-small shape on a flow/storage hot path with "
                        "no accounting evidence (reserve/Allocator/"
                        "flowmem) in the function or its class; charge it "
                        "to the monitor tree or waive with "
                        "allow-mem-accounting(reason)",
                    ))
    walk(file.tree.body, None, False)
    return findings
