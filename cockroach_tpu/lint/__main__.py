"""CLI: ``python -m cockroach_tpu.lint [--json] [--rule R ...] paths...``

Exit codes (the contract CI and editors key on):

* **0** — clean: every selected pass ran, no unsuppressed finding.
* **1** — findings: the tree violates an invariant (or waives one
  without a reason).
* **2** — internal error: the linter itself failed to run (unparseable
  file, unreadable path, bad arguments) — distinct from 1 so a wrapper
  can tell "the gate failed" from "the gate is broken".

``--changed-only FILE`` reads a newline-separated path list (typically
``git diff --name-only``) and reports only findings landing in those
files. The WHOLE path set is still linted — tree rules (lock-order,
shared-state, fault-coverage, untimed-wait, race-coverage) need the
full cross-module graph to be sound — only the report is filtered, so a
pre-commit hook gets correct findings fast without a pass silently
reasoning over half a program. ``--changed-only --git`` skips the file:
the changed set is computed directly from ``git diff --name-only HEAD``
(staged + unstaged) in the current repo.

``--timings`` prints per-pass wall seconds (plus the shared load/parse
step) to stderr — the budget the shared TreeCache defends.

``--race-map`` prints the race-coverage field↔site map — every shared
state the whole-program analysis sees with its coverage status
(locked / instrumented / atomic-publish / UNCOVERED / ...) — and exits
0; findings still come from the normal pass.
"""

from __future__ import annotations

import argparse
import pathlib
import subprocess
import sys

from .core import ALL_RULES, report_json, report_text, run_lint


def _changed_set(list_path: str) -> set[str]:
    """Posix-normalized path suffixes from a git-diff-style file list
    (blank lines and non-.py entries dropped)."""
    out = set()
    for line in pathlib.Path(list_path).read_text().splitlines():
        line = line.strip()
        if line and line.endswith(".py"):
            out.add(pathlib.PurePath(line).as_posix())
    return out


def _git_changed_set() -> set[str]:
    """Changed .py files straight from git: staged + unstaged vs HEAD,
    plus untracked — the exact set a pre-commit hook cares about."""
    out: set[str] = set()
    has_head = subprocess.run(
        ["git", "rev-parse", "--verify", "-q", "HEAD"],
        capture_output=True, timeout=30).returncode == 0
    # unborn branch (no commits yet): everything tracked is new
    diff_cmd = (["git", "diff", "--name-only", "HEAD"] if has_head
                else ["git", "ls-files"])
    for cmd in (diff_cmd,
                ["git", "ls-files", "--others", "--exclude-standard"]):
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=30)
        if res.returncode != 0:
            raise RuntimeError(
                f"{' '.join(cmd)} failed: {res.stderr.strip()}")
        for line in res.stdout.splitlines():
            line = line.strip()
            if line and line.endswith(".py"):
                out.add(pathlib.PurePath(line).as_posix())
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cockroach_tpu.lint",
        description="crlint: repo-specific AST static analysis")
    ap.add_argument("paths", nargs="+",
                    help="files or directories to lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings (stable file:line "
                         "order)")
    ap.add_argument("--rule", action="append", choices=ALL_RULES,
                    help="run only this rule (repeatable)")
    ap.add_argument("--changed-only", metavar="FILE", nargs="?",
                    const="", default=None,
                    help="newline-separated path list; lint everything "
                         "but report only findings in these files "
                         "(with --git the list comes from git itself)")
    ap.add_argument("--git", action="store_true",
                    help="with --changed-only: take the changed set "
                         "from 'git diff --name-only HEAD' + untracked "
                         "files instead of a list file")
    ap.add_argument("--timings", action="store_true",
                    help="print per-pass wall seconds to stderr")
    ap.add_argument("--race-map", action="store_true", dest="race_map",
                    help="print the race-coverage field↔site map and "
                         "exit 0 (no findings report)")
    args = ap.parse_args(argv)
    if args.changed_only == "" and not args.git:
        print("crlint: --changed-only needs a FILE (or --git)",
              file=sys.stderr)
        return 2
    try:
        if args.race_map:
            from .core import TreeCache, load_files
            from .racecoverage import coverage_map, render_map

            files = load_files(args.paths)
            print(render_map(coverage_map(files, TreeCache(files))))
            return 0
        timings: dict[str, float] = {}
        findings = run_lint(args.paths,
                            tuple(args.rule) if args.rule else None,
                            timings=timings)
        if args.changed_only is not None:
            changed = (_git_changed_set() if args.git
                       else _changed_set(args.changed_only))
            findings = [f for f in findings
                        if f.path in changed
                        or any(c.endswith("/" + f.path) for c in changed)]
    except Exception as e:
        # the linter failing to run is NOT a finding — exit 2 so CI can
        # distinguish a broken gate from a dirty tree
        print(f"crlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.timings:
        width = max((len(k) for k in timings), default=0)
        for name, secs in sorted(timings.items(),
                                 key=lambda kv: -kv[1]):
            print(f"  {name:<{width}}  {secs:7.3f}s", file=sys.stderr)
        print(f"  {'total':<{width}}  {sum(timings.values()):7.3f}s",
              file=sys.stderr)
    if args.as_json:
        print(report_json(findings))
    elif findings:
        print(report_text(findings), file=sys.stderr)
    else:
        rules = ", ".join(args.rule) if args.rule else "all rules"
        print(f"crlint clean ({rules}) over {', '.join(args.paths)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
