"""CLI: ``python -m cockroach_tpu.lint [--json] [--rule R ...] paths...``

Exit 0 when clean, 1 when any unsuppressed finding survives — the same
contract as scripts/check_lint.py, which wires this into tier-1.
"""

from __future__ import annotations

import argparse
import sys

from .core import ALL_RULES, report_json, report_text, run_lint


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cockroach_tpu.lint",
        description="crlint: repo-specific AST static analysis")
    ap.add_argument("paths", nargs="+",
                    help="files or directories to lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--rule", action="append", choices=ALL_RULES,
                    help="run only this rule (repeatable)")
    args = ap.parse_args(argv)
    findings = run_lint(args.paths,
                        tuple(args.rule) if args.rule else None)
    if args.as_json:
        print(report_json(findings))
    elif findings:
        print(report_text(findings), file=sys.stderr)
    else:
        rules = ", ".join(args.rule) if args.rule else "all rules"
        print(f"crlint clean ({rules}) over {', '.join(args.paths)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
