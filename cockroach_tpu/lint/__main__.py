"""CLI: ``python -m cockroach_tpu.lint [--json] [--rule R ...] paths...``

Exit codes (the contract CI and editors key on):

* **0** — clean: every selected pass ran, no unsuppressed finding.
* **1** — findings: the tree violates an invariant (or waives one
  without a reason).
* **2** — internal error: the linter itself failed to run (unparseable
  file, unreadable path, bad arguments) — distinct from 1 so a wrapper
  can tell "the gate failed" from "the gate is broken".

``--changed-only FILE`` reads a newline-separated path list (typically
``git diff --name-only``) and reports only findings landing in those
files. The WHOLE path set is still linted — tree rules (lock-order,
shared-state, fault-coverage) need the full cross-module graph to be
sound — only the report is filtered, so a pre-commit hook gets correct
findings fast without a pass silently reasoning over half a program.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

from .core import ALL_RULES, report_json, report_text, run_lint


def _changed_set(list_path: str) -> set[str]:
    """Posix-normalized path suffixes from a git-diff-style file list
    (blank lines and non-.py entries dropped)."""
    out = set()
    for line in pathlib.Path(list_path).read_text().splitlines():
        line = line.strip()
        if line and line.endswith(".py"):
            out.add(pathlib.PurePath(line).as_posix())
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cockroach_tpu.lint",
        description="crlint: repo-specific AST static analysis")
    ap.add_argument("paths", nargs="+",
                    help="files or directories to lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings (stable file:line "
                         "order)")
    ap.add_argument("--rule", action="append", choices=ALL_RULES,
                    help="run only this rule (repeatable)")
    ap.add_argument("--changed-only", metavar="FILE",
                    help="newline-separated path list; lint everything "
                         "but report only findings in these files")
    args = ap.parse_args(argv)
    try:
        findings = run_lint(args.paths,
                            tuple(args.rule) if args.rule else None)
        if args.changed_only:
            changed = _changed_set(args.changed_only)
            findings = [f for f in findings
                        if f.path in changed
                        or any(c.endswith("/" + f.path) for c in changed)]
    except Exception as e:
        # the linter failing to run is NOT a finding — exit 2 so CI can
        # distinguish a broken gate from a dirty tree
        print(f"crlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2
    if args.as_json:
        print(report_json(findings))
    elif findings:
        print(report_text(findings), file=sys.stderr)
    else:
        rules = ", ".join(args.rule) if args.rule else "all rules"
        print(f"crlint clean ({rules}) over {', '.join(args.paths)}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
