"""untimed-wait pass: blocking calls on serving threads carry deadlines.

Three PRs in a row shipped the same liveness bug: an untimed blocking
call that held a control-plane or serving thread forever (the dead-socket
``_tail`` loop PR 17 replaced, the admission timeout/grant race PR 8
fixed). Go-side CockroachDB leans on contexts — every RPC, every
condition wait sits under a ``context.Context`` deadline; this pass is
the static analog for our threaded plane:

1. reuse the whole-program thread analysis ``lint/sharedstate.py``
   builds (entry points, call graph, reachability — shared through
   ``core.TreeCache``, so the graph is computed once per lint run);
2. in every function reachable from a thread entry point, flag each
   **potentially-unbounded blocking primitive**:

   - ``x.wait()`` / ``x.wait_for(pred)`` with no timeout (Condition,
     Event);
   - ``q.get()`` / ``q.get(True)`` on a queue-typed receiver with no
     timeout;
   - ``t.join()`` with no timeout;
   - ``sock.recv(...)`` / ``sock.accept()`` with no deadline evidence —
     a ``settimeout(...)`` in the same function or class, or a
     ``utils/retry`` wrapper (``retry.call`` / ``Backoff``) driving it;
   - ``socket.create_connection(addr)`` without a ``timeout`` (the
     connect itself blocks long before any settimeout can apply);
   - bare ``lock.acquire()`` on a recognized lock with neither a
     timeout nor ``blocking=False``.

The contract mirrors the runtime one: a blocking call on a thread the
serving plane depends on must have a bound, after which the caller
either retries (utils/retry), reaps the peer, or surfaces a typed
error. Sites that legitimately block forever — a persistent-protocol
server loop parked on an idle client whose teardown story is "close()
severs the socket" — carry a reasoned
``# crlint: allow-untimed-wait(<why + who unblocks it>)`` pragma.

Scope: ``cockroach_tpu/`` except ``bench/`` (load generators are
clients of the system under test; a stuck bench worker fails the bench
run loudly and holds no serving thread hostage).
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile, attr_chain
from .lockorder import FuncKey
from .sharedstate import program

RULE = "untimed-wait"

_SKIP_PREFIXES = ("cockroach_tpu/bench/",)

# queue constructors whose .get() blocks (Counter etc. stay out: their
# .get() is dict.get)
_QUEUE_CTORS = {
    ("queue", "Queue"), ("queue", "SimpleQueue"), ("queue", "LifoQueue"),
    ("queue", "PriorityQueue"),
}

# receivers whose .wait()/.recv() are not thread blocking primitives
_NON_BLOCKING_BASES = {"os", "signal", "subprocess"}


def _kw(call: ast.Call, name: str) -> ast.AST | None:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _own_nodes(fn: ast.AST) -> list[ast.AST]:
    """The function's body nodes, EXCLUDING nested def/lambda bodies —
    those are separate functions with their own reachability."""
    out: list[ast.AST] = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


def _queue_ctor(value: ast.AST) -> bool:
    for n in ast.walk(value):
        if isinstance(n, ast.Call):
            chain = attr_chain(n.func)
            if chain and chain[-2:] in _QUEUE_CTORS:
                return True
    return False


def _queue_names(nodes: list[ast.AST]) -> set[str]:
    """Local names bound to a queue constructor within these nodes."""
    out: set[str] = set()
    for n in nodes:
        if isinstance(n, ast.Assign) and _queue_ctor(n.value):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _class_queue_attrs(src: SourceFile, cls: str) -> set[str]:
    """self-attrs of ``cls`` assigned a queue constructor anywhere in the
    class body."""
    out: set[str] = set()
    for node in src.tree.body:
        if not (isinstance(node, ast.ClassDef) and node.name == cls):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and _queue_ctor(sub.value):
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
    return out


def _deadline_evidence(nodes: list[ast.AST]) -> bool:
    """A socket deadline or retry-wrapper reference: ``settimeout(x)``
    with a non-None bound, ``create_connection(..., timeout=...)``, or a
    ``utils/retry`` policy (``retry.call`` / ``Backoff``)."""
    for n in nodes:
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Attribute) and f.attr == "settimeout":
            if n.args and not (isinstance(n.args[0], ast.Constant)
                               and n.args[0].value is None):
                return True
        chain = attr_chain(f)
        if chain and chain[-2:] == ("retry", "call"):
            return True
        if chain and chain[-1] == "Backoff":
            return True
        if chain and chain[-1] == "create_connection" \
                and _kw(n, "timeout") is not None:
            return True
    return False


def _receiver_base(f: ast.Attribute) -> str | None:
    node = f.value
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _fn_sites(fn_key: FuncKey, fn: ast.AST, src: SourceFile,
              idx, class_evidence: bool,
              class_queues: set[str]) -> list[tuple[int, str]]:
    """(line, message) blocking findings inside one function body."""
    nodes = _own_nodes(fn)
    fn_evidence = _deadline_evidence(nodes)
    local_queues = _queue_names(nodes)
    rel, cls, name = fn_key
    where = f"{src.modname}.{(cls + '.') if cls else ''}{name}"
    out: list[tuple[int, str]] = []
    for n in nodes:
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        chain = attr_chain(f)
        # socket.create_connection(addr) with no timeout: the CONNECT
        # blocks on the kernel's own (minutes-long) timeout
        if chain and chain[-1] == "create_connection":
            if _kw(n, "timeout") is None and len(n.args) < 2:
                out.append((n.lineno,
                            f"{where} dials with create_connection() and "
                            "no timeout on a serving thread — a black-"
                            "holed peer blocks the connect for the "
                            "kernel's own timeout (minutes); pass "
                            "timeout=, or waive with "
                            "allow-untimed-wait(reason)"))
            continue
        if not isinstance(f, ast.Attribute):
            continue
        base = _receiver_base(f)
        if base in _NON_BLOCKING_BASES:
            continue
        attr = f.attr
        if attr == "wait":
            if not n.args and _kw(n, "timeout") is None:
                out.append((n.lineno,
                            f"{where} calls .wait() with no timeout on a "
                            "serving thread — a lost wakeup parks the "
                            "thread forever; pass a timeout and loop, or "
                            "waive with allow-untimed-wait(reason)"))
        elif attr == "wait_for":
            if len(n.args) < 2 and _kw(n, "timeout") is None:
                out.append((n.lineno,
                            f"{where} calls .wait_for() with no timeout "
                            "on a serving thread — pass timeout= (the "
                            "predicate re-check loop already handles "
                            "spurious wakeups), or waive with "
                            "allow-untimed-wait(reason)"))
        elif attr == "get":
            recv_is_queue = False
            if (isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id == "self"):
                recv_is_queue = f.value.attr in class_queues
            elif isinstance(f.value, ast.Name):
                recv_is_queue = (f.value.id in local_queues
                                 or f.value.id in _module_queue_names(src))
            if not recv_is_queue:
                continue
            block_arg = n.args[0] if n.args else None
            nonblocking = (isinstance(block_arg, ast.Constant)
                           and block_arg.value is False) or (
                isinstance(_kw(n, "block"), ast.Constant)
                and _kw(n, "block").value is False)
            timed = _kw(n, "timeout") is not None or len(n.args) >= 2
            if not nonblocking and not timed:
                out.append((n.lineno,
                            f"{where} calls Queue.get() with no timeout "
                            "on a serving thread — if every producer "
                            "dies the consumer hangs forever; pass "
                            "timeout= and re-check liveness per tick, or "
                            "waive with allow-untimed-wait(reason)"))
        elif attr == "join":
            if not n.args and not n.keywords:
                out.append((n.lineno,
                            f"{where} calls .join() with no timeout on a "
                            "serving thread — a wedged child holds this "
                            "thread with it; pass timeout= and surface "
                            "the straggler, or waive with "
                            "allow-untimed-wait(reason)"))
        elif attr in ("recv", "recv_into", "recvfrom", "accept"):
            if not fn_evidence and not class_evidence:
                out.append((n.lineno,
                            f"{where} blocks in socket .{attr}() with no "
                            "deadline evidence (no settimeout/"
                            "create_connection(timeout=)/utils-retry in "
                            "the function or its class) on a serving "
                            "thread — a silent peer parks the thread "
                            "forever; set a socket timeout, or waive "
                            "with allow-untimed-wait(reason)"))
        elif attr == "acquire":
            lock = None
            if (isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id == "self" and cls):
                lock = idx.class_locks.get(cls, {}).get(f.value.attr)
            elif isinstance(f.value, ast.Name):
                lock = idx.mod_locks.get(f.value.id)
            if lock is None:
                continue
            first = n.args[0] if n.args else None
            nonblocking = isinstance(first, ast.Constant) \
                and first.value is False
            blocking_kw = _kw(n, "blocking")
            if isinstance(blocking_kw, ast.Constant) \
                    and blocking_kw.value is False:
                nonblocking = True
            timed = _kw(n, "timeout") is not None or len(n.args) >= 2
            if not nonblocking and not timed:
                out.append((n.lineno,
                            f"{where} bare-acquires {lock} with no "
                            "timeout on a serving thread — use a with "
                            "block where possible, or acquire(timeout=) "
                            "and handle the miss, or waive with "
                            "allow-untimed-wait(reason)"))
    return out


def _module_queue_names(src: SourceFile) -> set[str]:
    out: set[str] = set()
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and _queue_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def check(files: list[SourceFile], cache=None) -> list[Finding]:
    prog = program(files, cache)
    if prog is None:
        return []
    by_rel = {f.rel: f for f in files}
    thread_funcs = prog.thread_funcs() | prog.entries

    # class-level deadline evidence, computed lazily per (rel, cls)
    evid_memo: dict[tuple[str, str | None], bool] = {}
    queue_memo: dict[tuple[str, str | None], set] = {}

    def class_evidence(src: SourceFile, cls: str | None) -> bool:
        key = (src.rel, cls)
        if key not in evid_memo:
            found = False
            if cls is not None:
                for node in src.tree.body:
                    if isinstance(node, ast.ClassDef) and node.name == cls:
                        found = _deadline_evidence(list(ast.walk(node)))
            evid_memo[key] = found
        return evid_memo[key]

    def class_queues(src: SourceFile, cls: str | None) -> set:
        key = (src.rel, cls)
        if key not in queue_memo:
            queue_memo[key] = (_class_queue_attrs(src, cls)
                               if cls is not None else set())
        return queue_memo[key]

    out: list[Finding] = []
    for fk in sorted(thread_funcs, key=str):
        rec = prog.funcs.get(fk)
        if rec is None or rec.node is None:
            continue
        rel, cls, _name = fk
        if rel.startswith(_SKIP_PREFIXES):
            continue
        src = by_rel.get(rel)
        if src is None:
            continue
        idx = cache.index(src) if cache is not None else None
        if idx is None:
            from .lockorder import _ModuleIndex
            idx = _ModuleIndex(src)
        for line, msg in _fn_sites(fk, rec.node, src, idx,
                                   class_evidence(src, cls),
                                   class_queues(src, cls)):
            out.append(Finding(RULE, rel, line, msg))
    # one finding per site even when a function is reachable from many
    # entries (FuncKeys are unique, but nested defs can alias lines)
    seen: set = set()
    uniq: list[Finding] = []
    for fd in sorted(out, key=lambda f: (f.path, f.line, f.message)):
        k = (fd.path, fd.line, fd.message)
        if k not in seen:
            seen.add(k)
            uniq.append(fd)
    return uniq
