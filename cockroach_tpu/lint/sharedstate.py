"""shared-state pass: whole-program race detection for the control plane.

The threaded control plane (RPC accept/conn loops, lease/liveness loops,
gossip, changefeed handshakes, the metrics scraper, the plan-cache warmup
thread, per-consumer spool pulls) shares mutable state with the serving
path. Go-side CockroachDB runs every test under TSan; this pass is the
static half of our analogue (utils/racesan.py is the runtime half):

1. enumerate **thread entry points**: ``threading.Thread(target=f)`` /
   ``Timer`` / ``executor.submit(f)`` targets, including nested ``def``
   closures handed to Thread and one level of *spawn brokers* (a function
   that passes its own parameter as a Thread target — ``Node._spawn`` —
   makes every resolvable argument at its call sites an entry);
2. build the cross-module call graph (same resolution as the lock-order
   pass: ``self.m()``, module functions, package imports — plus
   attribute-type inference: ``self.liveness = NodeLiveness(...)`` in
   ``__init__`` lets ``self.liveness.heartbeat()`` resolve), and close
   reachability from every entry;
3. record every **mutable-state access** — ``self.attr`` writes (rebind,
   augmented, subscript store/del, known mutator-method calls) and reads,
   plus module-global rebinds/mutations — with the lock set held at the
   site (with-stack locks plus *always-held* locks inferred over the
   call graph: a method only ever called under ``self.mu`` is guarded);
4. flag state with a write/write or write/read pair reachable from two
   DIFFERENT entry points (the main thread counts as one) whose locksets
   are disjoint.

Not flagged (the documented-safe patterns):

- construction: accesses inside ``__init__``/``__post_init__``/``__new__``
  happen before the object is published to any thread;
- **GIL-atomic publish**: state whose every non-init write is a plain
  ``self.x = value`` rebind where ``value`` never reads ``self.x`` (no
  read-modify-write, no container mutation anywhere). A single STORE_ATTR
  is atomic under the GIL; stale reads of a flag/socket/thread handle are
  the pattern's contract (``self._srv``, ``self._thread = None``);
- lock/event objects themselves (they synchronize; they are not data);
- anything under a common recognized lock at every conflicting site.

Suppression: ``# crlint: allow-shared-state(<why>)`` on the flagged write
line, on the enclosing ``def`` line, or on ANY access site of the state —
including its ``__init__`` assignment, which is the ergonomic place to
document a deliberately lock-free structure once.

Scope: ``cockroach_tpu/`` only. Test trees spawn scenario-local threads
constantly; the invariant guarded here is the production control plane.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Finding, SourceFile
from .lockorder import (FuncKey, _is_lock_ctor, _ModuleIndex,
                        _resolve_imports, attr_chain)

RULE = "shared-state"

# mutating container/collection methods: a call self.x.m(...) with m here
# is a WRITE to x. Deliberately excludes queue.Queue's put/get/task_done
# (thread-safe by contract) and threading.Event's set/clear/wait.
_MUTATORS = {
    "append", "appendleft", "add", "update", "pop", "popleft", "popitem",
    "clear", "extend", "extendleft", "remove", "discard", "insert",
    "setdefault", "sort", "reverse", "rotate",
}
# constructors whose instances synchronize internally — attributes holding
# them are not data races even when poked from several threads
_THREADSAFE_CTORS = {
    ("threading", "Event"), ("threading", "Semaphore"),
    ("threading", "BoundedSemaphore"), ("threading", "Barrier"),
    ("threading", "local"), ("queue", "Queue"), ("queue", "SimpleQueue"),
    ("queue", "LifoQueue"), ("queue", "PriorityQueue"),
    ("collections", "Counter"),
}
_INIT_FUNCS = {"__init__", "__post_init__", "__new__", "__init_subclass__"}
_MAIN = "<main>"


@dataclass(frozen=True)
class Access:
    state: str          # <module>.<Class>.<attr> or <module>.<global>
    kind: str           # 'w' | 'r'
    wkind: str          # 'rebind' | 'aug' | 'store' | 'mut' | '' (reads)
    func: FuncKey
    lockset: tuple[str, ...]
    rel: str
    line: int
    rmw: bool = False   # write whose value expression reads the state
    in_init: bool = False


@dataclass
class _FnRec:
    key: FuncKey
    # callee -> list of (held locks, line, positional arg resolutions)
    calls: list = field(default_factory=list)
    accesses: list = field(default_factory=list)
    spawns: list = field(default_factory=list)  # resolved FuncKey targets
    # Thread target was one of our own parameters: (param index, name)
    broker_params: list = field(default_factory=list)
    # the function's AST (consumed by passes that re-walk reachable
    # bodies — lint/blocking.py scans these for blocking primitives)
    node: ast.AST | None = None


def _threadsafe_attr(value: ast.AST) -> bool:
    for n in ast.walk(value):
        if isinstance(n, ast.Call):
            chain = attr_chain(n.func)
            if chain and chain[-2:] in _THREADSAFE_CTORS:
                return True
    return False


class _Collector(ast.NodeVisitor):
    """One pass over a function body: accesses + calls + spawns, with the
    lock-held stack maintained exactly like lockorder._FuncWalker."""

    def __init__(self, idx: _ModuleIndex, cls: str | None,
                 imports: dict[str, str],
                 class_imports: dict[str, tuple[str, str]],
                 attr_types: dict[str, tuple[str, str]],
                 rec: _FnRec, params: list[str],
                 nested: dict[str, FuncKey],
                 out_nested: list,
                 safe_attrs: frozenset = frozenset()):
        self.idx = idx
        self.cls = cls
        self.imports = imports
        self.class_imports = class_imports
        self.attr_types = attr_types  # self-attr -> (module rel, Class)
        self.rec = rec
        self.params = params
        self.nested = nested          # local def name -> pseudo FuncKey
        self.out_nested = out_nested  # (name, node) nested defs to walk
        self.safe_attrs = safe_attrs  # attrs holding Event/Queue/... objects
        self.held: list[str] = []
        self.mod = idx.src.modname
        self.in_init = rec.key[2].split(".")[-1] in _INIT_FUNCS

    # -- naming ---------------------------------------------------------------

    def _state_of(self, expr: ast.AST) -> str | None:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.cls):
            if expr.attr in self.idx.class_locks.get(self.cls, {}):
                return None  # the lock itself is not data
            if expr.attr in self.safe_attrs:
                return None  # Event/Queue/...: synchronizes internally
            return f"{self.mod}.{self.cls}.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in self.idx.mod_globals:
            return f"{self.mod}.{expr.id}"
        return None

    def _lock_of(self, expr: ast.AST) -> str | None:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.cls):
            return self.idx.class_locks.get(self.cls, {}).get(expr.attr)
        if isinstance(expr, ast.Name):
            return self.idx.mod_locks.get(expr.id)
        return None

    def _note(self, state: str, kind: str, wkind: str, node: ast.AST,
              rmw: bool = False) -> None:
        self.rec.accesses.append(Access(
            state, kind, wkind, self.rec.key, tuple(self.held),
            self.idx.src.rel, node.lineno, rmw=rmw, in_init=self.in_init))

    def _reads_state(self, state: str, expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if self._state_of(n) == state:
                return True
        return False

    # -- function references (spawn targets, broker args) ---------------------

    def _func_ref(self, expr: ast.AST) -> FuncKey | None:
        rel = self.idx.src.rel
        if isinstance(expr, ast.Name):
            if expr.id in self.nested:
                return self.nested[expr.id]
            if expr.id in self.idx.functions:
                return (rel, None, expr.id)
            return None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                          ast.Name):
            if (expr.value.id == "self" and self.cls
                    and expr.attr in self.idx.methods.get(self.cls, {})):
                return (rel, self.cls, expr.attr)
            target = self.imports.get(expr.value.id)
            if target is not None:
                return (target, None, expr.attr)
        return None

    def _callee_of(self, call: ast.Call) -> FuncKey | None:
        f = call.func
        rel = self.idx.src.rel
        direct = self._func_ref(f)
        if direct is not None:
            return direct
        # self.<attr>.<m>() through __init__-inferred attribute types
        if (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"):
            typed = self.attr_types.get(f.value.attr)
            if typed is not None:
                return (typed[0], typed[1], f.attr)
        # ClassName(...) / mod.ClassName(...) constructor -> __init__
        if isinstance(f, ast.Name) and f.id in self.class_imports:
            mod_rel, cname = self.class_imports[f.id]
            return (mod_rel, cname, "__init__")
        if isinstance(f, ast.Name) and f.id in self.idx.methods:
            return (rel, f.id, "__init__")
        return None

    # -- visitors -------------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                self.held.append(lock)
                acquired.append(lock)
            else:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._write_target(t, node.value)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._write_target(node.target, node.value)
            self.visit(node.value)

    def _write_target(self, t: ast.AST, value: ast.AST) -> None:
        state = self._state_of(t)
        if state is not None:
            if _is_lock_ctor(value) or _threadsafe_attr(value):
                # assigning a synchronizer: structural, not data
                self._note(state, "w", "rebind", t, rmw=False)
                return
            self._note(state, "w", "rebind", t,
                       rmw=self._reads_state(state, value))
            return
        if isinstance(t, ast.Subscript):
            st = self._state_of(t.value)
            if st is not None:
                self._note(st, "w", "store", t)
                return
        if isinstance(t, (ast.Tuple, ast.List)):
            for elt in t.elts:
                self._write_target(elt, value)
            return
        self.visit(t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        state = self._state_of(node.target)
        if state is not None:
            self._note(state, "w", "aug", node, rmw=True)
        elif isinstance(node.target, ast.Subscript):
            st = self._state_of(node.target.value)
            if st is not None:
                self._note(st, "w", "store", node, rmw=True)
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                st = self._state_of(t.value)
                if st is not None:
                    self._note(st, "w", "store", t)
                    continue
            st = self._state_of(t)
            if st is not None:
                self._note(st, "w", "rebind", t)
                continue
            self.visit(t)

    def visit_Call(self, node: ast.Call) -> None:
        # spawn sites: Thread/Timer(target=...) and executor.submit(f, ...)
        chain = attr_chain(node.func)
        target_expr = None
        if (chain and chain[-1] in ("Thread", "Timer")) or (
                isinstance(node.func, ast.Name)
                and node.func.id in ("Thread", "Timer")):
            for kw in node.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "submit" and node.args:
            target_expr = node.args[0]
        if target_expr is not None:
            ref = self._func_ref(target_expr)
            if ref is not None:
                self.rec.spawns.append(ref)
            elif (isinstance(target_expr, ast.Name)
                    and target_expr.id in self.params):
                self.rec.broker_params.append(
                    self.params.index(target_expr.id))

        # mutator-method write: self.x.append(...)
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            st = self._state_of(f.value)
            if st is not None:
                self._note(st, "w", "mut", node)
                for a in node.args:
                    self.visit(a)
                for kw in node.keywords:
                    self.visit(kw.value)
                return

        callee = self._callee_of(node)
        if callee is not None:
            arg_refs = [self._func_ref(a) for a in node.args]
            self.rec.calls.append(
                (callee, tuple(self.held), node.lineno, arg_refs))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            st = self._state_of(node)
            if st is not None:
                self._note(st, "r", "", node)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            st = self._state_of(node)
            if st is not None:
                self._note(st, "r", "", node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs are separate pseudo-functions (thread closures!):
        # queue them for their own Collector run under the same class
        self.out_nested.append((node.name, node))

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass  # runs later under unknown held state


def _safe_attrs(src: SourceFile) -> dict[str, frozenset]:
    """class -> self-attrs assigned a thread-safe synchronizer ctor
    (Event, Queue, Semaphore ...) anywhere in the class body. Like locks,
    these coordinate threads; their method calls are not data accesses."""
    out: dict[str, frozenset] = {}
    for node in src.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        attrs: set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and _threadsafe_attr(sub.value):
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        attrs.add(t.attr)
        out[node.name] = frozenset(attrs)
    return out


def _mod_globals(src: SourceFile, idx: _ModuleIndex) -> set[str]:
    """Module-level names that some function-scope code REBINDs (via
    ``global``) or that hold a module-level mutable literal mutated
    in functions. Names bound to locks are excluded (they guard)."""
    declared: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Global):
            declared.update(node.names)
    # module-level mutable containers (dict/list/set literals)
    for node in src.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        else:
            continue
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp)) or (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id in ("dict", "list", "set", "deque")):
            for t in targets:
                if isinstance(t, ast.Name):
                    declared.add(t.id)
    declared -= set(idx.mod_locks)
    return declared


def _class_imports(src: SourceFile, files_by_rel: dict[str, SourceFile],
                   indexes: dict[str, "_ModuleIndex"],
                   ) -> dict[str, tuple[str, str]]:
    """alias -> (module rel, ClassName) for package-internal class
    imports (``from .lsm import Engine``)."""
    out: dict[str, tuple[str, str]] = {}
    pkg_dir = "/".join(src.rel.split("/")[:-1])
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        base_parts = pkg_dir.split("/")
        if node.level:
            base_parts = base_parts[:len(base_parts) - (node.level - 1)]
            base = "/".join(base_parts)
            mod = (base + "/" + node.module.replace(".", "/")
                   if node.module else base)
        else:
            mod = (node.module or "").replace(".", "/")
        cand = f"{mod}.py"
        idx = indexes.get(cand)
        if idx is None:
            continue
        for a in node.names:
            if a.name in idx.methods:
                out[a.asname or a.name] = (cand, a.name)
    return out


def _attr_types(cls_node_methods: dict[str, ast.FunctionDef],
                idx: _ModuleIndex,
                class_imports: dict[str, tuple[str, str]],
                rel: str) -> dict[str, tuple[str, str]]:
    """self.attr -> (module rel, Class) inferred from ``self.x = C(...)``
    assignments anywhere in the class (``__init__`` dominates)."""
    out: dict[str, tuple[str, str]] = {}
    for meth in cls_node_methods.values():
        for node in ast.walk(meth):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            f = node.value.func
            typed = None
            if isinstance(f, ast.Name):
                if f.id in class_imports:
                    typed = class_imports[f.id]
                elif f.id in idx.methods:
                    typed = (rel, f.id)
            if typed is None:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    out.setdefault(t.attr, typed)
    return out


def _analyze(files: list[SourceFile], cache=None):
    """Whole-program collection: returns (funcs, entries)."""
    known = {f.rel for f in files}
    files_by_rel = {f.rel: f for f in files}
    indexes: dict[str, _ModuleIndex] = {}
    for f in files:
        if cache is not None:
            idx = cache.index(f)
        else:
            idx = _ModuleIndex(f)
            idx.mod_globals = _mod_globals(f, idx)
        indexes[f.rel] = idx

    funcs: dict[FuncKey, _FnRec] = {}

    def walk_fn(idx: _ModuleIndex, cls: str | None, name: str,
                node: ast.FunctionDef, imports, class_imports, attr_types,
                safe):
        rec = _FnRec((idx.src.rel, cls, name), node=node)
        params = [a.arg for a in node.args.args
                  if a.arg not in ("self", "cls")]
        nested_defs: list = []
        # pre-scan direct children so references resolve forward too
        nested_names = {n.name: (idx.src.rel, cls, f"{name}.{n.name}")
                        for n in ast.walk(node)
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                        and n is not node}
        col = _Collector(idx, cls, imports, class_imports, attr_types,
                         rec, params, nested_names, nested_defs, safe)
        col.generic_visit(node)
        funcs[rec.key] = rec
        for sub_name, sub_node in nested_defs:
            walk_fn(idx, cls, f"{name}.{sub_name}", sub_node,
                    imports, class_imports, attr_types, safe)

    for f in files:
        idx = indexes[f.rel]
        imports = _resolve_imports(f, known)
        class_imports = _class_imports(f, files_by_rel, indexes)
        safe_by_cls = _safe_attrs(f)
        for name, node in idx.functions.items():
            walk_fn(idx, None, name, node, imports, class_imports, {},
                    frozenset())
        for cls, meths in idx.methods.items():
            atypes = _attr_types(meths, idx, class_imports, f.rel)
            for name, node in meths.items():
                walk_fn(idx, cls, name, node, imports, class_imports,
                        atypes, safe_by_cls.get(cls, frozenset()))

    # spawn entries: direct targets + one level of broker indirection
    entries: set[FuncKey] = set()
    brokers: dict[FuncKey, list[int]] = {}
    for key, rec in funcs.items():
        entries.update(rec.spawns)
        if rec.broker_params:
            brokers[key] = rec.broker_params
    for rec in funcs.values():
        for callee, _held, _line, arg_refs in rec.calls:
            for pidx in brokers.get(callee, ()):
                if pidx < len(arg_refs) and arg_refs[pidx] is not None:
                    entries.add(arg_refs[pidx])
    entries &= set(funcs)  # only entries we can see the body of
    return funcs, entries


def _reach(funcs: dict[FuncKey, _FnRec],
           roots: set[FuncKey]) -> dict[FuncKey, set[FuncKey]]:
    """root -> set of functions transitively callable from it."""
    adj: dict[FuncKey, list[FuncKey]] = {
        k: [c for c, _h, _l, _a in rec.calls if c in funcs]
        for k, rec in funcs.items()}
    out: dict[FuncKey, set[FuncKey]] = {}
    for root in roots:
        seen = {root}
        frontier = [root]
        while frontier:
            cur = frontier.pop()
            for nxt in adj.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        out[root] = seen
    return out


def _always_held(funcs: dict[FuncKey, _FnRec],
                 entries: set[FuncKey]) -> dict[FuncKey, frozenset]:
    """Locks held at EVERY call site of a function (interprocedural guard
    inference, decreasing fixpoint). Entries and uncalled functions hold
    nothing on entry."""
    callers: dict[FuncKey, list[tuple[FuncKey, tuple[str, ...]]]] = {}
    for key, rec in funcs.items():
        for callee, held, _line, _args in rec.calls:
            if callee in funcs:
                callers.setdefault(callee, []).append((key, held))
    universe = frozenset(
        lock for rec in funcs.values()
        for a in rec.accesses for lock in a.lockset) | frozenset(
        lock for rec in funcs.values()
        for _c, held, _l, _a in rec.calls for lock in held)
    ah: dict[FuncKey, frozenset] = {}
    for key in funcs:
        if key in entries or key not in callers:
            ah[key] = frozenset()
        else:
            ah[key] = universe
    changed = True
    while changed:
        changed = False
        for key, sites in callers.items():
            if key in entries:
                continue
            new = None
            for caller, held in sites:
                locks_here = frozenset(held) | ah[caller]
                new = locks_here if new is None else (new & locks_here)
            if new is not None and new != ah[key]:
                ah[key] = new
                changed = True
    return ah


@dataclass
class Program:
    """The whole-program thread analysis, computed once per lint run and
    shared (via ``core.TreeCache``) by every graph pass: shared-state,
    untimed-wait (lint/blocking.py) and race-coverage
    (lint/racecoverage.py)."""

    funcs: dict          # FuncKey -> _FnRec
    entries: set         # thread entry points (spawn targets + brokers)
    reach: dict          # entry -> transitively reachable FuncKeys
    ah: dict             # FuncKey -> locks held at every call site
    main_reach: set      # reachable from uncalled non-entry roots
    _ent_memo: dict = field(default_factory=dict)

    def entries_of(self, func: FuncKey) -> frozenset:
        """Entry points (thread roots + ``<main>``) this function runs
        under."""
        hit = self._ent_memo.get(func)
        if hit is None:
            e = {root for root in self.entries if func in self.reach[root]}
            if func in self.main_reach:
                e.add(_MAIN)
            hit = self._ent_memo[func] = frozenset(e)
        return hit

    def thread_funcs(self) -> set:
        """Every function reachable from some thread entry point."""
        out: set = set()
        for seen in self.reach.values():
            out |= seen
        return out

    def lockset(self, a: Access) -> frozenset:
        return frozenset(a.lockset) | self.ah.get(a.func, frozenset())


def program(files: list[SourceFile], cache=None) -> Program | None:
    """Whole-program analysis over the ``cockroach_tpu/`` subset of
    ``files`` (None when it is empty). Memoized on ``cache`` so the
    three graph passes pay for one analysis, not three."""
    def build():
        scoped = [f for f in files if f.rel.startswith("cockroach_tpu/")]
        if not scoped:
            return None
        funcs, entries = _analyze(scoped, cache)
        reach = _reach(funcs, entries)
        ah = _always_held(funcs, entries)
        # main-reachable: functions nobody in-package calls (public API /
        # test surface) that are not thread targets, plus all they reach
        called: set[FuncKey] = set()
        for rec in funcs.values():
            for callee, _h, _l, _a in rec.calls:
                called.add(callee)
        main_roots = {k for k in funcs
                      if k not in called and k not in entries}
        main_reach: set[FuncKey] = set()
        for _root, seen in _reach(funcs, main_roots).items():
            main_reach |= seen
        return Program(funcs, entries, reach, ah, main_reach)
    if cache is not None:
        return cache.memo("sharedstate.program", build)
    return build()


def analyze_shared_state(files: list[SourceFile], cache=None):
    """Returns (conflicts, entries) where conflicts maps a state id to the
    offending (write_access, other_access, entry_a, entry_b) tuple plus
    all access sites — consumed by check() and by tooling that wants the
    objects the pass names (utils/racesan.py's instrumentation list)."""
    prog = program(files, cache)
    if prog is None:
        return {}, set()
    funcs, entries, ah = prog.funcs, prog.entries, prog.ah

    def entries_of(func: FuncKey) -> frozenset:
        return prog.entries_of(func)

    # group accesses by state
    by_state: dict[str, list[Access]] = {}
    for rec in funcs.values():
        for a in rec.accesses:
            by_state.setdefault(a.state, []).append(a)

    conflicts: dict[str, dict] = {}
    for state, accesses in sorted(by_state.items()):
        live = [a for a in accesses if not a.in_init]
        writes = [a for a in live if a.kind == "w"]
        if not writes:
            continue
        # GIL-atomic publish: plain rebinds only, never read-modify-write
        if all(w.wkind == "rebind" and not w.rmw for w in writes):
            continue
        ent_cache: dict[FuncKey, frozenset] = {}

        def ent(a: Access) -> frozenset:
            if a.func not in ent_cache:
                ent_cache[a.func] = entries_of(a.func)
            return ent_cache[a.func]

        def lockset(a: Access) -> frozenset:
            return frozenset(a.lockset) | ah.get(a.func, frozenset())

        hit = None
        for w in writes:
            ew = ent(w)
            if not ew:
                continue
            for a in live:
                if a.kind == "r" and a is w:
                    continue
                ea = ent(a)
                cross = {(x, y) for x in ew for y in ea if x != y}
                if not cross:
                    continue
                if lockset(w) & lockset(a):
                    continue
                if w.kind == "r" and a.kind == "r":
                    continue
                pair = min(cross, key=lambda p: (str(p[0]), str(p[1])))
                hit = (w, a, *sorted(pair, key=str))
                break
            if hit:
                break
        if hit:
            conflicts[state] = {
                "pair": hit, "accesses": accesses,
                "locksets": (lockset(hit[0]), lockset(hit[1])),
            }
    return conflicts, entries


def _fmt_entry(e) -> str:
    if e == _MAIN:
        return "main"
    rel, cls, name = e
    return f"thread:{rel.rsplit('/', 1)[-1]}:{(cls + '.') if cls else ''}" \
           f"{name}"


def check(files: list[SourceFile], cache=None) -> list[Finding]:
    conflicts, _entries = analyze_shared_state(files, cache)
    by_rel = {f.rel: f for f in files}
    out: list[Finding] = []
    for state, info in sorted(conflicts.items()):
        w, a, e1, e2 = info["pair"]
        ls_w, ls_a = info["locksets"]
        # state-wide pragma: a waiver on ANY access site (incl. the
        # __init__ assignment) documents the whole structure once
        waived = False
        for acc in info["accesses"]:
            src = by_rel.get(acc.rel)
            if src is not None and src.allows(RULE, acc.line):
                waived = True
                break
        if waived:
            continue
        def _ls(ls: frozenset) -> str:
            return "{" + ", ".join(sorted(ls)) + "}" if ls else "no locks"
        out.append(Finding(
            RULE, w.rel, w.line,
            f"{state} is written here ({w.wkind}, {_ls(ls_w)}) on "
            f"[{_fmt_entry(e1)}] and "
            f"{'written' if a.kind == 'w' else 'read'} at "
            f"{a.rel}:{a.line} ({_ls(ls_a)}) on [{_fmt_entry(e2)}] with "
            "no common lock — guard both sites with one utils/locks "
            "OrderedLock, restructure to a GIL-atomic publish, or "
            "pragma-waive the documented pattern"))
    return out
