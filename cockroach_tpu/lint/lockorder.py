"""lock-order pass: the cross-module lock acquisition graph must be acyclic.

The distributed plane holds locks across module boundaries — DistSender's
``mu`` wraps lease checks that read liveness records, queue processing
takes range locks while the allocator scans — and the lease-guard work
already hit one real near-deadlock (the sender-lock/intent-wait cycle
documented in ROADMAP). This pass makes the discipline structural:

1. extract every lock **definition**: ``self.x = threading.Lock()`` /
   ``RLock()`` / ``Condition()`` (incl. dataclass
   ``field(default_factory=threading.Lock)``) and the ordered wrappers
   ``locks.lock/rlock/condition(...)`` / ``OrderedLock(...)`` — named
   ``<module>.<Class>.<attr>`` or ``<module>.<name>``;
2. build the per-function **lock-held call graph**: ``with self.x:``
   bodies record which locks are acquired and which functions are called
   while x is held (``self.m()``, same-module ``f()``, and
   ``alias.f()`` through package-relative imports are resolved);
3. close acquisitions over the call graph and emit edge A->B whenever B
   is (transitively) acquired while A is held;
4. fail on any cycle — a cycle is a thread-interleaving away from
   deadlock.

Re-entrant self-edges are excluded (RLock's business, mirrored by the
runtime OrderedLock in utils/locks.py, which enforces the same invariant
dynamically under ``debug.lock_order.enabled``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .core import Finding, SourceFile, attr_chain

RULE = "lock-order"

_LOCK_CTORS = {
    ("threading", "Lock"), ("threading", "RLock"), ("threading", "Condition"),
    ("locks", "lock"), ("locks", "rlock"), ("locks", "condition"),
}
_LOCK_CTOR_NAMES = {"OrderedLock", "OrderedRLock", "OrderedCondition"}

FuncKey = tuple[str, str | None, str]  # (module rel, class | None, func)


@dataclass
class FuncInfo:
    key: FuncKey
    # (lock id, locks held at that acquire, line)
    acquires: list[tuple[str, tuple[str, ...], int]] = field(
        default_factory=list)
    # (callee key, locks held at that call, line)
    calls: list[tuple[FuncKey, tuple[str, ...], int]] = field(
        default_factory=list)


def _is_lock_ctor(value: ast.AST) -> bool:
    for n in ast.walk(value):
        if isinstance(n, ast.Call):
            chain = attr_chain(n.func)
            if chain and chain[-2:] in _LOCK_CTORS:
                return True
            if (isinstance(n.func, ast.Name)
                    and n.func.id in _LOCK_CTOR_NAMES):
                return True
    return False


def _resolve_imports(src: SourceFile,
                     known: set[str]) -> dict[str, str]:
    """alias -> module rel for package-internal module imports."""
    out: dict[str, str] = {}
    pkg_dir = "/".join(src.rel.split("/")[:-1])
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom):
            base_parts = pkg_dir.split("/")
            if node.level:
                base_parts = base_parts[:len(base_parts) - (node.level - 1)]
                base = "/".join(base_parts)
                mod = (base + "/" + node.module.replace(".", "/")
                       if node.module else base)
            else:
                mod = (node.module or "").replace(".", "/")
            for a in node.names:
                cand = f"{mod}/{a.name}.py"
                if cand in known:
                    out[a.asname or a.name] = cand
        elif isinstance(node, ast.Import):
            for a in node.names:
                cand = a.name.replace(".", "/") + ".py"
                if cand in known:
                    out[a.asname or a.name] = cand
    return out


class _ModuleIndex:
    """Per-module symbol tables the function walker resolves against."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.mod_locks: dict[str, str] = {}    # name -> lock id
        self.class_locks: dict[str, dict[str, str]] = {}  # cls -> attr -> id
        self.functions: dict[str, ast.FunctionDef] = {}
        self.methods: dict[str, dict[str, ast.FunctionDef]] = {}
        mod = src.modname
        for node in src.tree.body:
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.mod_locks[t.id] = f"{mod}.{t.id}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                attrs: dict[str, str] = {}
                meths: dict[str, ast.FunctionDef] = {}
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Assign)
                            and _is_lock_ctor(sub.value)):
                        for t in sub.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                attrs[t.attr] = \
                                    f"{mod}.{node.name}.{t.attr}"
                    elif (isinstance(sub, ast.AnnAssign)
                            and sub.value is not None
                            and _is_lock_ctor(sub.value)
                            and isinstance(sub.target, ast.Name)):
                        attrs[sub.target.id] = \
                            f"{mod}.{node.name}.{sub.target.id}"
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        meths[sub.name] = sub
                self.class_locks[node.name] = attrs
                self.methods[node.name] = meths


class _FuncWalker(ast.NodeVisitor):
    def __init__(self, idx: _ModuleIndex, cls: str | None,
                 imports: dict[str, str], info: FuncInfo):
        self.idx = idx
        self.cls = cls
        self.imports = imports
        self.info = info
        self.held: list[str] = []

    def _lock_of(self, expr: ast.AST) -> str | None:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and self.cls):
            return self.idx.class_locks.get(self.cls, {}).get(expr.attr)
        if isinstance(expr, ast.Name):
            return self.idx.mod_locks.get(expr.id)
        return None

    def _callee_of(self, call: ast.Call) -> FuncKey | None:
        f = call.func
        rel = self.idx.src.rel
        if isinstance(f, ast.Name):
            if f.id in self.idx.functions:
                return (rel, None, f.id)
            return None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if (f.value.id == "self" and self.cls
                    and f.attr in self.idx.methods.get(self.cls, {})):
                return (rel, self.cls, f.attr)
            target = self.imports.get(f.value.id)
            if target is not None:
                return (target, None, f.attr)
        return None

    def visit_With(self, node: ast.With) -> None:
        acquired: list[str] = []
        for item in node.items:
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                self.info.acquires.append(
                    (lock, tuple(self.held), item.context_expr.lineno))
                self.held.append(lock)
                acquired.append(lock)
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for lock in reversed(acquired):
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        # explicit lock.acquire() — an acquisition without with-scoping
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"):
            lock = self._lock_of(node.func.value)
            if lock is not None:
                self.info.acquires.append(
                    (lock, tuple(self.held), node.lineno))
        callee = self._callee_of(node)
        if callee is not None:
            self.info.calls.append((callee, tuple(self.held), node.lineno))
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs run later, under unknown held state — skip bodies
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def build_lock_graph(files: list[SourceFile], cache=None):
    """Returns (lock ids, edges) where edges maps (held, acquired) ->
    (file rel, line) of the first site implying that ordering. ``cache``
    (a ``core.TreeCache``) shares the per-module indexes with the other
    graph passes instead of rebuilding them."""
    known = {f.rel for f in files}
    indexes = {f.rel: (cache.index(f) if cache is not None
                       else _ModuleIndex(f)) for f in files}
    funcs: dict[FuncKey, FuncInfo] = {}
    for f in files:
        idx = indexes[f.rel]
        imports = _resolve_imports(f, known)
        for name, node in idx.functions.items():
            info = FuncInfo((f.rel, None, name))
            _FuncWalker(idx, None, imports, info).generic_visit(node)
            funcs[info.key] = info
        for cls, meths in idx.methods.items():
            for name, node in meths.items():
                info = FuncInfo((f.rel, cls, name))
                _FuncWalker(idx, cls, imports, info).generic_visit(node)
                funcs[info.key] = info

    # close "locks acquired by this function, transitively" over calls
    closure: dict[FuncKey, set[str]] = {
        k: {l for l, _, _ in fi.acquires} for k, fi in funcs.items()}
    changed = True
    while changed:
        changed = False
        for k, fi in funcs.items():
            for callee, _, _ in fi.calls:
                extra = closure.get(callee)
                if extra and not extra <= closure[k]:
                    closure[k] |= extra
                    changed = True

    locks: set[str] = set()
    for idx in indexes.values():
        locks.update(idx.mod_locks.values())
        for attrs in idx.class_locks.values():
            locks.update(attrs.values())
    edges: dict[tuple[str, str], tuple[str, int]] = {}

    def add_edge(a: str, b: str, rel: str, line: int) -> None:
        if a != b:  # re-entrancy is not an ordering edge
            edges.setdefault((a, b), (rel, line))

    for k, fi in funcs.items():
        rel = k[0]
        for lock, held, line in fi.acquires:
            for h in held:
                add_edge(h, lock, rel, line)
        for callee, held, line in fi.calls:
            if not held:
                continue
            for lock in closure.get(callee, ()):
                for h in held:
                    add_edge(h, lock, rel, line)
    return locks, edges


def find_cycles(edges: dict[tuple[str, str], tuple[str, int]]):
    """Minimal deterministic cycle enumeration: one cycle per strongly
    connected component with >1 node."""
    adj: dict[str, list[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    for k in adj:
        adj[k].sort()

    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (the call graph is small but recursion depth
        # is not worth betting on)
        work = [(v, iter(adj[v]))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return sccs


def check(files: list[SourceFile], cache=None) -> list[Finding]:
    _, edges = build_lock_graph(files, cache=cache)
    out: list[Finding] = []
    for scc in find_cycles(edges):
        members = set(scc)
        sites = sorted(
            f"{a} -> {b} at {rel}:{line}"
            for (a, b), (rel, line) in edges.items()
            if a in members and b in members)
        anchor = min(
            ((rel, line) for (a, b), (rel, line) in edges.items()
             if a in members and b in members),
            key=lambda x: (x[0], x[1]))
        out.append(Finding(
            RULE, anchor[0], anchor[1],
            "lock acquisition cycle (deadlock-capable interleaving): "
            + "; ".join(sites)))
    return out
