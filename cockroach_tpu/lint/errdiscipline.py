"""broad-except pass: error discipline for the distributed plane.

The KV retry work made swallowed exceptions a correctness bug class: a
``WriteIntentError`` or ``AmbiguousResultError`` silently eaten inside
``kv/`` turns exactly-once semantics into maybe-twice, and a flow-layer
swallow turns a failed fragment into a wrong answer instead of a degraded
query. So inside ``kv/``, ``flow/``, ``server/``:

- every ``except Exception`` / ``except BaseException`` handler must
  contain a ``raise`` (bare re-raise or a typed error), or carry a
  ``# crlint: allow-broad-except(<why>)`` pragma on the except line —
  background loops that log-and-continue by design document it there;
- a handler whose entire body is ``pass`` (or ``...``) is a HARD error:
  no pragma suppresses it. Swallowing with zero trace is never a policy —
  at minimum the handler names a narrower exception type or logs.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

RULE = "broad-except"

SCOPE = ("cockroach_tpu/kv/", "cockroach_tpu/flow/", "cockroach_tpu/server/")
_BROAD = {"Exception", "BaseException"}


def _broad_names(type_node: ast.AST | None) -> bool:
    if type_node is None:  # bare `except:` is BaseException
        return True
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    for n in nodes:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            return True
    return False


def _is_silent(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)):
            continue  # docstring / `...`
        return False
    return True


def check(src: SourceFile) -> list[Finding]:
    if not src.rel.startswith(SCOPE):
        return []
    out: list[Finding] = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _broad_names(node.type):
            continue
        if _is_silent(node.body):
            out.append(Finding(
                RULE, src.rel, node.lineno,
                "silent `except Exception: pass` swallow — catch a typed "
                "exception, or raise/log; no pragma excuses a zero-trace "
                "swallow", suppressible=False))
        elif not any(isinstance(n, ast.Raise) for n in ast.walk(node)):
            out.append(Finding(
                RULE, src.rel, node.lineno,
                "broad `except Exception` without re-raise — re-raise, "
                "raise a typed error, or pragma the deliberate "
                "log-and-continue"))
    return out
