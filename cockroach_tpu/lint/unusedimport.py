"""unused-import pass: imported names must be referenced.

Dead imports are how dead code starts: a refactor drops the last use, the
import survives, and the module keeps paying (and advertising) a
dependency it no longer has — worse here, where importing jax-adjacent
modules is expensive. AST-accurate: a binding counts as used if its name
appears as a ``Name`` node anywhere else in the module or in an
``__all__`` string list (re-export). ``__init__.py`` files are exempt
wholesale (their imports ARE the public surface); deliberate shim
re-exports elsewhere carry ``# crlint: allow-unused-import(<why>)``.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile

RULE = "unused-import"


def _bindings(tree: ast.AST) -> list[tuple[str, int, str]]:
    """(bound name, line, display) for every import binding."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = a.asname or a.name.split(".")[0]
                out.append((name, node.lineno, a.name))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                out.append((a.asname or a.name, node.lineno, a.name))
    return out


def check(src: SourceFile) -> list[Finding]:
    if src.rel.endswith("__init__.py"):
        return []
    used: set[str] = set()
    exported: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
            if "__all__" in targets and isinstance(
                    node.value, (ast.List, ast.Tuple)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str):
                        exported.add(elt.value)
    out: list[Finding] = []
    for name, line, display in _bindings(src.tree):
        if name in used or name in exported:
            continue
        out.append(Finding(
            RULE, src.rel, line,
            f"import {display!r} (bound as {name!r}) is never used"))
    return out
