"""fault-coverage pass: fault sites, registry, and chaos tests stay closed.

CockroachDB's testing knobs only earn their keep while some test actually
drives each failure path. This tree rule closes the loop over three sets:

1. every ``faults.fire(...)``/``fire_scoped``/``partial_fraction`` call in
   product code must name a site registered in ``utils/faults.py``'s
   ``SITES`` literal (an unregistered site is invisible to the chaos
   matrix — nothing will ever test it);
2. every registered site must have at least one product fire call (a dead
   registration documents a failure mode nothing can inject);
3. when test files are in the linted set, every registered site must be
   exercised by at least one chaos-marked test — a test that names the
   site (or a node-scoped ``<site>.n<id>`` variant) in a string literal.

:func:`site_matrix` exposes the site↔test mapping;
``scripts/run_chaos_matrix.py`` consumes it and refuses to run a matrix
with uncovered sites. Waive a finding with
``# crlint: allow-fault-coverage(reason)`` on the offending line (for
registry findings: the site's ``SITES`` entry line).
"""

from __future__ import annotations

import ast
import re

from .core import Finding, SourceFile, attr_chain

RULE = "fault-coverage"

_FIRE_FUNCS = {"fire", "fire_scoped", "partial_fraction"}
_SCOPED_SUFFIX = re.compile(r"\.n\d+$")


def _registry(files: list[SourceFile]):
    """(sites dict, entry-line dict, faults.py rel) from the SITES literal;
    (None, None, None) when utils/faults.py is not in the linted set."""
    for f in files:
        if f.rel != "cockroach_tpu/utils/faults.py":
            continue
        for node in f.tree.body:
            tgt = None
            if isinstance(node, ast.AnnAssign):
                tgt, value = node.target, node.value
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            if (isinstance(tgt, ast.Name) and tgt.id == "SITES"
                    and isinstance(value, ast.Dict)):
                sites, lines = {}, {}
                for k, v in zip(value.keys, value.values):
                    if isinstance(k, ast.Constant):
                        sites[k.value] = (v.value
                                          if isinstance(v, ast.Constant)
                                          else "")
                        lines[k.value] = k.lineno
                return sites, lines, f.rel
    return None, None, None


def _fire_calls(files: list[SourceFile]):
    """(rel, line, site-literal-or-None) for every fire-family call in
    product code (tests drive sites through arm(), not through fire)."""
    out = []
    for f in files:
        if not f.rel.startswith("cockroach_tpu/"):
            continue
        if f.rel == "cockroach_tpu/utils/faults.py":
            continue
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or chain[-1] not in _FIRE_FUNCS:
                continue
            # faults.fire(...) / fire(...) — either spelling
            if len(chain) > 1 and chain[-2] not in ("faults",):
                continue
            site = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value
            out.append((f.rel, node.lineno, site))
    return out


def _is_chaos_marked(tree: ast.Module) -> bool:
    """Module-level ``pytestmark = pytest.mark.chaos`` (or a list holding
    it)."""
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Name) and t.id == "pytestmark"):
            continue
        marks = (node.value.elts
                 if isinstance(node.value, (ast.List, ast.Tuple))
                 else [node.value])
        for m in marks:
            chain = attr_chain(m)
            if chain and chain[-1] == "chaos":
                return True
    return False


def _has_chaos_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        chain = attr_chain(target)
        if chain and "chaos" in chain:
            return True
    return False


def _chaos_tests(files: list[SourceFile]):
    """test-id -> set of string literals appearing in its body. Literals
    in a module-level helper are attributed to every chaos test in that
    module that calls the helper (tests routinely factor arm() specs into
    helpers)."""
    out: dict[str, set] = {}
    for f in files:
        if not f.rel.startswith("tests/"):
            continue
        module_chaos = _is_chaos_marked(f.tree)
        helper_literals: dict[str, set] = {}
        for node in f.tree.body:
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and not node.name.startswith("test_")):
                helper_literals[node.name] = {
                    s.value for s in ast.walk(node)
                    if isinstance(s, ast.Constant)
                    and isinstance(s.value, str)
                }
        for node in f.tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not node.name.startswith("test_"):
                continue
            if not (module_chaos or _has_chaos_decorator(node)):
                continue
            lits: set = set()
            called: set = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Constant) \
                        and isinstance(sub.value, str):
                    lits.add(sub.value)
                if isinstance(sub, ast.Call):
                    chain = attr_chain(sub.func)
                    if chain:
                        called.add(chain[-1])
            for h in called & set(helper_literals):
                lits |= helper_literals[h]
            out[f"{f.rel}::{node.name}"] = lits
    return out


def site_matrix(files: list[SourceFile]) -> dict[str, list[str]]:
    """site -> sorted chaos tests naming it (directly or node-scoped)."""
    sites, _, _ = _registry(files)
    if not sites:
        return {}
    tests = _chaos_tests(files)
    matrix: dict[str, list[str]] = {s: [] for s in sites}
    for test_id, lits in tests.items():
        hit = {_SCOPED_SUFFIX.sub("", s) for s in lits}
        for s in sites:
            if s in hit:
                matrix[s].append(test_id)
    return {s: sorted(ts) for s, ts in matrix.items()}


def check(files: list[SourceFile], cache=None) -> list[Finding]:
    sites, entry_lines, faults_rel = _registry(files)
    if sites is None:
        return []  # fixture trees without the registry: nothing to close
    findings: list[Finding] = []
    fired: set = set()
    for rel, line, site in _fire_calls(files):
        if site is None:
            findings.append(Finding(
                RULE, rel, line,
                "fault site is not a string literal — the chaos matrix "
                "cannot map a computed site name to tests",
            ))
            continue
        base = _SCOPED_SUFFIX.sub("", site)
        fired.add(base)
        if base not in sites:
            findings.append(Finding(
                RULE, rel, line,
                f"fault site {site!r} is not registered in "
                "utils/faults.py SITES — register it (with a one-line "
                "description) so the chaos matrix can see it",
            ))
    for site, line in entry_lines.items():
        if site not in fired:
            findings.append(Finding(
                RULE, faults_rel, line,
                f"registered fault site {site!r} has no fire call in "
                "product code — a dead registration documents a failure "
                "mode nothing can inject",
            ))
    has_tests = any(f.rel.startswith("tests/") for f in files)
    if has_tests:
        matrix = site_matrix(files)
        for site, tests in matrix.items():
            if not tests:
                findings.append(Finding(
                    RULE, faults_rel, entry_lines[site],
                    f"registered fault site {site!r} is not exercised by "
                    "any chaos-marked test — add one (or waive with a "
                    "reason) so run_chaos_matrix.py covers it",
                ))
    return findings
