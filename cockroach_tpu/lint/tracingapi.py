"""tracing-api pass: spans come only through the contextvar API.

utils/tracing.py's Tracer owns span lifecycle: ``span``/``remote_span``/
``leaf_span`` set ids, register the span in the in-flight table, bind the
contextvar, and on exit compute duration and move roots into the finished
ring. A ``Span(...)`` constructed anywhere else produces a span that is
invisible to crdb_internal.node_inflight_trace_spans, never closes, and —
if appended to a live tree — double-counts in EXPLAIN ANALYZE. Likewise,
poking the tracer's contextvar or span stack directly breaks the
disjoint-per-session-tree invariant the concurrency tests pin down.

Flagged: any call of a ``Span`` name imported from utils.tracing, any
``tracing.Span(...)`` / ``*.Span(...)`` attribute call, and any attribute
access of ``_current``/``_stack``/``_run_span`` on a tracer object.

Exempt: cockroach_tpu/utils/tracing.py itself (the API being guarded —
``from_dict`` and ``synthetic_span`` are its sanctioned constructors).
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile, attr_chain

RULE = "tracing-api"

EXEMPT = ("cockroach_tpu/lint/", "cockroach_tpu/utils/tracing.py")
_PRIVATE = {"_current", "_stack", "_run_span"}


def check(src: SourceFile) -> list[Finding]:
    if src.rel.startswith(EXEMPT[0]) or src.rel == EXEMPT[1]:
        return []
    # names bound off the tracing module: `from ..utils.tracing import Span`
    span_names: set[str] = set()
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
                node.module == "tracing"
                or node.module.endswith(".tracing")):
            for a in node.names:
                if a.name == "Span":
                    span_names.add(a.asname or a.name)
    out: list[Finding] = []
    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in span_names:
                out.append(Finding(
                    RULE, src.rel, node.lineno,
                    "direct Span() construction bypasses the contextvar "
                    "tracer — use tracing.span/leaf_span/remote_span (or "
                    "synthetic_span for post-hoc stats folding)"))
            elif isinstance(fn, ast.Attribute) and fn.attr == "Span":
                chain = attr_chain(fn)
                label = ".".join(chain) if chain else "<expr>.Span"
                out.append(Finding(
                    RULE, src.rel, node.lineno,
                    f"direct {label}() construction bypasses the "
                    "contextvar tracer — use tracing.span/leaf_span/"
                    "remote_span (or synthetic_span)"))
        elif isinstance(node, ast.Attribute) and node.attr in _PRIVATE:
            out.append(Finding(
                RULE, src.rel, node.lineno,
                f"direct access to tracer internals (.{node.attr}) breaks "
                "the per-session span-tree invariant — go through the "
                "tracing module API"))
    return out
