"""host-sync pass: no implicit device->host transfer in the tile pull loop.

The overlapped-readback work (flow/runtime.py's double-buffered pull loop,
the speculative _ReadbackShrink) exists precisely because ONE per-tile host
sync serializes the whole pipeline against the device tunnel. This pass
keeps that class of regression out of the hot-path modules:

- ``int()``/``float()``/``bool()`` over an expression that mentions
  ``jnp``/``jax`` (a traced or device value) blocks until the value lands
  on host;
- ``.item()`` is the same sync spelled as a method;
- ``np.asarray``/``np.array`` on a device array is a blocking readback
  (``jnp.asarray`` — host->device — is NOT flagged);
- ``jax.device_get``/``jax.block_until_ready`` are explicit syncs;
- a truth test (``if``/``while``/``assert``/``and``/``or``/``not``) over a
  ``jnp.*`` call forces __bool__ on a traced value;
- ``jax.debug.print``/``jax.debug.callback`` (and ``pure_callback``/
  ``io_callback``) stage a host callback into the traced kernel — one
  host round trip per launch, and ``ordered=True`` serializes the whole
  stream behind it. Debug prints belong OUTSIDE the jit or behind a
  pragma while actively debugging.

Scope: the hot-path modules only (flow/runtime.py, flow/fuse.py,
flow/operators.py, ops/*). Host-boundary modules whose whole JOB is the
device<->host transfer (flow/external.py, flow/wire.py) are allowlisted
wholesale — flagging them would drown the signal in pragmas.

Deliberate syncs (the one stacked count fetch at query end, decode of
host-resident dictionary columns) carry ``# crlint: allow-host-sync(...)``
pragmas stating why they are not per-tile.
"""

from __future__ import annotations

import ast

from .core import Finding, SourceFile, attr_chain

RULE = "host-sync"

HOT_FILES = (
    "cockroach_tpu/flow/runtime.py",
    "cockroach_tpu/flow/fuse.py",
    "cockroach_tpu/flow/operators.py",
)
HOT_DIRS = ("cockroach_tpu/ops/",)
# host-boundary modules: device<->host transfer IS their contract
ALLOWLIST = (
    "cockroach_tpu/flow/external.py",
    "cockroach_tpu/flow/wire.py",
)

_CASTS = {"int", "float", "bool"}
_NP_SYNCS = {("np", "asarray"), ("np", "array"),
             ("numpy", "asarray"), ("numpy", "array")}
_JAX_SYNCS = {("jax", "device_get"), ("jax", "block_until_ready")}
# host callbacks staged INTO traced code: each kernel launch round-trips
# through the host (jax.debug.print/debug.callback ride the same effect
# machinery as io_callback; ordered=True additionally serializes the
# stream). One per tile re-creates exactly the per-tile sync this pass
# exists to keep out of the pull loop.
_HOST_CALLBACKS = {("jax", "debug", "print"), ("jax", "debug", "callback"),
                   ("jax", "pure_callback"),
                   ("jax", "experimental", "io_callback")}
_DEVICE_ROOTS = {"jnp", "jax"}
# jnp attributes that are host-side metadata, not traced computation
_HOST_SAFE_ATTRS = {"issubdtype", "iinfo", "finfo", "dtype", "result_type",
                    "promote_types", "can_cast", "bool_", "ndim", "shape"}
# np.array over a literal/comprehension builds a host array from host
# python values — no device readback involved
_HOST_LITERALS = (ast.List, ast.Tuple, ast.Dict, ast.Constant, ast.ListComp,
                  ast.GeneratorExp)


def in_scope(rel: str) -> bool:
    if rel in ALLOWLIST:
        return False
    return rel in HOT_FILES or rel.startswith(HOT_DIRS)


def _mentions_device(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in _DEVICE_ROOTS:
            return True
    return False


def _device_call(node: ast.AST) -> bool:
    """A direct jnp.*/jax.* call somewhere inside the expression (dtype
    metadata predicates like jnp.issubdtype excluded — they are host
    booleans)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            chain = attr_chain(n.func)
            if (chain and chain[0] in _DEVICE_ROOTS
                    and chain[-1] not in _HOST_SAFE_ATTRS):
                return True
    return False


def check(src: SourceFile) -> list[Finding]:
    if not in_scope(src.rel):
        return []
    out: list[Finding] = []

    def flag(node: ast.AST, msg: str) -> None:
        out.append(Finding(RULE, src.rel, node.lineno, msg))

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                flag(node, ".item() forces a device->host sync in a "
                          "hot-path module")
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in _CASTS and node.args
                    and _mentions_device(node.args[0])):
                flag(node, f"{node.func.id}() over a jnp/jax expression "
                           "blocks on a device->host transfer")
            elif chain in _NP_SYNCS:
                if not (node.args
                        and isinstance(node.args[0], _HOST_LITERALS)):
                    flag(node, f"{'.'.join(chain)}() materializes its "
                               "argument on host (blocking readback for "
                               "device arrays)")
            elif chain in _JAX_SYNCS:
                flag(node, f"{'.'.join(chain)}() is an explicit device "
                           "sync in a hot-path module")
            elif chain in _HOST_CALLBACKS:
                flag(node, f"{'.'.join(chain)}() stages a host callback "
                           "into traced code (one host round trip per "
                           "kernel launch; ordered=True serializes the "
                           "stream)")
        elif isinstance(node, (ast.If, ast.While, ast.Assert)):
            if _device_call(node.test):
                flag(node, "truth test over a jnp/jax call forces __bool__ "
                           "on a traced value (hidden sync)")
        elif isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            if _device_call(node.operand):
                flag(node, "`not` over a jnp/jax call forces __bool__ on a "
                           "traced value (hidden sync)")
    return out
