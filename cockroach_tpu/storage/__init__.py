"""Storage layer — MVCC kernels + LSM engine, TPU-first.

Reference mapping:
- ``mvcc.mvcc_scan_filter``  <- pebbleMVCCScanner's per-KV hot loop
  (pkg/storage/pebble_mvcc_scanner.go:381), vectorized over a sorted block.
- ``mvcc.merge_blocks``      <- pebble's compaction/merging iterator k-way
  merge, as one lane-parallel device sort.
- ``lsm.Engine``             <- the Pebble wrapper (pkg/storage/pebble.go):
  memtable, sorted runs, compaction trigger, checkpoints, MVCC stats.
"""

from .keys import DEFAULT_KEY_WIDTH, decode_keys, encode_keys
from .lsm import Engine, MVCCStats, WriteIntentError
from .mvcc import KVBlock, merge_blocks, mvcc_scan_filter, sort_block

__all__ = [
    "DEFAULT_KEY_WIDTH", "decode_keys", "encode_keys",
    "Engine", "MVCCStats", "WriteIntentError",
    "KVBlock", "merge_blocks", "mvcc_scan_filter", "sort_block",
]
