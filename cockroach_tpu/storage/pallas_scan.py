"""Pallas MVCC scan-filter — the pebbleMVCCScanner hot loop as a TPU kernel.

Reference: pkg/storage/pebble_mvcc_scanner.go:381 advances one KV at a
time; the jnp version (mvcc.mvcc_scan_filter) is ~8 separate fused passes
over the block (boundary compare, visibility algebra, segmented min scan,
broadcast-back, conflict algebra). This kernel runs the WHOLE filter in
one VMEM-resident pass over the batched-scan window layout:

- rows    = scan windows ([B, CW]: multi_scan_sources packs one scan per
  row, CW a multiple of 128 lanes — no key run crosses a row);
- u64 key words and i64 ts/txn arrive PRE-SPLIT as i32 hi/lo planes
  (Mosaic's native lane type; equality and ordering compose from 32-bit
  compares);
- the per-key "first visible position" is a segmented min-scan along the
  lane axis (log2(CW) shifted selects) followed by a reverse segmented
  fill — all register/VMEM traffic, no HBM round trips between passes.

The jnp filter stays the portable fallback and the correctness oracle
(tests/test_pallas_scan.py runs both, interpret mode on CPU); the real-
chip win is measured by the bench's YCSB phase on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import mvcc as mvcc_mod

_SUBLANES = 8  # window rows per grid step (f32/i32 sublane tile)


def _split_u64(a: jax.Array) -> tuple[jax.Array, jax.Array]:
    """u64/i64 [..]-array -> (hi, lo) i32 planes (bit pattern halves)."""
    u = a.astype(jnp.uint64)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
    lo = u.astype(jnp.uint32).astype(jnp.int32)
    return hi, lo


def _u32_le(a: jax.Array, b: jax.Array) -> jax.Array:
    """Unsigned a <= b on i32 bit patterns (flip sign bit, signed compare)."""
    bias = jnp.int32(-0x80000000)
    return (a ^ bias) <= (b ^ bias)


def _i64_le(ahi, alo, bhi, blo) -> jax.Array:
    """(ahi:alo) <= (bhi:blo) for signed 64-bit split into i32 planes."""
    return (ahi < bhi) | ((ahi == bhi) & _u32_le(alo, blo))


def _shift_right(x: jax.Array, k: int, fill):
    """Shift lanes right by k (element i reads i-k); fill on the left."""
    if k == 0:
        return x
    pad = jnp.full(x.shape[:-1] + (k,), fill, x.dtype)
    return jnp.concatenate([pad, x[..., :-k]], axis=-1)


def _shift_left(x: jax.Array, k: int, fill):
    if k == 0:
        return x
    pad = jnp.full(x.shape[:-1] + (k,), fill, x.dtype)
    return jnp.concatenate([x[..., k:], pad], axis=-1)


def _scan_filter_kernel(kh0, kl0, kh1, kl1, tshi, tslo, txhi, txlo,
                        tomb, mask, rthi_ref, rtlo_ref, rxhi_ref, rxlo_ref,
                        sel_ref, conf_ref):
    """One grid step: [_SUBLANES, CW] windows through the full filter."""
    CW = kh0.shape[-1]
    khi0, klo0 = kh0[:], kl0[:]
    khi1, klo1 = kh1[:], kl1[:]
    ts_hi, ts_lo = tshi[:], tslo[:]
    tx_hi, tx_lo = txhi[:], txlo[:]
    dead = mask[:] == 0
    is_tomb = tomb[:] != 0
    read_hi = rthi_ref[0]
    read_lo = rtlo_ref[0]
    rdr_hi = rxhi_ref[0]
    rdr_lo = rxlo_ref[0]

    # key-run boundaries: adjacent-equality on both 64-bit key words
    same = jnp.ones(khi0.shape, jnp.bool_)
    for h, l in ((khi0, klo0), (khi1, klo1)):
        ph = _shift_right(h, 1, 0)
        pl_ = _shift_right(l, 1, 0)
        same = same & (h == ph) & (l == pl_)
    prev_dead = _shift_right(dead.astype(jnp.int32), 1, 1) != 0
    lane = jax.lax.broadcasted_iota(jnp.int32, khi0.shape, 1)
    boundary = (~dead) & ((lane == 0) | (~same) | prev_dead)

    committed = (tx_hi == 0) & (tx_lo == 0)
    own = (tx_hi == rdr_hi) & (tx_lo == rdr_lo) & ~committed
    ts_le = _i64_le(ts_hi, ts_lo, read_hi, read_lo)
    visible = (~dead) & ((committed & ts_le) | own)

    big = jnp.int32(0x7FFFFFFF)
    cand = jnp.where(visible, lane, big)

    # segmented min-scan along lanes: prefix-min restarting at boundaries
    flags = boundary
    vals = cand
    k = 1
    while k < CW:
        sh_f = _shift_right(flags.astype(jnp.int32), k, 1) != 0
        sh_v = _shift_right(vals, k, big)
        vals = jnp.where(flags, vals, jnp.minimum(vals, sh_v))
        flags = flags | sh_f
        k *= 2
    # vals now holds, at each lane, the min over its segment PREFIX; the
    # segment TOTAL sits at the segment's last lane. Reverse fill: propagate
    # each segment's end value back over the segment.
    nxt_boundary = _shift_left(boundary.astype(jnp.int32), 1, 1) != 0
    nxt_dead = _shift_left(dead.astype(jnp.int32), 1, 1) != 0
    is_end = (~dead) & (nxt_boundary | nxt_dead)
    seeded = jnp.where(is_end, vals, big)
    rflags = is_end
    rvals = seeded
    k = 1
    while k < CW:
        sh_f = _shift_left(rflags.astype(jnp.int32), k, 0) != 0
        sh_v = _shift_left(rvals, k, big)
        rvals = jnp.where(rflags, rvals, jnp.minimum(rvals, sh_v))
        rflags = rflags | sh_f
        k *= 2
    first = rvals  # first visible lane of this lane's key run

    newest = visible & (lane == first)
    selected = newest & ~is_tomb

    conflict = (~dead) & ~committed & ~own & ts_le & (lane <= first)

    sel_ref[:] = selected.astype(jnp.int8)
    conf_ref[:] = conflict.astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("window", "interpret"))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def pallas_scan_filter(block, read_ts, reader_txn, window: int,
                       interpret: bool = False):
    """Drop-in for mvcc.mvcc_scan_filter over the window-packed layout:
    block capacity must be B*window with window % 128 == 0 and key width
    16 bytes (two u64 words). Returns (selected, conflict) flat bools."""
    from jax.experimental import pallas as pl

    N = block.capacity
    B = N // window
    words = mvcc_mod.key_words(block.key)
    assert words.shape[1] == 2, "pallas filter covers 16-byte keys"

    def plane(x):
        return x.reshape(B, window)

    kh0, kl0 = _split_u64(plane(words[:, 0]))
    kh1, kl1 = _split_u64(plane(words[:, 1]))
    tshi, tslo = _split_u64(plane(block.ts))
    txhi, txlo = _split_u64(plane(block.txn))
    tomb = plane(block.tomb).astype(jnp.int8)
    mask = plane(block.mask).astype(jnp.int8)
    rthi, rtlo = _split_u64(read_ts.reshape(1))
    rxhi, rxlo = _split_u64(reader_txn.reshape(1))

    rows = max(1, min(_SUBLANES, B))
    grid = ((B + rows - 1) // rows,)
    spec = pl.BlockSpec((rows, window), lambda i: (i, 0))
    sspec = pl.BlockSpec((1,), lambda i: (0,))  # read_ts/reader_txn scalars
    sel, conf = pl.pallas_call(
        _scan_filter_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, window), jnp.int8),
            jax.ShapeDtypeStruct((B, window), jnp.int8),
        ),
        grid=grid,
        in_specs=[spec] * 10 + [sspec] * 4,
        out_specs=(spec, spec),
        interpret=interpret,
    )(kh0, kl0, kh1, kl1, tshi, tslo, txhi, txlo, tomb, mask,
      rthi, rtlo, rxhi, rxlo)
    return sel.reshape(-1) != 0, conf.reshape(-1) != 0
