"""LSM storage engine — the Pebble-wrapper analog (pkg/storage/pebble.go).

Host-side orchestration of device-resident sorted runs:

- writes append to a host memtable (plus an in-memory WAL record list);
- ``flush`` sorts the memtable into an immutable device run (an "SST");
- when runs pile past ``l0_trigger`` they compact: ``mvcc.merge_blocks``
  (the k-way-merge kernel) + ``mvcc.mvcc_gc_filter`` — the Pebble compaction
  loop as one lane-parallel device pass;
- reads (``get``/``scan``) merge the relevant runs and run the
  ``mvcc_scan_filter`` kernel (pebble_mvcc_scanner.go:381 semantics);
- ``checkpoint``/``open_checkpoint`` persist runs+memtable to .npz files
  (pkg/storage/pebble.go:2077 CreateCheckpoint analog).

Intents: provisional writes carry a txn id; ``resolve_intents`` commits or
aborts them engine-wide (MVCCResolveWriteIntent). A scan that hits another
txn's visible intent raises WriteIntentError, like the reference.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from . import keys as K
from . import mvcc

_RUN_ALIGN = 1024


def _pad(n: int) -> int:
    """Next power-of-2 capacity >= n (min 1024): runs and merges then take
    only O(log) distinct static shapes, so every kernel compiles a handful
    of times total no matter how write volume fluctuates."""
    p = _RUN_ALIGN
    while p < n:
        p *= 2
    return p


def _shrink(block: mvcc.KVBlock) -> mvcc.KVBlock:
    """Slice a *sorted* block (dead rows last) down to a power-of-2 capacity
    covering its live rows — keeps merge/compaction capacities proportional
    to data, not to the sum of historical paddings."""
    live = int(np.asarray(jnp.sum(block.mask)))
    cap = _pad(live)
    if cap >= block.capacity:
        return block
    return jax.tree_util.tree_map(lambda x: x[:cap], block)


class WriteIntentError(Exception):
    def __init__(self, keys: list[bytes], txns: list[int]):
        super().__init__(f"conflicting intents on {keys} (txns {txns})")
        self.keys = keys
        self.txns = txns


@dataclass
class MVCCStats:
    """Coarse engine stats (enginepb.MVCCStats analog)."""

    live_count: int = 0
    key_count: int = 0
    val_count: int = 0
    intent_count: int = 0
    runs: int = 0
    compactions: int = 0
    flushes: int = 0


@dataclass
class _Memtable:
    keys: list[bytes] = field(default_factory=list)
    ts: list[int] = field(default_factory=list)
    seq: list[int] = field(default_factory=list)
    txn: list[int] = field(default_factory=list)
    tomb: list[bool] = field(default_factory=list)
    value: list[bytes] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.ts)


class Engine:
    """MVCC LSM engine over device-resident sorted runs."""

    def __init__(
        self,
        key_width: int = K.DEFAULT_KEY_WIDTH,
        val_width: int = 16,
        l0_trigger: int | None = None,
        memtable_size: int = 4096,
        gc_ts: int = 0,
    ):
        assert key_width % 8 == 0
        from ..utils import settings

        self.key_width = key_width
        self.val_width = val_width
        # DefaultPebbleOptions L0CompactionThreshold (pebble.go:363)
        self.l0_trigger = (
            l0_trigger if l0_trigger is not None
            else settings.get("storage.l0_compaction_threshold")
        )
        self.memtable_size = memtable_size
        self.gc_ts = gc_ts
        self.mem = _Memtable()
        self.runs: list[mvcc.KVBlock] = []  # sorted device runs, newest first
        self.stats = MVCCStats()
        self._seq = 0  # global write sequence: same-(key, ts) writes resolve
        # newest-sequence-wins (intent rewrites within a txn, TxnSeq analog)
        # host-side lock table (concurrency/lock_table.go analog): key ->
        # txn id holding an intent. Kept in sync by _append/resolve_intents
        # so lock checks are O(1) host lookups, never device merges.
        self._locks: dict[bytes, int] = {}

    # -- writes -------------------------------------------------------------

    def put(self, key: bytes | str, value: bytes | str, ts: int, txn: int = 0):
        self._append(key, value, ts, txn, tomb=False)

    def delete(self, key: bytes | str, ts: int, txn: int = 0):
        self._append(key, b"", ts, txn, tomb=True)

    def _append(self, key, value, ts: int, txn: int, tomb: bool):
        b = key.encode() if isinstance(key, str) else bytes(key)
        v = value.encode() if isinstance(value, str) else bytes(value)
        if b"\x00" in b:
            # zero-padded fixed-width encoding makes b"a" and b"a\x00"
            # indistinguishable (keys.py precondition) — enforce it here
            raise ValueError(f"key must not contain 0x00 bytes: {b!r}")
        if len(b) > self.key_width:
            raise ValueError(f"key too long ({len(b)} > {self.key_width})")
        if len(v) > self.val_width:
            raise ValueError(f"value too long ({len(v)} > {self.val_width})")
        self._seq += 1
        if txn != 0:
            self._locks[b] = int(txn)
        self.mem.keys.append(b)
        self.mem.ts.append(int(ts))
        self.mem.seq.append(self._seq)
        self.mem.txn.append(int(txn))
        self.mem.tomb.append(bool(tomb))
        self.mem.value.append(v)
        if len(self.mem) >= self.memtable_size:
            self.flush()

    # -- flush / compaction -------------------------------------------------

    def _mem_block(self) -> mvcc.KVBlock | None:
        if not len(self.mem):
            return None
        n = len(self.mem)
        keys = K.encode_keys(self.mem.keys, self.key_width)
        vals = np.zeros((n, self.val_width), dtype=np.uint8)
        vlen = np.zeros((n,), dtype=np.int32)
        for i, v in enumerate(self.mem.value):
            vals[i, : len(v)] = np.frombuffer(v, dtype=np.uint8)
            vlen[i] = len(v)
        return mvcc.block_from_host(
            keys,
            np.asarray(self.mem.ts),
            np.asarray(self.mem.txn),
            np.asarray(self.mem.tomb),
            vals,
            vlen,
            cap=_pad(n),
            seq=np.asarray(self.mem.seq),
        )

    def flush(self):
        """Memtable -> sorted immutable run (Pebble memtable flush)."""
        blk = self._mem_block()
        if blk is None:
            return
        self.runs.insert(0, mvcc.sort_block(blk))
        self.mem = _Memtable()
        self.stats.flushes += 1
        self.stats.runs = len(self.runs)
        if len(self.runs) > self.l0_trigger:
            self.compact()

    def compact(self, bottom: bool = True):
        """Merge all runs into one via the k-way merge kernel + GC filter."""
        self.flush_mem_only()
        if not self.runs:
            return
        total = sum(r.capacity for r in self.runs)
        merged = mvcc.merge_blocks(tuple(self.runs), cap=_pad(total))
        keep = mvcc.mvcc_gc_filter(merged, jnp.int64(self.gc_ts), bottom)
        merged = mvcc.KVBlock(
            key=merged.key, ts=merged.ts, seq=merged.seq, txn=merged.txn,
            tomb=merged.tomb, value=merged.value, vlen=merged.vlen,
            mask=merged.mask & keep,
        )
        self.runs = [_shrink(mvcc.sort_block(merged))]
        self.stats.compactions += 1
        self.stats.runs = 1

    def flush_mem_only(self):
        blk = self._mem_block()
        if blk is not None:
            self.runs.insert(0, mvcc.sort_block(blk))
            self.mem = _Memtable()
            self.stats.flushes += 1
            self.stats.runs = len(self.runs)

    # -- reads --------------------------------------------------------------

    def _merged_view(self) -> mvcc.KVBlock | None:
        """One sorted device view over memtable + all runs (the read path's
        merging iterator)."""
        self.flush_mem_only()
        if not self.runs:
            return None
        if len(self.runs) == 1:
            return self.runs[0]
        total = sum(r.capacity for r in self.runs)
        merged = _shrink(mvcc.merge_blocks(tuple(self.runs), cap=_pad(total)))
        self.runs = [merged]  # merged view is also a valid single run
        self.stats.runs = 1
        return merged

    def scan(
        self,
        start: bytes | str | None,
        end: bytes | str | None,
        ts: int,
        txn: int = 0,
        max_keys: int | None = None,
    ) -> list[tuple[bytes, bytes]]:
        """[start, end) snapshot scan at `ts` -> [(key, value)] host pairs."""
        view = self._merged_view()
        if view is None:
            return []
        sw = K.encode_bound(start, self.key_width)
        ew = K.encode_bound(end, self.key_width)
        sel, conflict = mvcc.mvcc_scan_filter(
            view, jnp.int64(ts), jnp.int64(txn),
            None if sw is None else jnp.asarray(sw),
            None if ew is None else jnp.asarray(ew),
        )
        conflict_np = np.asarray(conflict)
        if conflict_np.any():
            idx = np.nonzero(conflict_np)[0]
            ck = K.decode_keys(np.asarray(view.key)[idx])
            ct = [int(t) for t in np.asarray(view.txn)[idx]]
            raise WriteIntentError(ck, ct)
        sel_np = np.asarray(sel)
        idx = np.nonzero(sel_np)[0]
        if max_keys is not None:
            idx = idx[:max_keys]
        ks = K.decode_keys(np.asarray(view.key)[idx])
        vals = np.asarray(view.value)[idx]
        vls = np.asarray(view.vlen)[idx]
        return [(k, bytes(v[:n])) for k, v, n in zip(ks, vals, vls)]

    def get(self, key: bytes | str, ts: int, txn: int = 0) -> bytes | None:
        view = self._merged_view()
        if view is None:
            return None
        b = key.encode() if isinstance(key, str) else bytes(key)
        sw = K.encode_bound(b, self.key_width)
        ew = K.bound_next(sw)
        sel, conflict = mvcc.mvcc_scan_filter(
            view, jnp.int64(ts), jnp.int64(txn),
            jnp.asarray(sw), jnp.asarray(ew),
        )
        if np.asarray(conflict).any():
            idx = np.nonzero(np.asarray(conflict))[0]
            raise WriteIntentError(
                K.decode_keys(np.asarray(view.key)[idx]),
                [int(t) for t in np.asarray(view.txn)[idx]],
            )
        idx = np.nonzero(np.asarray(sel))[0]
        if not len(idx):
            return None
        i = idx[0]
        n = int(np.asarray(view.vlen)[i])
        return bytes(np.asarray(view.value)[i][:n])

    # -- intents ------------------------------------------------------------

    def resolve_intents(self, txn: int, commit_ts: int, commit: bool):
        """Commit or abort all of txn's intents across memtable + runs."""
        self._locks = {k: t for k, t in self._locks.items() if t != txn}
        self.flush_mem_only()
        self.runs = [
            mvcc.sort_block(
                mvcc.resolve_intents(
                    r, jnp.int64(txn), jnp.int64(commit_ts), commit
                )
            )
            for r in self.runs
        ]

    def has_committed_writes_in(
        self, start: bytes | None, end: bytes | None, ts_lo: int, ts_hi: int,
        point: bool = False,
    ) -> bool:
        """Any committed version in (ts_lo, ts_hi] within [start, end)?
        The read-refresh check (kvcoord txn_interceptor_span_refresher
        semantics: a txn's reads stay valid iff nothing committed under its
        read spans between read_ts and commit_ts). ``point=True`` checks
        exactly the key `start` (successor end bound, like get)."""
        view = self._merged_view()
        if view is None:
            return False
        words = K.key_words(view.key)
        sw = K.encode_bound(start, self.key_width)
        ew = K.bound_next(sw) if point else K.encode_bound(end, self.key_width)
        in_range = view.mask & K.words_in_range(
            words,
            None if sw is None else jnp.asarray(sw),
            None if ew is None else jnp.asarray(ew),
        )
        hit = (
            in_range & (view.txn == 0)
            & (view.ts > ts_lo) & (view.ts <= ts_hi)
        )
        return bool(np.asarray(jnp.any(hit)))

    def other_intent(self, key: bytes, txn: int) -> int | None:
        """Txn id of another transaction's intent on `key`, if any —
        the lock-table point lookup the write path does before laying an
        intent (concurrency_manager.SequenceReq's lock check). A pure host
        dict lookup: no device work on the write hot path."""
        b = key.encode() if isinstance(key, str) else bytes(key)
        holder = self._locks.get(b)
        return holder if holder is not None and holder != txn else None

    def newest_committed_ts(self, key: bytes) -> int:
        """Timestamp of the newest committed version of `key` (0 if none) —
        powers the WriteTooOld check."""
        view = self._merged_view()
        if view is None:
            return 0
        sw = K.encode_bound(key, self.key_width)
        ew = K.bound_next(sw)
        words = K.key_words(view.key)
        hit = (
            view.mask
            & K.words_in_range(words, jnp.asarray(sw), jnp.asarray(ew))
            & (view.txn == 0)
        )
        ts = jnp.where(hit, view.ts, 0)
        return int(np.asarray(jnp.max(ts)))

    def intent_keys(self, txn: int) -> list[bytes]:
        return sorted(k for k, t in self._locks.items() if t == txn)

    # -- stats / checkpoint -------------------------------------------------

    def compute_stats(self) -> MVCCStats:
        view = self._merged_view()
        s = self.stats
        if view is None:
            s.live_count = s.key_count = s.val_count = s.intent_count = 0
            return s
        mask = np.asarray(view.mask)
        s.val_count = int(mask.sum())
        s.intent_count = int((mask & (np.asarray(view.txn) != 0)).sum())
        words = np.asarray(K.key_words(view.key))[mask]
        s.key_count = len(np.unique(words, axis=0)) if len(words) else 0
        sel, _ = mvcc.mvcc_scan_filter(
            view, jnp.int64(np.iinfo(np.int64).max), jnp.int64(0)
        )
        s.live_count = int(np.asarray(sel).sum())
        return s

    def checkpoint(self, path: str):
        """Persist the engine state (CreateCheckpoint analog)."""
        self.flush_mem_only()
        os.makedirs(path, exist_ok=True)
        for i, r in enumerate(self.runs):
            np.savez(
                os.path.join(path, f"run{i:04d}.npz"),
                key=np.asarray(r.key), ts=np.asarray(r.ts),
                seq=np.asarray(r.seq),
                txn=np.asarray(r.txn), tomb=np.asarray(r.tomb),
                value=np.asarray(r.value), vlen=np.asarray(r.vlen),
                mask=np.asarray(r.mask),
            )
        with open(os.path.join(path, "MANIFEST"), "w") as f:
            f.write(f"{len(self.runs)} {self.key_width} {self.val_width}\n")

    @classmethod
    def open_checkpoint(cls, path: str, **kwargs) -> "Engine":
        with open(os.path.join(path, "MANIFEST")) as f:
            nruns, kw, vw = (int(x) for x in f.read().split())
        eng = cls(key_width=kw, val_width=vw, **kwargs)
        for i in range(nruns):
            z = np.load(os.path.join(path, f"run{i:04d}.npz"))
            eng.runs.append(
                mvcc.KVBlock(
                    key=jnp.asarray(z["key"]), ts=jnp.asarray(z["ts"]),
                    seq=jnp.asarray(z["seq"]),
                    txn=jnp.asarray(z["txn"]), tomb=jnp.asarray(z["tomb"]),
                    value=jnp.asarray(z["value"]), vlen=jnp.asarray(z["vlen"]),
                    mask=jnp.asarray(z["mask"]),
                )
            )
        eng.stats.runs = len(eng.runs)
        # restore the write-sequence high-water mark so post-restore writes
        # keep winning same-(key, ts) tie-breaks over persisted rows, and
        # rebuild the host lock table from persisted intents
        for r in eng.runs:
            m = np.asarray(r.mask)
            if m.any():
                eng._seq = max(eng._seq, int(np.asarray(r.seq)[m].max()))
            im = m & (np.asarray(r.txn) != 0)
            if im.any():
                ks = K.decode_keys(np.asarray(r.key)[np.nonzero(im)[0]])
                ts = np.asarray(r.txn)[np.nonzero(im)[0]]
                for kk, tt in zip(ks, ts):
                    eng._locks[kk] = int(tt)
        return eng
