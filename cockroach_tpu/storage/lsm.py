"""LSM storage engine — the Pebble-wrapper analog (pkg/storage/pebble.go).

Host-side orchestration of device-resident sorted runs:

- writes append to a durable on-disk WAL (write-ahead, pebble's wal/) and a
  host memtable;
- ``flush`` sorts the memtable into an immutable device run (an "SST");
- when runs pile past ``l0_trigger`` a SIZE-TIERED compaction merges only
  the smallest runs (``mvcc.merge_blocks`` + ``mvcc.mvcc_gc_filter`` — the
  Pebble compaction loop as one lane-parallel device pass); a full
  bottom-level compaction runs only on explicit ``compact(bottom=True)``.
  Partial merges are always safe: the global write sequence resolves
  same-(key, ts) winners regardless of which runs have merged;
- reads never mutate the run set. Bounded reads (get / short scans) gather
  only the in-range rows of each run + the memtable into small candidate
  tiles and merge THOSE (the merging-iterator role, pebble_mvcc_scanner.go
  :381 semantics via ``mvcc_scan_filter``), so point/short-range cost is
  O(candidates·log), not O(total history). Unbounded reads use a merged
  view cached per run-set generation;
- ``checkpoint``/``open_checkpoint`` persist runs to .npz files and
  truncate the WAL (pkg/storage/pebble.go:2077 CreateCheckpoint analog);
  a crash between checkpoints recovers by WAL replay at open.

Intents: provisional writes carry a txn id; ``resolve_intents`` commits or
aborts them engine-wide (MVCCResolveWriteIntent). A scan that hits another
txn's visible intent raises WriteIntentError, like the reference.
"""

from __future__ import annotations

import base64
import functools
import json
import os
import struct
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from . import blockcache
from . import keys as K
from . import mvcc
from ..utils import locks

_RUN_ALIGN = 1024
_CAND_ALIGN = 128  # candidate tiles for bounded reads start smaller

_WAL_MAGIC = b"CTWL"
# kind (0=write, 1=intent resolution, 2=ingest link), ts, seq, txn,
# tomb/commit, klen, vlen
_WAL_REC = struct.Struct("<BqqqBHH")
_REC_WRITE = 0
_REC_RESOLVE = 1
# ingest records carry the side-file name of a durably written run in the
# key field (AddSSTable's link-don't-copy durability: the run file is
# fsynced BEFORE the record is appended, so replay can always reload it)
_REC_INGEST = 2
# import records carry a side-file of full per-row MVCC fields (the
# snapshot-apply half of a range relocation); clear records carry the
# cleared span's [start, end) bounds in key/value (end b"" + flag=False
# means open-ended) — the replica-removal half
_REC_IMPORT = 3
_REC_CLEAR = 4
# batch records carry an ENTIRE stamped RPC mutation batch — ops, the
# (client id, sequence) dedup token, and the wire response — in one
# record. The torn-tail truncation of _arm_wal makes the record
# all-or-nothing across a crash, which is exactly the atomicity the
# exactly-once protocol needs: either the ops AND the replay-cache
# entry survive (a retry dedups) or neither does (a retry re-applies
# onto a store that never saw the batch). There is no window where the
# ops landed but the dedup entry didn't.
_REC_BATCH = 5


def _words_to_bytes(words) -> bytes:
    """Packed big-endian uint64 key words -> the original zero-padded key
    bytes (inverse of keys.encode_bound's word packing)."""
    return b"".join(int(w).to_bytes(8, "big") for w in np.asarray(words))


def _pad(n: int, align: int = _RUN_ALIGN) -> int:
    """Next power-of-2 capacity >= n (min `align`): blocks take only O(log)
    distinct static shapes, so kernels compile a handful of times total."""
    p = align
    while p < n:
        p *= 2
    return p


def _block_nbytes(blk: mvcc.KVBlock) -> int:
    """Logical bytes of a block's arrays — what run residency charges to
    the storage staging monitor (flow/memory.py staging accounts)."""
    return int(sum(int(x.size) * x.dtype.itemsize
                   for x in (blk.key, blk.ts, blk.seq, blk.txn,
                             blk.tomb, blk.value, blk.vlen, blk.mask)))


def _charge_run(run: mvcc.KVBlock) -> None:
    """Run residency joins the monitor tree (PR 8): reserved against the
    node budget, released when compaction drops the run and it is GC'd."""
    from ..flow import memory as flowmem

    flowmem.charge_object("storage/run-residency", run, _block_nbytes(run))


def _shrink(block: mvcc.KVBlock) -> mvcc.KVBlock:
    """Slice a *sorted* block (dead rows last) down to a power-of-2 capacity
    covering its live rows."""
    live = int(np.asarray(jnp.sum(block.mask)))
    cap = _pad(live)
    if cap >= block.capacity:
        return block
    return jax.tree_util.tree_map(lambda x: x[:cap], block)


@jax.jit  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _live_rows(block: mvcc.KVBlock) -> jax.Array:
    return jnp.sum(block.mask, dtype=jnp.int32)


@jax.jit  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _range_mask(block: mvcc.KVBlock, sw, ew):
    """In-range liveness mask + its count, one fused kernel per source
    shape (sw/ew None-ness is static trace structure)."""
    words = K.key_words(block.key)
    m = block.mask & K.words_in_range(words, sw, ew)
    return m, jnp.sum(m, dtype=jnp.int32)


@functools.partial(jax.jit, static_argnames=("size",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _slice_window(block: mvcc.KVBlock, pos, size: int) -> mvcc.KVBlock:
    """[pos, pos+size) window of a run — the iterator-seek read (O(size)
    device work regardless of run length)."""
    return jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_slice_in_dim(
            x, jnp.clip(pos, 0, max(0, x.shape[0] - size)), size, axis=0
        ),
        block,
    )


@functools.partial(jax.jit, static_argnames=("cap",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _gather_rows(block: mvcc.KVBlock, m: jax.Array, cap: int) -> mvcc.KVBlock:
    """Compact the rows where `m` into a tile of `cap` (row order kept, so a
    sorted source yields a sorted candidate tile)."""
    dest = jnp.where(m, jnp.cumsum(m.astype(jnp.int32)) - 1, cap)
    n = jnp.sum(m, dtype=jnp.int32)

    def take(x):
        shape = (cap,) + x.shape[1:]
        return jnp.zeros(shape, x.dtype).at[dest].set(x, mode="drop")

    return mvcc.KVBlock(
        key=take(block.key), ts=take(block.ts), seq=take(block.seq),
        txn=take(block.txn), tomb=take(block.tomb), value=take(block.value),
        vlen=take(block.vlen),
        mask=jnp.arange(cap, dtype=jnp.int32) < n,
    )


class WriteIntentError(Exception):
    def __init__(self, keys: list[bytes], txns: list[int]):
        super().__init__(f"conflicting intents on {keys} (txns {txns})")
        self.keys = keys
        self.txns = txns


from ..utils.errors import register_passthrough as _rp  # noqa: E402

_rp(WriteIntentError)  # expected error: crosses the query boundary unwrapped


@dataclass
class MVCCStats:
    """Coarse engine stats (enginepb.MVCCStats analog)."""

    live_count: int = 0
    key_count: int = 0
    val_count: int = 0
    intent_count: int = 0
    runs: int = 0
    compactions: int = 0
    flushes: int = 0


@dataclass
class _Memtable:
    keys: list[bytes] = field(default_factory=list)
    ts: list[int] = field(default_factory=list)
    seq: list[int] = field(default_factory=list)
    txn: list[int] = field(default_factory=list)
    tomb: list[bool] = field(default_factory=list)
    value: list[bytes] = field(default_factory=list)  # inline slot bytes
    vlen: list[int] = field(default_factory=list)  # LOGICAL value length
    # (vlen > engine.val_width marks an overflow pointer record)

    def __len__(self) -> int:
        return len(self.ts)


class _TsCache:
    """Newest committed write timestamp per key — the kvserver/tscache role
    backing the WriteTooOld check, LSM-shaped so BULK ingest stays O(1)
    python-side: each ingest lands as one sorted numpy key batch (void
    dtype: memcmp order), single writes overlay a dict, and lookups take
    max(overlay, binary search per batch). Batches fold together once the
    list grows, keeping the per-lookup batch count bounded. The prior
    per-key dict build was ~1M tobytes+dict inserts per 1M-key ingest —
    measured as a third of YCSB load time."""

    _MAX_BATCHES = 8

    def __init__(self, key_width: int):
        self.kw = key_width
        self.over: dict[bytes, int] = {}
        self.batches: list[tuple[np.ndarray, np.ndarray]] = []

    def _void(self, keys_u8: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(keys_u8).view(f"V{self.kw}").reshape(-1)

    def bulk(self, keys_u8: np.ndarray, ts) -> None:
        """[N, kw] uint8 keys committed at ts (scalar or [N] array)."""
        if len(keys_u8) == 0:
            return
        v = self._void(keys_u8)
        t = (np.full(len(v), int(ts), np.int64) if np.isscalar(ts)
             else np.asarray(ts, np.int64))
        order = np.argsort(v, kind="stable")
        self.batches.append((v[order], t[order]))
        if len(self.batches) > self._MAX_BATCHES:
            self._fold()

    # crlint: allow-mem-accounting(fold compacts already-resident ts-cache batches: a transient concat whose output is strictly smaller than its inputs)
    def _fold(self) -> None:
        ks = np.concatenate([k for k, _ in self.batches])
        ts = np.concatenate([t for _, t in self.batches])
        order = np.argsort(ks, kind="stable")
        k, t = ks[order], ts[order]
        new = np.concatenate([[True], k[1:] != k[:-1]])
        gid = np.cumsum(new) - 1
        mx = np.zeros(int(gid[-1]) + 1, np.int64)
        np.maximum.at(mx, gid, t)
        self.batches = [(k[new], mx)]

    def get(self, b: bytes, _default: int = 0) -> int:
        t = self.over.get(b, 0)
        if self.batches and len(b) <= self.kw:
            q = np.frombuffer(b.ljust(self.kw, b"\x00"),
                              dtype=f"V{self.kw}")[0]
            for keys, ts in self.batches:
                i = int(np.searchsorted(keys, q))
                if i < len(keys) and keys[i] == q:
                    t = max(t, int(ts[i]))
        return t

    def put(self, b: bytes, ts: int) -> None:
        if ts > self.over.get(b, 0):
            self.over[b] = ts


def _locked(fn):
    """Serialize a public Engine method under the engine mutex.

    The reference sequences concurrent requests through latches + the lock
    table (concurrency_manager.SequenceReq); this engine's reduced analog is
    one reentrant store mutex. Without it, a Node's background threads
    (liveness heartbeats, the tsdb ticker, jobs adoption) race
    resolve_intents' run-set rewrite against concurrent memtable appends and
    leave orphaned intent rows behind (observed: a committed heartbeat's
    intent resurrected by a racing flush)."""
    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        with self.mu:
            return fn(self, *a, **kw)
    return wrapper


class Engine:
    """MVCC LSM engine over device-resident sorted runs.

    Durability scope: with the default ``wal_fsync=False`` the WAL is written
    through the OS page cache only — acknowledged writes survive PROCESS
    crashes but can be lost on machine/kernel crashes. Pass ``wal_fsync=True``
    for fsync-per-record durability (Pebble's WAL sync default), at a large
    single-writer throughput cost."""

    def __init__(
        self,
        key_width: int = K.DEFAULT_KEY_WIDTH,
        val_width: int = 16,
        l0_trigger: int | None = None,
        memtable_size: int = 4096,
        gc_ts: int = 0,
        wal_path: str | None = None,
        wal_fsync: bool = False,
        compact_width: int = 4,
    ):
        assert key_width % 8 == 0
        self.mu = locks.rlock("storage.engine")
        from ..utils import settings

        self.key_width = key_width
        self.val_width = val_width
        # DefaultPebbleOptions L0CompactionThreshold (pebble.go:363)
        self.l0_trigger = (
            l0_trigger if l0_trigger is not None
            else settings.get("storage.l0_compaction_threshold")
        )
        self.memtable_size = memtable_size
        self.gc_ts = gc_ts
        self.compact_width = compact_width
        # admission control: every write path consults the IOGovernor and
        # pays a delay proportional to L0 overload (io_load_listener.go
        # role — slow writers BEFORE read amplification inverts)
        from .. utils.admission import IOGovernor

        self.governor = IOGovernor(self)
        # compaction merge kernel override: None = follow the
        # storage.pallas_merge setting; True/False force it (tests)
        self.pallas_merge: bool | None = None
        self._pallas_merge_interpret = False
        self.mem = _Memtable()
        self.runs: list[mvcc.KVBlock] = []  # sorted device runs, newest first
        self.stats = MVCCStats()
        self._seq = 0  # global write sequence: same-(key, ts) writes resolve
        # newest-sequence-wins (intent rewrites within a txn, TxnSeq analog)
        # host-side lock table (concurrency/lock_table.go analog): key ->
        # txn id holding an intent. Kept in sync by _append/resolve_intents
        # so lock checks are O(1) host lookups, never device merges.
        self._locks: dict[bytes, int] = {}
        # host-side newest-committed-timestamp index (tscache analog): keeps
        # the per-write WriteTooOld check off the device
        self._newest_committed = _TsCache(key_width)
        # read caches, invalidated by generation counters
        self._gen = 0  # bumps whenever the run set changes
        # per-run read-path metadata — seek keys + split-block bloom +
        # the token namespacing the run's block-cache entries
        # (storage/blockcache.py); keyed by id with a strong run ref so
        # ids can't be reused
        self._run_meta: dict[int, tuple[mvcc.KVBlock, blockcache.RunMeta]] = {}
        self._runs_view_cache: tuple[int, mvcc.KVBlock] | None = None
        self._scan_windows: dict[int, int] = {}  # max_keys -> learned window
        self._mem_cache: tuple[int, mvcc.KVBlock] | None = None
        self._overlay_cache = None  # ((gen, mem len), merged view)
        # variable-width value overflow heap (the WiscKey / pebble
        # value-separation shape): values longer than the fixed inline
        # slot live here, the slot stores an 8-byte offset pointer, and
        # vlen > val_width is the overflow marker. Append-only; dead
        # blobs are reclaimed only by checkpoint+reopen (value-log GC is
        # out of scope, like pebble's is a separate subsystem).
        self._blob = bytearray()
        # RPC replay cache (exactly-once writes): client id -> (last seq,
        # wire response). BatchClient serializes batches per connection,
        # so a window of ONE entry per client suffices — a retry can only
        # ever be for the newest seq. Entries persist via _REC_BATCH WAL
        # records and checkpoint side files; bounded at
        # _REPLAY_CACHE_MAX_CLIENTS with oldest-client eviction.
        self._replay_cache: dict[str, tuple[int, object]] = {}
        # durable write-ahead log
        self.wal_path = wal_path
        self.wal_fsync = wal_fsync
        self._wal = None
        self._replaying = False
        # optional DiskMonitor (storage/disk.py): when set, WAL appends
        # feed its rolling write-latency window
        self.disk_monitor = None
        if wal_path is not None:
            self._arm_wal(wal_path)

    # -- WAL ----------------------------------------------------------------

    def _arm_wal(self, path: str) -> None:
        """Replay any existing records, then open the WAL for appending
        (shared by fresh opens and checkpoint restores). Torn bytes past
        the last complete record are truncated away — appending after
        garbage would corrupt every future replay."""
        valid_off = 0
        if os.path.exists(path) and os.path.getsize(path) > 0:
            valid_off = self._replay_wal(path)
            if valid_off < os.path.getsize(path):
                with open(path, "r+b") as f:
                    f.truncate(valid_off)
        self.wal_path = path
        self._wal = open(path, "ab")
        if os.path.getsize(path) < len(_WAL_MAGIC):
            self._wal.truncate(0)
            self._wal.write(_WAL_MAGIC)
            self._wal.flush()

    def _wal_record(self, kind: int, key: bytes, value: bytes, ts: int,
                    seq: int, txn: int, flag: bool,
                    sync: bool = True) -> None:
        from ..utils import faults, tracing

        rec = _WAL_REC.pack(kind, ts, seq, txn, 1 if flag else 0,
                            len(key), len(value))
        mon = self.disk_monitor  # one read: may be attached concurrently
        t0 = time.time() if mon is not None else 0.0
        payload = rec + key + value
        # chaos sites (pebble errorfs analog): a `delay` fault models a
        # stalling disk, `error` EIO before any byte lands, `partial` a
        # torn append — half the record hits the file, then the "disk"
        # dies. Replay's torn-tail truncation must recover all three.
        with tracing.leaf_span("storage/wal.append", bytes=len(payload)):
            faults.fire("storage.wal.append")
            frac = faults.partial_fraction("storage.wal.append")
            if frac is not None:
                self._wal.write(payload[:max(1, int(len(payload) * frac))])
                self._wal.flush()
                raise faults.InjectedFault("storage.wal.append", "partial")
            self._wal.write(payload)
            self._wal.flush()
            # sync=False defers the fsync to an explicit wal_sync() call
            # (group-commit pipelining: the caller acks only after it)
            if self.wal_fsync and sync:
                with tracing.leaf_span("storage/wal.fsync"):
                    faults.fire("storage.wal.fsync")
                    os.fsync(self._wal.fileno())
        if mon is not None:
            # the WAL append IS the write-latency signal the disk monitor
            # tracks (pkg/storage/disk samples the same device)
            mon.observe(time.time() - t0)

    def _replay_wal(self, path: str) -> int:
        """Recover state lost in a crash: re-apply writes above the restored
        sequence high-water mark and ALL intent resolutions, in log order
        (resolutions are idempotent, so re-applying pre-checkpoint ones is
        harmless; skipping one would resurrect a committed txn's intents).
        Returns the offset just past the last COMPLETE record, so the
        caller can truncate torn bytes before appending."""
        with open(path, "rb") as f:
            data = f.read()
        if len(data) < len(_WAL_MAGIC):
            return 0  # torn header: nothing recoverable was logged
        if data[:4] != _WAL_MAGIC:
            raise ValueError(f"corrupt WAL header in {path!r}")
        off = 4
        valid_off = off
        self._replaying = True
        try:
            while off + _WAL_REC.size <= len(data):
                kind, ts, seq, txn, flag, klen, vlen = _WAL_REC.unpack_from(
                    data, off)
                off += _WAL_REC.size
                if off + klen + vlen > len(data):
                    break  # torn tail record: drop (standard WAL semantics)
                key = data[off: off + klen]
                value = data[off + klen: off + klen + vlen]
                off += klen + vlen
                valid_off = off
                if kind == _REC_RESOLVE:
                    self.resolve_intents(txn, ts, commit=bool(flag))
                elif kind == _REC_INGEST:
                    if seq > self._seq:
                        side = os.path.join(os.path.dirname(path) or ".",
                                            key.decode())
                        try:
                            z = np.load(side)
                            n = int(z["n"])
                        except (FileNotFoundError, ValueError, OSError,
                                KeyError, EOFError,
                                __import__("zipfile").BadZipFile) as e:
                            # missing OR torn/corrupt side file: only
                            # reachable after a machine crash with
                            # wal_fsync=False (no durability promise
                            # there — the OS may persist the WAL record
                            # and the npz in either order, or half of
                            # one). Warn and keep the store OPENABLE;
                            # refusing to start would turn that crash
                            # into permanent data loss of everything
                            # else too.
                            from ..utils import log

                            log.warning(log.STORAGE,
                                        "ingest side file missing/torn on "
                                        "replay; run dropped",
                                        file=side, error=str(e))
                            continue
                        # re-link through ingest(): _replaying suppresses
                        # the re-log, so the run lands exactly once
                        self.ingest(z["key"][:n], z["value"][:n], ts,
                                    seq=seq, vlens=z["vlen"][:n])
                elif kind == _REC_IMPORT:
                    if seq > self._seq:
                        side = os.path.join(os.path.dirname(path) or ".",
                                            key.decode())
                        try:
                            z = np.load(side)
                            rows = {f: z[f] for f in (
                                "key", "ts", "seq", "txn", "tomb", "value",
                                "vlen")}
                            if "blob" in z.files:
                                rows["blob"] = z["blob"]
                        except (FileNotFoundError, ValueError, OSError,
                                KeyError, EOFError,
                                __import__("zipfile").BadZipFile) as e:
                            from ..utils import log

                            log.warning(log.STORAGE,
                                        "import side file missing/torn on "
                                        "replay; run dropped",
                                        file=side, error=str(e))
                            continue
                        self.import_rows(rows)
                        # restore the marker allocated at emit time (the
                        # imported rows' own max seq may be lower)
                        self._seq = max(self._seq, seq)
                elif kind == _REC_CLEAR:
                    self.clear_span(key or None,
                                    value if flag else None)
                elif kind == _REC_BATCH:
                    self._replay_batch_record(seq, value)
                elif seq > self._seq:
                    self._raw_append(key, value, ts, seq, txn, bool(flag))
        finally:
            self._replaying = False
        self.flush_mem_only()
        return valid_off

    def _truncate_wal(self) -> None:
        if self._wal is None:
            return
        self._wal.close()
        self._wal = open(self.wal_path, "wb")
        self._wal.write(_WAL_MAGIC)
        self._wal.flush()
        if self.wal_fsync:
            os.fsync(self._wal.fileno())
        self._wal.close()
        self._wal = open(self.wal_path, "ab")

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
            self._wal = None

    # -- writes -------------------------------------------------------------

    @_locked
    def put(self, key: bytes | str, value: bytes | str, ts: int, txn: int = 0):
        self._append(key, value, ts, txn, tomb=False)

    @_locked
    def delete(self, key: bytes | str, ts: int, txn: int = 0):
        self._append(key, b"", ts, txn, tomb=True)

    def _append(self, key, value, ts: int, txn: int, tomb: bool):
        b = key.encode() if isinstance(key, str) else bytes(key)
        v = value.encode() if isinstance(value, str) else bytes(value)
        if b"\x00" in b:
            # zero-padded fixed-width encoding makes b"a" and b"a\x00"
            # indistinguishable (keys.py precondition) — enforce it here
            raise ValueError(f"key must not contain 0x00 bytes: {b!r}")
        if len(b) > self.key_width:
            raise ValueError(f"key too long ({len(b)} > {self.key_width})")
        if len(v) > self.val_width and self.val_width < 8:
            raise ValueError(
                f"value of {len(v)} bytes needs the overflow heap, which "
                f"requires val_width >= 8 (have {self.val_width})"
            )
        from ..utils import metric

        metric.ENGINE_WRITES.inc()
        self.governor.pace_write()
        seq = self._seq + 1
        if self._wal is not None:  # write-ahead: durable before visible
            self._wal_record(_REC_WRITE, b, v, int(ts), seq, int(txn), tomb)
        self._raw_append(b, v, int(ts), seq, int(txn), tomb)
        if len(self.mem) >= self.memtable_size:
            self.flush()

    def _raw_append(self, b: bytes, v: bytes, ts: int, seq: int, txn: int,
                    tomb: bool) -> None:
        self._seq = max(self._seq, seq)
        if txn != 0:
            self._locks[b] = int(txn)
        else:
            self._newest_committed.put(b, ts)
        n = len(v)
        if n > self.val_width:
            # overflow: payload to the heap, an offset pointer inline.
            # Done HERE (not _append) so WAL replay — which logs the full
            # value and re-runs this path — rebuilds the heap itself.
            off = len(self._blob)
            self._blob += v
            v = off.to_bytes(8, "little")
        self.mem.keys.append(b)
        self.mem.ts.append(ts)
        self.mem.seq.append(seq)
        self.mem.txn.append(txn)
        self.mem.tomb.append(tomb)
        self.mem.value.append(v)
        self.mem.vlen.append(n)

    # -- exactly-once RPC batches -------------------------------------------
    # (kvserver's replay protection reduced: the server consults this
    # cache before evaluating a stamped mutation batch, and the batch's
    # ops + dedup token + response persist in ONE atomic WAL record.)

    _REPLAY_CACHE_MAX_CLIENTS = 1024

    @_locked
    def replay_cache_get(self, cid: str, seq: int):
        """The cached wire response if (cid, seq) already applied, else
        None. A hit means the client's retry crossed a window where the
        first attempt DID land (severed response, server restart)."""
        ent = self._replay_cache.get(cid)
        if ent is not None and ent[0] == seq:
            return ent[1]
        return None

    def _set_replay_entry(self, cid: str, seq: int, resp) -> None:
        self._replay_cache.pop(cid, None)  # reinsert = refresh LRU order
        while len(self._replay_cache) >= self._REPLAY_CACHE_MAX_CLIENTS:
            self._replay_cache.pop(next(iter(self._replay_cache)))
        self._replay_cache[cid] = (int(seq), resp)

    def wal_sync(self) -> None:
        """fsync the WAL, covering every record appended with
        ``sync=False``. Deliberately NOT engine-locked: fsync flushes the
        whole file, so a sync racing later appends only over-delivers
        durability. Group-commit pipelining hinges on this — append +
        memtable apply under the mutex, sync outside it (the next batch
        forms and applies while this one's sync is on the disk), ack
        riders only after the sync returns."""
        from ..utils import faults, tracing

        w = self._wal
        if w is None or not self.wal_fsync:
            return
        with tracing.leaf_span("storage/wal.fsync"):
            faults.fire("storage.wal.fsync")
            os.fsync(w.fileno())

    @_locked
    def apply_rpc_batch(self, cid: str, seq: int, muts, resp,
                        sync: bool = True) -> None:
        """Apply a stamped mutation batch exactly once.

        muts: [(key bytes, value bytes, ts, txn, tomb), ...] as evaluated
        by the RPC server; resp: the JSON-serializable wire response to
        replay on a dedup hit. One _REC_BATCH WAL record covers ops +
        dedup entry + response, so crash recovery can never disagree with
        itself about whether the batch applied (see _REC_BATCH note)."""
        from ..utils import metric

        for k, v, _ts, _txn, _tomb in muts:
            if b"\x00" in k:
                raise ValueError(f"key must not contain 0x00 bytes: {k!r}")
            if len(k) > self.key_width:
                raise ValueError(
                    f"key too long ({len(k)} > {self.key_width})")
            if len(v) > self.val_width and self.val_width < 8:
                raise ValueError(
                    f"value of {len(v)} bytes needs the overflow heap, "
                    f"which requires val_width >= 8 (have {self.val_width})")
        self.governor.pace_write()
        base = self._seq + 1
        if self._wal is not None:
            payload = json.dumps({
                "cid": cid, "seq": int(seq),
                "muts": [[base64.b64encode(k).decode(),
                          base64.b64encode(v).decode(),
                          int(ts), int(txn), bool(tomb)]
                         for k, v, ts, txn, tomb in muts],
                "resp": resp,
            }).encode()
            # klen/vlen are uint16: struct.pack rejects a batch payload
            # past 64 KiB, surfacing as a typed error before any byte of
            # WAL or memtable state changes
            self._wal_record(_REC_BATCH, b"", payload, 0, base, 0, False,
                             sync=sync)
        for i, (k, v, ts, txn, tomb) in enumerate(muts):
            metric.ENGINE_WRITES.inc()
            self._raw_append(k, v, int(ts), base + i, int(txn), bool(tomb))
        self._set_replay_entry(cid, seq, resp)
        if len(self.mem) >= self.memtable_size:
            self.flush()

    def _replay_batch_record(self, seq: int, value: bytes) -> None:
        """WAL-replay half of apply_rpc_batch: re-apply ops above the seq
        high-water mark and ALWAYS restore the dedup entry (last record
        per client wins, matching log order)."""
        ent = json.loads(value.decode())
        if seq > self._seq:
            for i, (k64, v64, ts, txn, tomb) in enumerate(ent["muts"]):
                self._raw_append(
                    base64.b64decode(k64), base64.b64decode(v64),
                    int(ts), seq + i, int(txn), bool(tomb))
        self._set_replay_entry(ent["cid"], int(ent["seq"]), ent["resp"])

    def _resolve_value(self, row: np.ndarray, n: int) -> bytes:
        """Inline slot bytes + logical length -> the stored value (follows
        the overflow pointer when n exceeds the inline width)."""
        if n <= self.val_width:
            return bytes(row[:n])
        off = int.from_bytes(bytes(row[:8]), "little")
        return bytes(self._blob[off:off + n])

    # -- flush / compaction -------------------------------------------------

    def _mem_block(self) -> mvcc.KVBlock | None:
        if not len(self.mem):
            return None
        if self._mem_cache is not None and self._mem_cache[0] == len(self.mem):
            return self._mem_cache[1]
        n = len(self.mem)
        keys = K.encode_keys(self.mem.keys, self.key_width)
        vals = np.zeros((n, self.val_width), dtype=np.uint8)
        vlen = np.asarray(self.mem.vlen, dtype=np.int32)
        for i, v in enumerate(self.mem.value):
            if len(v):
                vals[i, : len(v)] = np.frombuffer(v, dtype=np.uint8)
        # sort on the HOST (canonical MVCC order: key asc, ts desc, seq
        # desc — _mvcc_sort_operands' ordering): a memtable is <=
        # memtable_size rows, so np.lexsort costs microseconds while the
        # device sort_block this replaces charged a ~10-20ms XLA sort to
        # EVERY scan batch that followed an insert (write-then-read
        # workloads pay one rebuild per batch)
        ts_arr = np.asarray(self.mem.ts, np.int64)
        seq_arr = np.asarray(self.mem.seq, np.int64)
        void_keys = np.ascontiguousarray(keys).view(
            f"V{self.key_width}").reshape(-1)
        order = np.lexsort((-seq_arr, -ts_arr, void_keys))
        blk = mvcc.block_from_host(
            keys[order],
            ts_arr[order],
            np.asarray(self.mem.txn)[order],
            np.asarray(self.mem.tomb)[order],
            vals[order],
            vlen[order],
            cap=_pad(n),
            seq=seq_arr[order],
        )
        from ..flow import memory as flowmem

        # memtable-block residency (cached until the next write changes
        # the memtable): charged like a run, released when the cache
        # entry is replaced and the old block is GC'd
        flowmem.charge_object("storage/run-residency", blk,
                              _block_nbytes(blk))
        self._mem_cache = (n, blk)
        return blk

    @_locked
    def ingest(self, keys: np.ndarray, values: np.ndarray, ts: int,
               seq: int | None = None,
               vlens: np.ndarray | None = None,
               presorted: bool = False) -> None:
        """Bulk ingest: land pre-built KV arrays as ONE sorted run — the
        AddSSTable path (kvserver/batcheval/cmd_add_sstable.go role; the
        reference's bulk loaders build SSTs client-side and link them into
        the LSM without touching the memtable/WAL). keys: [N, key_width]
        uint8 zero-padded; values: [N, <=val_width] uint8. All entries land
        committed at `ts`.

        One device sort builds the run; the WriteTooOld index takes the
        whole batch in one vectorized pass — per-row put() would pay host
        encode + append per key (the ingest-vs-write asymmetry the
        reference's IMPORT exists for).

        ``presorted=True`` promises the keys are already unique and in
        canonical run order (the RunBuilder sorted and deduped them
        device-side) — the landing re-sort is skipped."""
        n = len(keys)
        if n == 0:
            return
        self.governor.pace_write()
        if keys.shape[1] > self.key_width:
            raise ValueError("ingest keys wider than engine key width")
        if values.shape[1] > self.val_width:
            raise ValueError("ingest values wider than engine val width")
        if seq is None:
            seq = self._seq + 1
        self._seq = max(self._seq, seq)
        cap = _pad(n)
        kb = np.zeros((cap, self.key_width), dtype=np.uint8)
        kb[:n, : keys.shape[1]] = keys
        vb = np.zeros((cap, self.val_width), dtype=np.uint8)
        vb[:n, : values.shape[1]] = values
        vl = np.concatenate([
            (np.asarray(vlens, dtype=np.int32) if vlens is not None
             else np.full(n, values.shape[1], np.int32)),
            np.zeros(cap - n, np.int32),
        ])
        if self._wal is not None and not self._replaying:
            # durable-before-visible, same as _append: persist the run's
            # host arrays (live prefix only) to a side file, THEN append
            # the WAL record naming it — replay rebuilds the run from the
            # file. fsync (file + directory entry, the checkpoint()
            # discipline) only under wal_fsync, matching _wal_record.
            side = f"{self.wal_path}.ingest{int(seq):012d}.npz"
            with open(side, "wb") as f:
                np.savez(f, key=kb[:n], value=vb[:n], vlen=vl[:n],
                         n=np.int64(n), ts=np.int64(ts), seq=np.int64(seq))
                f.flush()
                if self.wal_fsync:
                    os.fsync(f.fileno())
            if self.wal_fsync:
                dfd = os.open(os.path.dirname(side) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            from ..utils import faults

            # chaos: crash window between the durable side file and the
            # WAL link record — the run must stay invisible (replay sees
            # no record; the orphan side file is cleaned at checkpoint)
            # and a retry must land it cleanly
            faults.fire("storage.ingest.link")
            self._wal_record(_REC_INGEST, os.path.basename(side).encode(),
                             b"", int(ts), int(seq), 0, False)
        blk = mvcc.KVBlock(
            key=jnp.asarray(kb),
            ts=jnp.full((cap,), int(ts), jnp.int64),
            seq=jnp.full((cap,), int(seq), jnp.int64),
            txn=jnp.zeros((cap,), jnp.int64),
            tomb=jnp.zeros((cap,), jnp.bool_),
            value=jnp.asarray(vb),
            vlen=jnp.asarray(vl),
            mask=jnp.asarray(np.arange(cap) < n),
        )
        run = blk if presorted else mvcc.sort_block(blk)
        _charge_run(run)
        self.runs.insert(0, run)
        self._gen += 1
        self.stats.flushes += 1
        self.stats.runs = len(self.runs)
        from ..utils import metric

        metric.ENGINE_INGESTS.inc()
        metric.INGEST_ROWS.inc(n)
        metric.INGEST_BYTES.inc(int(n * self.key_width + int(vl[:n].sum())))
        metric.ENGINE_RUNS.set(len(self.runs))
        self._register_run(run)
        # one sorted-batch tscache insert for the whole ingest (no per-key
        # host work — see _TsCache)
        self._newest_committed.bulk(kb[:n], int(ts))
        self._maybe_compact()

    @_locked
    def flush(self):
        """Memtable -> sorted immutable run (Pebble memtable flush)."""
        self.flush_mem_only()
        self._maybe_compact()

    @_locked
    def flush_mem_only(self):
        blk = self._mem_block()
        if blk is None:
            return
        self.runs.insert(0, blk)
        self.mem = _Memtable()
        self._mem_cache = None
        self._gen += 1
        self.stats.flushes += 1
        self.stats.runs = len(self.runs)
        from ..utils import metric

        metric.ENGINE_FLUSHES.inc()
        metric.ENGINE_RUNS.set(len(self.runs))
        self._register_run(blk)

    def _maybe_compact(self) -> None:
        """Size-tiered compaction trigger behind the IOGovernor's pacing
        decision: small debt may be deferred (storage.compaction.pacing.*)
        so back-to-back merges can't starve foreground reads; debt past
        max_debt_runs always compacts immediately."""
        if (len(self.runs) > self.l0_trigger
                and self.governor.pace_compaction()):
            self.compact(bottom=False)

    @_locked
    def compact(self, bottom: bool = True):
        """Compaction. bottom=True merges everything and elides bottom-level
        tombstones (a full/manual compaction); bottom=False is the
        size-tiered incremental pass: merge only the `compact_width`
        smallest runs (pebble's tiered L0->Lbase compaction picking)."""
        from ..utils import tracing

        self.flush_mem_only()
        if len(self.runs) < 2:
            return
        with tracing.leaf_span("storage/compaction", bottom=bottom,
                               runs=len(self.runs)):
            if bottom:
                picked = list(range(len(self.runs)))
            else:
                by_size = sorted(
                    range(len(self.runs)),
                    key=lambda i: self.runs[i].capacity
                )
                picked = sorted(by_size[: max(2, self.compact_width)])
            blocks = tuple(self.runs[i] for i in picked)
            total = sum(r.capacity for r in blocks)
            merged = self._merge_for_compaction(blocks, total)
            keep = mvcc.mvcc_gc_filter(merged, jnp.int64(self.gc_ts),
                                       bottom)
            merged = mvcc.KVBlock(
                key=merged.key, ts=merged.ts, seq=merged.seq,
                txn=merged.txn, tomb=merged.tomb, value=merged.value,
                vlen=merged.vlen, mask=merged.mask & keep,
            )
            merged = _shrink(mvcc.sort_block(merged))
            kept = [r for i, r in enumerate(self.runs)
                    if i not in set(picked)]
            # the merged run replaces its sources at the oldest picked
            # position
            kept.insert(min(len(kept), picked[0]), merged)
            self.runs = kept
            self._gen += 1
            from ..utils import faults

            try:
                # chaos: the run-set swap is visible but the cache/bloom
                # bookkeeping hasn't happened yet — invalidation MUST
                # still run (finally) or readers could be served stale
                # cached windows of the replaced runs
                faults.fire("storage.compaction.swap")
            finally:
                # the output run rebuilds its bloom; its inputs drop
                # their metadata and ONLY their own block-cache entries
                for b in blocks:
                    self._drop_run_meta(b)
                self._register_run(merged)
            self.stats.compactions += 1
            from ..utils import log, metric

            metric.ENGINE_COMPACTIONS.inc()
            log.debug(log.STORAGE, "compaction", runs=len(self.runs),
                      bottom=bottom)
            self.stats.runs = len(self.runs)
            self.governor.note_compaction()

    def _merge_for_compaction(self, blocks, total: int) -> mvcc.KVBlock:
        """Pick the compaction merge: the bitonic-merge Pallas kernel
        (pallas_merge.py — pebble mergingIter role, log2(N) stages over
        pre-sorted runs) when enabled and VMEM-sized, else concat+sort.
        Kernel output capacity is the padded power of two; the post-GC
        sort+_shrink in compact() trims it either way."""
        import jax

        from ..utils import settings
        from . import pallas_merge as pm

        use = self.pallas_merge
        if use is None:
            mode = settings.get("storage.pallas_merge")
            use = mode == "on" or (
                mode == "auto" and jax.default_backend() == "tpu"
            )
        if use and self.key_width == 16 and pm.eligible(blocks):
            interpret = (self._pallas_merge_interpret
                         or jax.default_backend() == "cpu")
            return pm.merge_runs(blocks, interpret=interpret)
        return mvcc.merge_blocks(blocks, cap=_pad(total))

    # -- read views ---------------------------------------------------------

    def _runs_view(self) -> mvcc.KVBlock | None:
        """One sorted device view over all runs, cached per generation;
        never mutates the run set."""
        if not self.runs:
            return None
        if (self._runs_view_cache is not None
                and self._runs_view_cache[0] == self._gen):
            return self._runs_view_cache[1]
        if len(self.runs) == 1:
            view = self.runs[0]
        else:
            total = sum(r.capacity for r in self.runs)
            view = _shrink(
                mvcc.merge_blocks(tuple(self.runs), cap=_pad(total))
            )
        self._runs_view_cache = (self._gen, view)
        return view

    def _merged_view(self) -> mvcc.KVBlock | None:
        """Sorted view over memtable + runs (the read path's merging
        iterator). Cached per (run-set generation, memtable length) so a
        write-then-N-reads workload pays one overlay merge, not N; the run
        set itself is never rewritten by reads."""
        rv = self._runs_view()
        mb = self._mem_block()
        if mb is None:
            return rv
        if rv is None:
            return mb
        key = (self._gen, len(self.mem))
        if (self._overlay_cache is not None
                and self._overlay_cache[0] == key):
            return self._overlay_cache[1]
        view = mvcc.merge_blocks(
            (mb, rv), cap=_pad(mb.capacity + rv.capacity)
        )
        self._overlay_cache = (key, view)
        return view

    def _bounded_view(self, sw, ew, limit_rows: int | None = None,
                      point: bytes | None = None):
        """Candidate view for a bounded read: gather only in-range rows of
        each source into small tiles and merge those — point/short-scan
        cost scales with matching rows, not total history.

        limit_rows clamps each SORTED run to its first limit_rows in-range
        entries (the pebbleMVCCScanner pagination discipline): a scan with
        max_keys must not gather half the keyspace just because its end
        bound is open. Returns (view, boundary): rows at or past `boundary`
        (the smallest truncation point across runs) are INCOMPLETE — some
        of their versions may have been cut — and callers must not emit
        them. boundary None means nothing was truncated."""
        sources = []
        mb = self._mem_block()
        if mb is not None:
            sources.append((mb, False))  # memtable is unsorted: never seek
        sources.extend((r, True) for r in self.runs)
        swj = None if sw is None else jnp.asarray(sw)
        ewj = None if ew is None else jnp.asarray(ew)
        parts = []
        boundary: bytes | None = None
        for src, sorted_run in sources:
            if (point is not None and sorted_run
                    and not self._bloom_might_contain(src, point)):
                # per-run bloom filter: the key is definitely absent —
                # skip the run's range-mask/gather entirely (pebble's
                # table-filter point-read pruning)
                from ..utils import metric

                metric.BLOOM_SKIPS.inc()
                continue
            if limit_rows is not None and sorted_run and sw is not None:
                # iterator seek: host binary search over the run's cached
                # key bytes finds the start position, one device
                # dynamic-slice lands the window — O(window), never
                # O(run length) (the pebble iterator SeekGE discipline)
                meta = self._meta_for(src)
                vkeys, n_live = meta.void_keys, meta.n_live
                if n_live == 0:
                    continue
                sw_raw = _words_to_bytes(sw)
                pos = int(np.searchsorted(
                    vkeys[:n_live],
                    np.frombuffer(sw_raw, dtype=vkeys.dtype)[0],
                    side="left",
                ))
                if pos >= n_live:
                    continue
                size = min(_pad(limit_rows, _CAND_ALIGN), src.capacity)
                cpos = min(pos, max(0, src.capacity - size))
                # block cache: runs are immutable, so a (token, pos,
                # size) window's contents never change — consult the
                # node cache before dispatching the device slice
                cache = blockcache.node_cache()
                win = cache.get(meta.token, cpos, size)
                if win is None:
                    win = _slice_window(src, cpos, size)
                    cache.put(meta.token, cpos, size, win)
                end_pos = cpos + size
                if end_pos < n_live:
                    cut = bytes(vkeys[end_pos - 1].tobytes())
                    if ew is None or cut < _words_to_bytes(ew):
                        if boundary is None or cut < boundary:
                            boundary = cut
                m, cnt = _range_mask(win, swj, ewj)
                cnt = int(np.asarray(cnt))
                if cnt == 0:
                    continue
                parts.append(_gather_rows(win, m, _pad(cnt, _CAND_ALIGN)))
                continue
            m, cnt = _range_mask(src, swj, ewj)
            cnt = int(np.asarray(cnt))
            if cnt == 0:
                continue
            parts.append(_gather_rows(src, m, _pad(cnt, _CAND_ALIGN)))
        if not parts:
            return None, None
        if len(parts) == 1:
            return parts[0], boundary
        total = sum(p.capacity for p in parts)
        view = mvcc.merge_blocks(tuple(parts), cap=_pad(total, _CAND_ALIGN))
        return view, boundary

    # -- per-run read metadata (blockcache.RunMeta: seek keys + bloom) ------

    def _meta_for(self, run: mvcc.KVBlock) -> blockcache.RunMeta:
        """Read-path metadata for a run. Built eagerly by _register_run at
        run construction (ingest/flush/compaction output); built lazily
        here for the rewrite paths (intent resolution, span clears) whose
        per-txn run churn would make eager bloom rebuilds a commit tax.
        Stale entries prune as the run set turns over — dropping a meta
        also invalidates its block-cache entries, or dead runs would pin
        cache bytes forever."""
        c = self._run_meta.get(id(run))
        if c is None or c[0] is not run:
            kb = np.asarray(run.key)
            void = np.ascontiguousarray(kb).view(
                f"V{kb.shape[1]}").reshape(-1)
            n_live = int(np.asarray(jnp.sum(run.mask, dtype=jnp.int32)))
            if len(self._run_meta) > 4 * max(1, len(self.runs)):
                live_ids = {id(r) for r in self.runs}
                cache = blockcache.node_cache()
                for k in [k for k in self._run_meta if k not in live_ids]:
                    cache.invalidate_run(self._run_meta[k][1].token)
                    del self._run_meta[k]
            c = self._run_meta[id(run)] = (
                run, blockcache.build_meta(void, n_live))
        return c[1]

    def _register_run(self, run: mvcc.KVBlock) -> None:
        """Eager metadata build for a newly constructed run — run
        construction is where the reference builds its table filters, so
        the first point read never pays the build."""
        self._meta_for(run).bloom()

    def _drop_run_meta(self, run: mvcc.KVBlock) -> None:
        c = self._run_meta.pop(id(run), None)
        if c is not None:
            blockcache.node_cache().invalidate_run(c[1].token)

    def _bloom_might_contain(self, run: mvcc.KVBlock, key: bytes) -> bool:
        """Per-run split-block bloom probe (pebble's table-filter role).
        False is a CRC-backed proof of absence; a filterless or corrupt
        run always answers maybe."""
        bloom = self._meta_for(run).bloom()
        if bloom is None:
            return True
        kb = np.zeros((1, self.key_width), np.uint8)  # crlint: allow-mem-accounting(single-key probe buffer, key_width bytes)
        raw = np.frombuffer(key, np.uint8)
        kb[0, :len(raw)] = raw
        h1, h2 = blockcache.bloom_hashes(
            np.ascontiguousarray(kb).view(f"V{self.key_width}").reshape(-1)
        )
        return bloom.might_contain(int(h1[0]), int(h2[0]))

    def _run_keys(self, run: mvcc.KVBlock):
        """Host copy of a sorted run's key bytes as a void array (memcmp
        ordering) + its live count — the SST block-index analog backing
        host-side iterator seeks."""
        m = self._meta_for(run)
        return m.void_keys, m.n_live

    def _view_for(self, sw, ew) -> mvcc.KVBlock | None:
        if sw is None and ew is None:
            return self._merged_view()
        return self._bounded_view(sw, ew)[0]

    # -- reads --------------------------------------------------------------

    @_locked
    def scan(
        self,
        start: bytes | str | None,
        end: bytes | str | None,
        ts: int,
        txn: int = 0,
        max_keys: int | None = None,
    ) -> list[tuple[bytes, bytes]]:
        """[start, end) snapshot scan at `ts` -> [(key, value)] host pairs.

        With max_keys, candidate gathering is CLAMPED per sorted run
        (pebbleMVCCScanner pagination): rows at/past the smallest
        truncation boundary are withheld (their version sets may be
        incomplete) and the clamp grows geometrically until max_keys
        complete rows emerge."""
        from ..utils import metric

        metric.ENGINE_SCANS.inc()
        sw = K.encode_bound(start, self.key_width)
        ew = K.encode_bound(end, self.key_width)
        limit = None
        if max_keys is not None and (sw is not None or ew is not None):
            limit = max(16, 4 * max_keys)
        while True:
            if limit is not None:
                view, boundary = self._bounded_view(sw, ew, limit)
            else:
                view, boundary = self._view_for(sw, ew), None
            if view is None:
                return []
            sel, conflict = mvcc.mvcc_scan_filter(
                view, jnp.int64(ts), jnp.int64(txn),
                None if sw is None else jnp.asarray(sw),
                None if ew is None else jnp.asarray(ew),
            )
            conflict_np = np.asarray(conflict)
            if conflict_np.any():
                idx = np.nonzero(conflict_np)[0]
                ck = K.decode_keys(np.asarray(view.key)[idx])
                ct = [int(t) for t in np.asarray(view.txn)[idx]]
                raise WriteIntentError(ck, ct)
            sel_np = np.asarray(sel)
            idx = np.nonzero(sel_np)[0]
            if boundary is not None:
                # emit only rows strictly below the truncation point
                keys_np = np.asarray(view.key)[idx]
                # crlint: allow-mem-accounting(one bool per candidate row of a truncated scan batch — bounded by the scan limit)
                below = np.array(
                    [bytes(k) < boundary for k in keys_np], dtype=bool
                )
                kept = idx[below]
                if max_keys is not None and len(kept) < max_keys:
                    # truncation occurred and complete rows don't cover the
                    # limit: more keys may hide past the boundary
                    limit *= 4
                    continue
                idx = kept
            if max_keys is not None:
                idx = idx[:max_keys]
            ks = K.decode_keys(np.asarray(view.key)[idx])
            vals = np.asarray(view.value)[idx]
            vls = np.asarray(view.vlen)[idx]
            return [(k, self._resolve_value(v, int(n)))
                    for k, v, n in zip(ks, vals, vls)]

    @_locked
    def scan_batch(
        self,
        starts: list[bytes | str],
        ts: int,
        txn: int = 0,
        max_keys: int = 64,
    ) -> list[list[tuple[bytes, bytes]]]:
        """B forward scans of up to max_keys rows each, in ONE device pass
        over the resident merged view — the kv Streamer analog (reference:
        pkg/kv/kvclient/kvstreamer; pebbleMVCCScanner per-scan semantics
        preserved). A serial scan() pays a dispatch+sync round trip per op
        (~70ms over the TPU tunnel); batching B scans amortizes that to one,
        which is the only way a scan-heavy workload (YCSB-E) can exceed
        1/RTT ops/sec on remote-attached hardware."""
        from ..utils import metric

        if not starts:
            return []
        metric.ENGINE_SCANS.inc(len(starts))
        # sorted sources, merged lazily per WINDOW (mergingIter shape): the
        # per-batch cost scales with the windows, never with the store — no
        # store-wide overlay re-sort when the memtable changed
        sources = []
        mb = self._mem_block()
        if mb is not None:
            sources.append(mb)
        sources.extend(self.runs)
        if not sources:
            return [[] for _ in starts]
        enc = [
            (s.encode() if isinstance(s, str) else bytes(s)) for s in starts
        ]
        starts_words = jnp.asarray(K.encode_bounds(enc, self.key_width))
        B = len(enc)
        max_cap = max(s.capacity for s in sources)
        # sticky converged window (keyed by max_keys): version-dense key
        # ranges force window growth past the initial 2*max_keys, and
        # re-learning the growth by retrying EVERY batch would pay the
        # whole ladder of extra device passes per call. 2x (not 4x): the
        # common case is ~1 visible version per key, and halving the
        # window halves every per-batch gather/merge/filter pass; dense
        # histories converge via the sticky growth after one retry
        window = self._scan_windows.get(
            max_keys, _pad(max(16, 2 * max_keys), _CAND_ALIGN)
        )
        while True:
            win, sel, conflict, complete, truncated = (
                mvcc.multi_scan_sources(
                    tuple(sources), starts_words, jnp.int64(ts),
                    jnp.int64(txn), window=window,
                )
            )
            # device-side: compact selected rows to [B, max_keys] BEFORE
            # materializing — the host (and the TPU tunnel) sees B*max_keys
            # rows, never the full windows
            keys_d, vals_d, vlen_d, counts_d = mvcc._emit_stage(
                win, sel & complete, B, max_keys
            )
            if bool(np.asarray(jnp.any(conflict))):
                cidx = np.nonzero(np.asarray(conflict))[0]
                raise WriteIntentError(
                    K.decode_keys(np.asarray(win.key)[cidx]),
                    [int(t) for t in np.asarray(win.txn)[cidx]],
                )
            counts = np.asarray(counts_d)
            # a truncated window with a short result must page forward even
            # if nothing in it was selected (e.g. a run of tombstones)
            truncated_np = np.asarray(truncated)
            if (truncated_np & (counts < max_keys)).any() and (
                window < max_cap
            ):
                window = min(_pad(window * 4, _CAND_ALIGN), _pad(max_cap))
                self._scan_windows[max_keys] = window
                continue
            keys_np = np.asarray(keys_d)
            vals_np = np.asarray(vals_d)
            vlen_np = np.asarray(vlen_d)
            out: list[list[tuple[bytes, bytes]]] = []
            for b in range(B):
                k = min(int(counts[b]), max_keys)
                ks = K.decode_keys(keys_np[b][:k])
                out.append([
                    (key, self._resolve_value(v, int(n)))
                    for key, v, n in zip(ks, vals_np[b][:k], vlen_np[b][:k])
                ])
            return out

    @_locked
    def get(self, key: bytes | str, ts: int, txn: int = 0) -> bytes | None:
        """Point read. The full consult order is bloom -> block cache ->
        device slice: each surviving run is seeked to a small candidate
        window (O(window), not O(run)) and the window is served from the
        node block cache when hot — a point read on a cached key set
        dispatches no device gather at all. A window cut inside the
        key's version set (boundary) grows geometrically, the pagination
        discipline scan() uses."""
        b = key.encode() if isinstance(key, str) else bytes(key)
        sw = K.encode_bound(b, self.key_width)
        ew = K.bound_next(sw)
        limit = 8
        while True:
            view, boundary = self._bounded_view(sw, ew, limit_rows=limit,
                                                point=b)
            if boundary is None:
                break
            # some run's window was cut inside [key, next(key)) — a
            # version of this key may be missing; widen and retry
            limit *= 4
        if view is None:
            return None
        sel, conflict = mvcc.mvcc_scan_filter(
            view, jnp.int64(ts), jnp.int64(txn),
            jnp.asarray(sw), jnp.asarray(ew),
        )
        if np.asarray(conflict).any():
            idx = np.nonzero(np.asarray(conflict))[0]
            raise WriteIntentError(
                K.decode_keys(np.asarray(view.key)[idx]),
                [int(t) for t in np.asarray(view.txn)[idx]],
            )
        idx = np.nonzero(np.asarray(sel))[0]
        if not len(idx):
            return None
        i = idx[0]
        n = int(np.asarray(view.vlen)[i])
        return self._resolve_value(np.asarray(view.value)[i], n)

    # -- intents ------------------------------------------------------------

    @_locked
    def resolve_intents(self, txn: int, commit_ts: int, commit: bool):
        """Commit or abort all of txn's intents across memtable + runs.
        WAL-logged: without a resolution record, crash replay would
        resurrect an acknowledged commit's writes as unresolved intents."""
        if self._wal is not None and not self._replaying:
            self._wal_record(_REC_RESOLVE, b"", b"", int(commit_ts), 0,
                             int(txn), commit)
        if commit:
            for k, t in self._locks.items():
                if t == txn:
                    self._newest_committed.put(k, int(commit_ts))
        self._locks = {k: t for k, t in self._locks.items() if t != txn}
        self.flush_mem_only()
        old_runs = self.runs
        self.runs = [
            mvcc.sort_block(
                mvcc.resolve_intents(
                    r, jnp.int64(txn), jnp.int64(commit_ts), commit
                )
            )
            for r in old_runs
        ]
        # every run object was replaced: retire their read metadata (and
        # block-cache entries); rebuilds stay lazy — see _meta_for
        for r in old_runs:
            self._drop_run_meta(r)
        self._gen += 1
        # the per-commit memtable flush above mints a new run every commit;
        # without a compaction hook here a commit-heavy workload grows
        # `runs` without bound and every cold _merged_view() rebuild pays
        # ~8ms/run — same trigger + IOGovernor pacing as the write path
        self._maybe_compact()

    @_locked
    def has_committed_writes_in(
        self, start: bytes | None, end: bytes | None, ts_lo: int, ts_hi: int,
        point: bool = False,
    ) -> bool:
        """Any committed version in (ts_lo, ts_hi] within [start, end)?
        The read-refresh check (kvcoord txn_interceptor_span_refresher
        semantics). ``point=True`` checks exactly the key `start`."""
        sw = K.encode_bound(start, self.key_width)
        ew = K.bound_next(sw) if point else K.encode_bound(end, self.key_width)
        view = self._view_for(sw, ew)
        if view is None:
            return False
        words = K.key_words(view.key)
        in_range = view.mask & K.words_in_range(
            words,
            None if sw is None else jnp.asarray(sw),
            None if ew is None else jnp.asarray(ew),
        )
        hit = (
            in_range & (view.txn == 0)
            & (view.ts > ts_lo) & (view.ts <= ts_hi)
        )
        return bool(np.asarray(jnp.any(hit)))

    @_locked
    def other_intent(self, key: bytes, txn: int) -> int | None:
        """Txn id of another transaction's intent on `key`, if any —
        the lock-table point lookup the write path does before laying an
        intent (concurrency_manager.SequenceReq's lock check). A pure host
        dict lookup: no device work on the write hot path."""
        b = key.encode() if isinstance(key, str) else bytes(key)
        holder = self._locks.get(b)
        return holder if holder is not None and holder != txn else None

    @_locked
    def newest_committed_ts(self, key: bytes) -> int:
        """Timestamp of the newest committed version of `key` (0 if none) —
        powers the WriteTooOld check. O(1) HOST lookup: the engine indexes
        newest-committed timestamps as writes land (like the reference's
        timestamp cache, kvserver/tscache) — a device point-read per write
        would re-upload the memtable per call and made ingest quadratic.
        open_checkpoint rebuilds the index per key from the restored runs."""
        b = key.encode() if isinstance(key, str) else bytes(key)
        return self._newest_committed.get(b, 0)

    @_locked
    def intent_keys(self, txn: int) -> list[bytes]:
        return sorted(k for k, t in self._locks.items() if t == txn)

    # -- range relocation (snapshot-rebalance primitives) -------------------

    @_locked
    def span_stats(self, start: bytes | None, end: bytes | None) -> dict:
        """Authoritative size accounting for [start, end) — the SpanStats
        RPC role feeding the split/merge size decision. Counts every live
        version's logical footprint (key width + stored value length), so
        MVCC history weighs in exactly as it does on disk."""
        view = self._merged_view()
        if view is None:
            return {"versions": 0, "logical_bytes": 0}
        sw = K.encode_bound(start, self.key_width)
        ew = K.encode_bound(end, self.key_width)
        m, _ = _range_mask(view,
                           None if sw is None else jnp.asarray(sw),
                           None if ew is None else jnp.asarray(ew))
        mask = np.asarray(m)
        n = int(mask.sum())
        vbytes = int(np.asarray(view.vlen)[mask].sum()) if n else 0
        return {"versions": n, "logical_bytes": n * self.key_width + vbytes}

    @_locked
    def export_span(self, start: bytes | None, end: bytes | None) -> dict:
        """Every VERSION in [start, end) — committed history, tombstones
        and intents included — as host arrays (the raft-snapshot payload
        role for kv/dist.py's move_range). Keys keep engine width."""
        view = self._merged_view()
        empty = {
            "key": np.zeros((0, self.key_width), np.uint8),
            "ts": np.zeros((0,), np.int64), "seq": np.zeros((0,), np.int64),
            "txn": np.zeros((0,), np.int64),
            "tomb": np.zeros((0,), np.bool_),
            "value": np.zeros((0, self.val_width), np.uint8),
            "vlen": np.zeros((0,), np.int32),
            "blob": np.zeros((0,), np.uint8),
        }
        if view is None:
            return empty
        sw = K.encode_bound(start, self.key_width)
        ew = K.encode_bound(end, self.key_width)
        m, _ = _range_mask(view,
                           None if sw is None else jnp.asarray(sw),
                           None if ew is None else jnp.asarray(ew))
        idx = np.nonzero(np.asarray(m))[0]
        if not len(idx):
            return empty
        vals_np = np.asarray(view.value)[idx]
        vlen_np = np.asarray(view.vlen)[idx]
        out = {
            "key": np.asarray(view.key)[idx],
            "ts": np.asarray(view.ts)[idx],
            "seq": np.asarray(view.seq)[idx],
            "txn": np.asarray(view.txn)[idx],
            "tomb": np.asarray(view.tomb)[idx],
            "value": vals_np,
            "vlen": vlen_np,
            # overflow payloads materialize into the export in row order
            # (this heap's offsets are meaningless to the importing
            # engine); import_rows re-homes them into its own heap by
            # walking the same order
            "blob": np.frombuffer(b"".join(
                self._resolve_value(vals_np[i], int(vlen_np[i]))
                for i in np.nonzero(vlen_np > self.val_width)[0]
            ), dtype=np.uint8),
        }
        from ..flow import memory as flowmem

        # the snapshot payload lives until the transport drops it —
        # charge its residency for that lifetime (anchored on the key
        # array: dicts take no weakrefs, and the arrays die together)
        flowmem.charge_object(
            "storage/export-staging", out["key"],
            int(sum(a.nbytes for a in out.values())))
        return out

    @_locked
    def import_rows(self, rows: dict) -> None:
        """Land exported versions as one sorted run (the snapshot-apply
        role). Rows keep their source-engine ts/seq/txn fields verbatim;
        this engine's sequence high-water mark is raised past the largest
        imported seq so future local writes always win same-(key, ts)
        ties. Committed rows refresh the tscache, intents restore their
        locks. WAL-logged via a side file (the ingest durability shape):
        acknowledged imports survive process crashes."""
        n = len(rows["ts"])
        if n == 0:
            return
        if rows["key"].shape[1] != self.key_width:
            raise ValueError("imported keys do not match engine key width")
        src_w = rows["value"].shape[1]
        if src_w > self.val_width:
            raise ValueError("imported values wider than engine val width")
        cap = _pad(n)

        def padrow(a, fill=0):
            out = np.full((cap,) + a.shape[1:], fill, a.dtype)
            out[:n] = a
            return out

        vb = np.zeros((cap, self.val_width), np.uint8)
        vb[:n, :src_w] = rows["value"]
        # re-home exported overflow payloads (vlen > SOURCE inline width)
        # — the exported pointer slots are meaningless here. A payload
        # that fits THIS engine's inline width lands inline (a narrower
        # source's overflow can be a wider target's inline row; storing a
        # pointer there would be read back as inline bytes); bigger ones
        # go to this engine's heap. The side file below persists the
        # original rows + blob, so crash replay re-runs this re-homing.
        vlen_in = np.asarray(rows["vlen"], np.int64)
        if (vlen_in > src_w).any():
            blob_b = bytes(np.asarray(rows["blob"], np.uint8).tobytes())
            off = 0
            for i in np.nonzero(vlen_in > src_w)[0]:
                ln = int(vlen_in[i])
                payload = blob_b[off:off + ln]
                off += ln
                vb[i] = 0
                if ln <= self.val_width:
                    vb[i, :ln] = np.frombuffer(payload, np.uint8)
                else:
                    ptr = len(self._blob)
                    self._blob += payload
                    vb[i, :8] = np.frombuffer(ptr.to_bytes(8, "little"),
                                              np.uint8)
        seq = rows["seq"].astype(np.int64)
        self._seq = max(self._seq, int(seq.max()))
        if self._wal is not None and not self._replaying:
            # durable-before-visible, the ingest() discipline: side file
            # first (fsynced under wal_fsync), then the WAL record naming
            # it. The marker seq is allocated ABOVE the current high-water
            # mark (and raises it) so the replay gate `seq > self._seq` is
            # strictly satisfied when earlier records have been re-applied.
            marker = self._seq + 1
            self._seq = marker
            side = f"{self.wal_path}.import{int(marker):012d}.npz"
            with open(side, "wb") as f:
                np.savez(f, key=rows["key"], ts=rows["ts"], seq=seq,
                         txn=rows["txn"], tomb=rows["tomb"],
                         value=rows["value"], vlen=rows["vlen"],
                         blob=np.asarray(rows.get(
                             "blob", np.zeros(0, np.uint8)), np.uint8))
                f.flush()
                if self.wal_fsync:
                    os.fsync(f.fileno())
            if self.wal_fsync:
                dfd = os.open(os.path.dirname(side) or ".", os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            self._wal_record(_REC_IMPORT, os.path.basename(side).encode(),
                             b"", 0, int(marker), 0, False)
        blk = mvcc.KVBlock(
            key=jnp.asarray(padrow(rows["key"])),
            ts=jnp.asarray(padrow(rows["ts"])),
            seq=jnp.asarray(padrow(seq)),
            txn=jnp.asarray(padrow(rows["txn"])),
            tomb=jnp.asarray(padrow(rows["tomb"])),
            value=jnp.asarray(vb),
            vlen=jnp.asarray(padrow(rows["vlen"])),
            mask=jnp.asarray(np.arange(cap) < n),
        )
        run = mvcc.sort_block(blk)
        _charge_run(run)
        self.runs.insert(0, run)
        self._gen += 1
        self.stats.runs = len(self.runs)
        self._register_run(run)
        committed = rows["txn"] == 0
        if committed.any():
            self._newest_committed.bulk(
                rows["key"][committed], rows["ts"][committed]
            )
        for i in np.nonzero(~committed)[0]:
            k = bytes(rows["key"][i]).rstrip(b"\x00")
            self._locks[k] = int(rows["txn"][i])
        self._maybe_compact()

    @_locked
    def clear_span(self, start: bytes | None, end: bytes | None) -> None:
        """Physically drop every version in [start, end) from the memtable
        and all runs — replica removal after a range moves away. NOT an
        MVCC delete: no tombstones, no history retained. WAL-logged (clear
        records replay in log order, like intent resolutions) so a crash
        cannot resurrect a departed range's data."""
        if self._wal is not None and not self._replaying:
            self._wal_record(_REC_CLEAR, start or b"", end or b"", 0, 0, 0,
                             end is not None)
        sw = K.encode_bound(start, self.key_width)
        ew = K.encode_bound(end, self.key_width)
        self.flush_mem_only()
        swj = None if sw is None else jnp.asarray(sw)
        ewj = None if ew is None else jnp.asarray(ew)
        new_runs = []
        for r in self.runs:
            m, cnt = _range_mask(r, swj, ewj)
            if int(np.asarray(cnt)) == 0:
                new_runs.append(r)
                continue
            # this run is rewritten or dropped: retire its read metadata
            # and block-cache entries (untouched runs keep theirs)
            self._drop_run_meta(r)
            keep = r.mask & ~m
            kept = int(np.asarray(jnp.sum(keep)))
            if kept == 0:
                continue
            r2 = mvcc.KVBlock(
                key=r.key, ts=r.ts, seq=r.seq, txn=r.txn, tomb=r.tomb,
                value=r.value, vlen=r.vlen, mask=keep,
            )
            new_runs.append(_shrink(mvcc.sort_block(r2)))
        self.runs = new_runs
        # drop lock-table entries for the departed span
        def _in(k: bytes) -> bool:
            if start is not None and k < start:
                return False
            return end is None or k < end
        self._locks = {k: t for k, t in self._locks.items() if not _in(k)}
        self._gen += 1
        self.stats.runs = len(self.runs)

    # -- stats / checkpoint -------------------------------------------------

    @_locked
    def compute_stats(self) -> MVCCStats:
        view = self._merged_view()
        s = self.stats
        if view is None:
            s.live_count = s.key_count = s.val_count = s.intent_count = 0
            return s
        mask = np.asarray(view.mask)
        s.val_count = int(mask.sum())
        s.intent_count = int((mask & (np.asarray(view.txn) != 0)).sum())
        words = np.asarray(K.key_words(view.key))[mask]
        s.key_count = len(np.unique(words, axis=0)) if len(words) else 0
        sel, _ = mvcc.mvcc_scan_filter(
            view, jnp.int64(np.iinfo(np.int64).max), jnp.int64(0)
        )
        s.live_count = int(np.asarray(sel).sum())
        return s

    @_locked
    def checkpoint(self, path: str):
        """Persist the engine state (CreateCheckpoint analog); the WAL
        truncates afterwards — everything below the checkpoint is durable
        in the .npz runs."""
        self.flush_mem_only()
        os.makedirs(path, exist_ok=True)
        for i, r in enumerate(self.runs):
            with open(os.path.join(path, f"run{i:04d}.npz"), "wb") as f:
                np.savez(
                    f,
                    key=np.asarray(r.key), ts=np.asarray(r.ts),
                    seq=np.asarray(r.seq),
                    txn=np.asarray(r.txn), tomb=np.asarray(r.tomb),
                    value=np.asarray(r.value), vlen=np.asarray(r.vlen),
                    mask=np.asarray(r.mask),
                )
                f.flush()
                os.fsync(f.fileno())
        if self._blob:
            # runs reference the overflow heap by offset; a checkpoint
            # without it would dangle every var-width value
            with open(os.path.join(path, "blob.bin"), "wb") as f:
                f.write(bytes(self._blob))
                f.flush()
                os.fsync(f.fileno())
        if self._replay_cache:
            # checkpoint truncates the WAL, which held the only durable
            # copy of the dedup entries — persist them alongside the runs
            # or a post-restore retry would double-apply
            with open(os.path.join(path, "replay_cache.json"), "w") as f:
                json.dump({cid: [s, r] for cid, (s, r)
                           in self._replay_cache.items()}, f)
                f.flush()
                os.fsync(f.fileno())
        with open(os.path.join(path, "MANIFEST"), "w") as f:
            f.write(f"{len(self.runs)} {self.key_width} {self.val_width}\n")
            f.flush()
            os.fsync(f.fileno())
        # the checkpoint must be durable BEFORE the WAL truncates, or a
        # crash in between loses acknowledged writes
        dfd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        self._truncate_wal()
        if self.wal_path is not None:
            # ingest/import side-files were only reachable through the
            # truncated WAL; their rows are in the checkpoint runs now
            import glob

            for pat in ("ingest", "import"):
                for side in glob.glob(f"{self.wal_path}.{pat}*.npz"):
                    try:
                        os.unlink(side)
                    except OSError:  # pragma: no cover - best-effort
                        pass

    @classmethod
    def open_checkpoint(cls, path: str, **kwargs) -> "Engine":
        with open(os.path.join(path, "MANIFEST")) as f:
            nruns, kw, vw = (int(x) for x in f.read().split())
        wal_path = kwargs.pop("wal_path", None)
        eng = cls(key_width=kw, val_width=vw, **kwargs)
        assert eng._wal is None, "pass wal_path to open_checkpoint, not cls"
        blob_path = os.path.join(path, "blob.bin")
        if os.path.exists(blob_path):
            with open(blob_path, "rb") as f:
                eng._blob = bytearray(f.read())
        rc_path = os.path.join(path, "replay_cache.json")
        if os.path.exists(rc_path):
            with open(rc_path) as f:
                eng._replay_cache = {
                    cid: (int(s), r) for cid, (s, r) in json.load(f).items()}
        for i in range(nruns):
            z = np.load(os.path.join(path, f"run{i:04d}.npz"))
            eng.runs.append(
                mvcc.KVBlock(
                    key=jnp.asarray(z["key"]), ts=jnp.asarray(z["ts"]),
                    seq=jnp.asarray(z["seq"]),
                    txn=jnp.asarray(z["txn"]), tomb=jnp.asarray(z["tomb"]),
                    value=jnp.asarray(z["value"]), vlen=jnp.asarray(z["vlen"]),
                    mask=jnp.asarray(z["mask"]),
                )
            )
        eng.stats.runs = len(eng.runs)
        eng._gen += 1
        # restore the write-sequence high-water mark so post-restore writes
        # keep winning same-(key, ts) tie-breaks over persisted rows, and
        # rebuild the host lock table from persisted intents
        for r in eng.runs:
            m = np.asarray(r.mask)
            if m.any():
                eng._seq = max(eng._seq, int(np.asarray(r.seq)[m].max()))
                cm = m & (np.asarray(r.txn) == 0)
                if cm.any():
                    # rebuild the per-key newest-committed index exactly —
                    # a global floor would block writers on EVERY key until
                    # the clock passed the restored max timestamp
                    idx = np.nonzero(cm)[0]
                    eng._newest_committed.bulk(
                        np.asarray(r.key)[idx], np.asarray(r.ts)[idx]
                    )
            im = m & (np.asarray(r.txn) != 0)
            if im.any():
                ks = K.decode_keys(np.asarray(r.key)[np.nonzero(im)[0]])
                ts = np.asarray(r.txn)[np.nonzero(im)[0]]
                for kk, tt in zip(ks, ts):
                    eng._locks[kk] = int(tt)
        if wal_path is not None:
            # replay records that postdate the checkpoint, then arm the WAL
            eng._arm_wal(wal_path)
        return eng
