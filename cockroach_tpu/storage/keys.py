"""Fixed-width key encoding for device-resident KV blocks.

Reference: CockroachDB MVCC keys are variable-length roachpb.Key bytes plus an
HLC timestamp suffix (pkg/storage/mvcc_key.go). TPUs want static shapes and
lane-parallel comparisons, so keys here are zero-padded fixed-width byte rows
([N, KW] uint8) whose big-endian uint64 "word lanes" compare in the same
lexicographic order as the raw bytes:

- zero-padding preserves order for keys that do not contain 0x00 bytes; the
  engine enforces max key length KW (longer keys are rejected, as the
  reference rejects keys over its limits).
- each group of 8 bytes packs into one big-endian uint64; (w0, w1, ...) tuple
  order == bytewise lexicographic order. All device comparisons, sorts and
  merges operate on these word lanes (VPU-friendly), never on strings.

Timestamps are a single int64 (the HLC walltime+logical pair collapsed; the
reference's ordering "key asc, ts desc" is preserved — pkg/storage/mvcc_key.go
EncodeMVCCKey puts the inverted ts after the key).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_KEY_WIDTH = 24  # 3 uint64 word lanes


def encode_keys(keys: list[bytes | str], width: int = DEFAULT_KEY_WIDTH) -> np.ndarray:
    """Host: list of byte/str keys -> [N, width] uint8, zero padded."""
    out = np.zeros((len(keys), width), dtype=np.uint8)
    for i, k in enumerate(keys):
        b = k.encode("utf-8") if isinstance(k, str) else bytes(k)
        if len(b) > width:
            raise ValueError(f"key longer than key width {width}: {b!r}")
        out[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
    return out


def decode_keys(arr: np.ndarray) -> list[bytes]:
    """Host: [N, width] uint8 -> raw bytes with zero padding stripped."""
    a = np.asarray(arr, dtype=np.uint8)
    if a.size == 0:
        return []
    # vectorized trailing-zero strip: length = width - leading zeros of the
    # reversed row (argmax finds the first nonzero; all-zero rows -> 0)
    nz = a[:, ::-1] != 0
    lens = np.where(nz.any(axis=1), a.shape[1] - nz.argmax(axis=1), 0)
    data = a.tobytes()
    w = a.shape[1]
    return [data[i * w: i * w + l] for i, l in enumerate(lens)]


def key_words(key: jax.Array) -> jax.Array:
    """[N, KW] uint8 -> [N, KW//8] big-endian uint64 word lanes.

    Tuple order over the word lanes equals bytewise lexicographic order.
    """
    assert key.shape[1] % 8 == 0, "key width must be a multiple of 8"
    from ..coldata.batch import pack_be_words

    return pack_be_words(key)


def words_cmp_lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a < b over [N, W] word lanes -> [N] bool."""
    lt = jnp.zeros(a.shape[:-1], dtype=jnp.bool_)
    eq = jnp.ones(a.shape[:-1], dtype=jnp.bool_)
    for i in range(a.shape[-1]):
        lt = lt | (eq & (a[..., i] < b[..., i]))
        eq = eq & (a[..., i] == b[..., i])
    return lt


def words_cmp_eq(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.all(a == b, axis=-1)


def words_in_range(
    words: jax.Array, start: jax.Array | None, end: jax.Array | None
) -> jax.Array:
    """start <= key < end over word lanes. start/end are [W] vectors (or None
    for unbounded), matching the reference's [start, end) scan bounds."""
    ok = jnp.ones(words.shape[:-1], dtype=jnp.bool_)
    if start is not None:
        ok = ok & ~words_cmp_lt(words, jnp.broadcast_to(start, words.shape))
    if end is not None:
        ok = ok & words_cmp_lt(words, jnp.broadcast_to(end, words.shape))
    return ok


def words_np(enc: np.ndarray) -> np.ndarray:
    """Host: [N, width] uint8 -> [N, width//8] uint64 big-endian word lanes
    (numpy view; no device round trip — encode_bound was measured at ~1.7ms
    per key when it packed words through a jnp dispatch)."""
    return (
        np.ascontiguousarray(enc).view(">u8").astype(np.uint64)
    )


def encode_bound(key: bytes | str | None, width: int = DEFAULT_KEY_WIDTH):
    """Host: one scan bound -> [width//8] uint64 word vector, or None."""
    if key is None:
        return None
    return words_np(encode_keys([key], width))[0]


def encode_bounds(keys: list[bytes | str], width: int = DEFAULT_KEY_WIDTH):
    """Host: batch of scan bounds -> [N, width//8] uint64 word lanes."""
    return words_np(encode_keys(keys, width))


def bound_next(words: np.ndarray) -> np.ndarray:
    """Host: the word-lane successor of an encoded key — the exclusive end
    bound for a point lookup (zero padding makes ``key + b"\\x00"`` encode
    identically to ``key``, so the successor is a +1 with carry instead;
    the reference's Key.Next() appends a 0x00 byte for the same purpose)."""
    out = np.array(words, dtype=np.uint64, copy=True)
    for i in range(len(out) - 1, -1, -1):
        out[i] = out[i] + np.uint64(1)
        if out[i] != 0:  # no carry
            break
    return out
