"""Pallas bitonic-merge — the LSM compaction k-way merge as a TPU kernel.

North-star kernel #2 (BASELINE.json): "Pebble's LSM compaction k-way merge
… become Pallas kernels". The reference merges K sorted SST runs with a
loser-tree of iterators advanced one KV at a time (pebble mergingIter;
consumed by the compaction loop). The portable engine path instead re-sorts
the concatenation (`mvcc.merge_blocks` -> `lax.sort`), paying the full
O(log^2 N) sorting-network depth and ignoring that every input run is
already sorted.

This kernel exploits the pre-sortedness: two sorted runs, with the second
reversed, form a BITONIC sequence, and a bitonic sequence sorts in log2(N)
compare-exchange stages (Batcher's bitonic merge network) instead of a full
sort's ~log2(N)^2/2. K runs merge as a pairwise tournament: log2(K) rounds
of 2-way merges, each a single VMEM-resident kernel launch.

Layout notes (mirrors pallas_scan.py):
- the flat N-row merge view is shaped [N//128, 128] (lane-major); a
  compare-exchange at stride s is a lane shift (s < 128) or a sublane-row
  shift (s >= 128) — both pad+concat selects, no gathers;
- the composite MVCC sort key (live-first, key words asc, ts desc, seq
  desc — exactly `mvcc._mvcc_sort_operands`) rides as i32 hi/lo planes;
  ordering composes from unsigned 32-bit compares;
- only the row PERMUTATION exits the kernel; the caller gathers the full
  KVBlock (values and all) once at the end.

The jnp concat+sort path stays the portable fallback and correctness
oracle (tests/test_pallas_merge.py runs both, interpret mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import mvcc as mvcc_mod
from .keys import key_words

# whole-merge VMEM residency cap: 2^17 rows x ~10 i32 planes ~= 5.3MB of
# ~16MB/core VMEM, leaving headroom for the stage temporaries
MAX_MERGE_ROWS = 1 << 17
_LANES = 128


def _split_u64(a: jax.Array) -> tuple[jax.Array, jax.Array]:
    """u64/i64 [..]-array -> (hi, lo) i32 planes (bit pattern halves)."""
    u = a.astype(jnp.uint64)
    hi = (u >> jnp.uint64(32)).astype(jnp.uint32).astype(jnp.int32)
    lo = u.astype(jnp.uint32).astype(jnp.int32)
    return hi, lo


def _operand_planes(block: mvcc_mod.KVBlock) -> list[jax.Array]:
    """The canonical MVCC sort key (mvcc._mvcc_sort_operands) as [cap] i32
    planes: [livemask, key-word hi/lo pairs, ts' hi/lo, seq' hi/lo], every
    plane compared UNSIGNED in the kernel. Pad rows use livemask=2, past
    any real row (live=0, dead=1)."""
    words = key_words(block.key)
    planes = [(~block.mask).astype(jnp.int32)]
    enc_ts = ~(block.ts.astype(jnp.uint64) ^ np.uint64(1 << 63))
    enc_seq = ~(block.seq.astype(jnp.uint64) ^ np.uint64(1 << 63))
    cols = [words[:, i] for i in range(words.shape[1])] + [enc_ts, enc_seq]
    for w in cols:
        hi, lo = _split_u64(w)
        planes += [hi, lo]
    return planes


def _ult(a: jax.Array, b: jax.Array) -> jax.Array:
    """Unsigned a < b on i32 bit patterns (flip sign bit, signed compare)."""
    bias = jnp.int32(-0x80000000)
    return (a ^ bias) < (b ^ bias)


def _lex_lt(xs: list[jax.Array], ys: list[jax.Array]) -> jax.Array:
    """Lexicographic unsigned xs < ys over parallel plane lists."""
    lt = jnp.zeros(xs[0].shape, jnp.bool_)
    eq = jnp.ones(xs[0].shape, jnp.bool_)
    for x, y in zip(xs, ys):
        lt = lt | (eq & _ult(x, y))
        eq = eq & (x == y)
    return lt


def _shift_rows(x: jax.Array, k: int, fill) -> tuple[jax.Array, jax.Array]:
    """(x shifted up by k rows, x shifted down by k rows) via pad+concat."""
    pad = jnp.full((k,) + x.shape[1:], fill, x.dtype)
    up = jnp.concatenate([x[k:], pad], axis=0)      # row r reads r+k
    down = jnp.concatenate([pad, x[:-k]], axis=0)   # row r reads r-k
    return up, down


def _shift_lanes(x: jax.Array, k: int, fill) -> tuple[jax.Array, jax.Array]:
    pad = jnp.full(x.shape[:-1] + (k,), fill, x.dtype)
    left = jnp.concatenate([x[..., k:], pad], axis=-1)   # lane c reads c+k
    right = jnp.concatenate([pad, x[..., :-k]], axis=-1)  # lane c reads c-k
    return left, right


def _merge_kernel(nplanes: int, *refs):
    """One launch = the whole bitonic merge: log2(N) compare-exchange
    stages over VMEM-resident planes; only the permutation is written."""
    in_refs, perm_out = refs[:-1], refs[-1]
    planes = [r[:] for r in in_refs[:nplanes]]
    perm = in_refs[nplanes][:]
    R, C = perm.shape
    N = R * C
    row = jax.lax.broadcasted_iota(jnp.int32, (R, C), 0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (R, C), 1)

    s = N // 2
    while s >= 1:
        if s >= C:
            rs = s // C
            is_low = (row & rs) == 0
            shifted = [_shift_rows(p, rs, 0) for p in planes]
            pperm = _shift_rows(perm, rs, -1)
        else:
            is_low = (lane & s) == 0
            shifted = [_shift_lanes(p, s, 0) for p in planes]
            pperm = _shift_lanes(perm, s, -1)
        partners = [jnp.where(is_low, fw, bw) for fw, bw in shifted]
        partner_perm = jnp.where(is_low, pperm[0], pperm[1])
        lt_xp = _lex_lt(planes, partners)
        # low slot keeps the min of the pair, high slot the max; the sort
        # key is total (seq is globally unique), so ties cannot occur
        take_mine = lt_xp == is_low
        planes = [jnp.where(take_mine, x, p)
                  for x, p in zip(planes, partners)]
        perm = jnp.where(take_mine, perm, partner_perm)
        s //= 2
    perm_out[:] = perm


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@functools.partial(jax.jit, static_argnames=("n_a", "n_b", "interpret"))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _merge_perm(a_planes, b_planes, n_a: int, n_b: int,
                interpret: bool = False) -> jax.Array:
    """Permutation that merges two sorted operand-plane sets. Returned
    indices address the row-concatenation [A; B] (pad slots are -1) and
    are themselves sorted by the composite key, pads last."""
    from jax.experimental import pallas as pl

    half = max(_next_pow2(max(n_a, n_b)), _LANES // 2)
    N = 2 * half
    R = N // _LANES

    def pad_side(planes, perm0, n, reverse):
        out_p, out_perm = [], None
        fills = [2] + [0] * (len(planes) - 1)  # livemask=2 sorts pads last
        for p, f in zip(planes, fills):
            p = jnp.concatenate([p, jnp.full((half - n,), f, p.dtype)])
            out_p.append(p[::-1] if reverse else p)
        perm = jnp.concatenate(
            [perm0, jnp.full((half - n,), -1, jnp.int32)])
        out_perm = perm[::-1] if reverse else perm
        return out_p, out_perm

    a_pad, a_perm = pad_side(a_planes, jnp.arange(n_a, dtype=jnp.int32),
                             n_a, reverse=False)
    # reversing the second sorted run makes [A; pads; rev(B)] bitonic
    b_pad, b_perm = pad_side(
        b_planes, jnp.arange(n_a, n_a + n_b, dtype=jnp.int32),
        n_b, reverse=True,
    )
    planes = [jnp.concatenate([x, y]).reshape(R, _LANES)
              for x, y in zip(a_pad, b_pad)]
    perm0 = jnp.concatenate([a_perm, b_perm]).reshape(R, _LANES)

    nplanes = len(planes)
    spec = pl.BlockSpec((R, _LANES), lambda: (0, 0))
    perm = pl.pallas_call(
        functools.partial(_merge_kernel, nplanes),
        out_shape=jax.ShapeDtypeStruct((R, _LANES), jnp.int32),
        in_specs=[spec] * (nplanes + 1),
        out_specs=spec,
        interpret=interpret,
    )(*planes, perm0)
    return perm.reshape(-1)


def merge_pair(a: mvcc_mod.KVBlock, b: mvcc_mod.KVBlock,
               interpret: bool = False) -> mvcc_mod.KVBlock:
    """Merge two SORTED KVBlocks into one sorted KVBlock (capacity the
    padded power of two; pad rows are dead). Device-resident end to end."""
    perm = _merge_perm(
        tuple(_operand_planes(a)), tuple(_operand_planes(b)),
        a.capacity, b.capacity, interpret=interpret,
    )
    big = jax.tree_util.tree_map(
        lambda x, y: jnp.concatenate([x, y], axis=0), a, b
    )
    safe = jnp.maximum(perm, 0)
    out = jax.tree_util.tree_map(lambda x: x[safe], big)
    return mvcc_mod.KVBlock(
        key=out.key, ts=out.ts, seq=out.seq, txn=out.txn, tomb=out.tomb,
        value=out.value, vlen=out.vlen, mask=out.mask & (perm >= 0),
    )


def eligible(blocks: tuple[mvcc_mod.KVBlock, ...]) -> bool:
    """The kernel handles whole-merge-in-VMEM shapes. Tournament caps
    inflate: merging two runs of capacity <= 2^k yields 2^(k+1), so the
    final round's launch is bounded by next_pow2(K) * next_pow2(max cap);
    anything past the VMEM budget takes the concat+sort fallback."""
    if len(blocks) < 2:
        return False
    bound = (_next_pow2(len(blocks))
             * 2 * _next_pow2(max(b.capacity for b in blocks)))
    return bound <= MAX_MERGE_ROWS


def merge_runs(blocks: tuple[mvcc_mod.KVBlock, ...],
               interpret: bool = False) -> mvcc_mod.KVBlock:
    """K-way merge as a pairwise tournament of bitonic merge kernels —
    log2(K) rounds, each half the launches of the last. Inputs must be
    sorted (LSM runs are); output is sorted with pads/dead rows last ONLY
    after a final dead-row compaction by the caller (compact() re-sorts
    post-GC anyway, and _shrink trims the pad tail)."""
    runs = list(blocks)
    while len(runs) > 1:
        nxt = []
        for i in range(0, len(runs) - 1, 2):
            nxt.append(merge_pair(runs[i], runs[i + 1], interpret=interpret))
        if len(runs) % 2:
            nxt.append(runs[-1])
        runs = nxt
    return runs[0]
