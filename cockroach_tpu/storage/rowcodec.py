"""SQL row <-> KV codec — the rowenc/colenc + cFetcher decode analog.

Reference: pkg/sql/rowenc encodes primary keys order-preservingly into
roachpb.Key bytes and packs the remaining columns into the value;
pkg/sql/colfetcher/cfetcher.go:230 decodes KV pairs straight into
coldata.Batch vectors, and pkg/storage/col_mvcc.go:25-90 runs that decode
inside the KV server ("direct columnar scan"). Here:

- keys:   1 prefix byte (0x01+table_id) + the int64 primary key in ten
  7-bit big-endian groups, each byte offset by 0x01 — order-preserving and
  NUL-free (the engine's zero-padded fixed-width keys cannot contain 0x00;
  the reference instead escapes 0x00 in its variable-length encoding).
- values: a null bitmap (1 bit per column, set = non-NULL) followed by one
  8-byte little-endian slot per column (floats as raw IEEE bits).
- decode: the entire value column of a KVBlock ([cap, VW] uint8) unpacks
  into typed device columns with shift-sum lane arithmetic — the direct
  columnar scan as a traced kernel, no per-row host loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import Family, Schema

PK_BYTES = 10  # ceil(64 / 7) groups
KEY_BYTES = 1 + PK_BYTES


# -- host-side encode (write path: rows arrive one at a time via kv.Txn) ----


MAX_TABLE_ID = 0xFD  # 0xFE would make table_span's end bound overflow a byte


def encode_pk(table_id: int, pk: int) -> bytes:
    """Order-preserving, NUL-free key for (table, int64 primary key)."""
    assert 0 <= table_id <= MAX_TABLE_ID
    u = (int(pk) & 0xFFFFFFFFFFFFFFFF) ^ (1 << 63)  # signed -> unsigned order
    out = bytearray([0x01 + table_id])
    for i in range(PK_BYTES - 1, -1, -1):
        out.append(0x01 + ((u >> (7 * i)) & 0x7F))
    return bytes(out)


def table_span(table_id: int) -> tuple[bytes, bytes]:
    """[start, end) covering every key of the table."""
    assert 0 <= table_id <= MAX_TABLE_ID
    return bytes([0x01 + table_id]), bytes([0x02 + table_id])


def decode_pk(key: bytes) -> int:
    u = 0
    for b in key[1:KEY_BYTES]:
        u = (u << 7) | (b - 0x01)
    return (u ^ (1 << 63)) - (1 << 64) if (u ^ (1 << 63)) >= (1 << 63) \
        else (u ^ (1 << 63))


def value_width(schema: Schema) -> int:
    nullbytes = (len(schema) + 7) // 8
    return nullbytes + 8 * len(schema)


def encode_row(schema: Schema, row: dict) -> bytes:
    """Pack one row into the fixed-width value payload. NULL = missing key
    or None value."""
    ncols = len(schema)
    nullbytes = (ncols + 7) // 8
    out = bytearray(nullbytes + 8 * ncols)
    for i, (name, t) in enumerate(zip(schema.names, schema.types)):
        v = row.get(name)
        if v is None:
            continue
        out[i // 8] |= 1 << (i % 8)  # set = non-NULL
        if t.family is Family.FLOAT:
            bits = np.float64(v).view(np.uint64)
        elif t.family is Family.BOOL:
            bits = np.uint64(1 if v else 0)
        else:
            bits = np.int64(int(v)).view(np.uint64)
        out[nullbytes + 8 * i: nullbytes + 8 * (i + 1)] = int(bits).to_bytes(
            8, "little")
    return bytes(out)


def decode_row(schema: Schema, value: bytes) -> dict:
    """Host-side single-row decode (debugging / point lookups)."""
    ncols = len(schema)
    nullbytes = (ncols + 7) // 8
    out = {}
    for i, (name, t) in enumerate(zip(schema.names, schema.types)):
        if not (value[i // 8] >> (i % 8)) & 1:
            out[name] = None
            continue
        bits = int.from_bytes(value[nullbytes + 8 * i: nullbytes + 8 * (i + 1)],
                              "little")
        if t.family is Family.FLOAT:
            out[name] = float(np.uint64(bits).view(np.float64))
        elif t.family is Family.BOOL:
            out[name] = bool(bits)
        else:
            v = bits - (1 << 64) if bits >= (1 << 63) else bits
            out[name] = v
    return out


def encode_pk_batch(table_id: int, pks: np.ndarray) -> np.ndarray:
    """Vectorized encode_pk: [N] int64 -> [N, KEY_BYTES] uint8 (the bulk
    write path's key encoder — one numpy pass, no per-row host loop)."""
    assert 0 <= table_id <= MAX_TABLE_ID
    u = (pks.astype(np.int64).astype(np.uint64)
         ^ np.uint64(1 << 63))
    n = len(pks)
    out = np.empty((n, KEY_BYTES), dtype=np.uint8)
    out[:, 0] = 0x01 + table_id
    for i in range(PK_BYTES):
        shift = np.uint64(7 * (PK_BYTES - 1 - i))
        out[:, 1 + i] = ((u >> shift) & np.uint64(0x7F)).astype(
            np.uint8) + 0x01
    return out


def encode_rows(schema: Schema, columns: dict[str, np.ndarray],
                valids: dict[str, np.ndarray] | None = None) -> np.ndarray:
    """Vectorized encode_row: typed host columns -> [N, value_width] uint8
    payloads (the colenc analog: the write path's columnar encoder; the
    per-row encode_row remains for single-row DML)."""
    valids = valids or {}
    ncols = len(schema)
    nullbytes = (ncols + 7) // 8
    n = len(next(iter(columns.values())))
    out = np.zeros((n, nullbytes + 8 * ncols), dtype=np.uint8)
    for i, (name, t) in enumerate(zip(schema.names, schema.types)):
        a = np.asarray(columns[name])
        v = valids.get(name)
        if t.family is Family.FLOAT:
            bits = a.astype(np.float64).view(np.uint64)
        elif t.family is Family.BOOL:
            bits = a.astype(np.uint64)
        else:
            bits = a.astype(np.int64).view(np.uint64)
        lanes = bits.astype("<u8").view(np.uint8).reshape(n, 8)
        off = nullbytes + 8 * i
        if v is None:
            out[:, i // 8] |= np.uint8(1 << (i % 8))
            out[:, off:off + 8] = lanes
        else:
            vb = np.asarray(v, dtype=bool)
            out[vb, i // 8] |= np.uint8(1 << (i % 8))
            out[vb, off:off + 8] = lanes[vb]
    return out


# -- device-side columnar decode (read path: the cFetcher kernel) -----------


def _le_words(bytes8: jax.Array) -> jax.Array:
    """[N, 8] uint8 -> [N] uint64 little-endian."""
    shifts = jnp.arange(8, dtype=jnp.uint64) * jnp.uint64(8)
    return jnp.sum(bytes8.astype(jnp.uint64) << shifts, axis=-1,
                   dtype=jnp.uint64)


def decode_columns(
    value: jax.Array,
    sel: jax.Array,
    schema: Schema,
    col_idxs: tuple[int, ...] | None = None,
) -> Batch:
    """[cap, VW] uint8 value payloads + selection mask -> columnar Batch.

    The direct-columnar-scan kernel (col_mvcc.go role): every requested
    column unpacks with lane-parallel shift sums; NULL bits gate `valid`."""
    ncols = len(schema)
    nullbytes = (ncols + 7) // 8
    idxs = col_idxs if col_idxs is not None else tuple(range(ncols))
    cols = []
    for i in idxs:
        t = schema.types[i]
        nb = value[:, i // 8]
        valid = ((nb >> np.uint8(i % 8)) & np.uint8(1)).astype(jnp.bool_)
        raw = _le_words(value[:, nullbytes + 8 * i: nullbytes + 8 * (i + 1)])
        if t.family is Family.FLOAT:
            # uint64 -> (lo32, hi32) -> f64: the axon X64 rewriter rejects
            # a direct u64<->f64 bitcast, the u32-pair route compiles
            # (correctness self-checked at backend init; see
            # utils/backend.float_bitcast_ok)
            from ..utils.backend import require_float_bitcast

            require_float_bitcast("FLOAT column decode")
            lo = (raw & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
            hi = (raw >> jnp.uint64(32)).astype(jnp.uint32)
            data = jax.lax.bitcast_convert_type(
                jnp.stack([lo, hi], axis=-1), jnp.float64
            )
        elif t.family is Family.BOOL:
            data = raw.astype(jnp.bool_)
        else:
            data = raw.astype(jnp.int64).astype(t.dtype)
        cols.append(Column(data=data, valid=valid & sel))
    return Batch(cols=tuple(cols), mask=sel)


def decode_pk_column(key: jax.Array) -> jax.Array:
    """[cap, KW] uint8 engine keys -> [cap] int64 primary keys (the inverse
    of encode_pk, vectorized)."""
    groups = (key[:, 1:KEY_BYTES].astype(jnp.uint64)
              - jnp.uint64(1)) & jnp.uint64(0x7F)
    shifts = (jnp.arange(PK_BYTES - 1, -1, -1, dtype=jnp.uint64)
              * jnp.uint64(7))
    u = jnp.sum(groups << shifts, axis=-1, dtype=jnp.uint64)
    return (u ^ jnp.uint64(1 << 63)).astype(jnp.int64)
