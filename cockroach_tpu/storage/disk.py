"""Disk health monitoring — the pkg/storage/disk + ballast reduction.

Reference: every store tracks device-level write stats and flags slow
disks (pkg/storage/disk/monitor.go); a preallocated ballast file
(pkg/storage/ballast.go) reserves headroom so an out-of-disk condition
can be relieved by deleting it instead of crashing unrecoverably.

Here the monitor samples the engine's OWN WAL appends (the latency that
actually gates writes) plus a periodic probe write, keeps a rolling
window, and trips a slow-disk flag when the p99 exceeds
``storage.disk.slow_threshold_ms``. Metrics feed /_status/vars via the
default registry; the Node surfaces the flag through /health.
"""

from __future__ import annotations

import collections
import os
import threading
import time
import weakref

from ..utils import locks, log, metric, settings

settings.register_float(
    "storage.disk.slow_threshold_ms", 100.0,
    "rolling p99 WAL/probe write latency above this flags the disk slow",
    lo=1.0, hi=60_000.0,
)

# process-wide gauges reflect the WORST store (max p99 / any slow) —
# the registry has no label dimension, and "any disk slow" is the signal
# an operator pages on; per-store numbers come from each Node's /health
DISK_WRITE_P99 = metric.DEFAULT.gauge(
    "storage_disk_write_p99_ms",
    "rolling p99 disk write latency (worst store)")
DISK_SLOW = metric.DEFAULT.gauge(
    "storage_disk_slow", "1 when ANY store's disk is flagged slow")
DISK_PROBES = metric.DEFAULT.counter(
    "storage_disk_probes", "disk health probe writes")

_MONITORS: weakref.WeakSet = weakref.WeakSet()  # every live DiskMonitor


class DiskMonitor:
    """Rolling-window write-latency tracker + optional background prober.

    ``observe(seconds)`` is called by the WAL append path; ``probe()``
    writes+fsyncs a small marker file to detect stalls even when the
    workload is idle (the reference's periodic stat sampling role)."""

    _PUBLISH_EVERY = 32  # amortize the O(window log window) p99 sort

    def __init__(self, dir_path: str, window: int = 256):
        self.dir = dir_path
        self.samples: collections.deque[float] = collections.deque(
            maxlen=window)
        self._lock = locks.lock("storage.disk_health")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._since_publish = 0
        self._slow = False
        _MONITORS.add(self)

    # -- sampling ------------------------------------------------------------

    def observe(self, seconds: float) -> None:
        with self._lock:
            self.samples.append(seconds * 1e3)
            self._since_publish += 1
            publish = self._since_publish >= self._PUBLISH_EVERY
            if publish:
                self._since_publish = 0
        # publishing sorts the window — amortized off the write hot path
        # (the prober loop publishes too, covering idle stores)
        if publish:
            self._publish()

    def probe(self) -> float:
        """One marker write+fsync; returns elapsed ms (also recorded)."""
        path = os.path.join(self.dir, ".disk_probe")
        t0 = time.time()
        with open(path, "wb") as f:
            f.write(b"x" * 512)
            f.flush()
            os.fsync(f.fileno())
        el = time.time() - t0
        DISK_PROBES.inc()
        self.observe(el)
        self._publish()  # the prober publishes even on idle stores
        return el * 1e3

    def p99_ms(self) -> float:
        with self._lock:
            if not self.samples:
                return 0.0
            s = sorted(self.samples)
            return s[min(len(s) - 1, int(len(s) * 0.99))]

    def is_slow(self) -> bool:
        # computed fresh (not the cached _slow flag): /health must see a
        # stall immediately, not at the next publish boundary
        return self.p99_ms() > settings.get("storage.disk.slow_threshold_ms")

    def _publish(self) -> None:
        p99 = self.p99_ms()
        slow = p99 > settings.get("storage.disk.slow_threshold_ms")
        if slow and not self._slow:
            log.warning(log.STORAGE, "disk flagged SLOW", dir=self.dir,
                        p99_ms=round(p99, 1))
        elif self._slow and not slow:
            log.info(log.STORAGE, "disk recovered", dir=self.dir,
                     p99_ms=round(p99, 1))
        self._slow = slow
        # gauges max-merge across every live monitor (worst store wins)
        worst = 0.0
        any_slow = False
        for m in list(_MONITORS):
            worst = max(worst, m.p99_ms())
            any_slow = any_slow or m._slow
        DISK_WRITE_P99.set(worst)
        DISK_SLOW.set(1.0 if any_slow else 0.0)

    # -- background prober ---------------------------------------------------

    def start(self, interval_s: float = 5.0) -> "DiskMonitor":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(interval_s,), name="disk-monitor",
            daemon=True)
        self._thread.start()
        return self

    def _loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.probe()
            except OSError as e:  # a failing probe IS the signal
                log.error(log.STORAGE, "disk probe failed", error=str(e))
                self.observe(settings.get(
                    "storage.disk.slow_threshold_ms") / 1e3 * 10)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# ballast


def create_ballast(dir_path: str, size_bytes: int = 16 << 20) -> str:
    """Preallocate the emergency-headroom file (ballast.go role). Returns
    its path; no-op if it already exists at (>=) the requested size."""
    path = os.path.join(dir_path, "EMERGENCY_BALLAST")
    try:
        if os.path.getsize(path) >= size_bytes:
            return path
    except OSError:
        pass
    with open(path, "wb") as f:
        # sparse-unfriendly fill so the space is genuinely reserved
        chunk = b"\0" * (1 << 20)
        left = size_bytes
        while left > 0:
            f.write(chunk[:min(len(chunk), left)])
            left -= len(chunk)
        f.flush()
        os.fsync(f.fileno())
    return path


def release_ballast(dir_path: str) -> bool:
    """Delete the ballast to relieve an out-of-disk condition. Returns
    True if space was freed."""
    path = os.path.join(dir_path, "EMERGENCY_BALLAST")
    try:
        os.unlink(path)
        log.warning(log.STORAGE, "ballast released", path=path)
        return True
    except OSError:
        return False
