"""MVCC kernels — the pebbleMVCCScanner hot loop, TPU-first.

Reference semantics (pkg/storage/pebble_mvcc_scanner.go:381): iterate entries
sorted by (key asc, ts desc); per key pick the newest version with ts <=
read_ts; skip deletion tombstones; an intent (provisional value of an
uncommitted txn) at ts <= read_ts from another txn is a WriteIntentError,
while the reader's own intent is visible regardless of its timestamp.

The reference walks this one KV at a time per range scan. Here the whole
sorted block is processed in one vectorized pass:

- key-run boundaries come from comparing adjacent key word lanes;
- "newest visible per key" is a segmented argmin over row position (rows are
  already ts-desc within a key), via ``jax.ops.segment_min``;
- intents, tombstones and bounds are boolean algebra over the block.

Compaction (pebble's merging iterator + GC, the "LSM compaction k-way merge"
north-star kernel) is the same machinery: sort the concatenation of runs by
(key, ts desc) with XLA's lane-parallel sort, then a segmented pass drops
versions shadowed below the GC threshold.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import segscan
from .keys import key_words, words_cmp_eq, words_in_range

_BIG = np.int32(2**31 - 1)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class KVBlock:
    """Columnar MVCC entries over a static-capacity tile.

    key   : [cap, KW] uint8 zero-padded key bytes
    ts    : [cap] int64 version timestamp (HLC collapsed to one int64)
    seq   : [cap] int64 write sequence; breaks ties among same-(key, ts)
            writes, newest-sequence-wins (the reference's intent sequence
            numbers, enginepb.TxnSeq)
    txn   : [cap] int64 intent owner txn id; 0 = committed
    tomb  : [cap] bool deletion tombstone
    value : [cap, VW] uint8 fixed-width value payload
    vlen  : [cap] int32 logical value length
    mask  : [cap] bool row liveness
    """

    key: jax.Array
    ts: jax.Array
    seq: jax.Array
    txn: jax.Array
    tomb: jax.Array
    value: jax.Array
    vlen: jax.Array
    mask: jax.Array

    @property
    def capacity(self) -> int:
        return self.mask.shape[0]


def empty_block(cap: int, key_width: int, val_width: int) -> KVBlock:
    return KVBlock(
        key=jnp.zeros((cap, key_width), jnp.uint8),
        ts=jnp.zeros((cap,), jnp.int64),
        seq=jnp.zeros((cap,), jnp.int64),
        txn=jnp.zeros((cap,), jnp.int64),
        tomb=jnp.zeros((cap,), jnp.bool_),
        value=jnp.zeros((cap, val_width), jnp.uint8),
        vlen=jnp.zeros((cap,), jnp.int32),
        mask=jnp.zeros((cap,), jnp.bool_),
    )


def block_from_host(
    keys: np.ndarray,
    ts: np.ndarray,
    txn: np.ndarray,
    tomb: np.ndarray,
    value: np.ndarray,
    vlen: np.ndarray,
    cap: int | None = None,
    seq: np.ndarray | None = None,
) -> KVBlock:
    """Pad on the HOST, then one upload per field. (The previous device
    `.at[:n].set` scatters re-specialized per live count n — every
    memtable rebuild after an insert paid ~50ms x 8 fields of XLA compile
    on the scan path.)"""
    n = len(ts)
    cap = cap or max(1, n)
    if seq is None:
        seq = np.zeros(n, dtype=np.int64)

    def pad(a: np.ndarray, dtype) -> jnp.ndarray:
        a = np.asarray(a, dtype=dtype)
        out = np.zeros((cap,) + a.shape[1:], dtype=dtype)
        out[:n] = a
        return jnp.asarray(out)

    mask = np.zeros(cap, np.bool_)
    mask[:n] = True
    return KVBlock(
        key=pad(keys, np.uint8),
        ts=pad(ts, np.int64),
        seq=pad(seq, np.int64),
        txn=pad(txn, np.int64),
        tomb=pad(tomb, np.bool_),
        value=pad(value, np.uint8),
        vlen=pad(vlen, np.int32),
        mask=jnp.asarray(mask),
    )


# ---------------------------------------------------------------------------
# Sorting / merging


def _mvcc_sort_operands(block: KVBlock) -> list[jax.Array]:
    """THE canonical MVCC sort key as lax.sort operands: dead rows last,
    key bytes ascending, ts DESC, seq DESC (sign bit flipped then inverted
    for the descending u64 encodings). sort_block and the window merge
    must agree exactly — the filter's newest-visible logic assumes it."""
    words = key_words(block.key)
    operands = [~block.mask]
    operands += [words[:, i] for i in range(words.shape[1])]
    operands.append(~(block.ts.astype(jnp.uint64) ^ np.uint64(1 << 63)))
    operands.append(~(block.seq.astype(jnp.uint64) ^ np.uint64(1 << 63)))
    return operands


@jax.jit  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def sort_block(block: KVBlock) -> KVBlock:
    """Sort by (key asc, ts desc), dead rows last — the SST/memtable order
    (pkg/storage/mvcc_key.go EncodeMVCCKey ordering)."""
    cap = block.capacity
    operands = _mvcc_sort_operands(block)
    perm = jnp.arange(cap, dtype=jnp.int32)
    res = jax.lax.sort(operands + [perm], num_keys=len(operands), is_stable=True)
    p = res[-1]
    return jax.tree_util.tree_map(lambda x: x[p], block)


@functools.partial(jax.jit, static_argnames=("cap",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def merge_blocks(blocks: tuple[KVBlock, ...], cap: int) -> KVBlock:
    """K-way merge of sorted runs into one sorted tile of `cap` rows.

    The reference merges with a loser-tree of iterators (pebble
    mergingIter); on TPU the idiomatic merge of K sorted runs is a single
    lane-parallel sort of the concatenation — XLA lowers it onto the VPU,
    and the pre-sortedness costs nothing.
    """
    big = jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *blocks
    )
    total = big.capacity
    if total < cap:
        pad = empty_block(cap - total, big.key.shape[1], big.value.shape[1])
        big = jax.tree_util.tree_map(
            lambda a, b: jnp.concatenate([a, b], axis=0), big, pad
        )
    return sort_block(big)


# ---------------------------------------------------------------------------
# The scan-filter kernel


def _key_boundaries(block: KVBlock, window: int | None = None) -> jax.Array:
    """True on the first row of each key run (block sorted by key). With
    `window`, every multiple-of-window position also starts a segment —
    the multi-scan kernel packs independent scan windows side by side and
    must not let a key run bleed across a window edge."""
    words = key_words(block.key)
    same = words_cmp_eq(words[1:], words[:-1]) & block.mask[1:] & block.mask[:-1]
    boundary = jnp.concatenate([jnp.ones((1,), jnp.bool_), ~same])
    if window:
        pos = jnp.arange(block.capacity, dtype=jnp.int32)
        boundary = boundary | (pos % window == 0)
    return boundary


def _seg_bcast(op, vals, boundary, live):
    """Per-segment total of `vals` under `op`, broadcast to every row of the
    segment. Backend-adaptive (ops/segscan.py): segmented scans on TPU
    (scatter serializes on the VPU, ~100ms per 1M-row op), segment_* on CPU
    (where scatter is a cheap serial loop and 20 scan passes are not)."""
    segop = jax.ops.segment_min if op is jnp.minimum else jax.ops.segment_max
    return segscan.seg_bcast(op, segop, vals, boundary, live)


@functools.partial(jax.jit, static_argnames=("window",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def mvcc_scan_filter(
    block: KVBlock,
    read_ts: jax.Array,
    reader_txn: jax.Array,
    start_words: jax.Array | None = None,
    end_words: jax.Array | None = None,
    window: int | None = None,
):
    """Newest-visible-version selection over a sorted block.

    Returns (selected, conflict):
      selected : [cap] bool — rows that the scan returns (newest version per
                 key with ts <= read_ts, own intents always visible, deletion
                 tombstones dropped, bounds applied)
      conflict : [cap] bool — intents of *other* txns at ts <= read_ts that
                 shadow the read (WriteIntentError rows; pebble_mvcc_scanner
                 accumulates these the same way)

    `window` (static) segments the block into independent scan windows
    (scan_batch packs one scan per window).
    """
    cap = block.capacity
    words = key_words(block.key)
    in_range = block.mask & words_in_range(words, start_words, end_words)
    boundary = _key_boundaries(block, window)

    own = block.txn == reader_txn
    committed = block.txn == 0
    # visibility: committed at or before read_ts, or the reader's own intent
    # (CRDB: a txn always reads its own provisional values)
    visible = in_range & ((committed & (block.ts <= read_ts)) | (own & (block.txn != 0)))

    pos = jnp.arange(cap, dtype=jnp.int32)
    cand_pos = jnp.where(visible, pos, _BIG)
    first = _seg_bcast(jnp.minimum, cand_pos, boundary, block.mask)
    newest = visible & (pos == first)

    # an *other-txn* intent visible to this read shadows any selected version
    # at-or-below it — that's a conflict, not a silent skip
    conflict = (
        in_range
        & (block.txn != 0)
        & ~own
        & (block.ts <= read_ts)
    )
    # conflicts only matter if they are the newest candidate or newer than it:
    # since rows are ts-desc, an intent above `first` within the segment
    # conflicts; one below `first` is shadowed and irrelevant.
    conflict = conflict & (pos <= first)

    selected = newest & ~block.tomb
    return selected, conflict


@functools.partial(jax.jit, static_argnames=("bottom",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def mvcc_gc_filter(block: KVBlock, gc_ts: jax.Array, bottom: bool):
    """Compaction GC (pebble compaction + MVCC GC semantics, pkg/storage
    mvcc.go GC): keep rows that are

    - intents (never GC'd by compaction),
    - versions with ts > gc_ts (still readable by someone), or
    - the newest version at-or-below gc_ts per key — unless `bottom` and it
      is a tombstone with nothing below it (tombstone elision at the last
      level).
    """
    cap = block.capacity
    boundary = _key_boundaries(block)
    pos = jnp.arange(cap, dtype=jnp.int32)

    old = block.mask & (block.txn == 0) & (block.ts <= gc_ts)
    cand_pos = jnp.where(old, pos, _BIG)
    first_old = _seg_bcast(jnp.minimum, cand_pos, boundary, block.mask)
    newest_old = old & (pos == first_old)

    keep = block.mask & (
        (block.txn != 0) | (block.ts > gc_ts) | newest_old
    )
    if bottom:
        # elide a kept tombstone when it is the oldest surviving row of its
        # key (nothing below it to shadow)
        keep_pos = jnp.where(keep, pos, -1)
        last_keep = _seg_bcast(jnp.maximum, keep_pos, boundary, block.mask)
        elide = keep & block.tomb & newest_old & (pos == last_keep)
        keep = keep & ~elide
    return keep


# ---------------------------------------------------------------------------
# Batched multi-scan (the kv Streamer analog)


def _lex_lt(a: jax.Array, b: jax.Array) -> jax.Array:
    """Lexicographic a < b over trailing word lanes ([..., W] uint64)."""
    lt = jnp.zeros(a.shape[:-1], jnp.bool_)
    gt = jnp.zeros(a.shape[:-1], jnp.bool_)
    for w in range(a.shape[-1]):
        aw, bw = a[..., w], b[..., w]
        undecided = ~lt & ~gt
        lt = lt | (undecided & (aw < bw))
        gt = gt | (undecided & (aw > bw))
    return lt


def seek_positions(
    view_words: jax.Array, query_words: jax.Array, n_live: jax.Array
) -> jax.Array:
    """First LIVE row position with key >= query, per query — the iterator
    SeekGE over the sorted view, as an unrolled branchless binary search
    (the same shape as ops/join.bsearch, lifted to multi-word keys).

    Dead rows sort past the live prefix but hold zero key bytes (they'd
    compare below every real key), so the search is clamped to n_live."""
    n = view_words.shape[0]
    bits = max(1, int(n).bit_length())
    pos = jnp.zeros(query_words.shape[:-1], jnp.int32)
    for sb in range(bits - 1, -1, -1):
        cand = pos + (1 << sb)
        rows = view_words[jnp.clip(cand - 1, 0, n - 1)]
        ok = (cand <= n_live) & _lex_lt(rows, query_words)
        pos = jnp.where(ok, cand, pos)
    return pos


@functools.partial(jax.jit, static_argnames=("window",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _gather_stage(view: KVBlock, lo, n_live, window: int):
    n = view.capacity
    c = jnp.arange(window, dtype=jnp.int32)
    idx = lo[:, None] + c[None, :]  # [B, window]
    valid = idx < n_live
    idxc = jnp.clip(idx, 0, n - 1).reshape(-1)
    return KVBlock(
        key=view.key[idxc],
        ts=view.ts[idxc],
        seq=view.seq[idxc],
        txn=view.txn[idxc],
        tomb=view.tomb[idxc],
        value=view.value[idxc],
        vlen=view.vlen[idxc],
        mask=view.mask[idxc] & valid.reshape(-1),
    )


@functools.partial(jax.jit, static_argnames=("window",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _window_merge_stage(wins: tuple[KVBlock, ...], cuts, truncs, window: int):
    """Merge S per-source windows per scan: concatenate along the window
    axis, then ONE small sort keyed (scan id, key asc, ts desc, seq desc,
    dead-last) — the lazy merging-iterator step, paying O(B*S*window)
    per batch instead of re-sorting the whole store.

    cuts: [S, B, W] per-source truncation cut keys; truncs: [S, B] bool.
    Returns (flat merged KVBlock of capacity B*(S*window), complete flags,
    truncated-per-scan)."""
    S = len(wins)
    B = truncs.shape[1]
    CW = S * window

    def cat(field):
        parts = [getattr(w, field).reshape((B, window) +
                                           getattr(w, field).shape[1:])
                 for w in wins]
        merged = jnp.concatenate(parts, axis=1)
        return merged.reshape((B * CW,) + merged.shape[2:])

    blk = KVBlock(**{f: cat(f) for f in (
        "key", "ts", "seq", "txn", "tomb", "value", "vlen", "mask")})
    wid = jnp.repeat(jnp.arange(B, dtype=jnp.int32), CW)
    # scan id leads; within a window the CANONICAL MVCC order applies
    operands = [wid] + _mvcc_sort_operands(blk)
    perm = jnp.arange(B * CW, dtype=jnp.int32)
    res = jax.lax.sort(operands + [perm], num_keys=len(operands),
                       is_stable=True)
    p = res[-1]
    blk = jax.tree_util.tree_map(lambda x: x[p], blk)

    # completeness: a scan is truncated if ANY source cut it; rows at or
    # past the smallest cut key among truncated sources are withheld
    truncated = truncs.any(axis=0)  # [B]
    _MAXW = jnp.full(cuts.shape[1:], ~jnp.uint64(0))
    cut = _MAXW
    for s in range(S):
        s_cut = jnp.where(truncs[s][:, None], cuts[s], _MAXW)
        take = _lex_lt(s_cut, cut)
        cut = jnp.where(take[:, None], s_cut, cut)
    wwords = key_words(blk.key).reshape(B, CW, -1)
    below = _lex_lt(wwords, cut[:, None, :])
    complete = (~truncated[:, None]) | below
    return blk, complete.reshape(-1), truncated


@functools.partial(jax.jit, static_argnames=("window",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _seek_cut_stage(src: KVBlock, starts_words, window: int):
    """Seek + cut-key extraction for ONE source. Deliberately jitted
    SEPARATELY from the window gather: fusing the unrolled binary search
    with the window gathers sends XLA:CPU's fusion planner into
    minutes-long compiles (the same pathology the multi_scan split fixed);
    apart they compile in ~1s each, and no host sync separates them."""
    vwords = key_words(src.key)
    n_live = jnp.sum(src.mask, dtype=jnp.int32)
    lo = seek_positions(vwords, starts_words, n_live)
    cut_idx = jnp.clip(lo + window - 1, 0, src.capacity - 1)
    return lo, n_live, vwords[cut_idx], (lo + window) < n_live


def _source_stage(src: KVBlock, starts_words, window: int):
    lo, n_live, cut, trunc = _seek_cut_stage(src, starts_words, window)
    return _gather_stage(src, lo, n_live, window), cut, trunc


def _filter_stage_flat(win: KVBlock, read_ts, reader_txn, window: int):
    """Window filter, Pallas-fused when eligible (storage.pallas_filter):
    the kernel runs the whole pebbleMVCCScanner decision in one
    VMEM-resident pass instead of ~8 separate fused HBM passes."""
    from ..utils import settings

    mode = settings.get("storage.pallas_filter")
    # auto: TPU only — the kernel's tiling/shift shapes target Mosaic and
    # have never been exercised through the Triton (GPU) lowering
    use = mode == "on" or (
        mode == "auto" and jax.default_backend() == "tpu"
    )
    if (use and win.key.shape[1] == 16 and window % 128 == 0
            and win.capacity % window == 0):
        from .pallas_scan import pallas_scan_filter

        return pallas_scan_filter(
            win, jnp.asarray(read_ts, jnp.int64),
            jnp.asarray(reader_txn, jnp.int64), window=window,
            interpret=jax.default_backend() == "cpu",
        )
    return _filter_stage_jnp(win, read_ts, reader_txn, window)


@functools.partial(jax.jit, static_argnames=("window",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _filter_stage_jnp(win: KVBlock, read_ts, reader_txn, window: int):
    return mvcc_scan_filter(win, read_ts, reader_txn, window=window)


@functools.partial(jax.jit, static_argnames=("B", "max_keys"))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _emit_stage(blk: KVBlock, flags, B: int, max_keys: int):
    """Compact each window's selected rows to its first max_keys slots ON
    DEVICE, so the host (and, over the TPU tunnel, the wire) receives
    B*max_keys rows instead of the full windows. One stable sort by
    (window, ~selected, position) puts every window's hits at the front
    of its slice."""
    N = blk.capacity
    CW = N // B
    wid = jnp.repeat(jnp.arange(B, dtype=jnp.int32), CW)
    pos = jnp.arange(N, dtype=jnp.int32)
    _, order = jax.lax.sort(
        [(wid.astype(jnp.int64) << 32)
         | ((~flags).astype(jnp.int64) << 31) | pos.astype(jnp.int64),
         pos], num_keys=1,
    )
    take = (jnp.arange(B, dtype=jnp.int32)[:, None] * CW
            + jnp.arange(max_keys, dtype=jnp.int32)[None, :]).reshape(-1)
    idx = order[take]
    counts = jnp.sum(flags.reshape(B, CW), axis=1, dtype=jnp.int32)
    return (blk.key[idx].reshape(B, max_keys, -1),
            blk.value[idx].reshape(B, max_keys, -1),
            blk.vlen[idx].reshape(B, max_keys),
            counts)


def multi_scan_sources(
    sources: tuple[KVBlock, ...],
    starts_words: jax.Array,  # [B, W]
    read_ts: jax.Array,
    reader_txn: jax.Array,
    window: int,
):
    """B scans against S SORTED sources (memtable block + runs) with NO
    up-front store-wide merge: per-source seeks + window gathers, one
    window-local merge sort, one filter pass. The per-batch cost scales
    with B*S*window, never with the store — the pebble mergingIter
    discipline, vectorized."""
    wins, cuts, truncs = [], [], []
    for src in sources:
        win, cut, trunc = _source_stage(src, starts_words, window)
        wins.append(win)
        cuts.append(cut)
        truncs.append(trunc)
    blk, complete, truncated = _window_merge_stage(
        tuple(wins), jnp.stack(cuts), jnp.stack(truncs), window
    )
    sel, conflict = _filter_stage_flat(blk, read_ts, reader_txn,
                                       len(sources) * window)
    return blk, sel, conflict, complete, truncated


# ---------------------------------------------------------------------------
# Intent resolution


@functools.partial(jax.jit, static_argnames=("commit",))  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def resolve_intents(
    block: KVBlock, txn_id: jax.Array, commit_ts: jax.Array, commit: bool
) -> KVBlock:
    """Commit (rewrite to committed at commit_ts) or abort (drop) all intents
    of one txn — intent resolution (reference: pkg/storage/mvcc.go
    MVCCResolveWriteIntent), applied blockwise."""
    is_intent = block.mask & (block.txn == txn_id) & (block.txn != 0)
    if commit:
        return KVBlock(
            key=block.key,
            ts=jnp.where(is_intent, commit_ts, block.ts),
            seq=block.seq,
            txn=jnp.where(is_intent, 0, block.txn),
            tomb=block.tomb,
            value=block.value,
            vlen=block.vlen,
            mask=block.mask,
        )
    return KVBlock(
        key=block.key,
        ts=block.ts,
        seq=block.seq,
        txn=block.txn,
        tomb=block.tomb,
        value=block.value,
        vlen=block.vlen,
        mask=block.mask & ~is_intent,
    )
