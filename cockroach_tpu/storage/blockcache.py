"""Block cache and split-block bloom filters — the Pebble read-path stack.

Reference: CockroachDB's storage engine puts two structures between the
iterator stack and disk (pebble/sstable): per-SST **bloom filters** so point
lookups skip tables that can't contain the key, and a node-wide **block
cache** so hot decoded blocks aren't re-read and re-decoded per lookup.
Here the analogues sit between `lsm.Engine`'s read paths and kernel
dispatch: a run that fails its bloom probe costs ~nothing instead of a
`pallas_scan`, and a seek window served from cache skips the
`_slice_window` device slice entirely.

Three pieces:

- ``SplitBloom``: split-block bloom filter in the RocksDB full-filter
  shape — every key maps to ONE 512-bit block, probes stay inside it
  (cache-line locality in the reference; here it keeps the probe loop a
  handful of scalar reads). A CRC taken at build time is verified lazily
  on the FIRST negative answer: a corrupt filter disables itself and
  answers "maybe" forever after, so false negatives are structurally
  impossible even under bit corruption (chaos site
  ``storage.bloom.build``).
- ``RunMeta``: per-run read-path metadata (sorted key column for seek
  binary search, live-row count, bloom), carrying a process-unique
  ``token`` that namespaces the run's block-cache entries — unlike
  ``id(run)``, tokens are never reused, so a dead run's cached windows
  can never be served for a new run that landed at the same address.
- ``BlockCache``: node-wide clock (second-chance) cache of decoded
  ``KVBlock`` windows keyed ``(run token, window position, window
  size)``. Runs are immutable, so entries never go stale — they are only
  *invalidated* when their run dies (compaction, intent resolution) or
  *evicted* by the clock sweep under budget pressure. The budget is
  ``storage.block_cache.size_bytes``, accounted as a ``cache``-level
  child of the root memory monitor tree (flow/memory.py) so cache
  residency and query scratch compete for the same node budget.
"""

from __future__ import annotations

import itertools
import threading
import zlib
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from ..utils import faults, locks, metric

BLOOM_BITS_PER_KEY = 10
BLOOM_K = 6  # near-optimal probe count at 10 bits/key (ln2 * 10 ≈ 6.9)
_BLOCK_BITS = 512  # one cache line in the reference full-filter layout

_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)
_H2_OFFSET = np.uint64(0x9E3779B97F4A7C15)
_H2_MULT = np.uint64(0xC2B2AE3D27D4EB4F)
_MASK64 = 0xFFFFFFFFFFFFFFFF


def bloom_hashes(void_keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized double hash over a void-dtype key column: FNV-1a as h1
    plus an independent mix as h2 (forced odd so the probe sequence
    ``h1 + i*h2`` walks every residue). One pass per key byte, all keys
    at once — building a filter for a whole run is a few numpy sweeps."""
    raw = void_keys.view(np.uint8).reshape(len(void_keys), -1)
    h1 = np.full(len(void_keys), _FNV_OFFSET, dtype=np.uint64)
    h2 = np.full(len(void_keys), _H2_OFFSET, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for j in range(raw.shape[1]):
            col = raw[:, j].astype(np.uint64)
            h1 = (h1 ^ col) * _FNV_PRIME
            h2 = (h2 + col) * _H2_MULT ^ (h2 >> np.uint64(29))
    return h1, h2 | np.uint64(1)


class SplitBloom:
    """Split-block bloom filter over one run's live keys.

    The block index comes from the HIGH half of h1 and the probe bits
    from the low halves of h1/h2, so block choice and in-block probes are
    decorrelated — reusing the same bits for both collapses the filter's
    effective k. At 10 bits/key the theoretical false-positive rate is
    ~1.2%; the property test holds the line at <3%.
    """

    __slots__ = ("bits", "nblocks", "crc", "disabled", "_verified",
                 "__weakref__")

    def __init__(self, bits: np.ndarray, nblocks: int, crc: int):
        self.bits = bits
        self.nblocks = nblocks
        self.crc = crc
        self.disabled = False
        self._verified = False

    @classmethod
    def build(cls, void_keys: np.ndarray) -> "SplitBloom":
        faults.fire("storage.bloom.build")
        n = len(void_keys)
        nblocks = max(1, -(-n * BLOOM_BITS_PER_KEY // _BLOCK_BITS))
        bits = np.zeros(nblocks * _BLOCK_BITS, dtype=bool)
        if n:
            h1, h2 = bloom_hashes(void_keys)
            base = ((h1 >> np.uint64(32)) % np.uint64(nblocks)).astype(
                np.int64) * _BLOCK_BITS
            with np.errstate(over="ignore"):
                for i in range(BLOOM_K):
                    bit = ((h1 + np.uint64(i) * h2)
                           % np.uint64(_BLOCK_BITS)).astype(np.int64)
                    bits[base + bit] = True
        crc = zlib.crc32(np.packbits(bits).tobytes())
        filt = cls(bits, nblocks, crc)
        from ..flow import memory as flowmem

        # filter residency (~BLOOM_BITS_PER_KEY bytes/key as host bools)
        # charges the node budget until compaction drops the run's meta
        flowmem.charge_object("storage/bloom-residency", filt,
                              int(bits.nbytes))
        frac = faults.partial_fraction("storage.bloom.build")
        if frac is not None:
            # chaos: silent bit corruption AFTER the checksum was taken —
            # the lazy CRC verify must catch it on the first negative
            bits[:: max(1, int(round(1 / frac)))] ^= True
        return filt

    def might_contain(self, h1: int, h2: int) -> bool:
        """Probe with a precomputed (h1, h2) pair. True means "maybe
        present"; False is a proof of absence (CRC-checked)."""
        if self.disabled:
            return True
        base = ((h1 >> 32) % self.nblocks) * _BLOCK_BITS
        for i in range(BLOOM_K):
            if not self.bits[base + ((h1 + i * h2) & _MASK64) % _BLOCK_BITS]:
                # a negative is only trustworthy from an intact filter:
                # _verify is True exactly when corruption was detected
                # (the filter then answers maybe, here and forever)
                return self._verify()
        return True

    def _verify(self) -> bool:
        """First-negative CRC check. Positives never need verification
        (a flipped-ON bit only costs a wasted scan); a negative from a
        corrupt filter would LOSE a row, so the first one pays one CRC
        pass. Returns True when the filter is corrupt (and disables it)."""
        if self._verified:
            return False
        if zlib.crc32(np.packbits(self.bits).tobytes()) != self.crc:
            self.disabled = True
            metric.BLOOM_CORRUPTIONS.inc()
            return True
        self._verified = True
        return False


# Tokens are process-global and monotonic: a compacted-away run's cache
# entries can never alias a newly built run's.
_TOKENS = itertools.count(1)


@dataclass
class RunMeta:
    """Read-path metadata for one immutable sorted run."""

    token: int
    void_keys: np.ndarray  # full sorted key column, void dtype (memcmp order)
    n_live: int
    _bloom: SplitBloom | None = None
    _bloom_built: bool = False

    def bloom(self) -> SplitBloom | None:
        """The run's filter, built on first demand. Engine's run
        constructors (ingest/flush/compaction) force the build eagerly;
        rewrite paths (intent resolution, span clears) leave it lazy so
        commit-heavy workloads don't pay filter builds per txn. None
        means "no filter" — every point read scans the run (correct,
        just slower)."""
        if not self._bloom_built:
            self._bloom_built = True
            try:
                self._bloom = SplitBloom.build(self.void_keys[: self.n_live])
            except faults.InjectedFault:
                self._bloom = None
        return self._bloom


def build_meta(void_keys: np.ndarray, n_live: int) -> RunMeta:
    return RunMeta(next(_TOKENS), void_keys, int(n_live))


def block_nbytes(block) -> int:
    """Resident size of a cached window: the sum of its leaf buffers."""
    import jax

    return int(sum(int(np.asarray(x).nbytes)
                   for x in jax.tree_util.tree_leaves(block)))


class BlockCache:
    """Node-wide clock cache of decoded KVBlock windows.

    Lock order: callers (Engine) hold ``storage.engine`` before
    ``storage.blockcache``; the cache never calls back into the engine,
    so the reverse edge cannot form.
    """

    def __init__(self, name: str = "storage/block-cache"):
        self._mu = locks.rlock("storage.blockcache")
        self._name = name
        # key -> [block, nbytes, ref_bit]; dict order is clock order
        self._entries: OrderedDict[tuple, list] = OrderedDict()
        self._mon = None
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _monitor(self):
        from ..flow import memory as flowmem

        if self._mon is None:
            # long-lived "cache"-level child of the root tree — NOT a
            # query-level monitor, so the per-query drain census ignores
            # it while cache residency still charges the node budget
            self._mon = flowmem.ROOT.child(self._name, level="cache")
        return self._mon

    def _budget(self) -> int:
        from ..utils import settings

        return int(settings.get("storage.block_cache.size_bytes"))

    def get(self, token: int, pos: int, size: int):
        with self._mu:
            e = self._entries.get((token, pos, size))
            if e is None:
                self.misses += 1
                metric.BLOCKCACHE_MISSES.inc()
                return None
            e[2] = True  # second chance
            self.hits += 1
            metric.BLOCKCACHE_HITS.inc()
            return e[0]

    def put(self, token: int, pos: int, size: int, block) -> None:
        budget = self._budget()
        if budget <= 0:
            return  # cache disabled
        nbytes = block_nbytes(block)
        if nbytes > budget:
            return  # a window larger than the whole budget never caches
        from ..flow import memory as flowmem

        with self._mu:
            key = (token, pos, size)
            if key in self._entries:
                return
            mon = self._monitor()
            mon.budget = budget  # track the live setting value
            # clock sweep: referenced entries get a second chance (ref
            # cleared, rotated to the back), unreferenced ones evict
            while mon.used + nbytes > budget and self._entries:
                k, e = next(iter(self._entries.items()))
                if e[2]:
                    e[2] = False
                    self._entries.move_to_end(k)
                else:
                    del self._entries[k]
                    mon.release(e[1])
                    self.evictions += 1
                    metric.BLOCKCACHE_EVICTIONS.inc()
            try:
                mon.reserve(nbytes)
            except flowmem.BudgetExceededError:
                return  # an ancestor refused: serve uncached
            self._entries[key] = [block, nbytes, False]
            metric.BLOCKCACHE_BYTES.set(mon.used)

    def invalidate_run(self, token: int) -> None:
        """Drop every cached window of one run — and ONLY that run's:
        compaction output must not flush innocent neighbours."""
        with self._mu:
            dead = [k for k in self._entries if k[0] == token]
            for k in dead:
                e = self._entries.pop(k)
                self._mon.release(e[1])
            if dead:
                metric.BLOCKCACHE_BYTES.set(self._mon.used)

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            if self._mon is not None:
                self._mon.release()
                metric.BLOCKCACHE_BYTES.set(0)

    def used_bytes(self) -> int:
        with self._mu:
            return int(self._mon.used) if self._mon is not None else 0

    def close(self) -> None:
        with self._mu:
            self._entries.clear()
            if self._mon is not None:
                self._mon.release()
                self._mon.close()
                self._mon = None

    def stats(self) -> dict:
        with self._mu:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "bytes": int(self._mon.used) if self._mon is not None else 0,
                "entries": len(self._entries),
            }

    def describe(self) -> str:
        """One-line summary for EXPLAIN ANALYZE."""
        s = self.stats()
        total = s["hits"] + s["misses"]
        if total == 0:
            return "cold (no lookups)"
        return (f"{100.0 * s['hits'] / total:.1f}% hit rate "
                f"({s['hits']}/{total} lookups), {s['entries']} windows, "
                f"{s['bytes']} bytes")


_NODE_CACHE: BlockCache | None = None
_NODE_LOCK = threading.Lock()


def node_cache() -> BlockCache:
    """The node-wide cache every Engine on this node shares (the
    reference's cache is likewise per-store-node, not per-SST)."""
    global _NODE_CACHE
    c = _NODE_CACHE
    if c is None:
        with _NODE_LOCK:
            if _NODE_CACHE is None:
                _NODE_CACHE = BlockCache()
            c = _NODE_CACHE
    return c


def refresh_gauges() -> None:
    c = _NODE_CACHE
    if c is not None:
        metric.BLOCKCACHE_BYTES.set(c.used_bytes())
