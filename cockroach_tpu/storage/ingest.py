"""Bulk ingest — device-built sorted runs (the AddSSTable client half).

Reference: CockroachDB's bulk loaders (IMPORT, index backfill, RESTORE)
never write row-at-a-time — they build whole SSTs client-side
(bulk/sst_batcher.go) and link them into Pebble with AddSSTable, paying
one WAL record per file instead of one per key. DPG (PAPERS.md) shows the
accelerator-native shape of the same idea: sorted-run construction is a
device-side sort, not a host loop.

``RunBuilder`` is that path here. Column batches (keys + encoded values
from ``rowcodec.encode_rows``) buffer on host; at ``target_rows`` they
upload once, sort per-batch with ``mvcc.sort_block``, merge with the
bitonic ``pallas_merge`` kernel when eligible (lax.sort concat merge
otherwise), dedup in one vectorized pass, and land in the LSM as a single
run through ``Engine.ingest(presorted=True)`` — memtable and per-key WAL
bypassed, crash safety via the engine's side-file + WAL link record.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from . import keys as K
from . import mvcc
from .lsm import _pad


def enabled() -> bool:
    """Route bulk loads through the run builder?"""
    from ..utils import settings

    return bool(settings.get("storage.bulk_ingest.enabled"))


@jax.jit  # crlint: allow-raw-jit(storage-plane kernel: dispatch budget scopes the SQL flow layer)
def _dedup_sorted(block: mvcc.KVBlock) -> mvcc.KVBlock:
    """Mask away same-key duplicates in a canonically sorted block,
    keeping the FIRST row of each key group. Rows carry their batch
    arrival index as a provisional seq, and canonical order is seq-desc
    within a key — so the survivor is the latest-added batch's row
    (AddSSTable's last-write-wins within one ingestion). All rows of a
    builder run share one timestamp, so key equality is version
    equality."""
    words = K.key_words(block.key)
    same = (K.words_cmp_eq(words[1:], words[:-1])
            & block.mask[1:] & block.mask[:-1])
    dup = jnp.concatenate([jnp.zeros((1,), jnp.bool_), same])
    return dataclasses.replace(block, mask=block.mask & ~dup)


class RunBuilder:
    """Accumulate host column batches into device-built sorted runs.

    ``add()`` buffers batches; each time ``target_rows`` accumulate they
    become ONE run in the engine. ``finish()`` flushes the tail and
    reports what landed. Later-added batches win duplicate keys, matching
    the order-dependent semantics of the per-row write path it replaces.
    """

    def __init__(self, engine, ts: int, target_rows: int = 1 << 18):
        self.engine = engine
        self.ts = int(ts)
        self.target_rows = int(target_rows)
        self._batches: list[tuple[np.ndarray, np.ndarray,
                                  np.ndarray | None]] = []
        self._pending = 0
        self.rows = 0
        self.runs = 0

    def add(self, keys, values, vlens=None) -> None:
        keys = np.asarray(keys, dtype=np.uint8)
        values = np.asarray(values, dtype=np.uint8)
        if len(keys) == 0:
            return
        if keys.shape[1] > self.engine.key_width:
            raise ValueError(
                f"key width {keys.shape[1]} > engine {self.engine.key_width}")
        if values.shape[1] > self.engine.val_width:
            raise ValueError(
                f"val width {values.shape[1]} > engine {self.engine.val_width}")
        vl = None if vlens is None else np.asarray(vlens, dtype=np.int32)
        self._batches.append((keys, values, vl))
        self._pending += len(keys)
        if self._pending >= self.target_rows:
            self._flush()

    def _block_for(self, kb_in, vb_in, vl_in, seq: int) -> mvcc.KVBlock:
        eng = self.engine
        n = len(kb_in)
        cap = _pad(n)
        from ..flow import memory as flowmem

        # host padding buffers live only until jnp.asarray copies them to
        # device; the merged run's residency is charged by Engine.ingest
        est = cap * (eng.key_width + eng.val_width + 4)
        with flowmem.staged("storage/ingest-staging", est):
            kb = np.zeros((cap, eng.key_width), np.uint8)
            kb[:n, : kb_in.shape[1]] = kb_in
            vb = np.zeros((cap, eng.val_width), np.uint8)
            vb[:n, : vb_in.shape[1]] = vb_in
            vl = np.zeros(cap, np.int32)
            vl[:n] = vb_in.shape[1] if vl_in is None else vl_in
            return mvcc.KVBlock(
                key=jnp.asarray(kb),
                ts=jnp.full((cap,), self.ts, jnp.int64),
                seq=jnp.full((cap,), seq, jnp.int64),
                txn=jnp.zeros((cap,), jnp.int64),
                tomb=jnp.zeros((cap,), jnp.bool_),
                value=jnp.asarray(vb),
                vlen=jnp.asarray(vl),
                mask=jnp.asarray(np.arange(cap) < n),
            )

    def _merge(self, blocks: tuple) -> mvcc.KVBlock:
        if len(blocks) == 1:
            return blocks[0]
        # the compaction merge picker's discipline: bitonic pallas kernel
        # when eligible, concat + lax.sort otherwise
        from ..utils import settings
        from . import pallas_merge as pm

        eng = self.engine
        use = eng.pallas_merge
        if use is None:
            mode = settings.get("storage.pallas_merge")
            use = mode == "on" or (mode == "auto"
                                   and jax.default_backend() == "tpu")
        if use and eng.key_width == 16 and pm.eligible(blocks):
            interpret = (eng._pallas_merge_interpret
                         or jax.default_backend() == "cpu")
            return pm.merge_runs(blocks, interpret=interpret)
        total = sum(b.capacity for b in blocks)
        return mvcc.merge_blocks(blocks, cap=_pad(total))

    def _flush(self) -> None:
        if not self._batches:
            return
        blocks = tuple(
            mvcc.sort_block(self._block_for(kb, vb, vl, seq=i + 1))
            for i, (kb, vb, vl) in enumerate(self._batches))
        self._batches.clear()
        self._pending = 0
        merged = _dedup_sorted(self._merge(blocks))
        # materialize the live rows on host (boolean select preserves the
        # canonical order) — the engine needs host arrays for the WAL
        # side file anyway
        m = np.asarray(merged.mask)
        keys = np.asarray(merged.key)[m]
        if len(keys) == 0:
            return
        vals = np.asarray(merged.value)[m]
        vlens = np.asarray(merged.vlen)[m]
        self.engine.ingest(keys, vals, self.ts, vlens=vlens, presorted=True)
        self.rows += len(keys)
        self.runs += 1

    def finish(self) -> dict:
        """Flush the tail batch and report {rows, runs} landed."""
        self._flush()
        return {"rows": self.rows, "runs": self.runs}
