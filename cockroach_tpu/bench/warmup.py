"""Cold-wall A/B: first-execution latency with and without the AOT menu.

One phase of the ``warmup`` bench job (bench.py runs ``warmup_off`` and
``warmup_on`` as SEPARATE worker subprocesses, each with a fresh
process-global kernel cache and the persistent XLA cache disabled, so
"first execution" is honestly cold):

- **off** — serve the ladder-shaped statements on a cold node: every
  first execution pays parse + plan + XLA compile. ``cold_s`` is that
  wall.
- **on** — build the warm menu first (``sql/warmmenu.py``, the
  readiness-gated server-start path), then serve the SAME statements:
  the menu already minted every (template, rung) kernel, so serving-path
  compiles must be 0 and ``cold_s`` is pure dispatch.

``cold_menu_speedup = cold_off / cold_on`` is the headline number;
``menu_oracle_ok`` (checksums equal across phases) is the bit-identity
guard — a warmed kernel must return byte-identical results to a
cold-compiled one.
"""

from __future__ import annotations

import hashlib
import time

__all__ = ["run_warmup_cold"]


def _checksum(out) -> str:
    """Stable digest of one statement's result columns."""
    import numpy as np

    h = hashlib.sha256()
    if isinstance(out, dict):
        for name in sorted(out):
            h.update(name.encode())
            col = out[name]
            try:
                h.update(np.asarray(col).tobytes())
            except (TypeError, ValueError):
                h.update(repr(col).encode())
    else:
        h.update(repr(out).encode())
    return h.hexdigest()[:16]


def run_warmup_cold(menu: bool, sf: float = 0.05) -> dict:
    """One warmup phase over a fresh TPC-H catalog. Returns cold wall,
    serving-path compile count, per-statement checksums, and (menu mode)
    the menu build cost — bench.py pairs two phases into the A/B."""
    from ..flow import dispatch
    from ..sql import warmmenu
    from ..sql.session import Session
    from ..utils import metric, settings
    from . import tpch

    cat = tpch.gen_tpch_cached(sf=sf)
    boot = Session(catalog=cat)
    out: dict = {"menu": bool(menu)}
    try:
        stmts = warmmenu._ladder_statements(cat)
        out["statements"] = len(stmts)
        if menu:
            settings.set("sql.warmup.menu.enabled", True)
            t0 = time.perf_counter()
            k0 = dispatch.compiles()
            warmmenu.build_menu(cat, boot.db, block=True)
            out["menu_build_s"] = round(time.perf_counter() - t0, 2)
            out["menu_kernels"] = dispatch.compiles() - k0
        serve = Session(catalog=cat, db=boot.db, bootstrap=False)
        try:
            hits0 = metric.SQL_WARMUP_MENU_HITS.value
            c0 = dispatch.compiles()
            sums = []
            t0 = time.perf_counter()
            for s in stmts:
                sums.append(_checksum(serve.execute(s)))
            out["cold_s"] = round(time.perf_counter() - t0, 3)
            out["serving_compiles"] = dispatch.compiles() - c0
            out["menu_hits"] = metric.SQL_WARMUP_MENU_HITS.value - hits0
            out["checksums"] = sums
        finally:
            serve.close()
    finally:
        if menu:
            settings.set("sql.warmup.menu.enabled", False)
        boot.close()
    return out
