"""TPC-H queries as relational plans (reference: pkg/workload/tpch/queries.go
holds the SQL text; here each query is built against sql.rel.Rel). Each
builder returns a Rel; oracles live in tests (pandas over the same catalog).
"""

from __future__ import annotations

from ..catalog import Catalog
from ..ops import expr as ex
from ..sql.rel import Rel
from .tpch import d


def q1(cat: Catalog, delta_days: int = 90) -> Rel:
    """Pricing summary report: scan lineitem, filter shipdate, aggregate by
    (returnflag, linestatus), order by the same."""
    li = Rel.scan(cat, "lineitem", (
        "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
        "l_discount", "l_tax", "l_shipdate",
    ))
    cutoff = d("1998-12-01") - delta_days
    li = li.filter(ex.Cmp("le", li.c("l_shipdate"), ex.lit(cutoff)))
    one = ex.Const(1.0, li.type_of("l_discount"))
    disc_price = ex.BinOp("*", li.c("l_extendedprice"),
                          ex.BinOp("-", one, li.c("l_discount")))
    one_tax = ex.Const(1.0, li.type_of("l_tax"))
    charge = ex.BinOp("*", disc_price, ex.BinOp("+", one_tax, li.c("l_tax")))
    li = li.project([
        ("l_returnflag", li.c("l_returnflag")),
        ("l_linestatus", li.c("l_linestatus")),
        ("l_quantity", li.c("l_quantity")),
        ("l_extendedprice", li.c("l_extendedprice")),
        ("l_discount", li.c("l_discount")),
        ("disc_price", disc_price),
        ("charge", charge),
    ])
    g = li.groupby(
        ["l_returnflag", "l_linestatus"],
        [
            ("sum_qty", "sum", "l_quantity"),
            ("sum_base_price", "sum", "l_extendedprice"),
            ("sum_disc_price", "sum", "disc_price"),
            ("sum_charge", "sum", "charge"),
            ("avg_qty", "avg", "l_quantity"),
            ("avg_price", "avg", "l_extendedprice"),
            ("avg_disc", "avg", "l_discount"),
            ("count_order", "count_rows", None),
        ],
    )
    return g.sort([("l_returnflag", False), ("l_linestatus", False)])


def q3(cat: Catalog, segment: str = "BUILDING",
       date: str = "1995-03-15") -> Rel:
    """Shipping priority: customer x orders x lineitem, top 10 by revenue."""
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_mktsegment"))
    cust = cust.filter(cust.str_eq("c_mktsegment", segment))
    orders = Rel.scan(
        cat, "orders",
        ("o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"),
    )
    orders = orders.filter(
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date)))
    )
    # orders ⋈ customer (FK->PK, unique build) — semi join keeps schema lean
    ord_c = orders.join(cust, on=[("o_custkey", "c_custkey")], how="semi")
    li = Rel.scan(
        cat, "lineitem",
        ("l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"),
    )
    li = li.filter(ex.Cmp("gt", li.c("l_shipdate"), ex.lit(d(date))))
    j = li.join(ord_c, on=[("l_orderkey", "o_orderkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    revenue = ex.BinOp("*", j.c("l_extendedprice"),
                       ex.BinOp("-", one, j.c("l_discount")))
    j = j.project([
        ("l_orderkey", j.c("l_orderkey")),
        ("revenue", revenue),
        ("o_orderdate", j.c("o_orderdate")),
        ("o_shippriority", j.c("o_shippriority")),
    ])
    g = j.groupby(
        ["l_orderkey", "o_orderdate", "o_shippriority"],
        [("revenue", "sum", "revenue")],
    )
    g = g.project([
        ("l_orderkey", g.c("l_orderkey")),
        ("revenue", g.c("revenue")),
        ("o_orderdate", g.c("o_orderdate")),
        ("o_shippriority", g.c("o_shippriority")),
    ])
    return g.sort([("revenue", True), ("o_orderdate", False)]).limit(10)


def q6(cat: Catalog, date: str = "1994-01-01", discount: float = 0.06,
       quantity: int = 24) -> Rel:
    """Forecast revenue change: pure scan-filter-aggregate."""
    li = Rel.scan(cat, "lineitem", (
        "l_quantity", "l_extendedprice", "l_discount", "l_shipdate",
    ))
    dt = li.type_of("l_discount")
    pred = ex.and_(
        ex.Cmp("ge", li.c("l_shipdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_shipdate"), ex.lit(d(date) + 365)),
        ex.between(li.c("l_discount"),
                   ex.Const(discount - 0.01, dt), ex.Const(discount + 0.01, dt)),
        ex.Cmp("lt", li.c("l_quantity"),
               ex.Const(quantity, li.type_of("l_quantity"))),
    )
    li = li.filter(pred)
    li = li.project([
        ("rev", ex.BinOp("*", li.c("l_extendedprice"), li.c("l_discount"))),
    ])
    return li.scalar_agg([("revenue", "sum", "rev")])


def q5(cat: Catalog, region: str = "ASIA", date: str = "1994-01-01") -> Rel:
    """Local supplier volume: 6-way join, group by nation."""
    reg = Rel.scan(cat, "region", ("r_regionkey", "r_name"))
    reg = reg.filter(reg.str_eq("r_name", region))
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name", "n_regionkey"))
    nat = nat.join(reg, on=[("n_regionkey", "r_regionkey")], how="semi")
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_nationkey"))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_nationkey"))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_custkey", "o_orderdate"))
    orders = orders.filter(ex.and_(
        ex.Cmp("ge", orders.c("o_orderdate"), ex.lit(d(date))),
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date) + 365)),
    ))
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_suppkey", "l_extendedprice", "l_discount",
    ))
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    j = j.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    j = j.join(supp, on=[("l_suppkey", "s_suppkey")], how="inner")
    # same-nation constraint: customer and supplier nation must match
    j = j.filter(ex.Cmp("eq", j.c("c_nationkey"), j.c("s_nationkey")))
    j = j.join(nat, on=[("s_nationkey", "n_nationkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    rev = ex.BinOp("*", j.c("l_extendedprice"),
                   ex.BinOp("-", one, j.c("l_discount")))
    j = j.project([("n_name", j.c("n_name")), ("revenue", rev)])
    g = j.groupby(["n_name"], [("revenue", "sum", "revenue")])
    return g.sort([("revenue", True)])


QUERIES = {"q1": q1, "q3": q3, "q5": q5, "q6": q6}
