"""TPC-H queries as relational plans (reference: pkg/workload/tpch/queries.go
holds the SQL text; here each query is built against sql.rel.Rel). Each
builder returns a Rel; oracles live in tests (pandas over the same catalog).
"""

from __future__ import annotations

from ..catalog import Catalog
from ..ops import expr as ex
from ..sql.rel import Rel
from .tpch import d


def q1(cat: Catalog, delta_days: int = 90) -> Rel:
    """Pricing summary report: scan lineitem, filter shipdate, aggregate by
    (returnflag, linestatus), order by the same."""
    li = Rel.scan(cat, "lineitem", (
        "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
        "l_discount", "l_tax", "l_shipdate",
    ))
    cutoff = d("1998-12-01") - delta_days
    li = li.filter(ex.Cmp("le", li.c("l_shipdate"), ex.lit(cutoff)))
    one = ex.Const(1.0, li.type_of("l_discount"))
    disc_price = ex.BinOp("*", li.c("l_extendedprice"),
                          ex.BinOp("-", one, li.c("l_discount")))
    one_tax = ex.Const(1.0, li.type_of("l_tax"))
    charge = ex.BinOp("*", disc_price, ex.BinOp("+", one_tax, li.c("l_tax")))
    li = li.project([
        ("l_returnflag", li.c("l_returnflag")),
        ("l_linestatus", li.c("l_linestatus")),
        ("l_quantity", li.c("l_quantity")),
        ("l_extendedprice", li.c("l_extendedprice")),
        ("l_discount", li.c("l_discount")),
        ("disc_price", disc_price),
        ("charge", charge),
    ])
    g = li.groupby(
        ["l_returnflag", "l_linestatus"],
        [
            ("sum_qty", "sum", "l_quantity"),
            ("sum_base_price", "sum", "l_extendedprice"),
            ("sum_disc_price", "sum", "disc_price"),
            ("sum_charge", "sum", "charge"),
            ("avg_qty", "avg", "l_quantity"),
            ("avg_price", "avg", "l_extendedprice"),
            ("avg_disc", "avg", "l_discount"),
            ("count_order", "count_rows", None),
        ],
    )
    return g.sort([("l_returnflag", False), ("l_linestatus", False)])


def q3(cat: Catalog, segment: str = "BUILDING",
       date: str = "1995-03-15") -> Rel:
    """Shipping priority: customer x orders x lineitem, top 10 by revenue."""
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_mktsegment"))
    cust = cust.filter(cust.str_eq("c_mktsegment", segment))
    orders = Rel.scan(
        cat, "orders",
        ("o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"),
    )
    orders = orders.filter(
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date)))
    )
    # orders ⋈ customer (FK->PK, unique build) — semi join keeps schema lean
    ord_c = orders.join(cust, on=[("o_custkey", "c_custkey")], how="semi")
    li = Rel.scan(
        cat, "lineitem",
        ("l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"),
    )
    li = li.filter(ex.Cmp("gt", li.c("l_shipdate"), ex.lit(d(date))))
    j = li.join(ord_c, on=[("l_orderkey", "o_orderkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    revenue = ex.BinOp("*", j.c("l_extendedprice"),
                       ex.BinOp("-", one, j.c("l_discount")))
    j = j.project([
        ("l_orderkey", j.c("l_orderkey")),
        ("revenue", revenue),
        ("o_orderdate", j.c("o_orderdate")),
        ("o_shippriority", j.c("o_shippriority")),
    ])
    g = j.groupby(
        ["l_orderkey", "o_orderdate", "o_shippriority"],
        [("revenue", "sum", "revenue")],
    )
    g = g.project([
        ("l_orderkey", g.c("l_orderkey")),
        ("revenue", g.c("revenue")),
        ("o_orderdate", g.c("o_orderdate")),
        ("o_shippriority", g.c("o_shippriority")),
    ])
    return g.sort([("revenue", True), ("o_orderdate", False)]).limit(10)


def q6(cat: Catalog, date: str = "1994-01-01", discount: float = 0.06,
       quantity: int = 24) -> Rel:
    """Forecast revenue change: pure scan-filter-aggregate."""
    li = Rel.scan(cat, "lineitem", (
        "l_quantity", "l_extendedprice", "l_discount", "l_shipdate",
    ))
    dt = li.type_of("l_discount")
    pred = ex.and_(
        ex.Cmp("ge", li.c("l_shipdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_shipdate"), ex.lit(d(date) + 365)),
        ex.between(li.c("l_discount"),
                   ex.Const(discount - 0.01, dt), ex.Const(discount + 0.01, dt)),
        ex.Cmp("lt", li.c("l_quantity"),
               ex.Const(quantity, li.type_of("l_quantity"))),
    )
    li = li.filter(pred)
    li = li.project([
        ("rev", ex.BinOp("*", li.c("l_extendedprice"), li.c("l_discount"))),
    ])
    return li.scalar_agg([("revenue", "sum", "rev")])


def q5(cat: Catalog, region: str = "ASIA", date: str = "1994-01-01") -> Rel:
    """Local supplier volume: 6-way join, group by nation."""
    reg = Rel.scan(cat, "region", ("r_regionkey", "r_name"))
    reg = reg.filter(reg.str_eq("r_name", region))
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name", "n_regionkey"))
    nat = nat.join(reg, on=[("n_regionkey", "r_regionkey")], how="semi")
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_nationkey"))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_nationkey"))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_custkey", "o_orderdate"))
    orders = orders.filter(ex.and_(
        ex.Cmp("ge", orders.c("o_orderdate"), ex.lit(d(date))),
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date) + 365)),
    ))
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_suppkey", "l_extendedprice", "l_discount",
    ))
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    j = j.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    j = j.join(supp, on=[("l_suppkey", "s_suppkey")], how="inner")
    # same-nation constraint: customer and supplier nation must match
    j = j.filter(ex.Cmp("eq", j.c("c_nationkey"), j.c("s_nationkey")))
    j = j.join(nat, on=[("s_nationkey", "n_nationkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    rev = ex.BinOp("*", j.c("l_extendedprice"),
                   ex.BinOp("-", one, j.c("l_discount")))
    j = j.project([("n_name", j.c("n_name")), ("revenue", rev)])
    g = j.groupby(["n_name"], [("revenue", "sum", "revenue")])
    return g.sort([("revenue", True)])


def q4(cat: Catalog, date: str = "1993-07-01") -> Rel:
    """Order priority checking: EXISTS (late lineitem) as a semi join."""
    late = Rel.scan(cat, "lineitem", ("l_orderkey", "l_commitdate",
                                      "l_receiptdate"))
    late = late.filter(
        ex.Cmp("lt", late.c("l_commitdate"), late.c("l_receiptdate"))
    )
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_orderdate",
                                      "o_orderpriority"))
    orders = orders.filter(ex.and_(
        ex.Cmp("ge", orders.c("o_orderdate"), ex.lit(d(date))),
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date) + 92)),
    ))
    j = orders.join(late, on=[("o_orderkey", "l_orderkey")], how="semi",
                    build_unique=False)
    g = j.groupby(["o_orderpriority"], [("order_count", "count_rows", None)])
    return g.sort([("o_orderpriority", False)])


def q9(cat: Catalog, color: str = "green") -> Rel:
    """Product type profit: 6-way join, LIKE filter on p_name, profit by
    (nation, year of order date)."""
    part = Rel.scan(cat, "part", ("p_partkey", "p_name"))
    part = part.filter(part.str_pred("p_name", lambda s: color in s))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_nationkey"))
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    ps = Rel.scan(cat, "partsupp", ("ps_partkey", "ps_suppkey",
                                    "ps_supplycost"))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_orderdate"))
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_partkey", "l_suppkey", "l_quantity",
        "l_extendedprice", "l_discount",
    ))
    j = li.join(part, on=[("l_partkey", "p_partkey")], how="semi")
    j = j.join(ps, on=[("l_partkey", "ps_partkey"),
                       ("l_suppkey", "ps_suppkey")], how="inner")
    j = j.join(supp, on=[("l_suppkey", "s_suppkey")], how="inner")
    j = j.join(nat, on=[("s_nationkey", "n_nationkey")], how="inner")
    j = j.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    amount = ex.BinOp(
        "-",
        ex.BinOp("*", j.c("l_extendedprice"),
                 ex.BinOp("-", one, j.c("l_discount"))),
        ex.BinOp("*", j.c("ps_supplycost"), j.c("l_quantity")),
    )
    j = j.project([
        ("nation", j.c("n_name")),
        ("o_year", ex.ExtractYear(j.c("o_orderdate"))),
        ("amount", amount),
    ])
    g = j.groupby(["nation", "o_year"], [("sum_profit", "sum", "amount")])
    return g.sort([("nation", False), ("o_year", True)])


def q10(cat: Catalog, date: str = "1993-10-01") -> Rel:
    """Returned item reporting: top 20 customers by lost revenue."""
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_custkey",
                                      "o_orderdate"))
    orders = orders.filter(ex.and_(
        ex.Cmp("ge", orders.c("o_orderdate"), ex.lit(d(date))),
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date) + 92)),
    ))
    li = Rel.scan(cat, "lineitem", ("l_orderkey", "l_extendedprice",
                                    "l_discount", "l_returnflag"))
    li = li.filter(li.str_eq("l_returnflag", "R"))
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    cust = Rel.scan(cat, "customer", (
        "c_custkey", "c_name", "c_acctbal", "c_nationkey", "c_phone",
        "c_address", "c_comment",
    ))
    j = j.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    j = j.join(nat, on=[("c_nationkey", "n_nationkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    rev = ex.BinOp("*", j.c("l_extendedprice"),
                   ex.BinOp("-", one, j.c("l_discount")))
    j = j.project([
        ("c_custkey", j.c("c_custkey")), ("c_name", j.c("c_name")),
        ("rev", rev), ("c_acctbal", j.c("c_acctbal")),
        ("n_name", j.c("n_name")), ("c_address", j.c("c_address")),
        ("c_phone", j.c("c_phone")), ("c_comment", j.c("c_comment")),
    ])
    g = j.groupby(
        ["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
         "c_address", "c_comment"],
        [("revenue", "sum", "rev")],
    )
    return g.sort([("revenue", True), ("c_custkey", False)]).limit(20)


def q12(cat: Catalog, mode1: str = "MAIL", mode2: str = "SHIP",
        date: str = "1994-01-01") -> Rel:
    """Shipping modes and order priority: CASE aggregation."""
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_shipmode", "l_commitdate", "l_receiptdate",
        "l_shipdate",
    ))
    li = li.filter(ex.and_(
        li.str_in("l_shipmode", [mode1, mode2]),
        ex.Cmp("lt", li.c("l_commitdate"), li.c("l_receiptdate")),
        ex.Cmp("lt", li.c("l_shipdate"), li.c("l_commitdate")),
        ex.Cmp("ge", li.c("l_receiptdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_receiptdate"), ex.lit(d(date) + 365)),
    ))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_orderpriority"))
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    high = j.str_in("o_orderpriority", ["1-URGENT", "2-HIGH"])
    one, zero = ex.lit(1), ex.lit(0)
    j = j.project([
        ("l_shipmode", j.c("l_shipmode")),
        ("high", ex.Case(((high, one),), zero)),
        ("low", ex.Case(((ex.Not(high), one),), zero)),
    ])
    g = j.groupby(["l_shipmode"], [
        ("high_line_count", "sum", "high"),
        ("low_line_count", "sum", "low"),
    ])
    return g.sort([("l_shipmode", False)])


def q14(cat: Catalog, date: str = "1995-09-01") -> Rel:
    """Promotion effect: 100 * promo revenue / total revenue."""
    li = Rel.scan(cat, "lineitem", ("l_partkey", "l_extendedprice",
                                    "l_discount", "l_shipdate"))
    li = li.filter(ex.and_(
        ex.Cmp("ge", li.c("l_shipdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_shipdate"), ex.lit(d(date) + 30)),
    ))
    part = Rel.scan(cat, "part", ("p_partkey", "p_type"))
    j = li.join(part, on=[("l_partkey", "p_partkey")], how="inner")
    promo = j.str_pred("p_type", lambda s: s.startswith("PROMO"))
    one = ex.Const(1.0, j.type_of("l_discount"))
    rev = ex.BinOp("*", j.c("l_extendedprice"),
                   ex.BinOp("-", one, j.c("l_discount")))
    zero = ex.Const(0.0, ex.expr_type(rev, j.schema))
    j = j.project([
        ("promo_rev", ex.Case(((promo, rev),), zero)),
        ("rev", rev),
    ])
    g = j.scalar_agg([
        ("promo", "sum", "promo_rev"), ("total", "sum", "rev"),
    ])
    ratio = ex.BinOp("/", g.c("promo"), g.c("total"))
    hundred = ex.Const(100.0, ex.expr_type(ratio, g.schema))
    return g.project([("promo_revenue", ex.BinOp("*", hundred, ratio))])


def q18(cat: Catalog, quantity: int = 300) -> Rel:
    """Large volume customer: HAVING subquery as groupby-filter-semi-join,
    top 100 by order value."""
    li = Rel.scan(cat, "lineitem", ("l_orderkey", "l_quantity"))
    big = li.groupby(["l_orderkey"], [("sum_qty", "sum", "l_quantity")])
    big = big.filter(ex.Cmp(
        "gt", big.c("sum_qty"), ex.Const(quantity, big.type_of("sum_qty"))
    ))
    orders = Rel.scan(cat, "orders", (
        "o_orderkey", "o_custkey", "o_orderdate", "o_totalprice",
    ))
    orders = orders.join(big, on=[("o_orderkey", "l_orderkey")], how="semi")
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_name"))
    j = orders.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    li2 = Rel.scan(cat, "lineitem", ("l_orderkey", "l_quantity"))
    j2 = li2.join(j, on=[("l_orderkey", "o_orderkey")], how="inner")
    g = j2.groupby(
        ["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"],
        [("sum_qty", "sum", "l_quantity")],
    )
    return g.sort([("o_totalprice", True), ("o_orderdate", False)]).limit(100)


def _revenue(rel: Rel, price: str = "l_extendedprice",
             disc: str = "l_discount") -> ex.Expr:
    one = ex.Const(1.0, rel.type_of(disc))
    return ex.BinOp("*", rel.c(price), ex.BinOp("-", one, rel.c(disc)))


def _const_key(rel: Rel, keep: list[tuple[str, ex.Expr]]) -> Rel:
    """Append a constant join key (the scalar-subquery bridge: a 1-row side
    joins on the constant, attaching its value to every row)."""
    return rel.project(keep + [("__k", ex.lit(1))])


def q2(cat: Catalog, size: int = 15, type_suffix: str = "BRASS",
       region: str = "EUROPE") -> Rel:
    """Minimum-cost supplier: the correlated MIN subquery decorrelates into
    a per-part MIN aggregate joined back on (partkey, supplycost)."""
    reg = Rel.scan(cat, "region", ("r_regionkey", "r_name"))
    reg = reg.filter(reg.str_eq("r_name", region))
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name", "n_regionkey"))
    nat = nat.join(reg, on=[("n_regionkey", "r_regionkey")], how="semi")
    supp = Rel.scan(cat, "supplier", (
        "s_suppkey", "s_name", "s_address", "s_nationkey", "s_phone",
        "s_acctbal", "s_comment",
    ))
    supp = supp.join(nat, on=[("s_nationkey", "n_nationkey")], how="inner")
    ps = Rel.scan(cat, "partsupp", ("ps_partkey", "ps_suppkey",
                                    "ps_supplycost"))
    eps = ps.join(supp, on=[("ps_suppkey", "s_suppkey")], how="inner")
    mi = eps.groupby(["ps_partkey"], [("min_cost", "min", "ps_supplycost")])
    mi = mi.project([("mk", mi.c("ps_partkey")), ("min_cost", mi.c("min_cost"))])
    part = Rel.scan(cat, "part", ("p_partkey", "p_mfgr", "p_type", "p_size"))
    part = part.filter(ex.and_(
        ex.Cmp("eq", part.c("p_size"),
               ex.Const(size, part.type_of("p_size"))),
        part.str_pred("p_type", lambda s: s.endswith(type_suffix)),
    ))
    j = eps.join(part, on=[("ps_partkey", "p_partkey")], how="inner")
    j = j.join(mi, on=[("ps_partkey", "mk")], how="inner")
    j = j.filter(ex.Cmp("eq", j.c("ps_supplycost"), j.c("min_cost")))
    j = j.project([
        ("s_acctbal", j.c("s_acctbal")), ("s_name", j.c("s_name")),
        ("n_name", j.c("n_name")), ("p_partkey", j.c("p_partkey")),
        ("p_mfgr", j.c("p_mfgr")), ("s_address", j.c("s_address")),
        ("s_phone", j.c("s_phone")), ("s_comment", j.c("s_comment")),
    ])
    return j.sort([("s_acctbal", True), ("n_name", False),
                   ("s_name", False), ("p_partkey", False)]).limit(100)


def q7(cat: Catalog, nation1: str = "FRANCE",
       nation2: str = "GERMANY") -> Rel:
    """Volume shipping between two nations: nation scanned twice (n1/n2)
    with the symmetric pair condition as a disjunction."""
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_suppkey", "l_extendedprice", "l_discount",
        "l_shipdate",
    ))
    li = li.filter(ex.and_(
        ex.Cmp("ge", li.c("l_shipdate"), ex.lit(d("1995-01-01"))),
        ex.Cmp("le", li.c("l_shipdate"), ex.lit(d("1996-12-31"))),
    ))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_custkey"))
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_nationkey"))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_nationkey"))
    n1 = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    n1 = n1.project([("n1key", n1.c("n_nationkey")),
                     ("supp_nation", n1.c("n_name"))])
    n2 = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    n2 = n2.project([("n2key", n2.c("n_nationkey")),
                     ("cust_nation", n2.c("n_name"))])
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    j = j.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    j = j.join(supp, on=[("l_suppkey", "s_suppkey")], how="inner")
    j = j.join(n1, on=[("s_nationkey", "n1key")], how="inner")
    j = j.join(n2, on=[("c_nationkey", "n2key")], how="inner")
    j = j.filter(ex.or_(
        ex.and_(j.str_eq("supp_nation", nation1),
                j.str_eq("cust_nation", nation2)),
        ex.and_(j.str_eq("supp_nation", nation2),
                j.str_eq("cust_nation", nation1)),
    ))
    j = j.project([
        ("supp_nation", j.c("supp_nation")),
        ("cust_nation", j.c("cust_nation")),
        ("l_year", ex.ExtractYear(j.c("l_shipdate"))),
        ("volume", _revenue(j)),
    ])
    g = j.groupby(["supp_nation", "cust_nation", "l_year"],
                  [("revenue", "sum", "volume")])
    return g.sort([("supp_nation", False), ("cust_nation", False),
                   ("l_year", False)])


def q8(cat: Catalog, nation: str = "BRAZIL", region: str = "AMERICA",
       ptype: str = "ECONOMY ANODIZED STEEL") -> Rel:
    """National market share: CASE-gated share of revenue per order year."""
    part = Rel.scan(cat, "part", ("p_partkey", "p_type"))
    part = part.filter(part.str_eq("p_type", ptype))
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_partkey", "l_suppkey", "l_extendedprice",
        "l_discount",
    ))
    li = li.join(part, on=[("l_partkey", "p_partkey")], how="semi")
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_custkey",
                                      "o_orderdate"))
    orders = orders.filter(ex.and_(
        ex.Cmp("ge", orders.c("o_orderdate"), ex.lit(d("1995-01-01"))),
        ex.Cmp("le", orders.c("o_orderdate"), ex.lit(d("1996-12-31"))),
    ))
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_nationkey"))
    j = j.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    reg = Rel.scan(cat, "region", ("r_regionkey", "r_name"))
    reg = reg.filter(reg.str_eq("r_name", region))
    n1 = Rel.scan(cat, "nation", ("n_nationkey", "n_regionkey"))
    n1 = n1.join(reg, on=[("n_regionkey", "r_regionkey")], how="semi")
    j = j.join(n1, on=[("c_nationkey", "n_nationkey")], how="semi")
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_nationkey"))
    j = j.join(supp, on=[("l_suppkey", "s_suppkey")], how="inner")
    n2 = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    n2 = n2.project([("n2key", n2.c("n_nationkey")),
                     ("nation", n2.c("n_name"))])
    j = j.join(n2, on=[("s_nationkey", "n2key")], how="inner")
    vol = _revenue(j)
    volt = ex.expr_type(vol, j.schema)
    is_nat = j.str_eq("nation", nation)
    j = j.project([
        ("o_year", ex.ExtractYear(j.c("o_orderdate"))),
        ("volume", vol),
        ("nat_volume", ex.Case(((is_nat, vol),), ex.Const(0.0, volt))),
    ])
    g = j.groupby(["o_year"], [("nat", "sum", "nat_volume"),
                               ("total", "sum", "volume")])
    g = g.project([
        ("o_year", g.c("o_year")),
        ("mkt_share", ex.BinOp("/", g.c("nat"), g.c("total"))),
    ])
    return g.sort([("o_year", False)])


def q11(cat: Catalog, nation: str = "GERMANY",
        fraction: float = 0.0001) -> Rel:
    """Important stock: HAVING against a scalar subquery — the global
    threshold attaches via a constant-key join against the 1-row aggregate."""
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    nat = nat.filter(nat.str_eq("n_name", nation))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_nationkey"))
    supp = supp.join(nat, on=[("s_nationkey", "n_nationkey")], how="semi")
    ps = Rel.scan(cat, "partsupp", ("ps_partkey", "ps_suppkey",
                                    "ps_supplycost", "ps_availqty"))
    ps = ps.join(supp, on=[("ps_suppkey", "s_suppkey")], how="semi")
    ps = ps.project([
        ("ps_partkey", ps.c("ps_partkey")),
        ("value", ex.BinOp("*", ps.c("ps_supplycost"),
                           ps.c("ps_availqty"))),
    ])
    g = ps.groupby(["ps_partkey"], [("value", "sum", "value")])
    tot = ps.scalar_agg([("total", "sum", "value")])
    tot = _const_key(tot, [("thr", ex.BinOp(
        "*", tot.c("total"), ex.lit(fraction)))])
    g = _const_key(g, [("ps_partkey", g.c("ps_partkey")),
                       ("value", g.c("value"))])
    j = g.join(tot, on=[("__k", "__k")], how="inner")
    j = j.filter(ex.Cmp("gt", j.c("value"), j.c("thr")))
    j = j.project([("ps_partkey", j.c("ps_partkey")),
                   ("value", j.c("value"))])
    return j.sort([("value", True)])


def q13(cat: Catalog, word1: str = "special",
        word2: str = "requests") -> Rel:
    """Customer order-count distribution: LEFT JOIN + COUNT of the nullable
    side, then a second aggregation over the counts."""
    import re as _re

    pat = _re.compile(f".*{word1}.*{word2}.*", _re.DOTALL)
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_custkey",
                                      "o_comment"))
    orders = orders.filter(
        ex.Not(orders.str_pred("o_comment", lambda s: bool(pat.match(s))))
    )
    orders = orders.project([("o_orderkey", orders.c("o_orderkey")),
                             ("o_custkey", orders.c("o_custkey"))])
    cust = Rel.scan(cat, "customer", ("c_custkey",))
    j = cust.join(orders, on=[("c_custkey", "o_custkey")], how="left",
                  build_unique=False)
    g = j.groupby(["c_custkey"], [("c_count", "count", "o_orderkey")])
    g2 = g.groupby(["c_count"], [("custdist", "count_rows", None)])
    return g2.sort([("custdist", True), ("c_count", True)])


def q15(cat: Catalog, date: str = "1996-01-01") -> Rel:
    """Top supplier: total revenue per supplier equal to the global MAX
    (scalar subquery via constant-key join)."""
    li = Rel.scan(cat, "lineitem", ("l_suppkey", "l_extendedprice",
                                    "l_discount", "l_shipdate"))
    li = li.filter(ex.and_(
        ex.Cmp("ge", li.c("l_shipdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_shipdate"), ex.lit(d(date) + 90)),
    ))
    li = li.project([("l_suppkey", li.c("l_suppkey")),
                     ("rev", _revenue(li))])
    rev = li.groupby(["l_suppkey"], [("total_revenue", "sum", "rev")])
    mx = rev.scalar_agg([("mx", "max", "total_revenue")])
    mx = _const_key(mx, [("mx", mx.c("mx"))])
    rev = _const_key(rev, [("l_suppkey", rev.c("l_suppkey")),
                           ("total_revenue", rev.c("total_revenue"))])
    j = rev.join(mx, on=[("__k", "__k")], how="inner")
    j = j.filter(ex.Cmp("eq", j.c("total_revenue"), j.c("mx")))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_name", "s_address",
                                      "s_phone"))
    j = supp.join(j, on=[("s_suppkey", "l_suppkey")], how="inner")
    j = j.project([
        ("s_suppkey", j.c("s_suppkey")), ("s_name", j.c("s_name")),
        ("s_address", j.c("s_address")), ("s_phone", j.c("s_phone")),
        ("total_revenue", j.c("total_revenue")),
    ])
    return j.sort([("s_suppkey", False)])


def q16(cat: Catalog, brand: str = "Brand#45",
        type_prefix: str = "MEDIUM POLISHED",
        sizes: tuple[int, ...] = (49, 14, 23, 45, 19, 3, 36, 9)) -> Rel:
    """Parts/supplier relationship: COUNT(DISTINCT) as distinct+count, and
    NOT IN as an anti join over provably non-null supplier keys."""
    part = Rel.scan(cat, "part", ("p_partkey", "p_brand", "p_type",
                                  "p_size"))
    part = part.filter(ex.and_(
        ex.Not(part.str_eq("p_brand", brand)),
        ex.Not(part.str_pred("p_type",
                             lambda s: s.startswith(type_prefix))),
        ex.or_(*[
            ex.Cmp("eq", part.c("p_size"),
                   ex.Const(s, part.type_of("p_size")))
            for s in sizes
        ]),
    ))
    ps = Rel.scan(cat, "partsupp", ("ps_partkey", "ps_suppkey"))
    j = ps.join(part, on=[("ps_partkey", "p_partkey")], how="inner")
    bad = Rel.scan(cat, "supplier", ("s_suppkey", "s_comment"))
    bad = bad.filter(bad.str_pred(
        "s_comment",
        lambda s: "Customer" in s and "Complaints" in s.split("Customer", 1)[1],
    ))
    j = j.join(bad, on=[("ps_suppkey", "s_suppkey")], how="anti")
    dist = j.distinct(["p_brand", "p_type", "p_size", "ps_suppkey"])
    g = dist.groupby(["p_brand", "p_type", "p_size"],
                     [("supplier_cnt", "count_rows", None)])
    return g.sort([("supplier_cnt", True), ("p_brand", False),
                   ("p_type", False), ("p_size", False)])


def q17(cat: Catalog, brand: str = "Brand#23",
        container: str = "MED BOX") -> Rel:
    """Small-quantity-order revenue: per-part AVG decorrelates into a
    grouped aggregate joined back on the part key."""
    part = Rel.scan(cat, "part", ("p_partkey", "p_brand", "p_container"))
    part = part.filter(ex.and_(
        part.str_eq("p_brand", brand),
        part.str_eq("p_container", container),
    ))
    li = Rel.scan(cat, "lineitem", ("l_partkey", "l_quantity",
                                    "l_extendedprice"))
    lif = li.join(part, on=[("l_partkey", "p_partkey")], how="semi")
    a = lif.groupby(["l_partkey"], [("avg_q", "avg", "l_quantity")])
    a = a.project([
        ("ak", a.c("l_partkey")),
        ("thr", ex.BinOp("*", ex.lit(0.2), a.c("avg_q"))),
    ])
    j = lif.join(a, on=[("l_partkey", "ak")], how="inner")
    j = j.filter(ex.Cmp("lt", j.c("l_quantity"), j.c("thr")))
    g = j.scalar_agg([("s", "sum", "l_extendedprice")])
    return g.project([("avg_yearly", ex.BinOp("/", g.c("s"),
                                              ex.lit(7.0)))])


def q19(cat: Catalog, qty1: int = 1, qty2: int = 10, qty3: int = 20,
        width: int = 10, sizes: tuple[int, int, int] = (5, 10, 15)) -> Rel:
    """Discounted revenue: disjunction of three conjunctive branches mixing
    part and lineitem predicates (quantity windows parameterized as in
    pkg/workload/tpch/queries.go)."""
    li = Rel.scan(cat, "lineitem", (
        "l_partkey", "l_quantity", "l_extendedprice", "l_discount",
        "l_shipmode", "l_shipinstruct",
    ))
    li = li.filter(ex.and_(
        li.str_in("l_shipmode", ["AIR", "AIR REG"]),
        li.str_eq("l_shipinstruct", "DELIVER IN PERSON"),
    ))
    part = Rel.scan(cat, "part", ("p_partkey", "p_brand", "p_container",
                                  "p_size"))
    j = li.join(part, on=[("l_partkey", "p_partkey")], how="inner")

    def branch(b, containers, qlo, qhi, smax):
        qt = j.type_of("l_quantity")
        return ex.and_(
            j.str_eq("p_brand", b),
            j.str_in("p_container", containers),
            ex.between(j.c("l_quantity"), ex.Const(qlo, qt),
                       ex.Const(qhi, qt)),
            ex.between(j.c("p_size"), ex.Const(1, j.type_of("p_size")),
                       ex.Const(smax, j.type_of("p_size"))),
        )

    j = j.filter(ex.or_(
        branch("Brand#12", ["SM CASE", "SM BOX", "SM PACK", "SM PKG"],
               qty1, qty1 + width, sizes[0]),
        branch("Brand#23", ["MED BAG", "MED BOX", "MED PKG", "MED PACK"],
               qty2, qty2 + width, sizes[1]),
        branch("Brand#34", ["LG CASE", "LG BOX", "LG PACK", "LG PKG"],
               qty3, qty3 + width, sizes[2]),
    ))
    j = j.project([("rev", _revenue(j))])
    return j.scalar_agg([("revenue", "sum", "rev")])


def q20(cat: Catalog, color: str = "forest", nation: str = "CANADA",
        date: str = "1994-01-01") -> Rel:
    """Potential part promotion: nested IN subqueries decorrelate into a
    per-(part,supp) lineitem sum joined against partsupp availability."""
    pf = Rel.scan(cat, "part", ("p_partkey", "p_name"))
    pf = pf.filter(pf.str_pred("p_name", lambda s: s.startswith(color)))
    li = Rel.scan(cat, "lineitem", ("l_partkey", "l_suppkey", "l_quantity",
                                    "l_shipdate"))
    li = li.filter(ex.and_(
        ex.Cmp("ge", li.c("l_shipdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_shipdate"), ex.lit(d(date) + 365)),
    ))
    li = li.join(pf, on=[("l_partkey", "p_partkey")], how="semi")
    s = li.groupby(["l_partkey", "l_suppkey"], [("q", "sum", "l_quantity")])
    s = s.project([
        ("pk2", s.c("l_partkey")), ("sk2", s.c("l_suppkey")),
        ("thr", ex.BinOp("*", ex.lit(0.5), s.c("q"))),
    ])
    ps = Rel.scan(cat, "partsupp", ("ps_partkey", "ps_suppkey",
                                    "ps_availqty"))
    ps = ps.join(pf, on=[("ps_partkey", "p_partkey")], how="semi")
    j = ps.join(s, on=[("ps_partkey", "pk2"), ("ps_suppkey", "sk2")],
                how="inner")
    j = j.filter(ex.Cmp("gt", j.c("ps_availqty"), j.c("thr")))
    good = j.distinct(["ps_suppkey"])
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    nat = nat.filter(nat.str_eq("n_name", nation))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_name", "s_address",
                                      "s_nationkey"))
    supp = supp.join(nat, on=[("s_nationkey", "n_nationkey")], how="semi")
    supp = supp.join(good, on=[("s_suppkey", "ps_suppkey")], how="semi")
    supp = supp.project([("s_name", supp.c("s_name")),
                         ("s_address", supp.c("s_address"))])
    return supp.sort([("s_name", False)])


def q21(cat: Catalog, nation: str = "SAUDI ARABIA") -> Rel:
    """Suppliers who kept orders waiting. The correlated EXISTS/NOT EXISTS
    with supplier inequality decorrelate into per-order distinct-supplier
    counts: EXISTS(other supp) == order has >= 2 distinct suppliers;
    NOT EXISTS(other LATE supp) == exactly 1 distinct late supplier (l1
    itself is late, so that one is l1's)."""
    li_all = Rel.scan(cat, "lineitem", ("l_orderkey", "l_suppkey"))
    ns = li_all.distinct(["l_orderkey", "l_suppkey"])
    ns = ns.groupby(["l_orderkey"], [("n_supp", "count_rows", None)])
    ns = ns.project([("ok1", ns.c("l_orderkey")),
                     ("n_supp", ns.c("n_supp"))])
    late = Rel.scan(cat, "lineitem", ("l_orderkey", "l_suppkey",
                                      "l_commitdate", "l_receiptdate"))
    late = late.filter(ex.Cmp("gt", late.c("l_receiptdate"),
                              late.c("l_commitdate")))
    late = late.project([("l_orderkey", late.c("l_orderkey")),
                         ("l_suppkey", late.c("l_suppkey"))])
    nl = late.distinct(["l_orderkey", "l_suppkey"])
    nl = nl.groupby(["l_orderkey"], [("n_late", "count_rows", None)])
    nl = nl.project([("ok2", nl.c("l_orderkey")),
                     ("n_late", nl.c("n_late"))])
    l1 = late  # the waiting lineitems themselves
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_orderstatus"))
    orders = orders.filter(orders.str_eq("o_orderstatus", "F"))
    j = l1.join(orders, on=[("l_orderkey", "o_orderkey")], how="semi")
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    nat = nat.filter(nat.str_eq("n_name", nation))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_name", "s_nationkey"))
    supp = supp.join(nat, on=[("s_nationkey", "n_nationkey")], how="semi")
    j = j.join(supp, on=[("l_suppkey", "s_suppkey")], how="inner")
    j = j.join(ns, on=[("l_orderkey", "ok1")], how="inner")
    j = j.join(nl, on=[("l_orderkey", "ok2")], how="inner")
    j = j.filter(ex.and_(
        ex.Cmp("ge", j.c("n_supp"), ex.lit(2)),
        ex.Cmp("eq", j.c("n_late"), ex.lit(1)),
    ))
    g = j.groupby(["s_name"], [("numwait", "count_rows", None)])
    return g.sort([("numwait", True), ("s_name", False)]).limit(100)


def q22(cat: Catalog,
        codes: tuple[str, ...] = ("13", "31", "23", "29", "30", "18", "17"),
        ) -> Rel:
    """Global sales opportunity: SUBSTRING becomes a host-side dictionary
    transform; the AVG subquery attaches via constant-key join; NOT EXISTS
    (orders) is an anti join."""
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_phone", "c_acctbal"))
    cust = cust.filter(
        cust.str_pred("c_phone", lambda s: s[:2] in codes)
    )
    cntry, cdict = cust.str_transform("c_phone", lambda s: s[:2])
    cust = cust.project([
        ("c_custkey", cust.c("c_custkey")),
        ("cntrycode", cntry),
        ("c_acctbal", cust.c("c_acctbal")),
    ]).with_dict("cntrycode", cdict)
    pos = cust.filter(ex.Cmp("gt", cust.c("c_acctbal"),
                             ex.Const(0.0, cust.type_of("c_acctbal"))))
    avg = pos.scalar_agg([("a", "avg", "c_acctbal")])
    avg = _const_key(avg, [("a", avg.c("a"))])
    cust = _const_key(cust, [
        ("c_custkey", cust.c("c_custkey")),
        ("cntrycode", cust.c("cntrycode")),
        ("c_acctbal", cust.c("c_acctbal")),
    ])
    # __k projection keeps the cntrycode dictionary (bare ColRef)
    j = cust.join(avg, on=[("__k", "__k")], how="inner")
    j = j.filter(ex.Cmp("gt", j.c("c_acctbal"), j.c("a")))
    orders = Rel.scan(cat, "orders", ("o_custkey",))
    j = j.join(orders, on=[("c_custkey", "o_custkey")], how="anti",
               build_unique=False)
    g = j.groupby(["cntrycode"], [
        ("numcust", "count_rows", None),
        ("totacctbal", "sum", "c_acctbal"),
    ])
    return g.sort([("cntrycode", False)])


QUERIES = {
    "q1": q1, "q2": q2, "q3": q3, "q4": q4, "q5": q5, "q6": q6, "q7": q7,
    "q8": q8, "q9": q9, "q10": q10, "q11": q11, "q12": q12, "q13": q13,
    "q14": q14, "q15": q15, "q16": q16, "q17": q17, "q18": q18, "q19": q19,
    "q20": q20, "q21": q21, "q22": q22,
}
