"""TPC-H queries as relational plans (reference: pkg/workload/tpch/queries.go
holds the SQL text; here each query is built against sql.rel.Rel). Each
builder returns a Rel; oracles live in tests (pandas over the same catalog).
"""

from __future__ import annotations

from ..catalog import Catalog
from ..ops import expr as ex
from ..sql.rel import Rel
from .tpch import d


def q1(cat: Catalog, delta_days: int = 90) -> Rel:
    """Pricing summary report: scan lineitem, filter shipdate, aggregate by
    (returnflag, linestatus), order by the same."""
    li = Rel.scan(cat, "lineitem", (
        "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
        "l_discount", "l_tax", "l_shipdate",
    ))
    cutoff = d("1998-12-01") - delta_days
    li = li.filter(ex.Cmp("le", li.c("l_shipdate"), ex.lit(cutoff)))
    one = ex.Const(1.0, li.type_of("l_discount"))
    disc_price = ex.BinOp("*", li.c("l_extendedprice"),
                          ex.BinOp("-", one, li.c("l_discount")))
    one_tax = ex.Const(1.0, li.type_of("l_tax"))
    charge = ex.BinOp("*", disc_price, ex.BinOp("+", one_tax, li.c("l_tax")))
    li = li.project([
        ("l_returnflag", li.c("l_returnflag")),
        ("l_linestatus", li.c("l_linestatus")),
        ("l_quantity", li.c("l_quantity")),
        ("l_extendedprice", li.c("l_extendedprice")),
        ("l_discount", li.c("l_discount")),
        ("disc_price", disc_price),
        ("charge", charge),
    ])
    g = li.groupby(
        ["l_returnflag", "l_linestatus"],
        [
            ("sum_qty", "sum", "l_quantity"),
            ("sum_base_price", "sum", "l_extendedprice"),
            ("sum_disc_price", "sum", "disc_price"),
            ("sum_charge", "sum", "charge"),
            ("avg_qty", "avg", "l_quantity"),
            ("avg_price", "avg", "l_extendedprice"),
            ("avg_disc", "avg", "l_discount"),
            ("count_order", "count_rows", None),
        ],
    )
    return g.sort([("l_returnflag", False), ("l_linestatus", False)])


def q3(cat: Catalog, segment: str = "BUILDING",
       date: str = "1995-03-15") -> Rel:
    """Shipping priority: customer x orders x lineitem, top 10 by revenue."""
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_mktsegment"))
    cust = cust.filter(cust.str_eq("c_mktsegment", segment))
    orders = Rel.scan(
        cat, "orders",
        ("o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"),
    )
    orders = orders.filter(
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date)))
    )
    # orders ⋈ customer (FK->PK, unique build) — semi join keeps schema lean
    ord_c = orders.join(cust, on=[("o_custkey", "c_custkey")], how="semi")
    li = Rel.scan(
        cat, "lineitem",
        ("l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"),
    )
    li = li.filter(ex.Cmp("gt", li.c("l_shipdate"), ex.lit(d(date))))
    j = li.join(ord_c, on=[("l_orderkey", "o_orderkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    revenue = ex.BinOp("*", j.c("l_extendedprice"),
                       ex.BinOp("-", one, j.c("l_discount")))
    j = j.project([
        ("l_orderkey", j.c("l_orderkey")),
        ("revenue", revenue),
        ("o_orderdate", j.c("o_orderdate")),
        ("o_shippriority", j.c("o_shippriority")),
    ])
    g = j.groupby(
        ["l_orderkey", "o_orderdate", "o_shippriority"],
        [("revenue", "sum", "revenue")],
    )
    g = g.project([
        ("l_orderkey", g.c("l_orderkey")),
        ("revenue", g.c("revenue")),
        ("o_orderdate", g.c("o_orderdate")),
        ("o_shippriority", g.c("o_shippriority")),
    ])
    return g.sort([("revenue", True), ("o_orderdate", False)]).limit(10)


def q6(cat: Catalog, date: str = "1994-01-01", discount: float = 0.06,
       quantity: int = 24) -> Rel:
    """Forecast revenue change: pure scan-filter-aggregate."""
    li = Rel.scan(cat, "lineitem", (
        "l_quantity", "l_extendedprice", "l_discount", "l_shipdate",
    ))
    dt = li.type_of("l_discount")
    pred = ex.and_(
        ex.Cmp("ge", li.c("l_shipdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_shipdate"), ex.lit(d(date) + 365)),
        ex.between(li.c("l_discount"),
                   ex.Const(discount - 0.01, dt), ex.Const(discount + 0.01, dt)),
        ex.Cmp("lt", li.c("l_quantity"),
               ex.Const(quantity, li.type_of("l_quantity"))),
    )
    li = li.filter(pred)
    li = li.project([
        ("rev", ex.BinOp("*", li.c("l_extendedprice"), li.c("l_discount"))),
    ])
    return li.scalar_agg([("revenue", "sum", "rev")])


def q5(cat: Catalog, region: str = "ASIA", date: str = "1994-01-01") -> Rel:
    """Local supplier volume: 6-way join, group by nation."""
    reg = Rel.scan(cat, "region", ("r_regionkey", "r_name"))
    reg = reg.filter(reg.str_eq("r_name", region))
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name", "n_regionkey"))
    nat = nat.join(reg, on=[("n_regionkey", "r_regionkey")], how="semi")
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_nationkey"))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_nationkey"))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_custkey", "o_orderdate"))
    orders = orders.filter(ex.and_(
        ex.Cmp("ge", orders.c("o_orderdate"), ex.lit(d(date))),
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date) + 365)),
    ))
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_suppkey", "l_extendedprice", "l_discount",
    ))
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    j = j.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    j = j.join(supp, on=[("l_suppkey", "s_suppkey")], how="inner")
    # same-nation constraint: customer and supplier nation must match
    j = j.filter(ex.Cmp("eq", j.c("c_nationkey"), j.c("s_nationkey")))
    j = j.join(nat, on=[("s_nationkey", "n_nationkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    rev = ex.BinOp("*", j.c("l_extendedprice"),
                   ex.BinOp("-", one, j.c("l_discount")))
    j = j.project([("n_name", j.c("n_name")), ("revenue", rev)])
    g = j.groupby(["n_name"], [("revenue", "sum", "revenue")])
    return g.sort([("revenue", True)])


def q4(cat: Catalog, date: str = "1993-07-01") -> Rel:
    """Order priority checking: EXISTS (late lineitem) as a semi join."""
    late = Rel.scan(cat, "lineitem", ("l_orderkey", "l_commitdate",
                                      "l_receiptdate"))
    late = late.filter(
        ex.Cmp("lt", late.c("l_commitdate"), late.c("l_receiptdate"))
    )
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_orderdate",
                                      "o_orderpriority"))
    orders = orders.filter(ex.and_(
        ex.Cmp("ge", orders.c("o_orderdate"), ex.lit(d(date))),
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date) + 92)),
    ))
    j = orders.join(late, on=[("o_orderkey", "l_orderkey")], how="semi",
                    build_unique=False)
    g = j.groupby(["o_orderpriority"], [("order_count", "count_rows", None)])
    return g.sort([("o_orderpriority", False)])


def q9(cat: Catalog, color: str = "green") -> Rel:
    """Product type profit: 6-way join, LIKE filter on p_name, profit by
    (nation, year of order date)."""
    part = Rel.scan(cat, "part", ("p_partkey", "p_name"))
    part = part.filter(part.str_pred("p_name", lambda s: color in s))
    supp = Rel.scan(cat, "supplier", ("s_suppkey", "s_nationkey"))
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    ps = Rel.scan(cat, "partsupp", ("ps_partkey", "ps_suppkey",
                                    "ps_supplycost"))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_orderdate"))
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_partkey", "l_suppkey", "l_quantity",
        "l_extendedprice", "l_discount",
    ))
    j = li.join(part, on=[("l_partkey", "p_partkey")], how="semi")
    j = j.join(ps, on=[("l_partkey", "ps_partkey"),
                       ("l_suppkey", "ps_suppkey")], how="inner")
    j = j.join(supp, on=[("l_suppkey", "s_suppkey")], how="inner")
    j = j.join(nat, on=[("s_nationkey", "n_nationkey")], how="inner")
    j = j.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    amount = ex.BinOp(
        "-",
        ex.BinOp("*", j.c("l_extendedprice"),
                 ex.BinOp("-", one, j.c("l_discount"))),
        ex.BinOp("*", j.c("ps_supplycost"), j.c("l_quantity")),
    )
    j = j.project([
        ("nation", j.c("n_name")),
        ("o_year", ex.ExtractYear(j.c("o_orderdate"))),
        ("amount", amount),
    ])
    g = j.groupby(["nation", "o_year"], [("sum_profit", "sum", "amount")])
    return g.sort([("nation", False), ("o_year", True)])


def q10(cat: Catalog, date: str = "1993-10-01") -> Rel:
    """Returned item reporting: top 20 customers by lost revenue."""
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_custkey",
                                      "o_orderdate"))
    orders = orders.filter(ex.and_(
        ex.Cmp("ge", orders.c("o_orderdate"), ex.lit(d(date))),
        ex.Cmp("lt", orders.c("o_orderdate"), ex.lit(d(date) + 92)),
    ))
    li = Rel.scan(cat, "lineitem", ("l_orderkey", "l_extendedprice",
                                    "l_discount", "l_returnflag"))
    li = li.filter(li.str_eq("l_returnflag", "R"))
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    cust = Rel.scan(cat, "customer", (
        "c_custkey", "c_name", "c_acctbal", "c_nationkey", "c_phone",
        "c_address", "c_comment",
    ))
    j = j.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    nat = Rel.scan(cat, "nation", ("n_nationkey", "n_name"))
    j = j.join(nat, on=[("c_nationkey", "n_nationkey")], how="inner")
    one = ex.Const(1.0, j.type_of("l_discount"))
    rev = ex.BinOp("*", j.c("l_extendedprice"),
                   ex.BinOp("-", one, j.c("l_discount")))
    j = j.project([
        ("c_custkey", j.c("c_custkey")), ("c_name", j.c("c_name")),
        ("rev", rev), ("c_acctbal", j.c("c_acctbal")),
        ("n_name", j.c("n_name")), ("c_address", j.c("c_address")),
        ("c_phone", j.c("c_phone")), ("c_comment", j.c("c_comment")),
    ])
    g = j.groupby(
        ["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
         "c_address", "c_comment"],
        [("revenue", "sum", "rev")],
    )
    return g.sort([("revenue", True), ("c_custkey", False)]).limit(20)


def q12(cat: Catalog, mode1: str = "MAIL", mode2: str = "SHIP",
        date: str = "1994-01-01") -> Rel:
    """Shipping modes and order priority: CASE aggregation."""
    li = Rel.scan(cat, "lineitem", (
        "l_orderkey", "l_shipmode", "l_commitdate", "l_receiptdate",
        "l_shipdate",
    ))
    li = li.filter(ex.and_(
        li.str_in("l_shipmode", [mode1, mode2]),
        ex.Cmp("lt", li.c("l_commitdate"), li.c("l_receiptdate")),
        ex.Cmp("lt", li.c("l_shipdate"), li.c("l_commitdate")),
        ex.Cmp("ge", li.c("l_receiptdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_receiptdate"), ex.lit(d(date) + 365)),
    ))
    orders = Rel.scan(cat, "orders", ("o_orderkey", "o_orderpriority"))
    j = li.join(orders, on=[("l_orderkey", "o_orderkey")], how="inner")
    high = j.str_in("o_orderpriority", ["1-URGENT", "2-HIGH"])
    one, zero = ex.lit(1), ex.lit(0)
    j = j.project([
        ("l_shipmode", j.c("l_shipmode")),
        ("high", ex.Case(((high, one),), zero)),
        ("low", ex.Case(((ex.Not(high), one),), zero)),
    ])
    g = j.groupby(["l_shipmode"], [
        ("high_line_count", "sum", "high"),
        ("low_line_count", "sum", "low"),
    ])
    return g.sort([("l_shipmode", False)])


def q14(cat: Catalog, date: str = "1995-09-01") -> Rel:
    """Promotion effect: 100 * promo revenue / total revenue."""
    li = Rel.scan(cat, "lineitem", ("l_partkey", "l_extendedprice",
                                    "l_discount", "l_shipdate"))
    li = li.filter(ex.and_(
        ex.Cmp("ge", li.c("l_shipdate"), ex.lit(d(date))),
        ex.Cmp("lt", li.c("l_shipdate"), ex.lit(d(date) + 30)),
    ))
    part = Rel.scan(cat, "part", ("p_partkey", "p_type"))
    j = li.join(part, on=[("l_partkey", "p_partkey")], how="inner")
    promo = j.str_pred("p_type", lambda s: s.startswith("PROMO"))
    one = ex.Const(1.0, j.type_of("l_discount"))
    rev = ex.BinOp("*", j.c("l_extendedprice"),
                   ex.BinOp("-", one, j.c("l_discount")))
    zero = ex.Const(0.0, ex.expr_type(rev, j.schema))
    j = j.project([
        ("promo_rev", ex.Case(((promo, rev),), zero)),
        ("rev", rev),
    ])
    g = j.scalar_agg([
        ("promo", "sum", "promo_rev"), ("total", "sum", "rev"),
    ])
    ratio = ex.BinOp("/", g.c("promo"), g.c("total"))
    hundred = ex.Const(100.0, ex.expr_type(ratio, g.schema))
    return g.project([("promo_revenue", ex.BinOp("*", hundred, ratio))])


def q18(cat: Catalog, quantity: int = 300) -> Rel:
    """Large volume customer: HAVING subquery as groupby-filter-semi-join,
    top 100 by order value."""
    li = Rel.scan(cat, "lineitem", ("l_orderkey", "l_quantity"))
    big = li.groupby(["l_orderkey"], [("sum_qty", "sum", "l_quantity")])
    big = big.filter(ex.Cmp(
        "gt", big.c("sum_qty"), ex.Const(quantity, big.type_of("sum_qty"))
    ))
    orders = Rel.scan(cat, "orders", (
        "o_orderkey", "o_custkey", "o_orderdate", "o_totalprice",
    ))
    orders = orders.join(big, on=[("o_orderkey", "l_orderkey")], how="semi")
    cust = Rel.scan(cat, "customer", ("c_custkey", "c_name"))
    j = orders.join(cust, on=[("o_custkey", "c_custkey")], how="inner")
    li2 = Rel.scan(cat, "lineitem", ("l_orderkey", "l_quantity"))
    j2 = li2.join(j, on=[("l_orderkey", "o_orderkey")], how="inner")
    g = j2.groupby(
        ["c_name", "c_custkey", "o_orderkey", "o_orderdate", "o_totalprice"],
        [("sum_qty", "sum", "l_quantity")],
    )
    return g.sort([("o_totalprice", True), ("o_orderdate", False)]).limit(100)


QUERIES = {
    "q1": q1, "q3": q3, "q4": q4, "q5": q5, "q6": q6, "q9": q9,
    "q10": q10, "q12": q12, "q14": q14, "q18": q18,
}
