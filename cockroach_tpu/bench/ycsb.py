"""YCSB workload over the MVCC engine — BASELINE config #5 (scan-heavy E).

Reference: pkg/workload/ycsb (workload E: 95% short range scans with
zipfian-ish starts, 5% inserts). The microbench drives the engine's real
read path — merged-view + mvcc_scan_filter on device — interleaved with
writes, so it prices the read-after-write merge cost the LSM design pays.

Load uses the bulk-ingest path (AddSSTable analog): the RunBuilder
(storage/ingest.py) accumulates chunks into device-built sorted/deduped
runs that link into the LSM with one WAL record per run; the operation
phase then measures scans against the multi-run LSM it produced. A
per-key put-path control over a sample of the keyspace prices the
ingest-vs-write asymmetry (``ingest_speedup``) and proves the two paths
produce bit-identical MVCC scans (``bit_identical``); a point-get phase
prices the bloom + block-cache read stack.
"""

from __future__ import annotations

import shutil
import tempfile
import time

import numpy as np

from ..storage import ingest as bulk
from ..storage.lsm import Engine


def _key(i: int) -> bytes:
    return b"user%012d" % i


def _keys_batch(idx: np.ndarray) -> np.ndarray:
    """Vectorized b'user%012d' encoding -> [N, 16] uint8."""
    n = len(idx)
    out = np.zeros((n, 16), dtype=np.uint8)
    out[:, :4] = np.frombuffer(b"user", dtype=np.uint8)
    digits = idx.astype(np.int64).copy()
    for p in range(12):
        out[:, 15 - p] = (digits % 10) + ord("0")
        digits //= 10
    return out


def run_ycsb_e(
    n_keys: int = 4096,
    ops: int = 64,
    scan_len: int = 64,
    insert_frac: float = 0.05,
    seed: int = 0,
    ingest_chunk: int = 1 << 17,
    concurrency: int = 64,
) -> dict:
    """Bulk-load n_keys (chunked ingest -> compaction churn), then run
    `ops` operations (scan_len-row scans + insert_frac inserts). Returns
    load + op throughputs.

    Scans issue through Engine.scan_batch in groups of `concurrency` — the
    vectorized analog of the reference's concurrent YCSB workers (pkg/
    workload/ycsb runs many goroutines against one store; over a remote-
    attached TPU, batching is the only way past the 1/RTT serial floor)."""
    import sys

    rng = np.random.default_rng(seed)
    tmp = tempfile.mkdtemp(prefix="ycsb_wal_")
    eng = Engine(key_width=16, val_width=16, memtable_size=4096,
                 wal_path=f"{tmp}/ingest.wal")

    def _vals_for(keys: np.ndarray) -> np.ndarray:
        vals = np.zeros((len(keys), 16), dtype=np.uint8)
        vals[:, 0] = ord("v")
        vals[:, 1:9] = keys[:, 7:15]  # value derived from key digits
        return vals

    t_load = time.time()
    ts = 1
    rb = bulk.RunBuilder(eng, ts=ts) if bulk.enabled() else None
    for lo in range(0, n_keys, ingest_chunk):
        hi = min(lo + ingest_chunk, n_keys)
        keys = _keys_batch(np.arange(lo, hi))
        vals = _vals_for(keys)
        if rb is not None:
            rb.add(keys, vals)
        else:
            eng.ingest(keys, vals, ts=ts)
        print(f"# ycsb load {hi}/{n_keys} ({time.time()-t_load:.0f}s, "
              f"{eng.stats.compactions} compactions)",
              file=sys.stderr, flush=True)
    if rb is not None:
        rb.finish()
    ts += 1
    load_s = time.time() - t_load

    # put-path control: the same rows, one WAL'd put at a time, over a
    # sample of the keyspace — the per-key write cost bulk ingest exists
    # to skip, and the bit-identity oracle for the ingest path
    sample_n = min(n_keys, 16384)
    eng_put = Engine(key_width=16, val_width=16, memtable_size=4096,
                     wal_path=f"{tmp}/put.wal")
    skeys = _keys_batch(np.arange(sample_n))
    svals = _vals_for(skeys)
    t_put = time.time()
    for i in range(sample_n):
        eng_put.put(bytes(skeys[i]), bytes(svals[i]), ts=1)
    put_s = time.time() - t_put
    put_rate = sample_n / put_s if put_s > 0 else 0.0
    ident = (eng.scan(_key(0), _key(sample_n), ts=ts, max_keys=sample_n)
             == eng_put.scan(_key(0), _key(sample_n), ts=ts,
                             max_keys=sample_n))
    print(f"# ycsb put control {put_rate:.0f} keys/s, "
          f"bit_identical={ident}", file=sys.stderr, flush=True)
    # warm BOTH source-set shapes the op phase will see before timing:
    # runs-only (post-flush) and runs+memtable (after the first insert —
    # the memtable source changes the scan kernel's source tuple)
    t_warm = time.time()
    eng.scan_batch([_key(0)] * concurrency, ts=ts, max_keys=scan_len)
    eng.put(_key(n_keys), b"warm", ts=ts)
    ts += 1
    next_pk = n_keys + 1
    eng.scan_batch([_key(0)] * concurrency, ts=ts, max_keys=scan_len)
    print(f"# ycsb scan warmup {time.time()-t_warm:.0f}s "
          f"(window={eng._scan_windows.get(scan_len)})",
          file=sys.stderr, flush=True)

    # point-get phase: the bloom -> block cache -> seek-window read
    # stack (50% present keys, 50% definite misses — the misses are
    # where blooms earn their bits)
    from ..storage import blockcache
    from ..utils import metric

    n_point = min(1024, 4 * ops)
    pt_keys = [_key(int(rng.integers(0, n_keys))) if i % 2 == 0
               else b"ghost%011d" % i for i in range(n_point)]
    eng.get(pt_keys[0], ts=ts)  # warm the point-path kernels
    bc0 = blockcache.node_cache().stats()
    skips0 = metric.BLOOM_SKIPS.value
    t_pt = time.time()
    for k in pt_keys:
        eng.get(k, ts=ts)
    pt_s = time.time() - t_pt
    bc1 = blockcache.node_cache().stats()
    lookups = (bc1["hits"] - bc0["hits"]) + (bc1["misses"] - bc0["misses"])
    hit_rate = (bc1["hits"] - bc0["hits"]) / lookups if lookups else 0.0
    print(f"# ycsb points {n_point} in {pt_s:.2f}s "
          f"(cache hit rate {hit_rate:.2f})", file=sys.stderr, flush=True)

    rows = 0
    t0 = time.time()
    done = 0
    while done < ops:
        group = min(concurrency, ops - done)
        starts = []
        n_scans = 0
        for _ in range(group):
            if rng.random() < insert_frac:
                eng.put(_key(next_pk), b"v%08d" % next_pk, ts=ts)
                next_pk += 1
                ts += 1
            else:
                starts.append(_key(int(rng.integers(0, n_keys))))
                n_scans += 1
        # pad to a FIXED batch shape (multi_scan_sources jit-specializes on B;
        # ragged tails would each compile their own kernel)
        while len(starts) < concurrency:
            starts.append(_key(0))
        for got in eng.scan_batch(starts, ts=ts,
                                  max_keys=scan_len)[:n_scans]:
            rows += len(got)
        done += group
        print(f"# ycsb ops {done}/{ops} ({time.time()-t0:.1f}s)",
              file=sys.stderr, flush=True)
    el = time.time() - t0
    compactions, runs = eng.stats.compactions, eng.stats.runs
    eng.close()
    eng_put.close()
    shutil.rmtree(tmp, ignore_errors=True)
    load_rate = n_keys / load_s if load_s > 0 else 0.0
    return {
        "n_keys": n_keys,
        "load_s": round(load_s, 3),
        "load_keys_per_sec": round(load_rate),
        "put_keys_per_sec": round(put_rate),
        "ingest_speedup": round(load_rate / put_rate, 2) if put_rate else 0.0,
        "bit_identical": bool(ident),
        "compactions": compactions,
        "runs": runs,
        "point_ops": n_point,
        "point_ops_per_sec": round(n_point / pt_s) if pt_s > 0 else 0,
        "blockcache_hit_rate": round(hit_rate, 3),
        "bloom_skips": int(metric.BLOOM_SKIPS.value - skips0),
        "ops": ops,
        "ops_per_sec": ops / el,
        "rows_scanned": rows,
        "rows_per_sec": rows / el if el > 0 else 0.0,
        "elapsed_s": el,
    }
