"""YCSB workload over the MVCC engine — BASELINE config #5 (scan-heavy E).

Reference: pkg/workload/ycsb (workload E: 95% short range scans with
zipfian-ish starts, 5% inserts). The microbench drives the engine's real
read path — merged-view + mvcc_scan_filter on device — interleaved with
writes, so it prices the read-after-write merge cost the LSM design pays.
"""

from __future__ import annotations

import time

import numpy as np

from ..storage.lsm import Engine


def _key(i: int) -> bytes:
    return b"user%012d" % i


def run_ycsb_e(
    n_keys: int = 4096,
    ops: int = 64,
    scan_len: int = 64,
    insert_frac: float = 0.05,
    seed: int = 0,
) -> dict:
    """Load n_keys, then run `ops` operations (scan_len-row scans, with an
    insert_frac share of inserts). Returns ops/sec + rows/sec."""
    rng = np.random.default_rng(seed)
    eng = Engine(key_width=16, val_width=16, memtable_size=4096)
    ts = 1
    for i in range(n_keys):
        eng.put(_key(i), b"v%08d" % i, ts=ts)
        ts += 1
    eng.flush()
    # warm the merged view + compile the scan kernel before timing
    eng.scan(_key(0), None, ts=ts, max_keys=scan_len)

    next_pk = n_keys
    rows = 0
    t0 = time.time()
    for op in range(ops):
        if rng.random() < insert_frac:
            eng.put(_key(next_pk), b"v%08d" % next_pk, ts=ts)
            next_pk += 1
            ts += 1
        else:
            start = int(rng.integers(0, n_keys))
            got = eng.scan(_key(start), None, ts=ts, max_keys=scan_len)
            rows += len(got)
    el = time.time() - t0
    return {
        "ops": ops,
        "ops_per_sec": ops / el,
        "rows_scanned": rows,
        "rows_per_sec": rows / el if el > 0 else 0.0,
        "elapsed_s": el,
    }
