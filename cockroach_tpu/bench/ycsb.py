"""YCSB workload over the MVCC engine — BASELINE config #5 (scan-heavy E).

Reference: pkg/workload/ycsb (workload E: 95% short range scans with
zipfian-ish starts, 5% inserts). The microbench drives the engine's real
read path — merged-view + mvcc_scan_filter on device — interleaved with
writes, so it prices the read-after-write merge cost the LSM design pays.

Load uses the bulk-ingest path (AddSSTable analog, Engine.ingest): pre-
built key/value arrays land as sorted runs in chunks, driving size-tiered
compaction churn exactly like the reference's IMPORT; the operation phase
then measures scans against the multi-run LSM it produced.
"""

from __future__ import annotations

import time

import numpy as np

from ..storage.lsm import Engine


def _key(i: int) -> bytes:
    return b"user%012d" % i


def _keys_batch(idx: np.ndarray) -> np.ndarray:
    """Vectorized b'user%012d' encoding -> [N, 16] uint8."""
    n = len(idx)
    out = np.zeros((n, 16), dtype=np.uint8)
    out[:, :4] = np.frombuffer(b"user", dtype=np.uint8)
    digits = idx.astype(np.int64).copy()
    for p in range(12):
        out[:, 15 - p] = (digits % 10) + ord("0")
        digits //= 10
    return out


def run_ycsb_e(
    n_keys: int = 4096,
    ops: int = 64,
    scan_len: int = 64,
    insert_frac: float = 0.05,
    seed: int = 0,
    ingest_chunk: int = 1 << 17,
    concurrency: int = 64,
) -> dict:
    """Bulk-load n_keys (chunked ingest -> compaction churn), then run
    `ops` operations (scan_len-row scans + insert_frac inserts). Returns
    load + op throughputs.

    Scans issue through Engine.scan_batch in groups of `concurrency` — the
    vectorized analog of the reference's concurrent YCSB workers (pkg/
    workload/ycsb runs many goroutines against one store; over a remote-
    attached TPU, batching is the only way past the 1/RTT serial floor)."""
    import sys

    rng = np.random.default_rng(seed)
    eng = Engine(key_width=16, val_width=16, memtable_size=4096)
    t_load = time.time()
    ts = 1
    for lo in range(0, n_keys, ingest_chunk):
        hi = min(lo + ingest_chunk, n_keys)
        idx = np.arange(lo, hi)
        keys = _keys_batch(idx)
        vals = np.zeros((hi - lo, 16), dtype=np.uint8)
        vals[:, 0] = ord("v")
        vals[:, 1:9] = keys[:, 7:15]  # value derived from key digits
        eng.ingest(keys, vals, ts=ts)
        ts += 1
        print(f"# ycsb load {hi}/{n_keys} ({time.time()-t_load:.0f}s, "
              f"{eng.stats.compactions} compactions)",
              file=sys.stderr, flush=True)
    load_s = time.time() - t_load
    # warm BOTH source-set shapes the op phase will see before timing:
    # runs-only (post-flush) and runs+memtable (after the first insert —
    # the memtable source changes the scan kernel's source tuple)
    t_warm = time.time()
    eng.scan_batch([_key(0)] * concurrency, ts=ts, max_keys=scan_len)
    eng.put(_key(n_keys), b"warm", ts=ts)
    ts += 1
    next_pk = n_keys + 1
    eng.scan_batch([_key(0)] * concurrency, ts=ts, max_keys=scan_len)
    print(f"# ycsb scan warmup {time.time()-t_warm:.0f}s "
          f"(window={eng._scan_windows.get(scan_len)})",
          file=sys.stderr, flush=True)

    rows = 0
    t0 = time.time()
    done = 0
    while done < ops:
        group = min(concurrency, ops - done)
        starts = []
        n_scans = 0
        for _ in range(group):
            if rng.random() < insert_frac:
                eng.put(_key(next_pk), b"v%08d" % next_pk, ts=ts)
                next_pk += 1
                ts += 1
            else:
                starts.append(_key(int(rng.integers(0, n_keys))))
                n_scans += 1
        # pad to a FIXED batch shape (multi_scan_sources jit-specializes on B;
        # ragged tails would each compile their own kernel)
        while len(starts) < concurrency:
            starts.append(_key(0))
        for got in eng.scan_batch(starts, ts=ts,
                                  max_keys=scan_len)[:n_scans]:
            rows += len(got)
        done += group
        print(f"# ycsb ops {done}/{ops} ({time.time()-t0:.1f}s)",
              file=sys.stderr, flush=True)
    el = time.time() - t0
    return {
        "n_keys": n_keys,
        "load_s": round(load_s, 3),
        "load_keys_per_sec": round(n_keys / load_s) if load_s > 0 else 0,
        "compactions": eng.stats.compactions,
        "runs": eng.stats.runs,
        "ops": ops,
        "ops_per_sec": ops / el,
        "rows_scanned": rows,
        "rows_per_sec": rows / el if el > 0 else 0.0,
        "elapsed_s": el,
    }
