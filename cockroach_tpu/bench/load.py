"""Mixed-workload serving-load harness — ROADMAP 3(c).

Reference: pkg/workload's mixed-cluster runs (YCSB writers beside TPC-H
readers against one node) are how CockroachDB prices admission control and
memory accounting under contention. This module drives the same shape
through the FULL SQL front door: N concurrent ``Session``s over one shared
KV store + TPC-H catalog, each thread mixing YCSB-style point ops (point
SELECT / INSERT on an indexed kv table) with small TPC-H-flavoured analytic
statements (scan-aggregate and top-K over lineitem/orders).

Because every statement passes through ``Session.execute``, the run
exercises — and measures — the whole resource observability plane:

- admission: each statement takes a WorkQueue slot (utils/admission.py);
  queue-wait lands in the ``admission_wait_seconds`` histogram, and p99
  queue-wait is recovered from the histogram's bucket deltas;
- memory: each statement opens a query monitor under its session
  (flow/memory.py); peak HBM is the node root's high-water over the run,
  cross-checked against the device allocator's peak where the backend
  reports one.

Returned dict feeds bench.py's ``load`` job (BENCH JSON ``mixed_load``
entry): ops/s by class, p99 queue-wait ms, peak-HBM bytes, spill and
admission counters.
"""

from __future__ import annotations

import threading
import time

import numpy as np

# analytic statements: TPC-H q1/q18 flavoured, sized so they plan and run
# in milliseconds at the harness's small scale factor but still walk the
# scan→aggregate→sort pipeline (operator accounts, spill checks, top-K)
_ANALYTIC_SQL = (
    "SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty, "
    "count(*) AS count_order FROM lineitem "
    "GROUP BY l_returnflag, l_linestatus "
    "ORDER BY l_returnflag, l_linestatus",
    "SELECT o_orderpriority, count(*) AS n FROM orders "
    "GROUP BY o_orderpriority ORDER BY n DESC LIMIT 5",
    # high-cardinality group-by (q18's first stage): the per-order partial
    # states actually occupy the agg spool, so the run's peak-HBM figure
    # reflects real buffering, not just 6-group partial tiles
    "SELECT l_orderkey, sum(l_quantity) AS sq FROM lineitem "
    "GROUP BY l_orderkey ORDER BY sq DESC LIMIT 10",
)


def _hist_snapshot(h) -> tuple[list[int], int]:
    with h._lock:
        return list(h.counts), h.n


def hist_quantile_from_deltas(buckets, before: list[int],
                              after: list[int], q: float) -> float:
    """Quantile from two cumulative-count snapshots of a fixed-bucket
    histogram (the Prometheus histogram_quantile discipline): returns the
    upper bound of the bucket where the q-th delta observation lands, 0.0
    when no observations arrived between the snapshots. The overflow
    bucket reports the last finite bound (a floor, not an estimate)."""
    deltas = [a - b for a, b in zip(after, before)]
    total = sum(deltas)
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0.0
    for i, d in enumerate(deltas):
        seen += d
        if seen >= rank:
            return float(buckets[i]) if i < len(buckets) else float(
                buckets[-1])
    return float(buckets[-1])


class _Counters:
    __slots__ = ("lock", "point_ops", "analytic_ops", "inserts",
                 "conflicts", "shed", "errors", "last_error")

    def __init__(self):
        self.lock = threading.Lock()
        self.point_ops = 0
        self.analytic_ops = 0
        self.inserts = 0
        self.conflicts = 0
        self.shed = 0
        self.errors = 0
        self.last_error = ""


def _load_worker(sess, stop: threading.Event, ctr: _Counters,
                 n_keys: int, analytic_frac: float, insert_frac: float,
                 seed: int) -> None:
    from ..kv.txn import TransactionRetryError
    from ..storage.lsm import WriteIntentError
    from ..utils.errors import AdmissionRejectedError

    rng = np.random.default_rng(seed)
    next_pk = n_keys + 1000 * seed  # per-thread pk range: no write-write conflicts
    while not stop.is_set():
        try:
            r = rng.random()
            if r < analytic_frac:
                sess.execute(_ANALYTIC_SQL[int(rng.integers(
                    0, len(_ANALYTIC_SQL)))])
                with ctr.lock:
                    ctr.analytic_ops += 1
            elif r < analytic_frac + insert_frac:
                sess.execute(
                    f"INSERT INTO ycsb_kv VALUES ({next_pk}, {next_pk % 997})")
                next_pk += 1
                with ctr.lock:
                    ctr.inserts += 1
            else:
                k = int(rng.integers(0, n_keys))
                sess.execute(f"SELECT v FROM ycsb_kv WHERE k = {k}")
                with ctr.lock:
                    ctr.point_ops += 1
        except (WriteIntentError, TransactionRetryError):
            # retryable read/write conflict (a point read landed on a
            # concurrent insert's intent): the client-retry case, counted
            # as contention rather than failure — the 40001 shape
            with ctr.lock:
                ctr.conflicts += 1
        except AdmissionRejectedError as e:
            # the node shed this statement (queue bound / rate limit /
            # overload): the 53300 shape — counted as shed-not-failed,
            # and the client backs off by the rejection's hint
            with ctr.lock:
                ctr.shed += 1
            stop.wait(min(max(e.retry_after_s, 0.002), 0.05))
        except Exception as e:  # crlint: allow-broad-except(load harness: one failed op must not kill the thread; failures are counted and reported)
            with ctr.lock:
                ctr.errors += 1
                ctr.last_error = f"{type(e).__name__}: {e}"[:200]


def run_mixed_load(sessions: int = 4, duration_s: float = 3.0,
                   sf: float = 0.01, n_keys: int = 512,
                   analytic_frac: float = 0.2, insert_frac: float = 0.1,
                   seed: int = 0) -> dict:
    """N concurrent sessions × (YCSB point ops + TPC-H analytics) for
    duration_s; returns throughput, p99 queue-wait, and peak-HBM figures.

    Setup (untimed): generate the TPC-H catalog at ``sf``, bootstrap one
    session over a fresh KV store, create + seed the ``ycsb_kv`` table.
    Then ``sessions`` threads share that store/catalog, each through its
    own Session (own monitor subtree, own admission entries)."""
    from ..flow import memory
    from ..sql.session import Session
    from ..utils import metric
    from .tpch import gen_tpch_cached

    cat = gen_tpch_cached(sf)
    boot = Session(catalog=cat)
    boot.execute("CREATE TABLE ycsb_kv (k INT PRIMARY KEY, v INT)")
    # seed in multi-row INSERTs (one statement per row would pay the
    # admission + planning toll n_keys times before the clock even starts)
    chunk = 128
    for lo in range(0, n_keys, chunk):
        rows = ", ".join(f"({k}, {k % 997})"
                         for k in range(lo, min(lo + chunk, n_keys)))
        boot.execute(f"INSERT INTO ycsb_kv VALUES {rows}")

    # warm the analytic plans/kernels off the clock (plan + kernel caches
    # are process-global, so workers serve steady-state from op one; a
    # loaded box must not report ops=0 just because first-compile ate the
    # whole window)
    for stmt in _ANALYTIC_SQL:
        boot.execute(stmt)

    workers = [Session(catalog=cat, db=boot.db, bootstrap=False)
               for _ in range(sessions)]

    wait_h = metric.ADMISSION_WAIT_SECONDS
    wait_before, n_before = _hist_snapshot(wait_h)
    mem_floor = memory.ROOT.high_water
    dev_before = memory.device_memory_stats()

    ctr = _Counters()
    stop = threading.Event()
    threads = [
        threading.Thread(
            target=_load_worker,
            args=(s, stop, ctr, n_keys, analytic_frac, insert_frac, i + 1),
            name=f"load-{i}", daemon=True)
        for i, s in enumerate(workers)
    ]
    t0 = time.time()
    for t in threads:
        t.start()
    stop.wait(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    elapsed = time.time() - t0

    wait_after, n_after = _hist_snapshot(wait_h)
    dev_after = memory.device_memory_stats()
    from ..utils import admission
    q = admission.sql_queue()

    total_ops = ctr.point_ops + ctr.analytic_ops + ctr.inserts
    peak_hbm = memory.ROOT.high_water
    out = {
        "sessions": sessions,
        "duration_s": round(elapsed, 3),
        "ops": total_ops,
        "ops_per_sec": round(total_ops / elapsed, 2) if elapsed > 0 else 0.0,
        "point_ops": ctr.point_ops,
        "analytic_ops": ctr.analytic_ops,
        "inserts": ctr.inserts,
        "conflicts": ctr.conflicts,
        "shed": ctr.shed,
        "errors": ctr.errors,
        "last_error": ctr.last_error,
        "admission_waits": n_after - n_before,
        "p99_queue_wait_ms": round(1e3 * hist_quantile_from_deltas(
            wait_h.buckets, wait_before, wait_after, 0.99), 4),
        "p50_queue_wait_ms": round(1e3 * hist_quantile_from_deltas(
            wait_h.buckets, wait_before, wait_after, 0.50), 4),
        "admission_slots": q.slots,
        "admission_timeouts": q.timeouts,
        "peak_hbm_bytes": peak_hbm,
        "peak_hbm_floor_bytes": mem_floor,  # node peak before the run
        "spills": memory.ROOT.spills,
        "drain_failures": memory.drain_failure_count(),
    }
    dev_peak = dev_after.get("peak_bytes_in_use", 0)
    if dev_peak:
        out["device_peak_bytes"] = dev_peak
        out["device_peak_delta_bytes"] = (
            dev_peak - dev_before.get("peak_bytes_in_use", 0))
    for s in workers:
        s.close()
    boot.close()
    return out


# ------------------------------------------- cross-session coalescing A/B

def _coalesce_worker(db, stop: threading.Event, tid: int, n_hot: int,
                     lat: list, ctr: _Counters, seed: int) -> None:
    """Mixed-DML worker over the non-txn KV surface (the coalescer's
    lane): 90% put / 10% delete. Writes are the amortization case — each
    solo write is one WAL record + one fsync, a train is one of each for
    the whole batch. Point reads ride trains too, but a read's cost is
    an MVCC device dispatch (identical either way), so the throughput
    A/B keeps the lane pure DML and leaves read semantics to the
    bit-identity oracle. Keys are per-thread so the A/B measures
    batching, not conflicts. Per-op wall time lands in ``lat`` for the
    p99 wait comparison."""
    rng = np.random.default_rng(seed)
    j = 0
    while not stop.is_set():
        r = rng.random()
        k = f"cl-{tid}-{j % n_hot}"
        t0 = time.perf_counter()
        try:
            if r < 0.9:
                db.put(k, f"v{tid}-{j}".encode())
            else:
                db.delete(k)
        except Exception as e:  # crlint: allow-broad-except(load harness: one failed op must not kill the thread; failures are counted and reported)
            with ctr.lock:
                ctr.errors += 1
                ctr.last_error = f"{type(e).__name__}: {e}"[:200]
            j += 1
            continue
        lat.append(time.perf_counter() - t0)
        with ctr.lock:
            ctr.point_ops += 1
        j += 1


def _coalesce_oracle(threads: int = 4, ops: int = 200, seed: int = 7) -> bool:
    """Bit-identity oracle: one deterministic concurrent mixed-DML script
    run coalesced and solo against fresh stores must leave byte-identical
    visible state (keys and values; timestamps are clock readings and
    differ between ANY two runs, solo included)."""
    from ..kv.txn import DB
    from ..utils import settings

    scripts = []
    for t in range(threads):
        rng = np.random.default_rng(seed * 1000 + t)
        ops_t = []
        for j in range(ops):
            r = rng.random()
            k = f"or-{t}-{int(rng.integers(0, 32))}"
            if r < 0.6:
                ops_t.append(("put", k, f"v{t}-{j}".encode()))
            elif r < 0.8:
                ops_t.append(("delete", k, b""))
            else:
                ops_t.append(("get", k, b""))
        scripts.append(ops_t)

    def run(coalesced: bool):
        db = DB()
        settings.set("kv.batch.coalesce.enabled", coalesced)
        try:
            def w(script):
                for kind, k, v in script:
                    if kind == "put":
                        db.put(k, v)
                    elif kind == "delete":
                        db.delete(k)
                    else:
                        db.get(k)
            ths = [threading.Thread(target=w, args=(s,), daemon=True)
                   for s in scripts]
            for t in ths:
                t.start()
            for t in ths:
                t.join(timeout=60.0)
        finally:
            settings.set("kv.batch.coalesce.enabled", False)
        return sorted(db.scan(None, None))

    return run(False) == run(True)


def _coalesce_phase(on: bool, sessions: int, duration_s: float,
                    n_hot: int) -> dict:
    """One timed phase over a fresh WAL-backed (fsync) store."""
    import os
    import tempfile

    from ..kv.txn import DB
    from ..storage.lsm import Engine
    from ..utils import metric, settings

    with tempfile.TemporaryDirectory() as td:
        db = DB(Engine(wal_path=os.path.join(td, "wal.log"),
                       wal_fsync=True))
        settings.set("kv.batch.coalesce.enabled", on)
        m0 = metric.KV_BATCH_COALESCED.value
        try:
            ctr = _Counters()
            lats: list[list[float]] = [[] for _ in range(sessions)]
            stop = threading.Event()
            threads = [
                threading.Thread(
                    target=_coalesce_worker,
                    args=(db, stop, i, n_hot, lats[i], ctr, 500 + i),
                    name=f"coal-{i}", daemon=True)
                for i in range(sessions)
            ]
            t0 = time.time()
            for t in threads:
                t.start()
            stop.wait(duration_s)
            stop.set()
            for t in threads:
                t.join(timeout=30.0)
            elapsed = time.time() - t0
        finally:
            settings.set("kv.batch.coalesce.enabled", False)
        flat = [x for l in lats for x in l]
        return {
            "ops_per_sec": (round(ctr.point_ops / elapsed, 2)
                            if elapsed > 0 else 0.0),
            "p99_wait_ms": _p99_ms(flat),
            "errors": ctr.errors,
            "last_error": ctr.last_error,
            "coalesced_ops": metric.KV_BATCH_COALESCED.value - m0,
        }


def run_coalesce_ab(sessions: int = 16, duration_s: float = 2.0,
                    n_hot: int = 64, seed: int = 0,
                    rounds: int = 3) -> dict:
    """Coalescing-off vs coalescing-on over a WAL-backed (fsync) store:
    ``sessions`` concurrent threads of mixed non-txn DML, same seeds both
    phases. Phases run INTERLEAVED (off,on × rounds) and the speedup is
    the median of per-round ratios — disk cache and CPU-governor drift
    inflate whichever phase runs later in a sequential A/B, and pairing
    cancels it. Emits ``coalesce_*`` keys for BENCH JSON ``mixed_load``
    — throughput speedup, p99 per-op wait ratio, batches merged, and the
    bit-identity oracle check_bench_regress.py enforces."""
    offs, ons = [], []
    for _ in range(max(1, rounds)):
        offs.append(_coalesce_phase(False, sessions, duration_s, n_hot))
        ons.append(_coalesce_phase(True, sessions, duration_s, n_hot))

    def med(vals):
        vals = sorted(vals)
        return vals[len(vals) // 2]

    off_ops = med([p["ops_per_sec"] for p in offs])
    on_ops = med([p["ops_per_sec"] for p in ons])
    ratios = [on["ops_per_sec"] / off["ops_per_sec"]
              for off, on in zip(offs, ons) if off["ops_per_sec"] > 0]
    off_p99 = med([p["p99_wait_ms"] for p in offs])
    on_p99 = med([p["p99_wait_ms"] for p in ons])
    return {
        "coalesce_sessions": sessions,
        "coalesce_rounds": len(offs),
        "coalesce_off_ops_per_sec": off_ops,
        "coalesce_on_ops_per_sec": on_ops,
        "coalesce_speedup": round(med(ratios), 3) if ratios else 0.0,
        "coalesce_off_p99_wait_ms": off_p99,
        "coalesce_on_p99_wait_ms": on_p99,
        "coalesce_p99_wait_ratio": (round(on_p99 / off_p99, 3)
                                    if off_p99 > 0 else 0.0),
        "coalesce_batched_ops": sum(p["coalesced_ops"] for p in ons),
        "coalesce_errors": (sum(p["errors"] for p in offs)
                            + sum(p["errors"] for p in ons)),
        "coalesce_oracle_ok": _coalesce_oracle(),
    }


# ------------------------------------------- multi-tenant overload oracle

def _point_worker(sess, stop: threading.Event, ctr: _Counters,
                  n_keys: int, think_s: float, seed: int) -> None:
    """Point-select worker for the overload phases: AdmissionRejected is
    shed-not-failed (the client honors the retry-after hint, bounded);
    think_s > 0 paces the tenant below its fair share (open-loop-ish)."""
    from ..kv.txn import TransactionRetryError
    from ..storage.lsm import WriteIntentError
    from ..utils.errors import AdmissionRejectedError

    rng = np.random.default_rng(seed)
    while not stop.is_set():
        k = int(rng.integers(0, n_keys))
        try:
            sess.execute(f"SELECT v FROM ycsb_kv WHERE k = {k}")
            with ctr.lock:
                ctr.point_ops += 1
        except AdmissionRejectedError as e:
            with ctr.lock:
                ctr.shed += 1
            stop.wait(min(max(e.retry_after_s, 0.002), 0.05))
        except (WriteIntentError, TransactionRetryError):
            with ctr.lock:
                ctr.conflicts += 1
        except Exception as e:  # crlint: allow-broad-except(load harness: one failed op must not kill the thread; failures are counted and reported)
            with ctr.lock:
                ctr.errors += 1
                ctr.last_error = f"{type(e).__name__}: {e}"[:200]
        if think_s > 0:
            stop.wait(think_s)


def _p99_ms(samples: list[float]) -> float:
    if not samples:
        return 0.0
    return round(1e3 * float(np.percentile(np.asarray(samples), 99)), 4)


def _run_phase(make_threads, duration_s: float):
    stop = threading.Event()
    threads = make_threads(stop)
    t0 = time.time()
    for t in threads:
        t.start()
    stop.wait(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    return time.time() - t0


def run_tenant_overload(duration_s: float = 6.0, sf: float = 0.004,
                        n_keys: int = 256, slots: int = 2,
                        max_queue_depth: int = 4,
                        well_sessions: int = 4, noisy_sessions: int = 8,
                        seed: int = 0) -> dict:
    """The overload-survival oracle (BENCH ``mixed_load.overload_*``):

    Phase 1 (saturation / solo baseline): the well-behaved tenant alone,
    closed-loop, with more sessions than slots — measures the node's
    saturation throughput and the tenant's solo p99 queue-wait (real
    self-queueing, not an empty-box zero).

    Phase 2 (overload): the same well-behaved tenant paced to ~1/4 of
    saturation (well under its fair share) beside a noisy tenant whose
    closed-loop sessions offer several times the node's capacity, with a
    token-bucket cap from its admission_rate tenant capability and the
    queue bounded at ``max_queue_depth``. The oracle asserts the
    serving plane survives being popular:

    - goodput stays >= 80% of saturation (no collapse past saturation);
    - every refusal is a typed AdmissionRejectedError (53300 shape),
      never a raw exception;
    - the noisy neighbor cannot push the well-behaved tenant's p99
      queue-wait past 2x its solo baseline (stride fair share + the
      vtime floor clamp: a paced tenant's arrivals slot in just under
      the last grant, so they wait one service residual, not the whole
      noisy backlog)."""
    from ..kv.tenant import TenantRegistry
    from ..sql.session import Session
    from ..utils import admission
    from .tpch import gen_tpch_cached

    cat = gen_tpch_cached(sf)
    boot = Session(catalog=cat)
    boot.execute("CREATE TABLE ycsb_kv (k INT PRIMARY KEY, v INT)")
    chunk = 128
    for lo in range(0, n_keys, chunk):
        rows = ", ".join(f"({k}, {k % 997})"
                         for k in range(lo, min(lo + chunk, n_keys)))
        boot.execute(f"INSERT INTO ycsb_kv VALUES {rows}")
    boot.execute("SELECT v FROM ycsb_kv WHERE k = 0")  # warm plan/kernels

    reg = TenantRegistry(boot.db)
    reg.bootstrap()
    well = reg.create("well_behaved")
    noisy = reg.create("noisy")

    # a dedicated bounded queue for the run (the process queue may be
    # sized for tier-1): swapped in exactly like the admission tests do
    saved = admission._SQL_QUEUE
    q = admission.WorkQueue(slots=slots, max_queue_depth=max_queue_depth)
    admission._SQL_QUEUE = q
    try:
        def mk_sessions(tenant_name, n):
            return [Session(catalog=cat, db=boot.db, bootstrap=False,
                            tenant=tenant_name) for _ in range(n)]

        # ---- phase 1: saturation + solo baseline (well tenant alone)
        well_s = mk_sessions("well_behaved", well_sessions)
        # untimed ramp: pay per-session first-execution costs (txn bind,
        # plan-cache fill) off the clock, or the short solo window reads
        # as compile time and understates saturation
        for s in well_s:
            for k in (1, 2):
                s.execute(f"SELECT v FROM ycsb_kv WHERE k = {k}")
        ctr1 = _Counters()
        d1 = _run_phase(
            lambda stop: [
                threading.Thread(
                    target=_point_worker,
                    args=(s, stop, ctr1, n_keys, 0.0, 100 + i),
                    name=f"well-solo-{i}", daemon=True)
                for i, s in enumerate(well_s)],
            duration_s * 0.4)
        sat_ops = ctr1.point_ops
        sat_per_sec = sat_ops / d1 if d1 > 0 else 0.0
        solo_waits = q.tenant_wait_samples(well.tenant_id)
        solo_p99_ms = _p99_ms(solo_waits)

        # ---- phase 2: overload — noisy neighbor at several times the
        # node's capacity, well tenant paced under its fair share
        # noisy bucket: above capacity (1.2x saturation) so the bucket
        # only clips bursts — steady-state shed comes from the queue
        # bound, fairness from the stride scheduler
        reg.set_capability("noisy", "admission_rate",
                           max(10.0, 1.2 * sat_per_sec))
        reg.set_capability("noisy", "admission_burst", 16)
        noisy_s = mk_sessions("noisy", noisy_sessions)
        # same untimed ramp as the well tenant: cold sessions entering a
        # timed window burn it on first-execution costs instead of load
        for s in noisy_s:
            for k in (1, 2):
                s.execute(f"SELECT v FROM ycsb_kv WHERE k = {k}")
        # pace well to ~25% of saturation across its threads
        think_s = (4.0 * well_sessions / sat_per_sec
                   if sat_per_sec > 0 else 0.01)
        n_solo_waits = len(solo_waits)
        ctr_w, ctr_n = _Counters(), _Counters()
        d2 = _run_phase(
            lambda stop: [
                threading.Thread(
                    target=_point_worker,
                    args=(s, stop, ctr_w, n_keys, think_s, 200 + i),
                    name=f"well-ovl-{i}", daemon=True)
                for i, s in enumerate(well_s)
            ] + [
                threading.Thread(
                    target=_point_worker,
                    args=(s, stop, ctr_n, n_keys, 0.0, 300 + i),
                    name=f"noisy-ovl-{i}", daemon=True)
                for i, s in enumerate(noisy_s)],
            duration_s * 0.6)
        goodput_ops = ctr_w.point_ops + ctr_n.point_ops
        shed = ctr_w.shed + ctr_n.shed
        attempts = goodput_ops + shed
        goodput_per_sec = goodput_ops / d2 if d2 > 0 else 0.0
        offered_per_sec = attempts / d2 if d2 > 0 else 0.0
        well_ovl_waits = q.tenant_wait_samples(
            well.tenant_id)[n_solo_waits:]
        ovl_p99_ms = _p99_ms(well_ovl_waits)

        errors = ctr1.errors + ctr_w.errors + ctr_n.errors
        isolation = (ovl_p99_ms / solo_p99_ms if solo_p99_ms > 0
                     else (0.0 if ovl_p99_ms == 0 else float("inf")))
        goodput_frac = (goodput_per_sec / sat_per_sec
                        if sat_per_sec > 0 else 0.0)
        oracle = {
            "oracle_goodput_ok": goodput_frac >= 0.8,
            "oracle_typed_ok": errors == 0 and shed > 0,
            "oracle_isolation_ok": isolation <= 2.0,
        }
        out = {
            "slots": slots,
            "max_queue_depth": max_queue_depth,
            "saturation_ops_per_sec": round(sat_per_sec, 2),
            "goodput_per_sec": round(goodput_per_sec, 2),
            "offered_per_sec": round(offered_per_sec, 2),
            "offered_x_saturation": round(
                offered_per_sec / sat_per_sec, 2) if sat_per_sec else 0.0,
            "goodput_frac_of_saturation": round(goodput_frac, 3),
            "shed": shed,
            "conflicts": ctr1.conflicts + ctr_w.conflicts + ctr_n.conflicts,
            "errors": errors,
            "last_error": (ctr_n.last_error or ctr_w.last_error
                           or ctr1.last_error),
            "well_solo_p99_wait_ms": solo_p99_ms,
            "well_overload_p99_wait_ms": ovl_p99_ms,
            "isolation_ratio": round(isolation, 3),
            "rejections_by_reason": dict(q.rejections_by_reason),
            **oracle,
            "oracle_ok": all(oracle.values()),
        }
        for s in well_s + noisy_s:
            s.close()
        return out
    finally:
        admission._SQL_QUEUE = saved
        boot.close()
