"""Mixed-workload serving-load harness — ROADMAP 3(c).

Reference: pkg/workload's mixed-cluster runs (YCSB writers beside TPC-H
readers against one node) are how CockroachDB prices admission control and
memory accounting under contention. This module drives the same shape
through the FULL SQL front door: N concurrent ``Session``s over one shared
KV store + TPC-H catalog, each thread mixing YCSB-style point ops (point
SELECT / INSERT on an indexed kv table) with small TPC-H-flavoured analytic
statements (scan-aggregate and top-K over lineitem/orders).

Because every statement passes through ``Session.execute``, the run
exercises — and measures — the whole resource observability plane:

- admission: each statement takes a WorkQueue slot (utils/admission.py);
  queue-wait lands in the ``admission_wait_seconds`` histogram, and p99
  queue-wait is recovered from the histogram's bucket deltas;
- memory: each statement opens a query monitor under its session
  (flow/memory.py); peak HBM is the node root's high-water over the run,
  cross-checked against the device allocator's peak where the backend
  reports one.

Returned dict feeds bench.py's ``load`` job (BENCH JSON ``mixed_load``
entry): ops/s by class, p99 queue-wait ms, peak-HBM bytes, spill and
admission counters.
"""

from __future__ import annotations

import threading
import time

import numpy as np

# analytic statements: TPC-H q1/q18 flavoured, sized so they plan and run
# in milliseconds at the harness's small scale factor but still walk the
# scan→aggregate→sort pipeline (operator accounts, spill checks, top-K)
_ANALYTIC_SQL = (
    "SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty, "
    "count(*) AS count_order FROM lineitem "
    "GROUP BY l_returnflag, l_linestatus "
    "ORDER BY l_returnflag, l_linestatus",
    "SELECT o_orderpriority, count(*) AS n FROM orders "
    "GROUP BY o_orderpriority ORDER BY n DESC LIMIT 5",
    # high-cardinality group-by (q18's first stage): the per-order partial
    # states actually occupy the agg spool, so the run's peak-HBM figure
    # reflects real buffering, not just 6-group partial tiles
    "SELECT l_orderkey, sum(l_quantity) AS sq FROM lineitem "
    "GROUP BY l_orderkey ORDER BY sq DESC LIMIT 10",
)


def _hist_snapshot(h) -> tuple[list[int], int]:
    with h._lock:
        return list(h.counts), h.n


def hist_quantile_from_deltas(buckets, before: list[int],
                              after: list[int], q: float) -> float:
    """Quantile from two cumulative-count snapshots of a fixed-bucket
    histogram (the Prometheus histogram_quantile discipline): returns the
    upper bound of the bucket where the q-th delta observation lands, 0.0
    when no observations arrived between the snapshots. The overflow
    bucket reports the last finite bound (a floor, not an estimate)."""
    deltas = [a - b for a, b in zip(after, before)]
    total = sum(deltas)
    if total <= 0:
        return 0.0
    rank = q * total
    seen = 0.0
    for i, d in enumerate(deltas):
        seen += d
        if seen >= rank:
            return float(buckets[i]) if i < len(buckets) else float(
                buckets[-1])
    return float(buckets[-1])


class _Counters:
    __slots__ = ("lock", "point_ops", "analytic_ops", "inserts",
                 "conflicts", "errors", "last_error")

    def __init__(self):
        self.lock = threading.Lock()
        self.point_ops = 0
        self.analytic_ops = 0
        self.inserts = 0
        self.conflicts = 0
        self.errors = 0
        self.last_error = ""


def _load_worker(sess, stop: threading.Event, ctr: _Counters,
                 n_keys: int, analytic_frac: float, insert_frac: float,
                 seed: int) -> None:
    from ..kv.txn import TransactionRetryError
    from ..storage.lsm import WriteIntentError

    rng = np.random.default_rng(seed)
    next_pk = n_keys + 1000 * seed  # per-thread pk range: no write-write conflicts
    while not stop.is_set():
        try:
            r = rng.random()
            if r < analytic_frac:
                sess.execute(_ANALYTIC_SQL[int(rng.integers(
                    0, len(_ANALYTIC_SQL)))])
                with ctr.lock:
                    ctr.analytic_ops += 1
            elif r < analytic_frac + insert_frac:
                sess.execute(
                    f"INSERT INTO ycsb_kv VALUES ({next_pk}, {next_pk % 997})")
                next_pk += 1
                with ctr.lock:
                    ctr.inserts += 1
            else:
                k = int(rng.integers(0, n_keys))
                sess.execute(f"SELECT v FROM ycsb_kv WHERE k = {k}")
                with ctr.lock:
                    ctr.point_ops += 1
        except (WriteIntentError, TransactionRetryError):
            # retryable read/write conflict (a point read landed on a
            # concurrent insert's intent): the client-retry case, counted
            # as contention rather than failure — the 40001 shape
            with ctr.lock:
                ctr.conflicts += 1
        except Exception as e:  # crlint: allow-broad-except(load harness: one failed op must not kill the thread; failures are counted and reported)
            with ctr.lock:
                ctr.errors += 1
                ctr.last_error = f"{type(e).__name__}: {e}"[:200]


def run_mixed_load(sessions: int = 4, duration_s: float = 3.0,
                   sf: float = 0.01, n_keys: int = 512,
                   analytic_frac: float = 0.2, insert_frac: float = 0.1,
                   seed: int = 0) -> dict:
    """N concurrent sessions × (YCSB point ops + TPC-H analytics) for
    duration_s; returns throughput, p99 queue-wait, and peak-HBM figures.

    Setup (untimed): generate the TPC-H catalog at ``sf``, bootstrap one
    session over a fresh KV store, create + seed the ``ycsb_kv`` table.
    Then ``sessions`` threads share that store/catalog, each through its
    own Session (own monitor subtree, own admission entries)."""
    from ..flow import memory
    from ..sql.session import Session
    from ..utils import metric
    from .tpch import gen_tpch_cached

    cat = gen_tpch_cached(sf)
    boot = Session(catalog=cat)
    boot.execute("CREATE TABLE ycsb_kv (k INT PRIMARY KEY, v INT)")
    # seed in multi-row INSERTs (one statement per row would pay the
    # admission + planning toll n_keys times before the clock even starts)
    chunk = 128
    for lo in range(0, n_keys, chunk):
        rows = ", ".join(f"({k}, {k % 997})"
                         for k in range(lo, min(lo + chunk, n_keys)))
        boot.execute(f"INSERT INTO ycsb_kv VALUES {rows}")

    # warm the analytic plans/kernels off the clock (plan + kernel caches
    # are process-global, so workers serve steady-state from op one; a
    # loaded box must not report ops=0 just because first-compile ate the
    # whole window)
    for stmt in _ANALYTIC_SQL:
        boot.execute(stmt)

    workers = [Session(catalog=cat, db=boot.db, bootstrap=False)
               for _ in range(sessions)]

    wait_h = metric.ADMISSION_WAIT_SECONDS
    wait_before, n_before = _hist_snapshot(wait_h)
    mem_floor = memory.ROOT.high_water
    dev_before = memory.device_memory_stats()

    ctr = _Counters()
    stop = threading.Event()
    threads = [
        threading.Thread(
            target=_load_worker,
            args=(s, stop, ctr, n_keys, analytic_frac, insert_frac, i + 1),
            name=f"load-{i}", daemon=True)
        for i, s in enumerate(workers)
    ]
    t0 = time.time()
    for t in threads:
        t.start()
    stop.wait(duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    elapsed = time.time() - t0

    wait_after, n_after = _hist_snapshot(wait_h)
    dev_after = memory.device_memory_stats()
    from ..utils import admission
    q = admission.sql_queue()

    total_ops = ctr.point_ops + ctr.analytic_ops + ctr.inserts
    peak_hbm = memory.ROOT.high_water
    out = {
        "sessions": sessions,
        "duration_s": round(elapsed, 3),
        "ops": total_ops,
        "ops_per_sec": round(total_ops / elapsed, 2) if elapsed > 0 else 0.0,
        "point_ops": ctr.point_ops,
        "analytic_ops": ctr.analytic_ops,
        "inserts": ctr.inserts,
        "conflicts": ctr.conflicts,
        "errors": ctr.errors,
        "last_error": ctr.last_error,
        "admission_waits": n_after - n_before,
        "p99_queue_wait_ms": round(1e3 * hist_quantile_from_deltas(
            wait_h.buckets, wait_before, wait_after, 0.99), 4),
        "p50_queue_wait_ms": round(1e3 * hist_quantile_from_deltas(
            wait_h.buckets, wait_before, wait_after, 0.50), 4),
        "admission_slots": q.slots,
        "admission_timeouts": q.timeouts,
        "peak_hbm_bytes": peak_hbm,
        "peak_hbm_floor_bytes": mem_floor,  # node peak before the run
        "spills": memory.ROOT.spills,
        "drain_failures": memory.drain_failure_count(),
    }
    dev_peak = dev_after.get("peak_bytes_in_use", 0)
    if dev_peak:
        out["device_peak_bytes"] = dev_peak
        out["device_peak_delta_bytes"] = (
            dev_peak - dev_before.get("peak_bytes_in_use", 0))
    for s in workers:
        s.close()
    boot.close()
    return out
