"""TPC-H data generator — schema-faithful, vectorized, seeded.

Mirrors the role of pkg/workload/tpch (reference: pkg/workload/tpch/tpch.go)
as the benchmark corpus generator. Distributions follow the TPC-H spec /
dbgen where they affect query selectivity (dates, quantities, discounts,
return flags, retail prices, the 2/3-of-customers-have-orders rule); text
columns use a bounded comment pool instead of dbgen's grammar (documented
divergence — LIKE predicates still select comparable fractions).

Scale: SF1 = 1.5M orders / ~6M lineitems / 150k customers / 200k parts /
10k suppliers / 800k partsupp, per spec.
"""

from __future__ import annotations

import numpy as np

from ..catalog import Catalog, Table
from ..coldata.types import DATE, DECIMAL, INT64, STRING, Schema

EPOCH = np.datetime64("1970-01-01")
START_DATE = (np.datetime64("1992-01-01") - EPOCH).astype(int)  # 8035
END_DATE = (np.datetime64("1998-08-02") - EPOCH).astype(int)
CURRENT_DATE = (np.datetime64("1995-06-17") - EPOCH).astype(int)


def d(s: str) -> int:
    """'YYYY-MM-DD' -> days since epoch (for query literals)."""
    return int((np.datetime64(s) - EPOCH).astype(int))


NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
TYPE_SYL1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_SYL2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_SYL3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
CONTAINER_SYL1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_SYL2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
P_NAME_WORDS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()
COMMENT_WORDS = (
    "furiously carefully quickly blithely slyly regular express special pending "
    "final ironic even bold unusual silent fluffy ruthless idle busy daring "
    "requests deposits packages theodolites accounts foxes ideas dependencies "
    "instructions excuses platelets asymptotes courts dolphins multipliers "
    "sleep wake nag haggle dazzle detect engage integrate boost breach cajole"
).split()

DEC2 = DECIMAL(12, 2)

# precise TPC-H comment LIKE targets (Q13 uses '%special%requests%')
_COMMENT_POOL_SIZE = 4096


def _comment_pool(rng: np.random.Generator) -> np.ndarray:
    words = rng.choice(COMMENT_WORDS, size=(_COMMENT_POOL_SIZE, 6))
    pool = np.array([" ".join(w) for w in words], dtype=object)
    # plant 'special ... requests' in ~1.2% (dbgen plants in a small fraction)
    n_special = _COMMENT_POOL_SIZE // 80
    idx = rng.choice(_COMMENT_POOL_SIZE, n_special, replace=False)
    for i in idx:
        pool[i] = "special packages wake slyly requests " + pool[i]
    return pool


def _money(rng, lo_cents: int, hi_cents: int, n: int) -> np.ndarray:
    return rng.integers(lo_cents, hi_cents + 1, n, dtype=np.int64)


def gen_tpch(sf: float = 0.01, seed: int = 19920101,
             via_arrow: bool = True) -> Catalog:
    """Generate the TPC-H catalog. via_arrow=True (default) round-trips
    every table through Apache Arrow (coldata/arrow.py), so the standard
    load path exercises the interchange format the way the reference's
    colserde sits on its wire path."""
    rng = np.random.default_rng(seed)
    cat = Catalog()
    pool = _comment_pool(rng)

    def comments(n):
        return pool[rng.integers(0, _COMMENT_POOL_SIZE, n)]

    n_part = int(200_000 * sf)
    n_supp = max(10, int(10_000 * sf))
    n_cust = int(150_000 * sf)
    n_order = int(1_500_000 * sf)

    # region / nation
    cat.add(Table.from_strings(
        "region",
        Schema.of(r_regionkey=INT64, r_name=STRING, r_comment=STRING),
        {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.array(REGIONS, dtype=object),
            "r_comment": comments(5),
        },
    ))
    cat.add(Table.from_strings(
        "nation",
        Schema.of(n_nationkey=INT64, n_name=STRING, n_regionkey=INT64,
                  n_comment=STRING),
        {
            "n_nationkey": np.arange(25, dtype=np.int64),
            "n_name": np.array([n for n, _ in NATIONS], dtype=object),
            "n_regionkey": np.array([r for _, r in NATIONS], dtype=np.int64),
            "n_comment": comments(25),
        },
    ))

    # supplier
    suppkey = np.arange(1, n_supp + 1, dtype=np.int64)
    cat.add(Table.from_strings(
        "supplier",
        Schema.of(s_suppkey=INT64, s_name=STRING, s_address=STRING,
                  s_nationkey=INT64, s_phone=STRING, s_acctbal=DEC2,
                  s_comment=STRING),
        {
            "s_suppkey": suppkey,
            "s_name": np.array([f"Supplier#{k:09d}" for k in suppkey], dtype=object),
            "s_address": comments(n_supp),
            "s_nationkey": rng.integers(0, 25, n_supp, dtype=np.int64),
            "s_phone": np.array(
                [f"{10+k%25}-{k%900+100}-{k%9000+1000}" for k in suppkey],
                dtype=object,
            ),
            "s_acctbal": _money(rng, -99_999, 999_999, n_supp),
            # dbgen plants 'Customer...Complaints' in 5 per 10k suppliers (Q16)
            "s_comment": np.where(
                rng.random(n_supp) < 0.0005,
                np.array(["Customer stuff Complaints"] * n_supp, dtype=object),
                comments(n_supp),
            ),
        },
    ))

    # part
    partkey = np.arange(1, n_part + 1, dtype=np.int64)
    pname_idx = rng.integers(0, len(P_NAME_WORDS), (n_part, 5))
    p_name = np.array(
        [" ".join(P_NAME_WORDS[j] for j in row) for row in pname_idx],
        dtype=object,
    )
    mfgr = rng.integers(1, 6, n_part)
    brand = mfgr * 10 + rng.integers(1, 6, n_part)
    p_type = np.array([
        f"{TYPE_SYL1[a]} {TYPE_SYL2[b]} {TYPE_SYL3[c]}"
        for a, b, c in zip(
            rng.integers(0, 6, n_part), rng.integers(0, 5, n_part),
            rng.integers(0, 5, n_part),
        )
    ], dtype=object)
    container = np.array([
        f"{CONTAINER_SYL1[a]} {CONTAINER_SYL2[b]}"
        for a, b in zip(rng.integers(0, 5, n_part), rng.integers(0, 8, n_part))
    ], dtype=object)
    # dbgen retail price formula (cents): 90000 + ((pk/10)%20001) + 100*(pk%1000)
    retail = (
        90_000 + (partkey // 10) % 20_001 + 100 * (partkey % 1_000)
    ).astype(np.int64)
    cat.add(Table.from_strings(
        "part",
        Schema.of(p_partkey=INT64, p_name=STRING, p_mfgr=STRING, p_brand=STRING,
                  p_type=STRING, p_size=INT64, p_container=STRING,
                  p_retailprice=DEC2, p_comment=STRING),
        {
            "p_partkey": partkey,
            "p_name": p_name,
            "p_mfgr": np.array([f"Manufacturer#{m}" for m in mfgr], dtype=object),
            "p_brand": np.array([f"Brand#{b}" for b in brand], dtype=object),
            "p_type": p_type,
            "p_size": rng.integers(1, 51, n_part, dtype=np.int64),
            "p_container": container,
            "p_retailprice": retail,
            "p_comment": comments(n_part),
        },
    ))

    # partsupp: 4 suppliers per part (spec formula)
    # dbgen's stride (S/4 + (pk-1)/S) can produce duplicate suppliers per part
    # at scaled-down S; a plain S/4 stride keeps i*stride distinct mod S for
    # i in 0..3 at every scale (3*floor(S/4) < S), preserving the spec's
    # "4 distinct suppliers per part" invariant that unique-build joins rely on
    ps_stride = max(1, n_supp // 4)
    ps_partkey = np.repeat(partkey, 4)
    n_ps = len(ps_partkey)
    i = np.tile(np.arange(4), n_part)
    ps_suppkey = ((ps_partkey + i * ps_stride) % n_supp) + 1
    cat.add(Table.from_strings(
        "partsupp",
        Schema.of(ps_partkey=INT64, ps_suppkey=INT64, ps_availqty=INT64,
                  ps_supplycost=DEC2, ps_comment=STRING),
        {
            "ps_partkey": ps_partkey,
            "ps_suppkey": ps_suppkey.astype(np.int64),
            "ps_availqty": rng.integers(1, 10_000, n_ps, dtype=np.int64),
            "ps_supplycost": _money(rng, 100, 100_000, n_ps),
            "ps_comment": comments(n_ps),
        },
    ))

    # customer
    custkey = np.arange(1, n_cust + 1, dtype=np.int64)
    cat.add(Table.from_strings(
        "customer",
        Schema.of(c_custkey=INT64, c_name=STRING, c_address=STRING,
                  c_nationkey=INT64, c_phone=STRING, c_acctbal=DEC2,
                  c_mktsegment=STRING, c_comment=STRING),
        {
            "c_custkey": custkey,
            "c_name": np.array([f"Customer#{k:09d}" for k in custkey], dtype=object),
            "c_address": comments(n_cust),
            "c_nationkey": rng.integers(0, 25, n_cust, dtype=np.int64),
            "c_phone": np.array(
                [f"{10+k%25}-{k%900+100}-{k%9000+1000}" for k in custkey],
                dtype=object,
            ),
            "c_acctbal": _money(rng, -99_999, 999_999, n_cust),
            "c_mktsegment": np.array(SEGMENTS, dtype=object)[
                rng.integers(0, 5, n_cust)
            ],
            "c_comment": comments(n_cust),
        },
    ))

    # orders: only customers with custkey % 3 != 0 place orders (spec)
    orderkey = np.arange(1, n_order + 1, dtype=np.int64)
    eligible = custkey[custkey % 3 != 0]
    o_custkey = eligible[rng.integers(0, len(eligible), n_order)]
    o_orderdate = rng.integers(START_DATE, END_DATE - 121, n_order).astype(np.int32)
    n_lines = rng.integers(1, 8, n_order)  # 1..7 per spec

    # lineitem (built first so orderstatus/totalprice can aggregate from it)
    l_orderkey = np.repeat(orderkey, n_lines)
    n_li = len(l_orderkey)
    l_linenumber = (
        np.arange(n_li) - np.repeat(np.cumsum(n_lines) - n_lines, n_lines) + 1
    ).astype(np.int64)
    l_partkey = rng.integers(1, n_part + 1, n_li, dtype=np.int64)
    l_suppkey = (
        (l_partkey + rng.integers(0, 4, n_li) * ps_stride) % n_supp
    ).astype(np.int64) + 1
    l_quantity = rng.integers(1, 51, n_li, dtype=np.int64) * 100  # DEC2
    l_extprice = (l_quantity // 100) * retail[l_partkey - 1]
    l_discount = rng.integers(0, 11, n_li, dtype=np.int64)  # 0.00-0.10 at DEC2
    l_tax = rng.integers(0, 9, n_li, dtype=np.int64)
    o_date_li = np.repeat(o_orderdate, n_lines).astype(np.int64)
    l_shipdate = (o_date_li + rng.integers(1, 122, n_li)).astype(np.int32)
    l_commitdate = (o_date_li + rng.integers(30, 91, n_li)).astype(np.int32)
    l_receiptdate = (l_shipdate + rng.integers(1, 31, n_li)).astype(np.int32)
    returnable = l_receiptdate <= CURRENT_DATE
    l_returnflag = np.where(
        returnable, np.where(rng.random(n_li) < 0.5, "R", "A"), "N"
    ).astype(object)
    l_linestatus = np.where(l_shipdate > CURRENT_DATE, "O", "F").astype(object)

    cat.add(Table.from_strings(
        "lineitem",
        Schema.of(l_orderkey=INT64, l_partkey=INT64, l_suppkey=INT64,
                  l_linenumber=INT64, l_quantity=DEC2, l_extendedprice=DEC2,
                  l_discount=DEC2, l_tax=DEC2, l_returnflag=STRING,
                  l_linestatus=STRING, l_shipdate=DATE, l_commitdate=DATE,
                  l_receiptdate=DATE, l_shipinstruct=STRING, l_shipmode=STRING,
                  l_comment=STRING),
        {
            "l_orderkey": l_orderkey,
            "l_partkey": l_partkey,
            "l_suppkey": l_suppkey,
            "l_linenumber": l_linenumber,
            "l_quantity": l_quantity,
            "l_extendedprice": l_extprice,
            "l_discount": l_discount * 1,  # cents at scale 2 (0.00-0.10)
            "l_tax": l_tax * 1,
            "l_returnflag": l_returnflag,
            "l_linestatus": l_linestatus,
            "l_shipdate": l_shipdate,
            "l_commitdate": l_commitdate,
            "l_receiptdate": l_receiptdate,
            "l_shipinstruct": np.array(INSTRUCTIONS, dtype=object)[
                rng.integers(0, 4, n_li)
            ],
            "l_shipmode": np.array(SHIPMODES, dtype=object)[
                rng.integers(0, 7, n_li)
            ],
            "l_comment": comments(n_li),
        },
        # np.repeat(orderkey, n_lines) clusters the fact table by order —
        # the TPC-H physical layout; enables ordered aggregation for
        # GROUP BY l_orderkey (q18's first stage)
        ordering=("l_orderkey",),
    ))

    # orders status/totalprice from lineitems
    li_f = l_linestatus == "F"
    f_per_order = np.bincount(l_orderkey - 1, weights=li_f, minlength=n_order)
    all_f = f_per_order == n_lines
    none_f = f_per_order == 0
    o_status = np.where(all_f, "F", np.where(none_f, "O", "P")).astype(object)
    gross = l_extprice * (100 - l_discount) * (100 + l_tax) // 10_000
    o_total = np.bincount(
        l_orderkey - 1, weights=gross.astype(np.float64), minlength=n_order
    ).astype(np.int64)
    cat.add(Table.from_strings(
        "orders",
        Schema.of(o_orderkey=INT64, o_custkey=INT64, o_orderstatus=STRING,
                  o_totalprice=DEC2, o_orderdate=DATE, o_orderpriority=STRING,
                  o_clerk=STRING, o_shippriority=INT64, o_comment=STRING),
        {
            "o_orderkey": orderkey,
            "o_custkey": o_custkey,
            "o_orderstatus": o_status,
            "o_totalprice": o_total,
            "o_orderdate": o_orderdate,
            "o_orderpriority": np.array(PRIORITIES, dtype=object)[
                rng.integers(0, 5, n_order)
            ],
            "o_clerk": np.array(
                [f"Clerk#{k:09d}" for k in rng.integers(1, max(2, int(1000*sf)) + 1, n_order)],
                dtype=object,
            ),
            "o_shippriority": np.zeros(n_order, dtype=np.int64),
            "o_comment": comments(n_order),
        },
        ordering=("o_orderkey",),
    ))
    if via_arrow:
        from ..coldata import arrow as arrow_mod

        for name in list(cat.tables):
            old = cat.tables[name]
            new = arrow_mod.table_from_arrow(
                name, arrow_mod.table_to_arrow(old)
            )
            # Arrow interchange carries data, not physical-layout
            # metadata; the round-trip preserves row order, so the
            # clustering declaration survives it
            new.ordering = old.ordering
            cat.tables[name] = new
    return cat


def save_catalog(cat: Catalog, path: str) -> None:
    """Serialize a generated catalog to one .npz (columns + valids + string
    dictionaries) so bench runs don't repay datagen (~80s at SF1)."""
    import os

    blob: dict[str, np.ndarray] = {}
    meta = []
    for name, t in cat.tables.items():
        meta.append(name)
        for cname in t.schema.names:
            blob[f"{name}.col.{cname}"] = np.asarray(t.columns[cname])
            if cname in t.valids:
                blob[f"{name}.valid.{cname}"] = np.asarray(t.valids[cname])
            if cname in t.dictionaries:
                blob[f"{name}.dict.{cname}"] = (
                    t.dictionaries[cname].values.astype(str)
                )
    blob["__tables__"] = np.array(meta, dtype=str)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **blob)
    os.replace(tmp, path)


def load_catalog(path: str, sf: float) -> Catalog | None:
    """Load a catalog saved by save_catalog; None if absent/corrupt."""
    import os

    from ..coldata.batch import Dictionary

    if not os.path.exists(path):
        return None
    try:
        z = np.load(path, allow_pickle=True)
        names = list(z["__tables__"])
        ref = gen_tpch(sf=0.0005)  # schemas only (tiny, fast)
        cat = Catalog()
        for name in names:
            schema = ref.get(name).schema
            cols, valids, dicts = {}, {}, {}
            for cname in schema.names:
                cols[cname] = z[f"{name}.col.{cname}"]
                vk = f"{name}.valid.{cname}"
                if vk in z:
                    valids[cname] = z[vk]
                dk = f"{name}.dict.{cname}"
                if dk in z:
                    dicts[cname] = Dictionary(z[dk].astype(object))
            cat.add(Table(name=name, schema=schema, columns=cols,
                          valids=valids, dictionaries=dicts,
                          ordering=ref.get(name).ordering))
        return cat
    except Exception:
        return None


_GEN_VERSION = 3  # bump when gen_tpch's data distributions change


def gen_tpch_cached(sf: float, seed: int = 19920101,
                    cache_dir: str | None = None) -> Catalog:
    """gen_tpch with a .npz disk cache keyed by (scale, seed, generator
    version) so generator changes can never silently reuse stale data."""
    import os

    if cache_dir is None:
        cache_dir = os.environ.get("TPCH_CACHE_DIR", ".cache")
    path = os.path.join(
        cache_dir, f"tpch_sf{sf:g}_s{seed}_v{_GEN_VERSION}.npz"
    )
    cat = load_catalog(path, sf)
    if cat is not None:
        return cat
    cat = gen_tpch(sf=sf, seed=seed)
    try:
        save_catalog(cat, path)
    except Exception:
        pass
    return cat


def to_pandas(cat: Catalog, name: str):
    """Decode a table to a pandas DataFrame for oracle computations."""
    import pandas as pd

    t = cat.get(name)
    out = {}
    for cname, typ in zip(t.schema.names, t.schema.types):
        col = t.columns[cname]
        if cname in t.dictionaries:
            out[cname] = t.dictionaries[cname].values[col]
        elif typ.family.name == "DECIMAL":
            out[cname] = col / 10.0**typ.scale
        else:
            out[cname] = col
    return pd.DataFrame(out)
