"""Changefeed fan-out bench — the subscriber-tree scaling oracle.

One :class:`~cockroach_tpu.kv.fanout.FanoutHub` demuxes a live write
stream to ~1k subscribers with a deliberately mixed consumer population:

- **fast** (the bulk): drained promptly through one selector loop —
  these measure sustained delivery throughput and end-to-end lag
  (the writer embeds its wall-clock time in every value);
- **slow** (a handful): tiny socket buffers, never read — these must
  walk the backpressure ladder to a typed eviction WITHOUT stalling
  the emit path or wedging their peers;
- **flapping** (a handful): dropped mid-stream, then re-subscribed
  from their last resolved checkpoint — exactly-once after dedup.

The oracle (BENCH ``fanout.fanout_oracle_ok``) asserts the plane
survived being popular: every sampled fast consumer and every
reconnected flapper observed exactly the ``changes_between`` history
(no loss, no duplication after (ts, key) dedup), and the changefeed
staging account drained to zero after close (no leaked buffer bytes).
"""

from __future__ import annotations

import json
import selectors
import socket
import struct
import threading
import time

_LEN = struct.Struct("<I")  # flow/dcn framing: little-endian u32 prefix


class _Consumer:
    """Client half of one subscription: incremental frame parser plus
    per-consumer delivery/frontier accounting (appends are GIL-atomic;
    the drain loop is the only writer)."""

    def __init__(self, sock: socket.socket, keep_events: bool):
        self.sock = sock
        self.buf = bytearray()
        self.resolved = 0
        self.delivered = 0
        self.error: dict | None = None
        self.events: dict | None = {} if keep_events else None

    def feed(self, data: bytes, lags: list, t_recv: float) -> None:
        self.buf.extend(data)
        while True:
            if len(self.buf) < _LEN.size:
                return
            n = _LEN.unpack_from(self.buf)[0]
            if len(self.buf) < _LEN.size + n:
                return
            payload = bytes(self.buf[_LEN.size:_LEN.size + n])
            del self.buf[:_LEN.size + n]
            frame = json.loads(payload.decode("utf-8"))
            if "resolved" in frame:
                self.resolved = max(self.resolved, int(frame["resolved"]))
            elif "error" in frame:
                self.error = frame
            else:
                self.delivered += 1
                val = frame.get("value")
                if self.events is not None:
                    self.events[(int(frame["ts"]), frame["key"])] = val
                if val:
                    try:
                        lags.append(t_recv - float(val))
                    except ValueError:
                        pass  # pre-bench row without an embedded clock


def _drain_loop(sel: selectors.DefaultSelector, stop: threading.Event,
                lags: list) -> None:
    """ONE thread drains every fast/flapping consumer (epoll under the
    hood): the bench's client side must not need a thread per socket to
    keep up, or 1k subscribers would measure the harness, not the hub."""
    while not stop.is_set():
        for key, _mask in sel.select(timeout=0.2):
            cons: _Consumer = key.data
            try:
                data = cons.sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError:
                data = b""
            if not data:
                try:
                    sel.unregister(cons.sock)
                except (KeyError, ValueError):
                    pass
                continue
            cons.feed(data, lags, time.time())


def _subscribe(hub, *, since: int = 0, sndbuf: int | None = None,
               keep_events: bool = False) -> tuple[_Consumer, object]:
    """One registration: a socketpair whose server half joins the tree
    and whose client half becomes a :class:`_Consumer`."""
    srv, cli = socket.socketpair()
    if sndbuf is not None:
        # a deliberately slow consumer: shrink both kernel buffers so
        # the sender wedges after a few frames instead of after the
        # default ~200KB of invisible kernel slack
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, sndbuf)
        cli.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, sndbuf)
    sub = hub.add_subscriber(srv, since=since)
    if sub is None:  # tree at max_subscribers: bounded refusal
        srv.close()
        cli.close()
        return None, None
    cli.setblocking(False)
    return _Consumer(cli, keep_events), sub


def run_fanout(subscribers: int = 1000, duration_s: float = 10.0,
               n_keys: int = 32, txns: int = 30, puts_per_txn: int = 8,
               slow: int = 20, flappers: int = 20) -> dict:
    """Run the fan-out bench; returns the BENCH ``detail["fanout"]``
    dict. See the module docstring for the population and the oracle."""
    from ..flow import memory as flowmem
    from ..kv.changefeed import changes_between
    from ..kv.fanout import FanoutHub
    from ..kv.txn import DB
    from ..storage.lsm import Engine
    from ..utils import metric, settings

    # val_width must hold the 17-byte "%.6f" wall-clock payload: the
    # engine's value lanes are fixed-width and silently NUL out writes
    # that don't fit the default 16
    db = DB(Engine(key_width=16, val_width=64))
    saved = {k: settings.get(k) for k in (
        "changefeed.fanout.send_deadline_s",
        "changefeed.fanout.heartbeat_s",
    )}
    # bench-scale liveness: a wedged consumer should be detected in ~2s
    # of wall time, not the production 5s default — the run is short
    settings.set("changefeed.fanout.send_deadline_s", 1.5)
    settings.set("changefeed.fanout.heartbeat_s", 0.25)
    evict0 = metric.CHANGEFEED_EVICTIONS.value
    sheds0 = metric.CHANGEFEED_SHEDS.value
    coal0 = metric.CHANGEFEED_EVENTS_COALESCED.value
    mon = flowmem.staging_monitor("changefeed")

    # poll slower than one cold overlay rebuild at this run count, or the
    # poller serializes the writer to one commit per rebuild (each commit
    # rewrites the engine's run set under the store mutex)
    hub = FanoutHub(db, poll_interval_s=0.5, name="bench")
    sel = selectors.DefaultSelector()
    lags: list[float] = []
    fast: list[_Consumer] = []
    flap: list[tuple[_Consumer, object]] = []
    slow_socks: list[socket.socket] = []
    n_fast = max(0, subscribers - slow - flappers)
    oracle_sample = 3  # full event maps only for a sample: O(events) each
    try:
        for i in range(n_fast):
            cons, _sub = _subscribe(hub, keep_events=(i < oracle_sample))
            if cons is None:
                break
            fast.append(cons)
            sel.register(cons.sock, selectors.EVENT_READ, cons)
        for _ in range(flappers):
            cons, sub = _subscribe(hub, keep_events=True)
            if cons is None:
                break
            flap.append((cons, sub))
            sel.register(cons.sock, selectors.EVENT_READ, cons)
        for _ in range(slow):
            cons, _sub = _subscribe(hub, sndbuf=4096)
            if cons is None:
                break
            slow_socks.append(cons.sock)  # held open, never drained

        stop = threading.Event()
        drainer = threading.Thread(target=_drain_loop,
                                   args=(sel, stop, lags),
                                   name="fanout-bench-drain", daemon=True)
        drainer.start()

        # -- write stream: several puts per txn (a statement batch), the
        # wall clock embedded in every value for end-to-end lag
        t0 = time.time()
        gap = (duration_s * 0.5) / max(txns, 1)
        seq = 0
        for t in range(txns):
            base = seq

            def w(txn, base=base):
                for j in range(puts_per_txn):
                    k = b"fk%03d" % ((base + j) % n_keys)
                    txn.put(k, b"%.6f" % time.time())
            db.txn(w)
            seq += puts_per_txn
            if t == txns // 2 and flap:
                # mid-stream drop: sever every flapper's client half; the
                # sender's next write fails and the hub evicts it
                for cons, _sub in flap:
                    try:
                        sel.unregister(cons.sock)
                    except (KeyError, ValueError):
                        pass
                    cons.sock.close()
            time.sleep(gap)
        hi = db.clock.now()

        # -- reconnect-from-frontier: each flapper re-dials with
        # since=<last checkpoint it saw>; dedup by (ts, key) must land it
        # on exactly the full history
        flap2: list[_Consumer] = []
        for cons, _sub in flap:
            re_cons, _re_sub = _subscribe(hub, since=cons.resolved,
                                          keep_events=True)
            if re_cons is None:
                continue
            re_cons.events.update(cons.events)  # pre-drop deliveries
            flap2.append(re_cons)
            sel.register(re_cons.sock, selectors.EVENT_READ, re_cons)

        # -- convergence: every drained consumer's frontier reaches hi
        watch = fast + flap2
        deadline = time.time() + max(30.0, duration_s * 3)
        while time.time() < deadline:
            if all(c.resolved >= hi for c in watch):
                break
            time.sleep(0.1)
        elapsed = time.time() - t0

        oracle, _res = changes_between(db, 0, hi)
        truth = {(int(e["ts"]), e["key"]): e["value"] for e in oracle}
        sustained = sum(1 for c in fast if c.resolved >= hi
                        and c.error is None)
        sampled = [c for c in fast[:oracle_sample]] + flap2
        oracle_ok = bool(sampled) and all(c.events == truth for c in sampled)
        delivered = sum(c.delivered for c in fast) + \
            sum(c.delivered for c in flap2)
        lag_sorted = sorted(lags)

        def pct(p: float) -> float:
            if not lag_sorted:
                return 0.0
            return lag_sorted[min(len(lag_sorted) - 1,
                                  int(p * (len(lag_sorted) - 1)))]

        peak = mon.high_water
        stop.set()
        drainer.join(timeout=5)
    finally:
        stop.set()
        hub.close()
        for s in slow_socks:
            s.close()
        for cons in fast:
            cons.sock.close()
        sel.close()
        for k, v in saved.items():
            settings.set(k, v)
    # the leak half of the oracle: close() must return every buffered
    # byte to the staging account
    oracle_ok = oracle_ok and mon.used == 0
    return {
        "subscribers": n_fast + len(flap) + len(slow_socks),
        "subscribers_sustained": sustained,
        "events_delivered": delivered,
        "events_delivered_per_sec": round(delivered / max(elapsed, 1e-9)),
        "p50_lag_ms": round(pct(0.50) * 1e3, 1),
        "p99_lag_ms": round(pct(0.99) * 1e3, 1),
        "evictions": metric.CHANGEFEED_EVICTIONS.value - evict0,
        "sheds": metric.CHANGEFEED_SHEDS.value - sheds0,
        "coalesced": metric.CHANGEFEED_EVENTS_COALESCED.value - coal0,
        "peak_fanout_bytes": int(peak),
        "fanout_oracle_ok": bool(oracle_ok),
    }
