"""TPC-DS workload (reduced) — the pkg/workload/tpcds analog.

Reference: pkg/workload/tpcds ships the official dsdgen tables and 99
queries. This reduction keeps the STAR-SCHEMA shape the benchmark's
reporting class exercises — a store_sales fact table against date_dim /
item / store dimensions with realistic key distributions — and the five
classic reporting queries over it (q3, q42, q52, q55, q59-lite), each
expressed as a Rel plan the engine runs locally AND distributed, with a
pandas oracle in the tests. Not dsdgen-bit-compatible (documented
divergence; the generator is seeded and deterministic)."""

from __future__ import annotations

import numpy as np

from ..catalog import Catalog, Table
from ..coldata.types import DECIMAL, FLOAT64, INT64, STRING, Schema
from ..ops import expr as ex
from ..sql.rel import Rel


def _eq(rel: Rel, col: str, v: int) -> Rel:
    return rel.filter(ex.Cmp("eq", rel.c(col), ex.lit(v)))

BRANDS = [f"brand#{i}" for i in range(1, 21)]
CATEGORIES = ["Sports", "Books", "Home", "Electronics", "Music",
              "Jewelry", "Shoes", "Men", "Women", "Children"]
MANAGERS = [f"mgr_{i}" for i in range(1, 9)]


def gen_tpcds(sf: float = 0.01, seed: int = 19980401) -> Catalog:
    """store_sales + date_dim + item + store at roughly TPC-DS row
    ratios (store_sales ~2.88M rows/SF)."""
    rng = np.random.default_rng(seed)
    cat = Catalog()

    # date_dim: 5 years of days with (year, moy, dom) breakdown
    n_days = 5 * 365
    d_date_sk = np.arange(n_days, dtype=np.int64)
    years = 1998 + d_date_sk // 365
    doy = d_date_sk % 365
    moy = np.minimum(doy // 30 + 1, 12)
    cat.add(Table.from_strings(
        "date_dim",
        Schema.of(d_date_sk=INT64, d_year=INT64, d_moy=INT64, d_dom=INT64),
        {
            "d_date_sk": d_date_sk,
            "d_year": years.astype(np.int64),
            "d_moy": moy.astype(np.int64),
            "d_dom": (doy % 30 + 1).astype(np.int64),
        },
    ))

    n_item = max(40, int(18_000 * sf))
    i_item_sk = np.arange(n_item, dtype=np.int64)
    brand_id = rng.integers(1, len(BRANDS) + 1, n_item)
    cat.add(Table.from_strings(
        "item",
        Schema.of(i_item_sk=INT64, i_brand_id=INT64, i_brand=STRING,
                  i_category=STRING, i_manager_id=INT64,
                  i_manufact_id=INT64),
        {
            "i_item_sk": i_item_sk,
            "i_brand_id": brand_id.astype(np.int64),
            "i_brand": np.array(BRANDS, dtype=object)[brand_id - 1],
            "i_category": np.array(CATEGORIES, dtype=object)[
                rng.integers(0, len(CATEGORIES), n_item)],
            "i_manager_id": rng.integers(1, 9, n_item).astype(np.int64),
            "i_manufact_id": rng.integers(1, 21, n_item).astype(np.int64),
        },
    ))

    n_store = max(2, int(12 * sf * 10))
    cat.add(Table.from_strings(
        "store",
        Schema.of(s_store_sk=INT64, s_store_name=STRING),
        {
            "s_store_sk": np.arange(n_store, dtype=np.int64),
            "s_store_name": np.array(
                [f"store_{i}" for i in range(n_store)], dtype=object),
        },
    ))

    # customer_demographics: the full cross of the reduced attribute space
    genders = ["M", "F"]
    maritals = ["M", "S", "D", "W", "U"]
    educations = ["Primary", "Secondary", "College", "2 yr Degree",
                  "4 yr Degree", "Advanced Degree", "Unknown"]
    n_cd = len(genders) * len(maritals) * len(educations)
    g_idx = np.arange(n_cd) // (len(maritals) * len(educations))
    m_idx = (np.arange(n_cd) // len(educations)) % len(maritals)
    e_idx = np.arange(n_cd) % len(educations)
    cat.add(Table.from_strings(
        "customer_demographics",
        Schema.of(cd_demo_sk=INT64, cd_gender=STRING,
                  cd_marital_status=STRING, cd_education_status=STRING),
        {
            "cd_demo_sk": np.arange(n_cd, dtype=np.int64),
            "cd_gender": np.array(genders, dtype=object)[g_idx],
            "cd_marital_status": np.array(maritals, dtype=object)[m_idx],
            "cd_education_status": np.array(educations, dtype=object)[e_idx],
        },
    ))

    n_promo = max(4, int(300 * sf))
    cat.add(Table.from_strings(
        "promotion",
        Schema.of(p_promo_sk=INT64, p_channel_email=STRING,
                  p_channel_event=STRING),
        {
            "p_promo_sk": np.arange(n_promo, dtype=np.int64),
            "p_channel_email": np.array(
                ["N" if x < 0.9 else "Y" for x in rng.random(n_promo)],
                dtype=object),
            "p_channel_event": np.array(
                ["N" if x < 0.8 else "Y" for x in rng.random(n_promo)],
                dtype=object),
        },
    ))

    n_sales = int(2_880_000 * sf)
    price = rng.integers(100, 30_000, n_sales)  # cents
    list_price = price + rng.integers(0, 5_000, n_sales)
    cat.add(Table.from_strings(
        "store_sales",
        Schema.of(ss_sold_date_sk=INT64, ss_item_sk=INT64,
                  ss_store_sk=INT64, ss_cdemo_sk=INT64, ss_promo_sk=INT64,
                  ss_quantity=INT64, ss_ext_sales_price=DECIMAL(12, 2),
                  ss_list_price=DECIMAL(12, 2),
                  ss_coupon_amt=DECIMAL(12, 2)),
        {
            "ss_sold_date_sk": rng.integers(0, n_days, n_sales
                                            ).astype(np.int64),
            "ss_item_sk": rng.integers(0, n_item, n_sales).astype(np.int64),
            "ss_store_sk": rng.integers(0, n_store, n_sales
                                        ).astype(np.int64),
            "ss_cdemo_sk": rng.integers(0, n_cd, n_sales).astype(np.int64),
            "ss_promo_sk": rng.integers(0, n_promo, n_sales
                                        ).astype(np.int64),
            "ss_quantity": rng.integers(1, 100, n_sales).astype(np.int64),
            "ss_ext_sales_price": price.astype(np.int64),
            "ss_list_price": list_price.astype(np.int64),
            "ss_coupon_amt": (rng.integers(0, 500, n_sales)
                              * (rng.random(n_sales) < 0.3)).astype(np.int64),
        },
    ))
    return cat


# ---------------------------------------------------------------------------
# queries (Rel plans; the tests also run them distributed)


def q3(cat: Catalog) -> Rel:
    """TPC-DS Q3: brand revenue by year for one manufacturer in December."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"))
    dd = _eq(Rel.scan(cat, "date_dim"), "d_moy", 12)
    it = _eq(Rel.scan(cat, "item"), "i_manufact_id", 5)
    j = (ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")])
         .join(it, on=[("ss_item_sk", "i_item_sk")]))
    g = j.groupby(["d_year", "i_brand_id", "i_brand"],
                  [("sum_agg", "sum", "ss_ext_sales_price")])
    return g.sort([("d_year", False), ("sum_agg", True),
                   ("i_brand_id", False)]).limit(100)


def q42(cat: Catalog) -> Rel:
    """TPC-DS Q42: category revenue for one month/year."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"))
    dd = _eq(_eq(Rel.scan(cat, "date_dim"), "d_moy", 11), "d_year", 2000)
    it = Rel.scan(cat, "item")
    j = (ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")])
         .join(it, on=[("ss_item_sk", "i_item_sk")]))
    g = j.groupby(["d_year", "i_category"],
                  [("rev", "sum", "ss_ext_sales_price")])
    return g.sort([("rev", True), ("d_year", False),
                   ("i_category", False)]).limit(100)


def q52(cat: Catalog) -> Rel:
    """TPC-DS Q52: brand revenue for one month/year (ordered by revenue)."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"))
    dd = _eq(_eq(Rel.scan(cat, "date_dim"), "d_moy", 12), "d_year", 1999)
    it = Rel.scan(cat, "item")
    j = (ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")])
         .join(it, on=[("ss_item_sk", "i_item_sk")]))
    g = j.groupby(["d_year", "i_brand_id", "i_brand"],
                  [("rev", "sum", "ss_ext_sales_price")])
    return g.sort([("d_year", False), ("rev", True),
                   ("i_brand_id", False)]).limit(100)


def q55(cat: Catalog) -> Rel:
    """TPC-DS Q55: brand revenue for one manager's items in one month."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"))
    dd = _eq(_eq(Rel.scan(cat, "date_dim"), "d_moy", 11), "d_year", 2001)
    it = _eq(Rel.scan(cat, "item"), "i_manager_id", 3)
    j = (ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")])
         .join(it, on=[("ss_item_sk", "i_item_sk")]))
    g = j.groupby(["i_brand_id", "i_brand"],
                  [("rev", "sum", "ss_ext_sales_price")])
    return g.sort([("rev", True), ("i_brand_id", False)]).limit(100)


def q59_lite(cat: Catalog) -> Rel:
    """Q59 (reduced): weekly store revenue — store x month totals here
    (the full query's week-over-week self-join is out of this slice)."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_sold_date_sk", "ss_store_sk", "ss_ext_sales_price"))
    dd = Rel.scan(cat, "date_dim")
    st = Rel.scan(cat, "store")
    j = (ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")])
         .join(st, on=[("ss_store_sk", "s_store_sk")]))
    g = j.groupby(["s_store_name", "d_year", "d_moy"],
                  [("rev", "sum", "ss_ext_sales_price")])
    return g.sort([("s_store_name", False), ("d_year", False),
                   ("d_moy", False)]).limit(500)


def q7(cat: Catalog) -> Rel:
    """TPC-DS Q7: average quantity/list price/coupon/sales price per item
    for one demographic slice, excluding promoted-by-email sales."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_sold_date_sk", "ss_item_sk", "ss_cdemo_sk",
                   "ss_promo_sk", "ss_quantity", "ss_ext_sales_price",
                   "ss_list_price", "ss_coupon_amt"))
    dd = _eq(Rel.scan(cat, "date_dim"), "d_year", 2000)
    cd = Rel.scan(cat, "customer_demographics")
    cd = cd.filter(cd.str_eq("cd_gender", "M"))
    cd = cd.filter(cd.str_eq("cd_marital_status", "S"))
    cd = cd.filter(cd.str_eq("cd_education_status", "College"))
    pr = Rel.scan(cat, "promotion")
    pr = pr.filter(pr.str_eq("p_channel_email", "N"))
    it = Rel.scan(cat, "item", ("i_item_sk", "i_brand_id"))
    j = (ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")])
         .join(cd, on=[("ss_cdemo_sk", "cd_demo_sk")])
         .join(pr, on=[("ss_promo_sk", "p_promo_sk")])
         .join(it, on=[("ss_item_sk", "i_item_sk")]))
    g = j.groupby(["i_brand_id"], [
        ("agg1", "avg", "ss_quantity"),
        ("agg2", "avg", "ss_list_price"),
        ("agg3", "avg", "ss_coupon_amt"),
        ("agg4", "avg", "ss_ext_sales_price"),
    ])
    return g.sort([("i_brand_id", False)]).limit(100)


def q19_lite(cat: Catalog) -> Rel:
    """TPC-DS Q19 (reduced): brand revenue for one manager cohort in one
    month — manufacturer breakdown without the customer-geography anti
    filter (no customer_address table in this slice)."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"))
    dd = _eq(_eq(Rel.scan(cat, "date_dim"), "d_moy", 11), "d_year", 1999)
    it = _eq(Rel.scan(cat, "item"), "i_manager_id", 7)
    j = (ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")])
         .join(it, on=[("ss_item_sk", "i_item_sk")]))
    g = j.groupby(["i_brand_id", "i_brand", "i_manufact_id"],
                  [("ext_price", "sum", "ss_ext_sales_price")])
    return g.sort([("ext_price", True), ("i_brand_id", False),
                   ("i_manufact_id", False)]).limit(100)


def q53_lite(cat: Catalog) -> Rel:
    """TPC-DS Q53 (reduced): manufacturers whose monthly revenue deviates
    from their average monthly revenue — the avg-as-window-over-partition
    shape (sum per (manufact, month), avg of those sums per manufact)."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price"))
    dd = Rel.scan(cat, "date_dim")
    it = Rel.scan(cat, "item", ("i_item_sk", "i_manufact_id"))
    j = (ss.join(dd, on=[("ss_sold_date_sk", "d_date_sk")])
         .join(it, on=[("ss_item_sk", "i_item_sk")]))
    g = j.groupby(["i_manufact_id", "d_year", "d_moy"],
                  [("sum_sales", "sum", "ss_ext_sales_price")])
    w = g.window(["i_manufact_id"], [("d_year", False), ("d_moy", False)],
                 [("avg_monthly", "avg", "sum_sales")])
    dev = w.filter(ex.Cmp(
        "gt",
        ex.Func1("abs", ex.BinOp(
            "-", ex.Cast(w.c("sum_sales"), FLOAT64), w.c("avg_monthly"))),
        ex.BinOp("*", ex.Const(0.1, FLOAT64), w.c("avg_monthly")),
    ))
    return dev.sort([("i_manufact_id", False), ("d_year", False),
                     ("d_moy", False)]).limit(200)


def q65_lite(cat: Catalog) -> Rel:
    """TPC-DS Q65 (reduced): store/item pairs whose revenue falls below
    95% of the store's average item revenue — an aggregate joined against
    an aggregate of itself (the reference's sa/sc sub-aggregation join;
    spec uses 10% but this generator's uniform sales concentrate per-item
    revenue near the mean, so 95% keeps the predicate selective)."""
    ss = Rel.scan(cat, "store_sales",
                  ("ss_item_sk", "ss_store_sk", "ss_ext_sales_price"))
    per_item = ss.groupby(["ss_store_sk", "ss_item_sk"],
                          [("revenue", "sum", "ss_ext_sales_price")])
    per_store = per_item.groupby(
        ["ss_store_sk"], [("ave", "avg", "revenue")]
    )
    per_store = per_store.project([
        ("sb_store_sk", per_store.c("ss_store_sk")),
        ("ave", per_store.c("ave")),
    ])
    j = per_item.join(per_store, on=[("ss_store_sk", "sb_store_sk")])
    low = j.filter(ex.Cmp(
        "le", ex.Cast(j.c("revenue"), FLOAT64),
        ex.BinOp("*", ex.Const(0.95, FLOAT64), j.c("ave")),
    ))
    st = Rel.scan(cat, "store")
    out = low.join(st, on=[("ss_store_sk", "s_store_sk")])
    return out.sort([("s_store_name", False), ("ss_item_sk", False)]
                    ).limit(200)


QUERIES = {"q3": q3, "q7": q7, "q19_lite": q19_lite, "q42": q42,
           "q52": q52, "q53_lite": q53_lite, "q55": q55,
           "q59_lite": q59_lite, "q65_lite": q65_lite}
