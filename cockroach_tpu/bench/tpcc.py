"""TPC-C workload (reduced) — the pkg/workload/tpcc analog.

Reference: pkg/workload/tpcc generates the 9-table schema and drives
NewOrder/Payment/OrderStatus/Delivery/StockLevel in their spec mix;
roachtest's tpcc check asserts the consistency invariants (3.3.2.x: e.g.
W_YTD == sum(D_YTD)). This reduction keeps the transactional heart —
NewOrder and Payment as MULTI-STATEMENT KV TRANSACTIONS with contention on
the district cursor — over the Session/KVTable surface, plus the two
invariants those transactions maintain. Out of scope until the schema layer
grows composite primary keys: item/stock tables (order lines price from a
deterministic item function), carrier/delivery queues.
"""

from __future__ import annotations

import time

import numpy as np

from ..kv.txn import TransactionRetryError
from ..sql import Session

W_YTD_START = 30000_00  # cents, spec initial warehouse ytd


def load(sess: Session, warehouses: int = 1, districts: int = 10,
         customers: int = 30) -> None:
    """CREATE + populate the reduced schema (ids flattened into single-int
    primary keys: district pk = w*100+d, customer pk = (w*100+d)*10000+c)."""
    assert districts <= 99 and customers <= 9999, \
        "pk packing bounds: districts <= 99, customers <= 9999"
    sess.execute("""
        create table warehouse (
            w_id int primary key, w_tax decimal(4, 4),
            w_ytd decimal(12, 2))
    """)
    sess.execute("""
        create table district (
            d_pk int primary key, d_w_id int, d_id int,
            d_tax decimal(4, 4), d_ytd decimal(12, 2),
            d_next_o_id int)
    """)
    sess.execute("""
        create table customer (
            c_pk int primary key, c_w_id int, c_d_id int, c_id int,
            c_balance decimal(12, 2), c_ytd_payment decimal(12, 2),
            c_payment_cnt int, c_delivery_cnt int)
    """)
    sess.execute("""
        create table orders (
            o_pk int primary key, o_w_id int, o_d_id int, o_c_id int,
            o_ol_cnt int, o_entry_d int, o_total decimal(12, 2))
    """)
    for w in range(1, warehouses + 1):
        sess.execute(
            f"insert into warehouse values ({w}, 0.1000, 30000.00)")
        rows = ", ".join(
            f"({w * 100 + d}, {w}, {d}, 0.0500, 3000.00, 1)"
            for d in range(1, districts + 1)
        )
        sess.execute(f"insert into district values {rows}")
        crows = []
        for d in range(1, districts + 1):
            for c in range(1, customers + 1):
                pk = (w * 100 + d) * 10000 + c
                crows.append(f"({pk}, {w}, {d}, {c}, -10.00, 10.00, 1, 0)")
        sess.execute(f"insert into customer values {', '.join(crows)}")


def _district(sess: Session, w: int, d: int) -> dict:
    t = sess.catalog.tables["district"]
    return t.get_row(w * 100 + d)


def new_order(sess: Session, w: int, d: int, c: int, ol_cnt: int,
              entry_day: int) -> int:
    """NewOrder: allocate the district's next order id (THE contended
    write), insert the order with a deterministic total. Returns o_id."""
    dt = sess.catalog.tables["district"]
    ot = sess.catalog.tables["orders"]

    def op(txn):
        drow = dt.get_row_txn(txn, w * 100 + d)
        o_id = drow["d_next_o_id"]
        assert o_id < 1_000_000, "order id exceeds pk packing bound"
        drow["d_next_o_id"] = o_id + 1
        dt.insert(txn, drow)  # MVCC: new version of the district cursor
        total = sum(100 + ((o_id * 7 + i) % 900) for i in range(ol_cnt))
        ot.insert(txn, {
            "o_pk": (w * 100 + d) * 1000000 + o_id,
            "o_w_id": w, "o_d_id": d, "o_c_id": c, "o_ol_cnt": ol_cnt,
            "o_entry_d": entry_day, "o_total": total,
        })
        return o_id

    return sess.db.txn(op)


def payment(sess: Session, w: int, d: int, c: int, amount_cents: int):
    """Payment: W_YTD += h, D_YTD += h, customer balance += h / counters —
    three tables in ONE transaction (the invariant-bearing write set)."""
    wt = sess.catalog.tables["warehouse"]
    dt = sess.catalog.tables["district"]
    ct = sess.catalog.tables["customer"]

    def op(txn):
        wrow = wt.get_row_txn(txn, w)
        wrow["w_ytd"] += amount_cents
        wt.insert(txn, wrow)
        drow = dt.get_row_txn(txn, w * 100 + d)
        drow["d_ytd"] += amount_cents
        dt.insert(txn, drow)
        cpk = (w * 100 + d) * 10000 + c
        crow = ct.get_row_txn(txn, cpk)
        crow["c_balance"] -= amount_cents
        crow["c_ytd_payment"] += amount_cents
        crow["c_payment_cnt"] += 1
        ct.insert(txn, crow)

    sess.db.txn(op)


def check_consistency(sess: Session, warehouses: int = 1,
                      districts: int = 10) -> None:
    """The tpcc 3.3.2 invariants this reduction maintains:
    (1) W_YTD == W_YTD_START + sum of district YTD deltas;
    (2) D_NEXT_O_ID - 1 == max order id in the district."""
    res = sess.execute(
        "select w_id, w_ytd from warehouse order by w_id")
    dres = sess.execute(
        "select d_w_id, sum(d_ytd) as s from district group by d_w_id "
        "order by d_w_id")
    for w_ytd, dsum in zip(res["w_ytd"], dres["s"]):
        lhs = round(float(w_ytd) * 100)
        rhs = round(W_YTD_START + (float(dsum) * 100
                                   - districts * 3000_00))
        assert lhs == rhs, f"W_YTD {lhs} != 30000.00 + district deltas {rhs}"
    per = sess.execute(
        "select o_w_id, o_d_id, max(o_pk) as m, count(*) as n "
        "from orders group by o_w_id, o_d_id")
    seen = {
        (int(wd), int(dd)): int(m) - (int(wd) * 100 + int(dd)) * 1000000
        for wd, dd, m in zip(per["o_w_id"], per["o_d_id"], per["m"])
    }
    for w in range(1, warehouses + 1):
        for d in range(1, districts + 1):
            drow = _district(sess, w, d)
            max_oid = seen.get((w, d), 0)
            assert drow["d_next_o_id"] - 1 == max_oid, (
                f"district cursor {drow['d_next_o_id']} vs max order "
                f"{max_oid}"
            )


def run_mix(sess: Session, txns: int = 40, warehouses: int = 1,
            districts: int = 10, customers: int = 30,
            seed: int = 0) -> dict:
    """Drive the NewOrder/Payment mix (~45/43 of the spec mix, renormalized
    to the two implemented transactions); returns tpmC-style throughput."""
    from ..utils import metric

    rng = np.random.default_rng(seed)
    new_orders = 0
    give_ups = 0
    retries0 = metric.TXN_RETRIES.value
    t0 = time.time()
    for i in range(txns):
        w = int(rng.integers(1, warehouses + 1))
        d = int(rng.integers(1, districts + 1))
        c = int(rng.integers(1, customers + 1))
        try:
            if rng.random() < 0.51:  # 45/(45+43)
                new_order(sess, w, d, c, ol_cnt=int(rng.integers(5, 16)),
                          entry_day=20000 + i)
                new_orders += 1
            else:
                payment(sess, w, d, c,
                        amount_cents=int(rng.integers(100, 500000)))
        except TransactionRetryError:
            give_ups += 1  # DB.txn exhausted ITS retries and dropped the txn
    el = time.time() - t0
    return {
        "txns": txns,
        "new_orders": new_orders,
        "retries": int(metric.TXN_RETRIES.value - retries0),
        "give_ups": give_ups,
        "tpmC": new_orders / el * 60 if el > 0 else 0.0,
        "elapsed_s": el,
    }
