"""TPC-C workload (reduced) — the pkg/workload/tpcc analog.

Reference: pkg/workload/tpcc generates the 9-table schema and drives
NewOrder/Payment/OrderStatus/Delivery/StockLevel in their spec mix;
roachtest's tpcc check asserts the consistency invariants (3.3.2.x: e.g.
W_YTD == sum(D_YTD)). This reduction keeps the transactional heart —
NewOrder and Payment issued as client-driven SQL TRANSACTION BLOCKS
(BEGIN .. read .. write .. COMMIT with the canonical 40001 retry loop)
with contention on the district cursor, plus read-only OrderStatus, plus
the two invariants those transactions maintain. Out of scope until the
schema layer grows composite primary keys: item/stock tables (order lines
price from a deterministic item function), carrier/delivery queues.
"""

from __future__ import annotations

import time

import numpy as np

from ..kv.txn import TransactionRetryError
from ..sql import Session

W_YTD_START = 30000_00  # cents, spec initial warehouse ytd


def load(sess: Session, warehouses: int = 1, districts: int = 10,
         customers: int = 30) -> None:
    """CREATE + populate the reduced schema (ids flattened into single-int
    primary keys: district pk = w*100+d, customer pk = (w*100+d)*10000+c)."""
    assert districts <= 99 and customers <= 9999, \
        "pk packing bounds: districts <= 99, customers <= 9999"
    sess.execute("""
        create table warehouse (
            w_id int primary key, w_tax decimal(4, 4),
            w_ytd decimal(12, 2))
    """)
    sess.execute("""
        create table district (
            d_pk int primary key, d_w_id int, d_id int,
            d_tax decimal(4, 4), d_ytd decimal(12, 2),
            d_next_o_id int)
    """)
    sess.execute("""
        create table customer (
            c_pk int primary key, c_w_id int, c_d_id int, c_id int,
            c_balance decimal(12, 2), c_ytd_payment decimal(12, 2),
            c_payment_cnt int, c_delivery_cnt int)
    """)
    sess.execute("""
        create table orders (
            o_pk int primary key, o_w_id int, o_d_id int, o_c_id int,
            o_ol_cnt int, o_entry_d int, o_total decimal(12, 2))
    """)
    for w in range(1, warehouses + 1):
        sess.execute(
            f"insert into warehouse values ({w}, 0.1000, 30000.00)")
        rows = ", ".join(
            f"({w * 100 + d}, {w}, {d}, 0.0500, 3000.00, 1)"
            for d in range(1, districts + 1)
        )
        sess.execute(f"insert into district values {rows}")
        crows = []
        for d in range(1, districts + 1):
            for c in range(1, customers + 1):
                pk = (w * 100 + d) * 10000 + c
                crows.append(f"({pk}, {w}, {d}, {c}, -10.00, 10.00, 1, 0)")
        sess.execute(f"insert into customer values {', '.join(crows)}")


def _district(sess: Session, w: int, d: int) -> dict:
    t = sess.catalog.tables["district"]
    return t.get_row(w * 100 + d)


def _sql_txn_block(sess: Session, stmts_fn, max_retries: int = 16):
    """Issue a client-driven BEGIN..COMMIT block with the retry loop every
    CRDB client implements around 40001 (reference docs' canonical retry
    loop; the server cannot replay client-issued statements). stmts_fn
    runs the statements (it may SELECT mid-block and branch on results)."""
    for _ in range(max_retries):
        try:
            sess.execute("BEGIN")
            out = stmts_fn()
            sess.execute("COMMIT")
            return out
        except TransactionRetryError:
            if sess._txn is not None:
                sess.execute("ROLLBACK")
            continue
    raise TransactionRetryError("txn block gave up after retries")


def new_order(sess: Session, w: int, d: int, c: int, ol_cnt: int,
              entry_day: int) -> int:
    """NewOrder as a SQL transaction block: read the district's next order
    id (THE contended cursor), bump it, insert the order — all atomic."""
    dpk = w * 100 + d

    def stmts():
        r = sess.execute(
            f"select d_next_o_id from district where d_pk = {dpk}")
        o_id = int(r["d_next_o_id"][0])
        assert o_id < 1_000_000, "order id exceeds pk packing bound"
        sess.execute(
            f"update district set d_next_o_id = {o_id + 1} "
            f"where d_pk = {dpk}")
        total = sum(100 + ((o_id * 7 + i) % 900) for i in range(ol_cnt))
        sess.execute(
            f"insert into orders values ({dpk * 1000000 + o_id}, {w}, {d}, "
            f"{c}, {ol_cnt}, {entry_day}, {total / 100:.2f})")
        return o_id

    return _sql_txn_block(sess, stmts)


def payment(sess: Session, w: int, d: int, c: int, amount_cents: int):
    """Payment as a SQL transaction block: W_YTD += h, D_YTD += h, customer
    balance/counters — three tables in ONE atomic block."""
    amt = f"{amount_cents / 100:.2f}"
    cpk = (w * 100 + d) * 10000 + c

    def stmts():
        sess.execute(
            f"update warehouse set w_ytd = w_ytd + {amt} where w_id = {w}")
        sess.execute(
            f"update district set d_ytd = d_ytd + {amt} "
            f"where d_pk = {w * 100 + d}")
        sess.execute(
            f"update customer set c_balance = c_balance - {amt}, "
            f"c_ytd_payment = c_ytd_payment + {amt}, "
            f"c_payment_cnt = c_payment_cnt + 1 where c_pk = {cpk}")

    _sql_txn_block(sess, stmts)


def order_status(sess: Session, w: int, d: int, c: int) -> dict:
    """OrderStatus: a read-only SQL block — customer balance + their most
    recent order (tpcc.go orderStatus shape, reduced to the tables here)."""
    cpk = (w * 100 + d) * 10000 + c

    def stmts():
        cr = sess.execute(
            f"select c_balance, c_payment_cnt from customer "
            f"where c_pk = {cpk}")
        orr = sess.execute(
            f"select max(o_pk) as m, count(*) as n from orders "
            f"where o_w_id = {w} and o_d_id = {d} and o_c_id = {c}")
        return {
            "c_balance": float(cr["c_balance"][0]),
            "c_payment_cnt": int(cr["c_payment_cnt"][0]),
            "latest_o_id": (None if int(orr["n"][0]) == 0
                            else int(orr["m"][0]) % 1000000),
        }

    return _sql_txn_block(sess, stmts)


def check_consistency(sess: Session, warehouses: int = 1,
                      districts: int = 10) -> None:
    """The tpcc 3.3.2 invariants this reduction maintains:
    (1) W_YTD == W_YTD_START + sum of district YTD deltas;
    (2) D_NEXT_O_ID - 1 == max order id in the district."""
    res = sess.execute(
        "select w_id, w_ytd from warehouse order by w_id")
    dres = sess.execute(
        "select d_w_id, sum(d_ytd) as s from district group by d_w_id "
        "order by d_w_id")
    for w_ytd, dsum in zip(res["w_ytd"], dres["s"]):
        lhs = round(float(w_ytd) * 100)
        rhs = round(W_YTD_START + (float(dsum) * 100
                                   - districts * 3000_00))
        assert lhs == rhs, f"W_YTD {lhs} != 30000.00 + district deltas {rhs}"
    per = sess.execute(
        "select o_w_id, o_d_id, max(o_pk) as m, count(*) as n "
        "from orders group by o_w_id, o_d_id")
    seen = {
        (int(wd), int(dd)): int(m) - (int(wd) * 100 + int(dd)) * 1000000
        for wd, dd, m in zip(per["o_w_id"], per["o_d_id"], per["m"])
    }
    for w in range(1, warehouses + 1):
        for d in range(1, districts + 1):
            drow = _district(sess, w, d)
            max_oid = seen.get((w, d), 0)
            assert drow["d_next_o_id"] - 1 == max_oid, (
                f"district cursor {drow['d_next_o_id']} vs max order "
                f"{max_oid}"
            )


def run_mix(sess: Session, txns: int = 40, warehouses: int = 1,
            districts: int = 10, customers: int = 30,
            seed: int = 0) -> dict:
    """Drive the NewOrder/Payment mix (~45/43 of the spec mix, renormalized
    to the two implemented transactions); returns tpmC-style throughput."""
    from ..utils import metric

    rng = np.random.default_rng(seed)
    new_orders = 0
    give_ups = 0
    retries0 = metric.TXN_RETRIES.value
    t0 = time.time()
    for i in range(txns):
        w = int(rng.integers(1, warehouses + 1))
        d = int(rng.integers(1, districts + 1))
        c = int(rng.integers(1, customers + 1))
        try:
            roll = rng.random()
            if roll < 0.48:  # 45/(45+43+4 renormalized)
                new_order(sess, w, d, c, ol_cnt=int(rng.integers(5, 16)),
                          entry_day=20000 + i)
                new_orders += 1
            elif roll < 0.95:
                payment(sess, w, d, c,
                        amount_cents=int(rng.integers(100, 500000)))
            else:
                order_status(sess, w, d, c)
        except TransactionRetryError:
            give_ups += 1  # the block exhausted its retries and was dropped
    el = time.time() - t0
    return {
        "txns": txns,
        "new_orders": new_orders,
        "retries": int(metric.TXN_RETRIES.value - retries0),
        "give_ups": give_ups,
        "tpmC": new_orders / el * 60 if el > 0 else 0.0,
        "elapsed_s": el,
    }
