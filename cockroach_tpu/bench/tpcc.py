"""TPC-C workload — the pkg/workload/tpcc analog.

Reference: pkg/workload/tpcc generates the 9-table schema and drives the
five spec transactions (NewOrder 45 / Payment 43 / OrderStatus 4 /
Delivery 4 / StockLevel 4); roachtest's tpcc check asserts the consistency
invariants (3.3.2.x: e.g. W_YTD == sum(D_YTD)). This implementation keeps
the full transaction mix and the contended district cursor, issued as
client-driven SQL TRANSACTION BLOCKS (BEGIN .. read .. write .. COMMIT
with the canonical 40001 retry loop). Reductions vs the spec, documented:
ids are flattened into single-int primary keys (the schema layer's
composite-pk reduction), character filler columns are dropped, and the
item catalog prices from a deterministic function rather than random load.
"""

from __future__ import annotations

import time

import numpy as np

from ..kv.txn import TransactionRetryError
from ..sql import Session

W_YTD_START = 30000_00  # cents, spec initial warehouse ytd
STOCK_START = 50  # initial s_quantity for every stock row


def _item_price_cents(i: int) -> int:
    """Deterministic item price (spec: uniform 1.00..100.00; here a fixed
    function so consistency checks can recompute totals exactly)."""
    return 100 + (i * 37) % 9900


def load(sess: Session, warehouses: int = 1, districts: int = 10,
         customers: int = 30, items: int = 100) -> None:
    """CREATE + populate the schema (ids flattened into single-int primary
    keys: district pk = w*100+d, customer pk = (w*100+d)*10000+c, stock pk
    = w*1000000+i, order pk = d_pk*1000000+o_id, order_line pk =
    o_pk*100+n, new_order pk = order pk)."""
    assert districts <= 99 and customers <= 9999 and items <= 999999, \
        "pk packing bounds: districts <= 99, customers <= 9999"
    sess.execute("""
        create table warehouse (
            w_id int primary key, w_tax decimal(4, 4),
            w_ytd decimal(12, 2))
    """)
    sess.execute("""
        create table district (
            d_pk int primary key, d_w_id int, d_id int,
            d_tax decimal(4, 4), d_ytd decimal(12, 2),
            d_next_o_id int)
    """)
    sess.execute("""
        create table customer (
            c_pk int primary key, c_w_id int, c_d_id int, c_id int,
            c_balance decimal(12, 2), c_ytd_payment decimal(12, 2),
            c_payment_cnt int, c_delivery_cnt int)
    """)
    sess.execute("""
        create table orders (
            o_pk int primary key, o_w_id int, o_d_id int, o_c_id int,
            o_ol_cnt int, o_entry_d int, o_carrier_id int,
            o_total decimal(12, 2))
    """)
    sess.execute("""
        create table new_order (no_pk int primary key, no_w_id int,
            no_d_id int)
    """)
    sess.execute("""
        create table order_line (
            ol_pk int primary key, ol_o_pk int, ol_w_id int, ol_d_id int,
            ol_number int, ol_i_id int, ol_quantity int,
            ol_amount decimal(12, 2), ol_delivery_d int)
    """)
    sess.execute("""
        create table item (i_id int primary key, i_price decimal(12, 2))
    """)
    sess.execute("""
        create table stock (
            s_pk int primary key, s_w_id int, s_i_id int, s_quantity int,
            s_ytd int, s_order_cnt int)
    """)
    irows = ", ".join(
        f"({i}, {_item_price_cents(i) / 100:.2f})"
        for i in range(1, items + 1)
    )
    sess.execute(f"insert into item values {irows}")
    for w in range(1, warehouses + 1):
        sess.execute(
            f"insert into warehouse values ({w}, 0.1000, 30000.00)")
        rows = ", ".join(
            f"({w * 100 + d}, {w}, {d}, 0.0500, 3000.00, 1)"
            for d in range(1, districts + 1)
        )
        sess.execute(f"insert into district values {rows}")
        crows = []
        for d in range(1, districts + 1):
            for c in range(1, customers + 1):
                pk = (w * 100 + d) * 10000 + c
                crows.append(f"({pk}, {w}, {d}, {c}, -10.00, 10.00, 1, 0)")
        sess.execute(f"insert into customer values {', '.join(crows)}")
        srows = ", ".join(
            f"({w * 1000000 + i}, {w}, {i}, {STOCK_START}, 0, 0)"
            for i in range(1, items + 1)
        )
        sess.execute(f"insert into stock values {srows}")


def _district(sess: Session, w: int, d: int) -> dict:
    t = sess.catalog.tables["district"]
    return t.get_row(w * 100 + d)


def _sql_txn_block(sess: Session, stmts_fn, max_retries: int = 16):
    """Issue a client-driven BEGIN..COMMIT block with the retry loop every
    CRDB client implements around 40001 (reference docs' canonical retry
    loop; the server cannot replay client-issued statements). stmts_fn
    runs the statements (it may SELECT mid-block and branch on results)."""
    for _ in range(max_retries):
        try:
            sess.execute("BEGIN")
            out = stmts_fn()
            sess.execute("COMMIT")
            return out
        except TransactionRetryError:
            if sess._txn is not None:
                sess.execute("ROLLBACK")
            continue
    raise TransactionRetryError("txn block gave up after retries")


def new_order(sess: Session, w: int, d: int, c: int, ol_cnt: int,
              entry_day: int, items: int = 100, seed: int = 0) -> int:
    """NewOrder (spec 2.4): read + bump the district cursor (THE contended
    row), insert the order, its order lines, the new_order queue entry,
    and decrement each line's stock (wrap +91 below 10, spec 2.4.2.2)."""
    dpk = w * 100 + d
    rng = np.random.default_rng((seed << 20) ^ (dpk << 8) ^ entry_day)
    line_items = [int(rng.integers(1, items + 1)) for _ in range(ol_cnt)]
    line_qty = [int(rng.integers(1, 11)) for _ in range(ol_cnt)]

    def stmts():
        r = sess.execute(
            f"select d_next_o_id from district where d_pk = {dpk}")
        o_id = int(r["d_next_o_id"][0])
        assert o_id < 1_000_000, "order id exceeds pk packing bound"
        sess.execute(
            f"update district set d_next_o_id = {o_id + 1} "
            f"where d_pk = {dpk}")
        o_pk = dpk * 1000000 + o_id
        total = 0
        lrows = []
        for n, (i_id, qty) in enumerate(zip(line_items, line_qty), 1):
            amount = _item_price_cents(i_id) * qty
            total += amount
            lrows.append(
                f"({o_pk * 100 + n}, {o_pk}, {w}, {d}, {n}, {i_id}, "
                f"{qty}, {amount / 100:.2f}, 0)"
            )
            spk = w * 1000000 + i_id
            sr = sess.execute(
                f"select s_quantity from stock where s_pk = {spk}")
            sq = int(sr["s_quantity"][0])
            nq = sq - qty if sq - qty >= 10 else sq - qty + 91
            sess.execute(
                f"update stock set s_quantity = {nq}, s_ytd = s_ytd + "
                f"{qty}, s_order_cnt = s_order_cnt + 1 where s_pk = {spk}")
        sess.execute(
            f"insert into orders values ({o_pk}, {w}, {d}, {c}, {ol_cnt}, "
            f"{entry_day}, 0, {total / 100:.2f})")
        sess.execute(f"insert into order_line values {', '.join(lrows)}")
        sess.execute(
            f"insert into new_order values ({o_pk}, {w}, {d})")
        return o_id

    return _sql_txn_block(sess, stmts)


def payment(sess: Session, w: int, d: int, c: int, amount_cents: int):
    """Payment (spec 2.5): W_YTD += h, D_YTD += h, customer balance and
    counters — three tables in ONE atomic block."""
    amt = f"{amount_cents / 100:.2f}"
    cpk = (w * 100 + d) * 10000 + c

    def stmts():
        sess.execute(
            f"update warehouse set w_ytd = w_ytd + {amt} where w_id = {w}")
        sess.execute(
            f"update district set d_ytd = d_ytd + {amt} "
            f"where d_pk = {w * 100 + d}")
        sess.execute(
            f"update customer set c_balance = c_balance - {amt}, "
            f"c_ytd_payment = c_ytd_payment + {amt}, "
            f"c_payment_cnt = c_payment_cnt + 1 where c_pk = {cpk}")

    _sql_txn_block(sess, stmts)


def order_status(sess: Session, w: int, d: int, c: int) -> dict:
    """OrderStatus (spec 2.6): read-only — customer balance + their most
    recent order and its lines."""
    cpk = (w * 100 + d) * 10000 + c

    def stmts():
        cr = sess.execute(
            f"select c_balance, c_payment_cnt from customer "
            f"where c_pk = {cpk}")
        orr = sess.execute(
            f"select max(o_pk) as m, count(*) as n from orders "
            f"where o_w_id = {w} and o_d_id = {d} and o_c_id = {c}")
        latest = None
        lines = 0
        if int(orr["n"][0]) > 0:
            o_pk = int(orr["m"][0])
            latest = o_pk % 1000000
            lr = sess.execute(
                f"select count(*) as n from order_line "
                f"where ol_o_pk = {o_pk}")
            lines = int(lr["n"][0])
        return {
            "c_balance": float(cr["c_balance"][0]),
            "c_payment_cnt": int(cr["c_payment_cnt"][0]),
            "latest_o_id": latest,
            "latest_lines": lines,
        }

    return _sql_txn_block(sess, stmts)


def delivery(sess: Session, w: int, carrier_id: int,
             delivery_day: int, districts: int = 10) -> int:
    """Delivery (spec 2.7): for each district, deliver the OLDEST undelivered
    order — pop it from the new_order queue, stamp the carrier, mark its
    order lines delivered, credit the customer the order total and bump
    their delivery count. Returns orders delivered."""

    def stmts():
        delivered = 0
        for d in range(1, districts + 1):
            nr = sess.execute(
                f"select min(no_pk) as m, count(*) as n from new_order "
                f"where no_w_id = {w} and no_d_id = {d}")
            if int(nr["n"][0]) == 0:
                continue  # spec: skipped delivery, not an error
            o_pk = int(nr["m"][0])
            sess.execute(f"delete from new_order where no_pk = {o_pk}")
            orow = sess.execute(
                f"select o_c_id, o_total from orders where o_pk = {o_pk}")
            c = int(orow["o_c_id"][0])
            total = float(orow["o_total"][0])
            sess.execute(
                f"update orders set o_carrier_id = {carrier_id} "
                f"where o_pk = {o_pk}")
            sess.execute(
                f"update order_line set ol_delivery_d = {delivery_day} "
                f"where ol_o_pk = {o_pk}")
            cpk = (w * 100 + d) * 10000 + c
            sess.execute(
                f"update customer set c_balance = c_balance + {total:.2f},"
                f" c_delivery_cnt = c_delivery_cnt + 1 "
                f"where c_pk = {cpk}")
            delivered += 1
        return delivered

    return _sql_txn_block(sess, stmts)


def stock_level(sess: Session, w: int, d: int, threshold: int = 45,
                recent: int = 20) -> int:
    """StockLevel (spec 2.8): count DISTINCT items from the district's most
    recent orders whose stock is below the threshold — the analytic read
    in the mix (order_line join stock)."""
    dpk = w * 100 + d

    def stmts():
        r = sess.execute(
            f"select d_next_o_id from district where d_pk = {dpk}")
        next_o = int(r["d_next_o_id"][0])
        lo_pk = dpk * 1000000 + max(1, next_o - recent)
        hi_pk = dpk * 1000000 + next_o
        res = sess.execute(
            f"select count(*) as n from "
            f"(select distinct ol_i_id from order_line "
            f" where ol_o_pk >= {lo_pk} and ol_o_pk < {hi_pk}) li, stock "
            f"where stock.s_i_id = li.ol_i_id and stock.s_w_id = {w} "
            f"and stock.s_quantity < {threshold}")
        return int(res["n"][0])

    return _sql_txn_block(sess, stmts)


def check_consistency(sess: Session, warehouses: int = 1,
                      districts: int = 10) -> None:
    """The tpcc 3.3.2 invariants maintained here:
    (1) W_YTD == W_YTD_START + sum of district YTD deltas;
    (2) D_NEXT_O_ID - 1 == max order id in the district == max new_order id
        when the queue is non-empty (3.3.2.3/3.3.2.4);
    (3) per order: sum(ol_amount) == o_total and count(ol) == o_ol_cnt
        (3.3.2.8 shape);
    (4) stock s_ytd == total quantity ordered of that item in that
        warehouse (conservation through NewOrder's stock updates)."""
    res = sess.execute(
        "select w_id, w_ytd from warehouse order by w_id")
    dres = sess.execute(
        "select d_w_id, sum(d_ytd) as s from district group by d_w_id "
        "order by d_w_id")
    for w_ytd, dsum in zip(res["w_ytd"], dres["s"]):
        lhs = round(float(w_ytd) * 100)
        rhs = round(W_YTD_START + (float(dsum) * 100
                                   - districts * 3000_00))
        assert lhs == rhs, f"W_YTD {lhs} != 30000.00 + district deltas {rhs}"
    per = sess.execute(
        "select o_w_id, o_d_id, max(o_pk) as m, count(*) as n "
        "from orders group by o_w_id, o_d_id")
    seen = {
        (int(wd), int(dd)): int(m) - (int(wd) * 100 + int(dd)) * 1000000
        for wd, dd, m in zip(per["o_w_id"], per["o_d_id"], per["m"])
    }
    for w in range(1, warehouses + 1):
        for d in range(1, districts + 1):
            drow = _district(sess, w, d)
            max_oid = seen.get((w, d), 0)
            assert drow["d_next_o_id"] - 1 == max_oid, (
                f"district cursor {drow['d_next_o_id']} vs max order "
                f"{max_oid}"
            )
    # (3) order totals match their lines
    ol = sess.execute(
        "select ol_o_pk, sum(ol_amount) as s, count(*) as n "
        "from order_line group by ol_o_pk")
    by_o = {int(o): (float(s), int(n))
            for o, s, n in zip(ol["ol_o_pk"], ol["s"], ol["n"])}
    orders = sess.execute(
        "select o_pk, o_total, o_ol_cnt from orders")
    for o_pk, total, cnt in zip(orders["o_pk"], orders["o_total"],
                                orders["o_ol_cnt"]):
        s, n = by_o.get(int(o_pk), (0.0, 0))
        assert n == int(cnt), f"order {o_pk}: {n} lines vs o_ol_cnt {cnt}"
        assert round(s * 100) == round(float(total) * 100), (
            f"order {o_pk}: sum(ol_amount) {s} != o_total {total}"
        )
    # (4) stock ytd conservation vs order lines
    so = sess.execute(
        "select ol_w_id, ol_i_id, sum(ol_quantity) as q from order_line "
        "group by ol_w_id, ol_i_id")
    want = {(int(w_), int(i_)): int(q)
            for w_, i_, q in zip(so["ol_w_id"], so["ol_i_id"], so["q"])}
    st = sess.execute(
        "select s_w_id, s_i_id, s_ytd from stock where s_ytd > 0")
    got = {(int(w_), int(i_)): int(y)
           for w_, i_, y in zip(st["s_w_id"], st["s_i_id"], st["s_ytd"])}
    assert got == want, f"stock s_ytd mismatch: {got} vs {want}"


def run_mix(sess: Session, txns: int = 40, warehouses: int = 1,
            districts: int = 10, customers: int = 30, items: int = 100,
            seed: int = 0) -> dict:
    """Drive the full five-transaction spec mix (NewOrder 45 / Payment 43 /
    OrderStatus 4 / Delivery 4 / StockLevel 4); returns tpmC-style
    throughput (NewOrders per minute, the spec metric)."""
    from ..utils import metric

    rng = np.random.default_rng(seed)
    new_orders = 0
    give_ups = 0
    counts = {"new_order": 0, "payment": 0, "order_status": 0,
              "delivery": 0, "stock_level": 0}
    retries0 = metric.TXN_RETRIES.value
    t0 = time.time()
    for i in range(txns):
        w = int(rng.integers(1, warehouses + 1))
        d = int(rng.integers(1, districts + 1))
        c = int(rng.integers(1, customers + 1))
        try:
            roll = rng.random()
            if roll < 0.45:
                new_order(sess, w, d, c, ol_cnt=int(rng.integers(5, 16)),
                          entry_day=20000 + i, items=items, seed=seed + i)
                new_orders += 1
                counts["new_order"] += 1
            elif roll < 0.88:
                payment(sess, w, d, c,
                        amount_cents=int(rng.integers(100, 500000)))
                counts["payment"] += 1
            elif roll < 0.92:
                order_status(sess, w, d, c)
                counts["order_status"] += 1
            elif roll < 0.96:
                delivery(sess, w, carrier_id=int(rng.integers(1, 11)),
                         delivery_day=20000 + i, districts=districts)
                counts["delivery"] += 1
            else:
                stock_level(sess, w, d)
                counts["stock_level"] += 1
        except TransactionRetryError:
            give_ups += 1  # the block exhausted its retries and was dropped
    el = time.time() - t0
    return {
        "txns": txns,
        "counts": counts,
        "new_orders": new_orders,
        "retries": int(metric.TXN_RETRIES.value - retries0),
        "give_ups": give_ups,
        "tpmC": new_orders / el * 60 if el > 0 else 0.0,
        "elapsed_s": el,
    }
