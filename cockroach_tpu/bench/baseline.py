"""Measured CPU baselines for the north-star denominator.

BASELINE.md's target is "≥10× rows/sec vs the 8-vCPU colexec baseline", and
the reference's own rule is that the baseline must be *measured*, not quoted
(reference: pkg/cmd/roachtest/tests/tpchbench.go:203-223 runs the real ladder;
pkg/workload/tpch/tpch.go:370 validates results). This image cannot execute
the reference (no Go toolchain, no vendored deps, zero egress — verified
2026-08-02), so this module measures the two closest executable stand-ins on
the SAME box and data the engine is benched on:

- **pandas**: vectorized C columnar evaluation, single core. This is the
  per-core throughput stand-in for colexec (both are columnar batch engines
  running compiled loops; the reference's own tpchvec results put colexec
  within ~1-3× of its row engine, and pandas is at least as fast per core on
  these aggregate/join shapes).
- **sqlite**: a row-at-a-time compiled engine with real SQL semantics — the
  stand-in for the reference's *row* engine lower bound.

Scaling argument (recorded in BASELINE.md): colexec on 8 vCPUs is bounded
above by 8× its single-core throughput (DistSQL scaling is sublinear across
cores on one node: shared memtable/KV iterator contention, stream setup).
Taking pandas-single-core as the per-core colexec proxy,

    colexec_8vcpu_est(q)  =  pandas_1core_time(q) / 8        (generous bound)
    vs_colexec_est        =  vs_pandas / 8

so the north-star "10× the 8-vCPU baseline" is "vs_pandas ≥ 80" per query.
All numbers this module emits are measured on this box at the stated SF.
"""

from __future__ import annotations

import json
import sqlite3
import time


from . import tpch

# Columns each ladder query actually touches — loading only these keeps the
# sqlite ingest proportional to the workload, not the full 16-col schema.
_NEEDED = {
    "lineitem": ["l_orderkey", "l_partkey", "l_suppkey", "l_quantity",
                 "l_extendedprice", "l_discount", "l_tax", "l_returnflag",
                 "l_linestatus", "l_shipdate"],
    "orders": ["o_orderkey", "o_custkey", "o_orderdate", "o_shippriority",
               "o_totalprice"],
    "customer": ["c_custkey", "c_name", "c_mktsegment"],
    "supplier": ["s_suppkey", "s_nationkey"],
    "nation": ["n_nationkey", "n_name"],
    "part": ["p_partkey", "p_name"],
    "partsupp": ["ps_partkey", "ps_suppkey", "ps_supplycost"],
}

# Real TPC-H SQL text (dates as integer days since epoch, matching the
# generator's DATE encoding; decimals pre-scaled to floats by to_pandas).
_SQL = {
    "q1": """
        SELECT l_returnflag, l_linestatus, sum(l_quantity),
               sum(l_extendedprice),
               sum(l_extendedprice*(1-l_discount)),
               sum(l_extendedprice*(1-l_discount)*(1+l_tax)),
               avg(l_quantity), avg(l_extendedprice), avg(l_discount),
               count(*)
        FROM lineitem WHERE l_shipdate <= {cutoff}
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
    """,
    "q3": """
        SELECT l_orderkey, sum(l_extendedprice*(1-l_discount)) AS revenue,
               o_orderdate, o_shippriority
        FROM customer, orders, lineitem
        WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
          AND l_orderkey = o_orderkey
          AND o_orderdate < {date} AND l_shipdate > {date}
        GROUP BY l_orderkey, o_orderdate, o_shippriority
        ORDER BY revenue DESC, o_orderdate LIMIT 10
    """,
    "q9": """
        SELECT n_name AS nation, o_year, sum(amount) AS sum_profit FROM (
          SELECT n_name, o_orderdate/365 AS o_year,
                 l_extendedprice*(1-l_discount) - ps_supplycost*l_quantity
                   AS amount
          FROM part, supplier, lineitem, partsupp, orders, nation
          WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey
            AND ps_partkey = l_partkey AND p_partkey = l_partkey
            AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
            AND p_name LIKE '%green%'
        ) GROUP BY nation, o_year ORDER BY nation, o_year DESC
    """,
    "q18": """
        SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
               sum(l_quantity)
        FROM customer, orders, lineitem
        WHERE o_orderkey IN (
            SELECT l_orderkey FROM lineitem GROUP BY l_orderkey
            HAVING sum(l_quantity) > 300)
          AND c_custkey = o_custkey AND o_orderkey = l_orderkey
        GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
        ORDER BY o_totalprice DESC, o_orderdate LIMIT 100
    """,
}


def _pandas_time(qname: str, frames: dict, runs: int = 2) -> float:
    """Best-of-runs single-core pandas time for one ladder query. The query
    bodies mirror bench.py's oracle implementations (which also assert
    engine-result equality every bench run)."""
    import pandas as pd

    li = frames["lineitem"]
    times = []
    for _ in range(runs):
        if qname == "q1":
            t0 = time.time()
            cutoff = tpch.d("1998-12-01") - 90
            f = li[li.l_shipdate <= cutoff].copy()
            f["disc_price"] = f.l_extendedprice * (1 - f.l_discount)
            f["charge"] = f.disc_price * (1 + f.l_tax)
            f.groupby(["l_returnflag", "l_linestatus"]).agg(
                sum_qty=("l_quantity", "sum"),
                sum_base_price=("l_extendedprice", "sum"),
                sum_disc_price=("disc_price", "sum"),
                sum_charge=("charge", "sum"),
                avg_qty=("l_quantity", "mean"),
                avg_price=("l_extendedprice", "mean"),
                avg_disc=("l_discount", "mean"),
                count_order=("l_quantity", "size"),
            ).sort_index()
            times.append(time.time() - t0)
        elif qname == "q3":
            o, c = frames["orders"], frames["customer"]
            t0 = time.time()
            date = tpch.d("1995-03-15")
            cb = c[c.c_mktsegment == "BUILDING"]
            ob = o[o.o_orderdate < date].merge(
                cb, left_on="o_custkey", right_on="c_custkey")
            lb = li[li.l_shipdate > date]
            j = lb.merge(ob, left_on="l_orderkey", right_on="o_orderkey")
            j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
            (j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
             .agg(revenue=("revenue", "sum")).reset_index()
             .sort_values(["revenue", "o_orderdate"], ascending=[False, True])
             .head(10))
            times.append(time.time() - t0)
        elif qname == "q9":
            o, s = frames["orders"], frames["supplier"]
            n, p, ps = frames["nation"], frames["part"], frames["partsupp"]
            t0 = time.time()
            pg = p[p.p_name.str.contains("green")]
            j = (li[li.l_partkey.isin(pg.p_partkey)]
                 .merge(ps, left_on=["l_partkey", "l_suppkey"],
                        right_on=["ps_partkey", "ps_suppkey"])
                 .merge(s, left_on="l_suppkey", right_on="s_suppkey")
                 .merge(n, left_on="s_nationkey", right_on="n_nationkey")
                 .merge(o, left_on="l_orderkey", right_on="o_orderkey"))
            j["o_year"] = pd.to_datetime(
                j.o_orderdate, unit="D", origin="unix").dt.year
            j["amount"] = (j.l_extendedprice * (1 - j.l_discount)
                           - j.ps_supplycost * j.l_quantity)
            (j.groupby(["n_name", "o_year"]).agg(sum_profit=("amount", "sum"))
             .reset_index()
             .sort_values(["n_name", "o_year"], ascending=[True, False]))
            times.append(time.time() - t0)
        elif qname == "q18":
            o, c = frames["orders"], frames["customer"]
            t0 = time.time()
            qty = li.groupby("l_orderkey").l_quantity.sum()
            big = qty[qty > 300].index
            j = (o[o.o_orderkey.isin(big)]
                 .merge(c, left_on="o_custkey", right_on="c_custkey")
                 .merge(li, left_on="o_orderkey", right_on="l_orderkey"))
            (j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                        "o_totalprice"])
             .agg(sum_qty=("l_quantity", "sum")).reset_index()
             .sort_values(["o_totalprice", "o_orderdate"],
                          ascending=[False, True])
             .head(100))
            times.append(time.time() - t0)
        else:
            raise ValueError(qname)
    return min(times)


def _sqlite_load(frames: dict) -> tuple[sqlite3.Connection, float]:
    """Load the needed columns into an in-memory sqlite DB; returns (conn,
    load_seconds). No explicit indexes — sqlite's planner builds automatic
    transient indexes for the joins, which is how an ad-hoc analytic run
    against a row engine behaves."""
    conn = sqlite3.connect(":memory:")
    t0 = time.time()
    for name, cols in _NEEDED.items():
        df = frames[name]
        decls = []
        import pandas.api.types as ptypes

        for cname in cols:
            kind = ("REAL" if ptypes.is_float_dtype(df[cname]) else
                    "INTEGER" if ptypes.is_integer_dtype(df[cname])
                    else "TEXT")
            decls.append(f"{cname} {kind}")
        conn.execute(f"CREATE TABLE {name} ({', '.join(decls)})")
        ph = ", ".join("?" * len(cols))
        rows = list(zip(*[df[cname].tolist() for cname in cols]))
        conn.executemany(f"INSERT INTO {name} VALUES ({ph})", rows)
    conn.commit()
    return conn, time.time() - t0


def _sqlite_time(qname: str, conn: sqlite3.Connection,
                 runs: int = 2) -> float:
    sql = _SQL[qname].format(cutoff=tpch.d("1998-12-01") - 90,
                             date=tpch.d("1995-03-15"))
    times = []
    for _ in range(runs):
        t0 = time.time()
        conn.execute(sql).fetchall()
        times.append(time.time() - t0)
    return min(times)


def measure(sf: float = 1.0, queries=("q1", "q3", "q9", "q18"),
            with_sqlite: bool = True, runs: int = 2) -> dict:
    """Measure the stand-in baselines; returns the BASELINE_MEASURED dict."""
    import os
    import platform as plat

    cat = tpch.gen_tpch_cached(sf=sf)
    nrows = cat.get("lineitem").num_rows
    frames = {name: tpch.to_pandas(cat, name) for name in _NEEDED}
    out = {
        "sf": sf,
        "lineitem_rows": int(nrows),
        "box": {"nproc": os.cpu_count(), "machine": plat.machine(),
                "python": plat.python_version()},
        "method": ("pandas single-core + sqlite row engine on this box; "
                   "colexec_8vcpu_est = pandas_1core / 8 (see module doc)"),
        "queries": {},
    }
    conn = None
    if with_sqlite:
        conn, load_s = _sqlite_load(frames)
        out["sqlite_load_s"] = round(load_s, 1)
    for q in queries:
        p = _pandas_time(q, frames, runs=runs)
        entry = {
            "pandas_1core_s": round(p, 3),
            "pandas_rows_per_sec": round(nrows / p),
            "colexec_8vcpu_est_s": round(p / 8, 3),
            "colexec_8vcpu_est_rows_per_sec": round(nrows / (p / 8)),
        }
        if conn is not None:
            s = _sqlite_time(q, conn, runs=runs)
            entry["sqlite_1core_s"] = round(s, 3)
        out["queries"][q] = entry
        print(f"# baseline {q}: pandas {p:.2f}s"
              + (f", sqlite {entry.get('sqlite_1core_s', '-')}s"
                 if conn else ""), flush=True)
    if conn is not None:
        conn.close()
    return out


def main() -> None:
    import os

    sf = float(os.environ.get("TPCH_SF", "1.0"))
    res = measure(sf=sf)
    path = os.environ.get("BASELINE_OUT", "BASELINE_MEASURED.json")
    with open(path, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps({"written": path, "sf": sf}), flush=True)


if __name__ == "__main__":
    main()
