"""Materialized-view maintenance bench — the 1k-standing-views oracle.

One base table, a fleet of ~1k registered views all sharing ONE shape
class (same parameterized q1 shape, distinct date literals), refreshed
against a sustained write stream of inserts, updates and deletes. What
the BENCH ``detail["views"]`` payload must show:

- **refresh lag** p50/p99 (wall-clock age of the oldest buffered event
  when its flush lands) stays bounded while every flush refreshes the
  whole fleet;
- **dispatches per flush** is O(shape classes), NOT O(views): the delta
  kernel folds the staged event tiles into every view's accumulator row
  in one vmapped fused dispatch (``views_dispatch_ok``);
- **delta vs rescan**: the steady path does delta work only — zero
  base-table rescans after the create-time population
  (``delta_vs_rescan`` = events applied incrementally per rescan);
- **bit-identity** (``views_oracle_ok``): sampled views equal a fresh
  full rescan of their defining query with the planner rewrite off —
  enforced as pass/fail by scripts/check_bench_regress.py.
"""

from __future__ import annotations

import os
import time

import numpy as np

_FLAGS = "ABCDEFGH"


def _dates(n: int) -> list[str]:
    out = []
    for y in range(1995, 1999):
        for mo in range(1, 13):
            for dd in range(1, 29):
                out.append(f"{y}-{mo:02d}-{dd:02d}")
    step = max(1, len(out) // n)
    return (out[::step] * ((n // len(out[::step])) + 1))[:n]


def _q(date: str) -> str:
    return ("SELECT flag, sum(qty) AS sq, avg(price) AS ap, count(*) AS n "
            f"FROM t WHERE d <= DATE '{date}' GROUP BY flag ORDER BY flag")


def run_views(views: int = 1000, rounds: int = 8,
              writes_per_round: int = 64, base_rows: int = 240,
              sample: int = 5) -> dict:
    """Run the matview bench; returns the BENCH ``detail["views"]``
    payload (see module docstring for the oracle contract)."""
    from ..flow import dispatch
    from ..sql import Session, matview
    from ..utils import metric, settings

    s = Session(val_width=160)
    s.execute("CREATE TABLE t (k INT PRIMARY KEY, flag STRING, "
              "qty DECIMAL(12,2), price DECIMAL(12,2), d DATE)")
    rng = np.random.default_rng(7)
    dates = _dates(max(views, 1))
    t0 = time.time()
    for lo in range(0, base_rows, 40):
        rows = ", ".join(
            f"({k}, '{_FLAGS[k % len(_FLAGS)]}', {k % 97}.25, "
            f"{(k * 3) % 89}.50, DATE '{dates[k % len(dates)]}')"
            for k in range(lo, min(lo + 40, base_rows)))
        s.execute(f"INSERT INTO t VALUES {rows}")
    for i in range(views):
        s.execute(f"CREATE MATERIALIZED VIEW v{i} AS {_q(dates[i])}")
    setup_s = time.time() - t0

    reg = matview.registry_for(s.catalog)
    m = reg.maintainers["t"]
    full0 = metric.MATVIEW_FULL_RESCANS.value
    mm0 = metric.MATVIEW_MINMAX_RESCANS.value
    ev0 = metric.MATVIEW_DELTA_EVENTS.value

    live = list(range(base_rows))
    next_k = base_rows
    lags_ms: list[float] = []
    per_flush: list[int] = []
    t1 = time.time()
    for _ in range(rounds):
        stmts = []
        for _ in range(writes_per_round):
            op = rng.integers(0, 10)
            if op < 6 or not live:
                stmts.append(
                    f"INSERT INTO t VALUES ({next_k}, "
                    f"'{_FLAGS[next_k % len(_FLAGS)]}', "
                    f"{next_k % 53}.75, {next_k % 71}.25, "
                    f"DATE '{dates[next_k % len(dates)]}')")
                live.append(next_k)
                next_k += 1
            elif op < 9:
                k = int(live[int(rng.integers(0, len(live)))])
                stmts.append(f"UPDATE t SET qty = {k % 61}.50, "
                             f"price = {k % 43}.00 WHERE k = {k}")
            else:
                k = live.pop(int(rng.integers(0, len(live))))
                stmts.append(f"DELETE FROM t WHERE k = {k}")
        for st in stmts:
            s.execute(st)
        m.pump()
        d0 = dispatch.total()
        m.flush()
        per_flush.append(dispatch.total() - d0)
        vs = m.views()
        if vs:
            lags_ms.append(vs[0].last_lag_s * 1e3)
    steady_s = time.time() - t1

    full_steady = metric.MATVIEW_FULL_RESCANS.value - full0
    mm_steady = metric.MATVIEW_MINMAX_RESCANS.value - mm0
    events = metric.MATVIEW_DELTA_EVENTS.value - ev0
    classes = len(m.classes)

    # sampled bit-identity oracle: standing state vs fresh full rescan,
    # planner rewrite OFF so the reference cannot serve from the view
    oracle_ok = True
    idx = sorted({int(i) for i in
                  np.linspace(0, views - 1, num=min(sample, views))})
    prev = settings.get("sql.matview.rewrite.enabled")
    settings.set("sql.matview.rewrite.enabled", False)
    try:
        for i in idx:
            fresh = s.execute(_q(dates[i]))
            got = s.execute(f"SELECT * FROM v{i} ORDER BY flag")
            same = list(fresh) == list(got) and all(
                np.array_equal(np.asarray(fresh[c]), np.asarray(got[c]))
                for c in fresh)
            if not same:
                oracle_ok = False
    finally:
        settings.set("sql.matview.rewrite.enabled", prev)
    matview.close_all(s.catalog)

    return {
        "views": views,
        "rounds": rounds,
        "writes_per_round": writes_per_round,
        "shape_classes": classes,
        "setup_s": round(setup_s, 2),
        "steady_s": round(steady_s, 2),
        "events_applied": int(events),
        "refresh_lag_p50_ms": round(float(np.percentile(lags_ms, 50)), 3),
        "refresh_lag_p99_ms": round(float(np.percentile(lags_ms, 99)), 3),
        "dispatches_per_flush_mean": round(float(np.mean(per_flush)), 2),
        "dispatches_per_flush_max": int(max(per_flush)),
        "full_rescans_steady": int(full_steady),
        "minmax_rescans_steady": int(mm_steady),
        "delta_vs_rescan": round(
            float(events) / max(1.0, full_steady + mm_steady), 1),
        # O(kernels), not O(views): every flush refreshed the whole fleet
        # in at most one fused dispatch per shape class, with no steady-
        # state base rescans
        "views_dispatch_ok": bool(
            max(per_flush) <= classes and full_steady == 0),
        "views_oracle_ok": bool(oracle_ok),
    }


if __name__ == "__main__":
    import json

    print(json.dumps(run_views(
        views=int(os.environ.get("BENCH_VIEWS_N", "1000")),
        rounds=int(os.environ.get("BENCH_VIEWS_ROUNDS", "8")),
    ), indent=2))
